"""Model-health plane tier-1 suite (ISSUE 14; CPU, loopback only).

Covers the acceptance criteria:
  * diagnostics are observationally FREE: a diag_stride run's trained
    params and best checkpoints are BIT-identical to a diagnostics-off
    run, and `mean_k violations[k]² == conditional_loss` to f32 ulps;
  * every completed training run dir carries a verified ``health.json``
    with finite per-moment violations; old run dirs read as None (the
    report renders its "(no health data)" placeholder byte-stably —
    asserted against the checked-in ``ref_runs`` dirs);
  * the promotion gate end-to-end: a healthy quick-train candidate
    passes with the health gates ON; a NaN-weights candidate is rejected
    ``moment_violation``; a drifted-panel candidate is rejected
    ``data_drift``; both reasons are counted in the report CLI's
    promotion section;
  * a ``nan_loss``-injected supervised run trips the health counters
    (guard trips recorded in events AND health.json) while the
    divergence guard's rollback path still completes the run;
  * serving exposes the ``dlap_model_*`` generation-quality gauges and
    the drift alert counter; every hot-swap replays the canary ring and
    records a ``serve/canary`` events row; a non-finite canary REVERTS
    the swap and 5xxs the reload;
plus the plots panels' graceful skip, the BENCH_HEALTH.json bars, and
the ruff/AST lint gate over the new modules.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
from deeplearninginassetpricing_paperreplication_tpu.observability import (
    EventLog,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.drift import (
    drift_report,
    psi,
    read_profile,
    reference_profile,
    score_request,
    series_profile,
    write_profile,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.modelhealth import (
    HealthThresholds,
    read_health,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
    compare_parity,
    format_summary,
    load_run,
    summarize_run,
)
from deeplearninginassetpricing_paperreplication_tpu.ops.diagnostics import (
    panel_diagnostics,
)
from deeplearninginassetpricing_paperreplication_tpu.ops.losses import (
    conditional_loss,
    unconditional_loss,
)
from deeplearninginassetpricing_paperreplication_tpu.reliability.promotion import (
    GateRejection,
    promote,
    read_pointer,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.engine import (
    InferenceEngine,
    InferenceRequest,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.server import (
    ServingService,
)
from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
    save_params,
)
from deeplearninginassetpricing_paperreplication_tpu.training.trainer import (
    train_3phase,
)
from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
    GANConfig,
    TrainConfig,
)

REPO = Path(__file__).resolve().parents[1]
PKG = "deeplearninginassetpricing_paperreplication_tpu"
REF_RUNS = REPO / "ref_runs"

T, N, F, M = 12, 64, 10, 6


def _make_cfg(**overrides):
    base = dict(macro_feature_dim=M, individual_feature_dim=F,
                hidden_dim=(8, 8), num_units_rnn=(4,))
    base.update(overrides)
    return GANConfig(**base)


def _panel(seed=11, t=T, n=N, scale=1.0, shift=0.0):
    rng = np.random.default_rng(seed)
    return {
        "macro": rng.standard_normal((t, M)).astype(np.float32),
        "individual": (rng.standard_normal((t, n, F)) * scale
                       + shift).astype(np.float32),
        "returns": (rng.standard_normal((t, n)) * 0.05).astype(np.float32),
        "mask": np.ones((t, n), np.float32),
    }


@pytest.fixture(scope="module")
def hcfg():
    return _make_cfg()


@pytest.fixture(scope="module")
def panel():
    return _panel()


@pytest.fixture(scope="module")
def tcfg():
    return TrainConfig(num_epochs_unc=3, num_epochs_moment=1,
                       num_epochs=3, ignore_epoch=0)


@pytest.fixture(scope="module")
def trained_runs(tmp_path_factory, hcfg, panel, tcfg):
    """One quick train WITHOUT diagnostics and one WITH (same seed/data):
    the bit-identity pair, and the health.json / gate / report / plots
    fixture."""
    root = tmp_path_factory.mktemp("health_runs")
    valid = _panel(seed=12, t=8)
    out = {}
    for name, stride in (("off", None), ("on", 2)):
        d = root / name
        _gan, params, history, _tr = train_3phase(
            hcfg, panel, valid, tcfg=tcfg, save_dir=str(d), seed=3,
            verbose=False, diag_stride=stride)
        out[name] = {"dir": d, "params": params, "history": history}
    out["valid"] = valid
    return out


def _write_member(d: Path, cfg, seed, nan=False, profile=None):
    d.mkdir(parents=True, exist_ok=True)
    cfg.save(d / "config.json")
    params = GAN(cfg).init(jax.random.key(seed))
    if nan:
        params = jax.tree.map(lambda x: x * np.nan, params)
    save_params(d / "best_model_sharpe.msgpack", params)
    if profile is not None:
        write_profile(d, profile)
    return str(d)


# --------------------------------------------------------------------------
# diagnostic kernels: exact relation to the losses
# --------------------------------------------------------------------------


def test_diagnostics_match_losses(panel):
    rng = np.random.default_rng(0)
    w = rng.standard_normal((T, N)).astype(np.float32)
    h = rng.standard_normal((8, T, N)).astype(np.float32)
    mask = (rng.random((T, N)) > 0.15).astype(np.float32)
    r = panel["returns"]
    diag = {k: np.asarray(v) for k, v in panel_diagnostics(
        w, r, mask, h, weighted=True).items()}
    loss_cond, _ = conditional_loss(w, r, mask, h, True)
    loss_unc, _ = unconditional_loss(w, r, mask, True)
    # mean_k violations² IS the conditional loss; sqrt(unc) the norm
    assert np.allclose((diag["moment_violations"] ** 2).mean(),
                       float(loss_cond), rtol=1e-5)
    assert np.allclose(diag["moment_violation_max"],
                       diag["moment_violations"].max())
    assert np.allclose(diag["unc_violation"] ** 2, float(loss_unc),
                       rtol=1e-5)
    assert np.allclose(diag["adv_gap"],
                       float(loss_cond) - float(loss_unc), rtol=1e-5)
    assert diag["sdf_finite_frac"] == 1.0
    # normalized book invariants: Σ|w| = 1 ⇒ HHI ∈ [1/N, 1], shorts < 1
    assert 1.0 / N <= diag["weight_hhi"] <= 1.0
    assert 0.0 <= diag["short_fraction"] <= 1.0
    assert diag["turnover"] >= 0.0
    # equal-weight book: HHI = 1/N_valid, zero shorts, zero turnover
    ones = np.ones((T, N), np.float32)
    d2 = {k: np.asarray(v) for k, v in panel_diagnostics(
        ones, r, ones, h, weighted=True).items()}
    assert np.allclose(d2["weight_hhi"], 1.0 / N, rtol=1e-5)
    assert d2["short_fraction"] == 0.0
    assert np.allclose(d2["turnover"], 0.0, atol=1e-7)


# --------------------------------------------------------------------------
# observational freeness + history/health artifacts
# --------------------------------------------------------------------------


def test_diag_stride_is_observationally_free(trained_runs):
    """THE bit-identity bar: params, best checkpoints, and base history
    identical with diagnostics on or off."""
    off, on = trained_runs["off"], trained_runs["on"]
    for a, b in zip(jax.tree.leaves(off["params"]),
                    jax.tree.leaves(on["params"])):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    for fname in ("best_model_sharpe.msgpack", "best_model_loss.msgpack",
                  "final_model.msgpack"):
        fa, fb = off["dir"] / fname, on["dir"] / fname
        assert fa.exists() == fb.exists()
        if fa.exists():
            assert fa.read_bytes() == fb.read_bytes(), fname
    for key in ("train_loss", "valid_loss", "valid_sharpe", "test_sharpe"):
        np.testing.assert_array_equal(off["history"][key],
                                      on["history"][key])


def test_diag_history_fields_and_stride(trained_runs):
    h = np.load(trained_runs["on"]["dir"] / "history.npz",
                allow_pickle=True)
    assert "diag_moment_violations" in h.files
    assert "diag_weight_hhi" in h.files
    mv = np.asarray(h["diag_moment_violations"])
    assert mv.ndim == 2 and mv.shape[1] == 8  # [epochs, K]
    # the explicit stride sentinel marks exactly the computed epochs
    computed = np.nonzero(np.asarray(h["diag_computed"]))[0]
    # stride-2 over two 3-epoch sdf phases: per-phase epochs 0 and 2
    # compute → history rows 0, 2 (phase 1) and 3, 5 (phase 3)
    assert list(computed) == [0, 2, 3, 5]
    assert np.isfinite(mv[computed]).all() and (mv[computed] > 0).all()
    # off-stride epochs are zeros, never NaN
    assert np.isfinite(mv).all()
    # the diagnostics-off run has NO diag fields
    h_off = np.load(trained_runs["off"]["dir"] / "history.npz",
                    allow_pickle=True)
    assert not [k for k in h_off.files if k.startswith("diag_")]


def test_health_json_written_verified_and_read(trained_runs, tmp_path):
    for name in ("off", "on"):
        d = trained_runs[name]["dir"]
        assert (d / "health.json").exists()
        assert (d / "health.json.sha256").exists()  # verified artifact
        doc = read_health(d)
        assert doc is not None and doc["finite"] is True
        per = doc["diagnostics"]["moment_violations"]
        assert len(per) == 8 and all(np.isfinite(v) for v in per)
        assert doc["guard_trips"] == 0
        assert HealthThresholds().classify(doc["diagnostics"]) == []
    # the diag run carries its last in-training readings
    doc_on = read_health(trained_runs["on"]["dir"])
    assert doc_on["diag_stride"] == 2
    assert "diag_moment_violation_max" in doc_on.get("history_last", {})
    # an old / empty run dir reads as None, never raises
    assert read_health(tmp_path) is None
    assert read_health(REF_RUNS / "small120x500") is None


# --------------------------------------------------------------------------
# drift: reference profiles + PSI/KS scoring
# --------------------------------------------------------------------------


def test_drift_profile_roundtrip_and_scoring(tmp_path, panel):
    profile = reference_profile(panel, source="unit")
    write_profile(tmp_path, profile)
    assert (tmp_path / "reference_profile.json.sha256").exists()
    back = read_profile(tmp_path)
    assert back["n_periods"] == T and len(back["individual"]) == F
    assert len(back["macro"]) == M

    # an identically-distributed panel scores stable...
    same = _panel(seed=99)
    assert drift_report(back, same)["max_psi"] < 0.25
    # ...a shifted/rescaled one scores drifted
    shifted = _panel(seed=99, scale=3.0, shift=2.0)
    assert drift_report(back, shifted)["max_psi"] > 0.25
    # per-request scoring: same API, one month's cross-section
    rng = np.random.default_rng(5)
    assert score_request(back, rng.standard_normal((N, F)))["max_psi"] < 0.25
    assert score_request(
        back, rng.standard_normal((N, F)) * 4 + 3)["max_psi"] > 0.25
    # series with too few samples score None (PSI noise, not drift):
    # the macro series of a 4-month panel drop out of the aggregates
    tiny = _panel(seed=4, t=4)
    rep = drift_report(back, tiny)
    assert all(rep["per_series"][f"macro{j}"]["psi"] is None
               for j in range(M))
    # constant reference series degrade, never raise
    entry = series_profile(np.ones(100))
    assert psi(entry, np.ones(64)) is not None
    assert psi(entry, np.zeros(64)) > psi(entry, np.ones(64))
    # unusable profile path reads as None
    assert read_profile(tmp_path / "nowhere") is None


# --------------------------------------------------------------------------
# promotion gate: moment_violation + data_drift end to end
# --------------------------------------------------------------------------


def test_gate_health_end_to_end(tmp_path, trained_runs, hcfg, panel):
    """Acceptance: a healthy quick-train candidate passes with finite
    per-moment violations recorded in health.json; a NaN-weights
    candidate and a drifted-panel candidate are rejected with reasons
    moment_violation / data_drift; both are counted in the report CLI's
    promotion section."""
    ctl = tmp_path / "ctl"
    run_dir = tmp_path / "events_run"
    events = EventLog(run_dir)
    valid = trained_runs["valid"]

    # the healthy candidate IS a completed training run dir, with its
    # finite per-moment violations already recorded in health.json
    candidate = trained_runs["on"]["dir"]
    health = read_health(candidate)
    assert health["finite"] and all(
        np.isfinite(v) for v in health["diagnostics"]["moment_violations"])
    write_profile(candidate, reference_profile(panel, source="train"))
    head = promote(ctl, [str(candidate)], valid_batch=valid,
                   source="healthy", moment_tolerance=1.0,
                   drift_threshold=0.25, events=events)
    assert head["moment_violation_max"] is not None
    assert head["moment_violation_max"] < 1.0
    assert head["drift_max_psi"] is not None
    assert head["drift_max_psi"] < 0.25

    # NaN-weights candidate → moment_violation (the health gate sees the
    # broken moments BEFORE the finite-params check attributes it)
    vnan = [_write_member(tmp_path / "nan" / f"m{s}", hcfg, s, nan=True)
            for s in (1, 2)]
    with pytest.raises(GateRejection) as e:
        promote(ctl, vnan, valid_batch=valid, source="nan",
                moment_tolerance=1.0, events=events)
    assert e.value.reason == "moment_violation"
    # without the opt-in knob the legacy reason is unchanged
    with pytest.raises(GateRejection) as e:
        promote(ctl, vnan, valid_batch=valid, source="nan2", events=events)
    assert e.value.reason == "nonfinite_params"

    # drifted-panel candidate: its reference profile (the data it trained
    # on) diverges from the panel it would serve → data_drift
    drift_prof = reference_profile(
        _panel(seed=7, scale=5.0, shift=3.0), source="drifted")
    vdrift = [_write_member(tmp_path / "drift" / f"m{s}", hcfg, s + 50,
                            profile=drift_prof) for s in (1, 2)]
    with pytest.raises(GateRejection) as e:
        promote(ctl, vdrift, valid_batch=valid, source="drift",
                sharpe_tolerance=None, moment_tolerance=1.0,
                drift_threshold=0.25, events=events)
    assert e.value.reason == "data_drift"

    # the incumbent never moved
    assert read_pointer(ctl)["source"] == "healthy"
    events.close()

    # both rejection reasons are bucketed in the report CLI's promotion
    # section, next to the legacy ones
    summary = summarize_run(load_run(run_dir))
    rejections = summary["promotion"]["rejections_by_reason"]
    assert rejections["moment_violation"] == 1
    assert rejections["data_drift"] == 1
    assert rejections["nonfinite_params"] == 1
    text = format_summary(summary)
    assert "moment_violation:1" in text.replace(" ", "") or \
        "moment_violation" in text


def test_nan_loss_run_trips_health_counters_and_completes(
        tmp_path, synthetic_dir, splits):
    """The fault-matrix satellite: a nan_loss-injected supervised run
    trips the health counters (guard/trip events + health.json
    guard_trips) while the divergence guard's rollback path still
    completes the run — and the completed (recovered) run dir then
    PASSES the health-gated promotion."""
    run_dir = tmp_path / "nanrun"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DLAP_FAULT_PLAN=json.dumps([{
                   "site": "trainer/epoch_loop", "action": "nan_loss"}]),
               DLAP_FAULT_STATE=str(tmp_path / "fault_state.json"),
               DLAP_FAULT_EVENTS=str(tmp_path / "fault_events.jsonl"))
    proc = subprocess.run(
        [sys.executable, "-m", f"{PKG}.train",
         "--data_dir", str(synthetic_dir), "--save_dir", str(run_dir),
         "--epochs_unc", "2", "--epochs_moment", "1", "--epochs", "2",
         "--ignore_epoch", "0", "--hidden_dim", "8", "--rnn_dim", "4",
         "--dropout", "0.0", "--diag_stride", "1", "--no_pipeline"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=540)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert (tmp_path / "fault_events.jsonl").exists()

    # the guard tripped, rolled back, and the run completed with a
    # HEALTHY final model — health.json carries the trip as evidence
    health = read_health(run_dir)
    assert health is not None
    assert health["guard_trips"] >= 1
    assert health["finite"] is True
    rows = [json.loads(line) for line in
            (run_dir / "events.jsonl").read_text().splitlines()]
    assert any(r.get("name") == "guard/trip" for r in rows)
    assert any(r.get("name") == "health/written" for r in rows)

    # the recovered run passes the health-gated promotion (its params
    # are finite and its moments hold — the guard did its job)
    valid = splits[1].full_batch()
    ctl = tmp_path / "ctl"
    head = promote(ctl, [str(run_dir)], valid_batch=valid,
                   source="recovered", moment_tolerance=1.0)
    assert head["moment_violation_max"] < 1.0


# --------------------------------------------------------------------------
# serving: dlap_model_* gauges, drift alerts, reload canary
# --------------------------------------------------------------------------


def test_serving_quality_drift_and_canary(tmp_path, hcfg, panel):
    v1 = [_write_member(tmp_path / "v1" / f"m{s}", hcfg, s)
          for s in (1, 2)]
    v2 = [_write_member(tmp_path / "v2" / f"m{s}", hcfg, s + 10)
          for s in (1, 2)]
    vnan = [_write_member(tmp_path / "nan" / f"m{s}", hcfg, s + 20,
                          nan=True) for s in (1, 2)]
    run_dir = tmp_path / "serve_run"
    events = EventLog(run_dir)
    engine = InferenceEngine(v1, macro_history=panel["macro"],
                             stock_buckets=(N,), batch_buckets=(1,),
                             events=events)
    service = ServingService(
        engine, run_dir=str(run_dir), events=events,
        reference_profile=reference_profile(panel),
        drift_every=1, drift_psi_threshold=0.25)
    try:
        for t in range(3):
            st, body = service.handle("POST", "/v1/sdf", {
                "individual": panel["individual"][t].tolist(),
                "returns": panel["returns"][t].tolist(), "month": t})
            assert st == 200, body

        # generation-quality gauges describe what was served
        quality = engine.generation_quality()
        assert quality["outputs"] == 3
        assert quality["finite_fraction"] == 1.0
        assert abs(quality["weight_norm_mean"] - 1.0) < 1e-4
        assert quality["sdf_mean"] is not None
        prom = service.metrics_prom()
        for gauge in ("dlap_model_generation", "dlap_model_finite_fraction",
                      "dlap_model_weight_norm", "dlap_model_sdf_mean",
                      "dlap_model_drift_alerts_total",
                      "dlap_model_drift_scored_total"):
            assert gauge in prom, gauge
        assert service.metrics()["model_health"]["drift"]["enabled"]

        # an in-distribution request does not alert; a drifted one does
        alerts0 = service.drift_alerts
        st, _ = service.handle("POST", "/v1/weights", {
            "individual": (panel["individual"][0] * 8 + 5).tolist(),
            "month": 0})
        assert st == 200
        assert service.drift_alerts > alerts0

        # hot-swap: the canary ring replays across the swap and records
        # the divergence row
        st, body = service.handle("POST", "/v1/reload",
                                  {"checkpoint_dirs": v2})
        assert st == 200 and body["swapped"] is True
        assert body["canary"]["replayed"] > 0
        assert body["canary"]["max_weight_delta"] > 0
        assert body["canary"]["finite"] is True
        # the swap reset the generation-quality window, and the canary
        # replays ride observe=False — the new generation's gauges
        # describe LIVE traffic only (none yet)
        assert engine.generation_quality()["outputs"] == 0
        fp = engine.params_fingerprint

        # a generation whose canary replays non-finite is REVERTED + 5xx
        st, body = service.handle("POST", "/v1/reload",
                                  {"checkpoint_dirs": vnan})
        assert st == 500
        assert "canary" in body["error"]
        assert engine.params_fingerprint == fp  # still serving v2

        # the revert is a true IN-MEMORY restore (serve/restore, not a
        # disk re-read): an in-place rewrite of the SAME member dirs with
        # bad bytes — the rolling-refit shape, where the old bytes exist
        # nowhere on disk — also reverts and keeps serving finite outputs
        save_params(Path(v2[0]) / "best_model_sharpe.msgpack",
                    jax.tree.map(lambda x: x * np.nan,
                                 GAN(hcfg).init(jax.random.key(11))))
        st, body = service.handle("POST", "/v1/reload",
                                  {"checkpoint_dirs": v2})
        assert st == 500
        assert engine.params_fingerprint == fp
        res = engine.infer_one(InferenceRequest(
            individual=panel["individual"][0], month=0))
        assert np.isfinite(res.weights).all()
    finally:
        service.close()
        events.close()

    rows = [json.loads(line) for line in
            (run_dir / "events.jsonl").read_text().splitlines()]
    canary = [r for r in rows if r.get("name") == "serve/canary"]
    assert len(canary) == 3  # one per swap (incl. the two reverted ones)
    assert any(r.get("finite") is False for r in canary)
    # each revert left a serve/restore row, NOT a phantom swapped reload
    assert sum(1 for r in rows if r.get("name") == "serve/restore") == 2
    assert any(r.get("name") == "model/drift_alert" for r in rows)


# --------------------------------------------------------------------------
# report: health section, ref_runs byte-stability, parity column
# --------------------------------------------------------------------------


def test_report_health_section_on_new_run(trained_runs):
    summary = summarize_run(load_run(trained_runs["on"]["dir"]))
    mh = summary["model_health"]
    assert mh["finite"] is True
    assert mh["moment_violation_max"] is not None
    assert len(mh["moment_violations"]) == 8
    text = format_summary(summary)
    assert "model health:" in text
    assert "moment violations" in text
    assert "(no health data)" not in text


def test_report_old_run_dirs_render_placeholder_byte_stably():
    """The satellite bar: OLD (pre-health-plane) run dirs — the
    checked-in ref_runs — summarize without KeyError, render the
    "(no health data)" placeholder, and are byte-stable across
    invocations."""
    for name in ("small120x500", "mid2000"):
        d = REF_RUNS / name
        first = format_summary(summarize_run(load_run(d)))
        second = format_summary(summarize_run(load_run(d)))
        assert first == second  # byte-stable
        assert "model health: (no health data)" in first
        summary = summarize_run(load_run(d))
        assert "model_health" not in summary  # JSON section stays absent


def test_parity_gains_moment_violation_column(trained_runs, tmp_path):
    summary = summarize_run(load_run(trained_runs["on"]["dir"]))
    summary["sharpe"] = {"valid": 0.5, "test": 0.4}
    run_mv = summary["model_health"]["moment_violation_max"]

    # baseline WITH a recorded moment reference → gated comparison
    base = tmp_path / "PARITY_T.json"
    base.write_text(json.dumps({"reference": {
        "sharpe": {"valid": 0.5, "test": 0.4},
        "moment_violation_max": run_mv}}))
    par = compare_parity(summary, base)
    assert par["moment_violation"]["within_bar"] is True
    assert par["moment_violation"]["abs_delta"] == 0.0

    # legacy baseline without one → informational column, never an error
    base2 = tmp_path / "PARITY_OLD.json"
    base2.write_text(json.dumps({"reference": {
        "sharpe": {"valid": 0.5, "test": 0.4}}}))
    par2 = compare_parity(summary, base2)
    assert par2["moment_violation"]["within_bar"] is None
    assert par2["moment_violation"]["run"] == run_mv
    summary["parity"] = par2
    assert "moment violation:" in format_summary(summary)

    # a run with no health data renders the explicit absence marker
    old = summarize_run(load_run(REF_RUNS / "small120x500"))
    old["sharpe"] = {"valid": 0.5, "test": 0.4}
    par3 = compare_parity(old, base2)
    assert par3["moment_violation"] is None
    old["parity"] = par3
    assert "(no moment-condition data)" in format_summary(old)


# --------------------------------------------------------------------------
# plots: new panels render from diag fields, skip gracefully without
# --------------------------------------------------------------------------


def test_plots_health_panels_render_and_skip(trained_runs, tmp_path):
    pytest.importorskip("matplotlib")
    from deeplearninginassetpricing_paperreplication_tpu.plots import (
        plot_moment_violations,
        plot_weight_concentration,
    )

    out1 = tmp_path / "mv.png"
    assert plot_moment_violations(
        str(trained_runs["on"]["dir"]), str(out1)) is not None
    assert out1.exists() and out1.stat().st_size > 0
    out2 = tmp_path / "wc.png"
    assert plot_weight_concentration(
        str(trained_runs["on"]["dir"]), str(out2)) is not None
    assert out2.exists()
    # pre-diagnostics run dirs (the diag-off twin AND the checked-in
    # torch-era ref_runs) skip gracefully: None returned, nothing written
    for old in (trained_runs["off"]["dir"], REF_RUNS / "small120x500"):
        skip = tmp_path / "skip.png"
        assert plot_moment_violations(str(old), str(skip)) is None
        assert plot_weight_concentration(str(old), str(skip)) is None
        assert not skip.exists()


# --------------------------------------------------------------------------
# bench artifact + budgets + lint gates
# --------------------------------------------------------------------------


def test_bench_health_artifact_and_budgets():
    data = json.loads((REPO / "BENCH_HEALTH.json").read_text())
    assert data["params_bit_identical"] == 1
    assert data["throughput_ratio_on_off"] >= 0.95
    assert data["diag_stride"] >= 1
    assert "diag_moment_violations" in data["diag_history_fields"]

    budgets = json.loads((REPO / "budgets.json").read_text())
    names = {b["name"] for b in budgets["budgets"]}
    assert {"health_diag_overhead_ratio",
            "health_diag_params_bit_identical"} <= names


def _ast_unused_imports(path: Path):
    """F401-lite: top-level imports never referenced elsewhere."""
    import ast

    tree = ast.parse(path.read_text())
    imported = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                imported[(a.asname or a.name).split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name != "*":
                    imported[a.asname or a.name] = a.name
    used = {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
    used |= {n.attr for n in ast.walk(tree) if isinstance(n, ast.Attribute)}
    source = path.read_text()
    return [name for name in imported
            if name not in used and f"\"{name}\"" not in source
            and f"'{name}'" not in source]


def test_health_modules_lint_clean():
    targets = [
        REPO / PKG / "ops" / "diagnostics.py",
        REPO / PKG / "observability" / "modelhealth.py",
        REPO / PKG / "observability" / "drift.py",
        REPO / PKG / "training" / "trainer.py",
        REPO / PKG / "plots.py",
    ]
    try:
        import ruff  # noqa: F401

        proc = subprocess.run(
            [sys.executable, "-m", "ruff", "check",
             *[str(t) for t in targets]],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, proc.stdout + proc.stderr
    except ImportError:
        problems = {t.name: _ast_unused_imports(t) for t in targets}
        problems = {k: v for k, v in problems.items() if v}
        assert not problems, f"unused imports: {problems}"
