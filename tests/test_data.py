"""Panel data core: mask semantics, normalization, subsampling, padding."""

import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.data.panel import (
    MISSING_VALUE,
    PanelDataset,
    load_panel,
    load_splits,
)


def _write_npz(tmp_path, data, macro=None):
    char_path = tmp_path / "char.npz"
    np.savez(
        char_path,
        data=data.astype(np.float32),
        date=np.arange(data.shape[0]),
        variable=np.array(["RET"] + [f"c{i}" for i in range(data.shape[2] - 1)]),
    )
    macro_path = None
    if macro is not None:
        macro_path = tmp_path / "macro.npz"
        np.savez(macro_path, data=macro.astype(np.float32), date=np.arange(macro.shape[0]))
    return char_path, macro_path


def test_mask_sentinel_semantics(tmp_path):
    # data[:,:,0] = returns; sentinel on return OR any feature invalidates
    T, N, F = 3, 4, 2
    data = np.ones((T, N, F + 1), dtype=np.float32) * 0.1
    data[0, 0, 0] = MISSING_VALUE          # missing return
    data[1, 1, 2] = MISSING_VALUE          # missing feature
    data[2, 2, 0] = np.nan                 # NaN return
    char_path, _ = _write_npz(tmp_path, data)
    ds = load_panel(char_path)
    assert not ds.mask[0, 0] and not ds.mask[1, 1] and not ds.mask[2, 2]
    assert ds.mask.sum() == T * N - 3
    # masked entries zero-filled
    assert ds.returns[0, 0] == 0.0
    assert np.all(ds.individual[1, 1] == 0.0)
    # threshold is sentinel + 1: a value of -98.0 is VALID (reference quirk)
    data2 = np.ones((1, 1, 2), dtype=np.float32)
    data2[0, 0, 0] = -98.0
    sub = tmp_path / "threshold"
    sub.mkdir()
    char_path2, _ = _write_npz(sub, data2)
    ds2 = load_panel(char_path2)
    assert ds2.mask[0, 0]


def test_macro_normalization_train_stats(tmp_path):
    T, N = 5, 3
    data = np.full((T, N, 3), 0.5, dtype=np.float32)
    macro = np.arange(T * 2, dtype=np.float32).reshape(T, 2) * 10
    char_path, macro_path = _write_npz(tmp_path, data, macro)
    train = load_panel(char_path, macro_path)
    # z-scored with own stats
    np.testing.assert_allclose(train.macro.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(
        train.macro.std(axis=0), macro.std(axis=0) / (macro.std(axis=0) + 1e-8), rtol=1e-4
    )
    # valid reuses train stats → NOT zero-mean under its own distribution
    valid = load_panel(
        char_path, macro_path, mean_macro=train.mean_macro + 5.0, std_macro=train.std_macro
    )
    assert abs(valid.macro.mean()) > 0.01


def test_load_splits_share_stats(splits):
    train, valid, test = splits
    np.testing.assert_array_equal(valid.mean_macro, train.mean_macro)
    np.testing.assert_array_equal(test.std_macro, train.std_macro)
    assert train.T == 24 and valid.T == 8 and test.T == 12
    assert train.N == 64 and train.individual_feature_dim == 10
    assert train.macro_feature_dim == 6
    # masked entries must be exactly zero
    assert np.all(train.returns[~train.mask] == 0.0)
    assert np.all(train.individual[~train.mask] == 0.0)


def test_subsample_picks_most_valid_stocks(splits):
    train = splits[0]
    sub = train.subsample(n_periods=10, n_stocks=16)
    assert sub.T == 10 and sub.N == 16
    # chosen stocks have the highest full-history valid counts
    counts = train.mask.sum(axis=0)
    chosen_min = np.sort(counts)[-16]
    sub_counts_full = sub.mask.sum(axis=0)
    assert sub.macro.shape == (10, 6)
    assert counts.max() >= sub_counts_full.max()
    assert np.sort(counts)[-16:].min() == chosen_min


def test_pad_stocks_inert(splits):
    train = splits[0]
    padded = train.pad_stocks(48)
    assert padded.N % 48 == 0
    assert padded.mask[:, train.N :].sum() == 0
    assert np.all(padded.returns[:, train.N :] == 0.0)
    np.testing.assert_array_equal(padded.returns[:, : train.N], train.returns)
    # already-aligned panel is returned unchanged
    assert train.pad_stocks(1) is train


def test_full_batch_dtypes(splits):
    batch = splits[0].full_batch()
    assert batch["mask"].dtype == np.float32
    assert batch["returns"].dtype == np.float32
    assert batch["individual"].dtype == np.float32
    assert batch["macro"].dtype == np.float32
    assert batch["individual"].shape == (24, 64, 10)


def test_valid_per_period(splits):
    train = splits[0]
    np.testing.assert_array_equal(
        train.valid_per_period(), train.mask.sum(axis=1).astype(np.float32)
    )


def test_native_codec_matches_numpy_decode():
    """data/_native codec: bit-identical to the NumPy mask/zero-fill path."""
    import numpy as np
    import pytest
    from deeplearninginassetpricing_paperreplication_tpu.data import native

    if not native.native_available():
        pytest.skip("no C++ toolchain available")
    rng = np.random.default_rng(3)
    T, N, F = 7, 23, 5
    data = rng.standard_normal((T, N, 1 + F)).astype(np.float32)
    data[rng.random((T, N)) < 0.4, 0] = -99.99
    feat = data[:, :, 1:]
    feat[rng.random((T, N, F)) < 0.1] = -99.99
    data[0, 1, 0] = np.nan
    data[2, 3, 2] = np.nan  # NaN feature must also invalidate
    out = native.decode_panel(data, -98.99)
    assert out is not None
    ret, ind = data[:, :, 0], data[:, :, 1:]
    mask = (ret > -98.99) & ~np.isnan(ret) & np.all(ind > -98.99, axis=2)
    np.testing.assert_array_equal(out[2], mask)
    np.testing.assert_array_equal(out[0], np.where(mask, ret, 0).astype(np.float32))
    np.testing.assert_array_equal(
        out[1], np.where(mask[:, :, None], ind, 0).astype(np.float32)
    )


# -- mask-aware host→device transfer ------------------------------------------


def test_packed_transfer_bit_exact(synthetic_dir):
    """device_put_batch(packed=True) must land the same dense arrays on
    device as a plain transfer — packing relies on the loader's zero-fill
    guarantee and rebuilds the mask from the indices."""
    import jax.numpy as jnp

    from deeplearninginassetpricing_paperreplication_tpu.data.transfer import (
        device_put_batch,
        sync_batch,
    )

    ds, _, _ = load_splits(synthetic_dir)
    batch = ds.full_batch()
    batch["n_assets"] = np.float32(ds.N)  # extra key passes through
    dense = device_put_batch(batch, packed=False)
    packed = device_put_batch(batch, packed=True)
    sync_batch(packed)
    assert set(dense) == set(packed)
    for k in dense:
        np.testing.assert_array_equal(np.asarray(dense[k]), np.asarray(packed[k]))
    # synthetic coverage is well under the auto threshold → auto packs;
    # result must still be exact
    auto = device_put_batch(batch)
    for k in dense:
        np.testing.assert_array_equal(np.asarray(dense[k]), np.asarray(auto[k]))


def test_packed_transfer_full_coverage_roundtrip():
    """A fully-valid panel (coverage 1.0) takes the dense path under auto but
    must stay exact when packing is forced."""
    from deeplearninginassetpricing_paperreplication_tpu.data.transfer import (
        device_put_batch,
    )

    rng = np.random.default_rng(0)
    T, N, F = 5, 7, 3
    batch = {
        "individual": rng.standard_normal((T, N, F)).astype(np.float32),
        "returns": rng.standard_normal((T, N)).astype(np.float32),
        "mask": np.ones((T, N), np.float32),
        "macro": rng.standard_normal((T, 2)).astype(np.float32),
    }
    forced = device_put_batch(batch, packed=True)
    for k in batch:
        np.testing.assert_array_equal(np.asarray(forced[k]), batch[k])


def test_bf16_wire_transfer_rounds_identically(synthetic_dir):
    """bf16_wire: packed and dense paths must land IDENTICAL f32 arrays whose
    values are exactly the bf16 rounding of the loader's panel — so the
    compute route's later f32→bf16 cast sees the same bits as an f32 wire."""
    import jax.numpy as jnp

    from deeplearninginassetpricing_paperreplication_tpu.data.transfer import (
        device_put_batch,
        sync_batch,
    )

    ds, _, _ = load_splits(synthetic_dir)
    batch = ds.full_batch()
    dense = device_put_batch(batch, packed=False, bf16_wire=True)
    packed = device_put_batch(batch, packed=True, bf16_wire=True)
    sync_batch(packed)
    expected = (
        np.asarray(batch["individual"]).astype(jnp.bfloat16).astype(np.float32)
    )
    assert np.asarray(dense["individual"]).dtype == np.float32
    np.testing.assert_array_equal(np.asarray(dense["individual"]), expected)
    np.testing.assert_array_equal(np.asarray(packed["individual"]), expected)
    # returns / mask stay f32-exact on the bf16 wire
    np.testing.assert_array_equal(np.asarray(packed["returns"]), batch["returns"])
    np.testing.assert_array_equal(np.asarray(packed["mask"]), batch["mask"])


def test_transfer_rejects_non_f32_panel():
    """The loader contract is a float32 panel; packed and dense paths would
    coerce a float64 panel differently, so both must refuse it loudly."""
    import pytest as _pytest

    from deeplearninginassetpricing_paperreplication_tpu.data.transfer import (
        device_put_batch,
    )

    batch = {
        "individual": np.zeros((2, 3, 4), np.float64),
        "returns": np.zeros((2, 3), np.float32),
        "mask": np.ones((2, 3), np.float32),
    }
    with _pytest.raises(TypeError, match="float32 panel"):
        device_put_batch(batch, packed=True)
    with _pytest.raises(TypeError, match="float32 panel"):
        device_put_batch(batch, packed=False)


def test_schema_validator_passes_on_synthetic(synthetic_dir):
    """The synthetic generator emits the exact schema the validator checks
    (shapes, YYYYMM dates, -99.99 sentinel) — a clean panel must PASS."""
    from deeplearninginassetpricing_paperreplication_tpu.data.download import (
        validate_schema,
    )

    ok, report = validate_schema(synthetic_dir, verbose=False)
    assert ok, report
    assert report["Char_train.npz"]["shape"][2] == 11  # 1 + F
    assert 0.0 < report["Char_train.npz"]["missing_frac"] < 1.0


def test_schema_validator_catches_corruption(synthetic_dir, tmp_path):
    """A user pointing --check at real downloaded bytes must get loud,
    specific failures: NaN in the panel (sentinel convention violated),
    char/macro date disagreement, and a truncated macro split."""
    import shutil

    from deeplearninginassetpricing_paperreplication_tpu.data.download import (
        validate_schema,
    )

    bad = tmp_path / "bad_data"
    shutil.copytree(synthetic_dir, bad)

    with np.load(bad / "char" / "Char_train.npz") as z:
        char = {k: z[k].copy() for k in z.files}
    char["data"][0, 0, 1] = np.nan
    np.savez(bad / "char" / "Char_train.npz", **char)

    with np.load(bad / "macro" / "macro_valid.npz") as z:
        macro = {k: z[k].copy() for k in z.files}
    macro["data"] = macro["data"][:-2]
    macro["date"] = macro["date"][:-2]
    np.savez(bad / "macro" / "macro_valid.npz", **macro)

    ok, report = validate_schema(bad, verbose=False)
    assert not ok
    assert any("sentinel" in e for e in report["Char_train.npz"]["errors"])
    assert any("char T=" in e for e in report["cross_split"]["errors"])
