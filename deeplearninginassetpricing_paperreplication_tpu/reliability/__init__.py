"""Reliability layer: supervised execution, deterministic fault injection,
verified generational checkpoints, and the trainer divergence guard.

  * :mod:`.faults`     — plan-driven fault injector (``DLAP_FAULT_PLAN``)
    behind named injection sites threaded through the trainer, checkpoint
    IO, the startup pipeline, sweep buckets, and the serving engine; zero
    overhead with no plan set;
  * :mod:`.supervisor` — the supervise loop + ``python -m ...supervise``
    CLI: heartbeat watchdog (SIGKILL on hang), restart with backoff and
    automatic ``--resume``, crash-loop policy, ``supervise/*`` telemetry;
  * :mod:`.verified`   — atomic + sha256-verified + generational file IO
    (every checkpoint write goes through it; loads fall back
    generation-by-generation to the last good file);
  * :mod:`.guard`      — the divergence guard's non-finite segment check
    and :class:`~.guard.DivergenceError`;
  * :mod:`.ledger`     — the durable sweep ledger: one verified record per
    completed architecture bucket, keyed by content, plus quarantine
    markers for poison buckets;
  * :mod:`.scheduler`  — the file-locked leased work queue N sweep workers
    claim buckets from (lease expiry → takeover, K failed claims →
    quarantine), and the supervise-a-fleet helper.

:mod:`.supervisor` and :mod:`.scheduler` are intentionally NOT imported
here: the others stay importable without pulling argparse/subprocess
machinery, and ``faults``/``ledger`` remain stdlib-only for by-path
loading by thin parents.
"""

from .faults import (
    ENV_EVENTS,
    ENV_PLAN,
    ENV_STATE,
    FaultInjected,
    FaultInjector,
    FaultPlanError,
    get_injector,
    inject,
    reset_injector,
)
from .guard import DivergenceError, segment_nonfinite
from .ledger import LEDGER_DIRNAME, QUEUE_FILENAME, SweepLedger, bucket_key
from .verified import (
    check_digest,
    clear_generations,
    digest_path,
    generation_candidates,
    generation_path,
    load_verified,
    rotate_generations,
    verified_exists,
    write_verified,
)

__all__ = [
    "ENV_EVENTS",
    "ENV_PLAN",
    "ENV_STATE",
    "LEDGER_DIRNAME",
    "QUEUE_FILENAME",
    "DivergenceError",
    "FaultInjected",
    "FaultInjector",
    "FaultPlanError",
    "SweepLedger",
    "bucket_key",
    "check_digest",
    "clear_generations",
    "digest_path",
    "generation_candidates",
    "generation_path",
    "get_injector",
    "inject",
    "load_verified",
    "reset_injector",
    "rotate_generations",
    "segment_nonfinite",
    "verified_exists",
    "write_verified",
]
