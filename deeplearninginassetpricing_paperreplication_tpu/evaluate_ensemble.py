"""Ensemble evaluation CLI — the reference's ``python -m src.evaluate_ensemble``
(``/root/reference/src/evaluate_ensemble.py``), with the K-model loop replaced
by one vmapped program.

Two modes:
  * ``--checkpoint_dirs d1 d2 ...`` — load trained run directories
    (config.json + best_model_sharpe.msgpack) and evaluate the weight-averaged
    ensemble, matching the reference CLI;
  * ``--train_seeds 42 123 ...`` — train the whole ensemble from scratch as a
    single vmapped 3-phase program, then evaluate (no reference counterpart:
    the reference trains members serially, ~6 h CPU for 9 models).

    python -m deeplearninginassetpricing_paperreplication_tpu.evaluate_ensemble \
        --data_dir data/synthetic_data --checkpoint_dirs ckpt_s42 ckpt_s123 ...
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

# cache-aware drop-in for data.panel.load_splits through the CHUNKED panel
# store (data/diskcache.py store_chunked): evaluation re-loads the same
# panel the training run already decoded, so re-runs mmap the per-shard
# decode instead of re-paying the npz decode, and a torn shard re-decodes
# alone — bit-identical to load_splits either way
from .data.pipeline import load_splits_chunked
from .observability import (
    EventLog,
    Heartbeat,
    RunLogger,
    set_run_logger,
    write_manifest,
)
from .parallel.ensemble import ensemble_metrics, train_ensemble
from .training.checkpoint import load_checkpoint_dir
from .utils.config import GANConfig, TrainConfig

PAPER_TEST_SHARPE = 0.75  # Chen-Pelger-Zhu Table 1, GAN test SR (monthly)


# GANConfig fields that determine parameter SHAPES (a mismatch would
# otherwise surface as an opaque tree-map shape error deep inside jnp.stack)
# or change the DETERMINISTIC eval-mode forward (normalize_w toggles the
# masked zero-mean inside SDFNet) — either way members must agree.
_ARCHITECTURE_FIELDS = (
    "macro_feature_dim", "individual_feature_dim", "hidden_dim", "use_rnn",
    "num_units_rnn", "hidden_dim_moment", "num_condition_moment",
    "normalize_w",
)


def validate_stackable_configs(checkpoint_dirs: List[str]) -> "GANConfig":
    """Check that every run dir's ``config.json`` shares one architecture.

    Raises a field-by-field ``ValueError`` (naming the offending directory)
    on any mismatch that affects parameter shapes or the deterministic
    eval-mode forward, BEFORE a single params file is read — a mixed
    ensemble fails fast and legibly instead of deep inside a tree-map
    shape error. Remaining differences (dropout, loss shaping) stack fine
    and are eval-inert (dropout is off and losses are not evaluated on
    the serve/ensemble path), so they only warn. Returns the first config.
    """
    import warnings

    cfgs = [GANConfig.load(Path(d) / "config.json") for d in checkpoint_dirs]
    cfg0 = cfgs[0]
    for d, cfg in zip(checkpoint_dirs[1:], cfgs[1:]):
        diffs = [
            f"{f}: {getattr(cfg0, f)!r} (in {checkpoint_dirs[0]}) vs "
            f"{getattr(cfg, f)!r} (in {d})"
            for f in _ARCHITECTURE_FIELDS
            if getattr(cfg, f) != getattr(cfg0, f)
        ]
        if diffs:
            raise ValueError(
                "checkpoint architectures differ — ensemble members must "
                "share parameter shapes and the eval-mode forward to stack "
                "(to ensemble ACROSS architectures, average normalized "
                "weight matrices via "
                "parallel.ensemble.ensemble_metrics_from_weights):\n  "
                + "\n  ".join(diffs)
            )
        if cfg != cfg0:
            other = [
                f.name for f in dataclasses.fields(GANConfig)
                if f.name not in _ARCHITECTURE_FIELDS
                and getattr(cfg, f.name) != getattr(cfg0, f.name)
            ]
            warnings.warn(
                f"checkpoint configs differ in non-architectural fields "
                f"{other} ({checkpoint_dirs[0]} vs {d}); stacking anyway — "
                "these do not affect deterministic evaluation",
                stacklevel=2,
            )
    return cfg0


def stack_checkpoints(
    checkpoint_dirs: List[str],
    which: str = "best_model_sharpe",
    allow_missing: bool = False,
    coverage_out: Optional[Dict] = None,
):
    """Load K run dirs and stack their params along the ensemble axis.

    All checkpoints must share one architecture (the reference implicitly
    assumes this too — it averages [T, N] weight matrices, not params);
    :func:`validate_stackable_configs` enforces it up front.

    `allow_missing` (quorum-ensemble semantics): member run dirs that are
    absent, lack a config, or whose every checkpoint generation is corrupt
    are SKIPPED — with one warning listing each skipped dir and why —
    instead of the first one failing the whole ensemble. Architecture
    MISMATCHES still raise (that is a caller error, not a casualty).
    `coverage_out`, when given, is filled with ``used`` / ``skipped``
    (dir + reason) so callers can enforce a quorum and record the drops.
    """
    skipped: List[Dict[str, str]] = []
    present: List[str] = []
    for d in checkpoint_dirs:
        if allow_missing:
            # a member's config must LOAD, not merely exist: config.json is
            # a plain write (a kill mid-save tears it), and a torn config
            # is exactly the casualty quorum mode exists to survive
            try:
                GANConfig.load(Path(d) / "config.json")
            except Exception as e:  # noqa: BLE001 — absent/torn/invalid
                skipped.append({
                    "dir": str(d),
                    "reason": f"unusable config.json ({type(e).__name__}: "
                              f"{e})" if (Path(d) / "config.json").exists()
                    else "missing config.json",
                })
                continue
        present.append(d)
    if not present:
        raise ValueError(
            "no usable checkpoint dirs: "
            + "; ".join(f"{s['dir']}: {s['reason']}" for s in skipped)
        )
    validate_stackable_configs(present)
    gans, params_list, used = [], [], []
    for d in present:
        try:
            gan, params = load_checkpoint_dir(d, which)
        except (FileNotFoundError, ValueError) as e:
            if not allow_missing:
                raise
            skipped.append({"dir": str(d), "reason": str(e)})
            continue
        gans.append(gan)
        params_list.append(params)
        used.append(str(d))
    if not params_list:
        raise ValueError(
            "no usable checkpoint dirs: "
            + "; ".join(f"{s['dir']}: {s['reason']}" for s in skipped)
        )
    if skipped:
        import warnings

        warnings.warn(
            f"skipping {len(skipped)} of {len(checkpoint_dirs)} ensemble "
            "member dirs:\n  "
            + "\n  ".join(f"{s['dir']}: {s['reason']}" for s in skipped),
            stacklevel=2,
        )
    if coverage_out is not None:
        coverage_out["used"] = used
        coverage_out["skipped"] = skipped
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)
    return gans[0], stacked


def evaluate_ensemble(
    checkpoint_dirs: List[str],
    data_dir: str,
    verbose: bool = True,
    quorum: Optional[int] = None,
) -> Dict[str, float]:
    """Reference-CLI-compatible entry: returns the same summary dict shape
    (train/valid/test ensemble Sharpe + individual Sharpes).

    `quorum`: proceed with ≥ quorum loadable members, skipping absent or
    corrupt run dirs (with a warning listing them) instead of failing the
    evaluation on the first casualty; the summary then carries
    ``used_dirs`` / ``skipped_dirs``. None keeps strict loading."""
    coverage: Dict = {}
    gan, vparams = stack_checkpoints(
        checkpoint_dirs,
        allow_missing=quorum is not None,
        coverage_out=coverage if quorum is not None else None,
    )
    if quorum is not None and len(coverage.get("used", [])) < quorum:
        raise ValueError(
            f"only {len(coverage['used'])} of {len(checkpoint_dirs)} "
            f"ensemble members loadable, quorum is {quorum}; skipped: "
            + "; ".join(f"{s['dir']}: {s['reason']}"
                        for s in coverage["skipped"])
        )
    train_ds, valid_ds, test_ds = load_splits_chunked(data_dir)

    def batch(ds):
        return {k: jnp.asarray(v) for k, v in ds.full_batch().items()}

    results = {}
    for split, ds in (("train", train_ds), ("valid", valid_ds), ("test", test_ds)):
        results[split] = ensemble_metrics(gan, vparams, batch(ds))

    n_members = (len(coverage["used"]) if coverage.get("used")
                 else len(checkpoint_dirs))
    if verbose:
        _print_report(results, n_members)
    out = {
        "train_sharpe": float(results["train"]["ensemble_sharpe"]),
        "valid_sharpe": float(results["valid"]["ensemble_sharpe"]),
        "test_sharpe": float(results["test"]["ensemble_sharpe"]),
        "individual_sharpes": results["test"]["individual_sharpes"].tolist(),
    }
    if quorum is not None:
        out["used_dirs"] = coverage.get("used", [])
        out["skipped_dirs"] = coverage.get("skipped", [])
    return out


def _print_report(results, n_models):
    indiv = results["test"]["individual_sharpes"]
    print("=" * 70)
    print(f"ENSEMBLE EVALUATION ({n_models} models, averaged weights)")
    print("=" * 70)
    print("\nIndividual model test Sharpes (paper convention, negated):")
    for i, s in enumerate(indiv):
        print(f"  Model {i+1}: {s:.4f}")
    print(f"  mean {indiv.mean():.4f}  std {indiv.std():.4f}")
    print("\nEnsemble (averaged weights):")
    for split in ("train", "valid", "test"):
        print(f"  {split:5s} Sharpe: {float(results[split]['ensemble_sharpe']):.4f}")
    test = float(results["test"]["ensemble_sharpe"])
    print("\nRisk-premium metrics (paper Table 1 companions; per-stock OLS betas):")
    for split in ("train", "valid", "test"):
        print(f"  {split:5s} EV: {float(results[split]['explained_variation']):7.4f}"
              f"   XS-R2: {float(results[split]['cross_sectional_r2']):7.4f}")
    print(f"\nPaper GAN test Sharpe: {PAPER_TEST_SHARPE}")
    print(f"Ours / paper: {test / PAPER_TEST_SHARPE:.1%}")
    print("=" * 70)


def main(argv=None):
    from .utils.platform import apply_env_platforms

    apply_env_platforms()
    p = argparse.ArgumentParser(description="Evaluate (or train) a model ensemble")
    p.add_argument("--data_dir", type=str, required=True)
    p.add_argument("--checkpoint_dirs", type=str, nargs="+", default=None)
    p.add_argument("--quorum", type=int, default=None, metavar="Q",
                   help="With --checkpoint_dirs: evaluate with ≥Q loadable "
                        "members, skipping absent/corrupt run dirs (listed "
                        "in a warning) instead of failing on the first one")
    p.add_argument("--train_seeds", type=int, nargs="+", default=None,
                   help="Train the ensemble from scratch, vmapped over seeds")
    p.add_argument("--epochs_unc", type=int, default=256)
    p.add_argument("--epochs_moment", type=int, default=64)
    p.add_argument("--epochs", type=int, default=1024)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--ignore_epoch", type=int, default=64)
    p.add_argument("--member_chunk", type=int, default=None,
                   help="Train at most this many seeds per vmapped program "
                        "(sequential chunks). Rarely needed on TPU: the fused-"
                        "kernel route costs ~0.1 GB HBM per member at the real "
                        "panel shape, so 9 seeds fit one 16 GB chip; the plain-"
                        "XLA route (CPU / pallas off) needs ~2.1 GB per member "
                        "— use 3-5 there")
    p.add_argument("--save_dir", type=str, default=None,
                   help="With --train_seeds: persist each member as a "
                        "checkpoint dir (seed_<s>/config.json + "
                        "best_model_sharpe.msgpack) plus ensemble_report.json")
    args = p.parse_args(argv)

    if (args.checkpoint_dirs is None) == (args.train_seeds is None):
        p.error("pass exactly one of --checkpoint_dirs / --train_seeds")

    if args.checkpoint_dirs:
        evaluate_ensemble(args.checkpoint_dirs, args.data_dir,
                          quorum=args.quorum)
        return

    train_ds, valid_ds, test_ds = load_splits_chunked(args.data_dir)

    def batch(ds):
        return {k: jnp.asarray(v) for k, v in ds.full_batch().items()}

    cfg = GANConfig(
        macro_feature_dim=train_ds.macro_feature_dim,
        individual_feature_dim=train_ds.individual_feature_dim,
    )
    tcfg = TrainConfig(
        num_epochs_unc=args.epochs_unc,
        num_epochs_moment=args.epochs_moment,
        num_epochs=args.epochs,
        lr=args.lr,
        ignore_epoch=args.ignore_epoch,
    )

    # startup manifest + sinks whenever there is an artifact dir to describe
    events = EventLog(args.save_dir) if args.save_dir else EventLog()
    set_run_logger(RunLogger(events=events))
    hb = None
    if args.save_dir:
        hb = Heartbeat(Path(args.save_dir) / "heartbeat.json", events=events)
        hb.beat("setup")
        write_manifest(
            args.save_dir, "evaluate_ensemble", events=events,
            config=cfg, tcfg=tcfg, data_dir=args.data_dir, argv=argv,
            extra={"train_seeds": list(args.train_seeds)},
        )
        hb.beat("train_ensemble")
    with events.span("ensemble/train", n_seeds=len(args.train_seeds)):
        gan, vparams, _history = train_ensemble(
            cfg, batch(train_ds), batch(valid_ds), batch(test_ds),
            seeds=args.train_seeds, tcfg=tcfg, member_chunk=args.member_chunk,
            heartbeat=hb,
        )
    if hb is not None:
        hb.beat("evaluate", memory=True)
    results = {
        split: ensemble_metrics(gan, vparams, batch(ds))
        for split, ds in (("train", train_ds), ("valid", valid_ds), ("test", test_ds))
    }
    _print_report(results, len(args.train_seeds))

    if args.save_dir:
        from .training.checkpoint import save_params

        if hb is not None:
            hb.beat("save")  # a death here is the save path, not evaluate
        save_dir = Path(args.save_dir)
        for si, seed in enumerate(args.train_seeds):
            mdir = save_dir / f"seed_{seed}"
            mdir.mkdir(parents=True, exist_ok=True)
            cfg.save(mdir / "config.json")
            save_params(
                mdir / "best_model_sharpe.msgpack",
                jax.tree.map(lambda x, i=si: x[i], vparams),
            )
        from .reliability.verified import write_verified

        write_verified(save_dir / "ensemble_report.json", json.dumps(
            {
                "seeds": list(args.train_seeds),
                "ensemble_sharpe": {
                    s: float(results[s]["ensemble_sharpe"])
                    for s in ("train", "valid", "test")
                },
                "explained_variation": {
                    s: float(results[s]["explained_variation"])
                    for s in ("train", "valid", "test")
                },
                "cross_sectional_r2": {
                    s: float(results[s]["cross_sectional_r2"])
                    for s in ("train", "valid", "test")
                },
                "individual_test_sharpes":
                    results["test"]["individual_sharpes"].tolist(),
            },
            indent=2,
        ).encode())
        print(f"Saved {len(args.train_seeds)} member checkpoints to {save_dir}")
    if hb is not None:
        hb.beat("done", memory=True)
    events.close()


if __name__ == "__main__":
    main()
