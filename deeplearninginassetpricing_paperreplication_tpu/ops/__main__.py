"""``python -m ….ops`` — the cross-plane ops console entry point.

The console itself lives in :mod:`..observability.statusboard` (stdlib
file reading; byte-deterministic ``status`` / ``timeline``); this shim
only gives it the ``ops`` command name.
"""

import sys

from ..observability.statusboard import main

if __name__ == "__main__":
    sys.exit(main())
