"""Run manifests: make any artifact directory self-describing.

``manifest.json`` is written once at CLI startup and answers, post-hoc,
every "what exactly produced this run dir?" question: config (and its
hash), seed, schedule, library versions, device topology, git sha, and a
content fingerprint of the input data. Everything is best-effort — a
manifest must never be the reason a training run fails, so each probe
degrades to ``None`` rather than raising.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import sys
import time
from pathlib import Path
from typing import Any, Dict, Optional

from .events import EventLog, new_run_id

MANIFEST_SCHEMA_VERSION = 1
_FINGERPRINT_BYTES = 65536  # head+tail window hashed per data file


def _as_dict(obj) -> Optional[Dict[str, Any]]:
    if obj is None:
        return None
    if hasattr(obj, "to_dict"):
        return obj.to_dict()
    if dataclasses.is_dataclass(obj):
        return dataclasses.asdict(obj)
    return dict(obj)


def config_hash(config) -> Optional[str]:
    """sha256 of the canonical (sorted-key) JSON of a config dict/dataclass
    — the stable identity two runs compare to know they trained the same
    model."""
    d = _as_dict(config)
    if d is None:
        return None
    blob = json.dumps(d, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()


def data_fingerprint(data_dir) -> Optional[Dict[str, Any]]:
    """Content fingerprint of a data directory: per-file (relative path,
    size, head/tail window) folded into one sha256. Windowed hashing keeps
    the real-shape panel (~GB of npz) cheap while still catching any
    regeneration, truncation, or swapped split."""
    data_dir = Path(data_dir)
    if not data_dir.exists():
        return None
    h = hashlib.sha256()
    n_files = 0
    total_bytes = 0
    for p in sorted(data_dir.rglob("*")):
        if not p.is_file():
            continue
        size = p.stat().st_size
        h.update(str(p.relative_to(data_dir)).encode())
        h.update(str(size).encode())
        try:
            with open(p, "rb") as f:
                h.update(f.read(_FINGERPRINT_BYTES))
                if size > 2 * _FINGERPRINT_BYTES:
                    f.seek(-_FINGERPRINT_BYTES, 2)
                    h.update(f.read(_FINGERPRINT_BYTES))
        except OSError:
            h.update(b"<unreadable>")
        n_files += 1
        total_bytes += size
    return {
        "root": str(data_dir),
        "n_files": n_files,
        "total_bytes": total_bytes,
        "digest": h.hexdigest(),
    }


def _git_sha() -> Optional[str]:
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=Path(__file__).resolve().parents[2],
            capture_output=True, text=True, timeout=10,
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None


def _versions() -> Dict[str, Optional[str]]:
    vers: Dict[str, Optional[str]] = {
        "python": sys.version.split()[0],
    }
    for mod in ("jax", "jaxlib", "numpy", "flax", "optax"):
        try:
            vers[mod] = __import__(mod).__version__
        except Exception:
            vers[mod] = None
    return vers


def device_topology(mesh=None) -> Dict[str, Any]:
    """Backend + per-device identity (and the mesh layout when one is in
    play) — enough to reconstruct how a run was fanned out across chips."""
    try:
        import jax

        topo: Dict[str, Any] = {
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "devices": [
                {
                    "id": d.id,
                    "platform": d.platform,
                    "device_kind": d.device_kind,
                    "process_index": d.process_index,
                }
                for d in jax.local_devices()
            ],
        }
    except Exception as e:  # report tooling without a backend
        return {"error": repr(e)}
    if mesh is not None:
        topo["mesh"] = {
            "shape": list(mesh.devices.shape),
            "axis_names": list(mesh.axis_names),
        }
    return topo


def build_manifest(
    kind: str,
    run_id: Optional[str] = None,
    config=None,
    tcfg=None,
    seed: Optional[int] = None,
    data_dir=None,
    argv=None,
    mesh=None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Assemble the manifest dict (pure; no filesystem writes)."""
    manifest = {
        "schema": MANIFEST_SCHEMA_VERSION,
        "kind": kind,
        "run_id": run_id or new_run_id(),
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "argv": list(argv) if argv is not None else sys.argv[1:],
        "seed": seed,
        "config": _as_dict(config),
        "config_hash": config_hash(config),
        "train_config": _as_dict(tcfg),
        "versions": _versions(),
        "devices": device_topology(mesh),
        "git_sha": _git_sha(),
        "data": data_fingerprint(data_dir) if data_dir is not None else None,
    }
    if extra:
        manifest.update(extra)
    return manifest


def write_manifest(run_dir, kind: str, events: Optional[EventLog] = None,
                   **kwargs) -> Dict[str, Any]:
    """Build + write ``<run_dir>/manifest.json``. The write is recorded as
    an event when `events` is given. run_id precedence: an explicit
    ``run_id=`` kwarg wins (cross-process shared launch ids), then the
    EventLog's id (so events and manifest cross-reference), then a fresh
    one."""
    run_dir = Path(run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    run_id = kwargs.pop("run_id", None)
    if run_id is None and events is not None:
        run_id = events.run_id
    manifest = build_manifest(kind, run_id=run_id, **kwargs)
    (run_dir / "manifest.json").write_text(json.dumps(manifest, indent=2))
    if events is not None:
        events.emit("manifest", kind, path=str(run_dir / "manifest.json"),
                    config_hash=manifest["config_hash"])
    return manifest


def load_manifest(run_dir) -> Optional[Dict[str, Any]]:
    path = Path(run_dir) / "manifest.json"
    if not path.exists():
        return None
    return json.loads(path.read_text())


def update_manifest(run_dir, **patch: Any) -> Optional[Dict[str, Any]]:
    """Merge `patch` into an existing ``manifest.json`` (atomically).

    The manifest is written at STARTUP, but some provenance only exists at
    the end — quorum-dropped ensemble members, a degraded sweep's coverage.
    Recording those IN the manifest keeps the run dir's one self-description
    authoritative. Best-effort like everything here: no manifest (or an
    unreadable one) returns None rather than raising."""
    import os

    run_dir = Path(run_dir)
    path = run_dir / "manifest.json"
    try:
        manifest = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None
    manifest.update(patch)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(manifest, indent=2))
    os.replace(tmp, path)
    return manifest
