"""Trainer divergence guard: detect non-finite segments, abort before they
reach a checkpoint.

The on-device phase scans happily carry NaN params forward — a blown-up loss
at epoch 300 silently poisons every later epoch, the best trackers (NaN
comparisons are False, so the *pre-divergence* best survives, masking the
blowup), and ultimately the written checkpoints. The guard closes that hole
at the trainer's natural sync points: after each segment dispatch it checks
the segment's per-epoch loss/grad series (tiny [k]-float device fetches) for
non-finite values, and on a trip the trainer rolls the carry back to the
pre-segment snapshot and retries; after ``guard_max_trips`` CONSECUTIVE
trips it raises :class:`DivergenceError` instead of writing NaN checkpoints.

Numbers are unchanged: the check reads series the scan already produces, so
a guarded run's outputs are bit-identical to an unguarded one.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

# the per-epoch series the check reads, whichever of them a phase produces
GUARD_KEYS = ("train_loss", "train_loss_cond", "grad_norm")


class DivergenceError(RuntimeError):
    """Non-finite loss/grads persisted across the guard's retry budget."""


def segment_nonfinite(hist: Dict[str, Any]) -> bool:
    """True when any guarded per-epoch series in one segment's stacked
    history contains a non-finite value (host-side check; the arrays are
    [segment_len] floats, so the fetch is a few hundred bytes)."""
    for k in GUARD_KEYS:
        if k in hist and not np.all(np.isfinite(np.asarray(hist[k]))):
            return True
    return False
