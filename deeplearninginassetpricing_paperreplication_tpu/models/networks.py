"""SDF (generator) and Moment (discriminator) networks as Flax modules.

Architecture replicates the reference (``/root/reference/src/model.py``):

  * SDFNet (model.py:164-281): optional TorchLSTM over macro → tile per stock
    → concat [individual, macro_state] → FFN [64, 64] (ReLU + Dropout 0.05)
    → Dense(1) → mask → cross-sectional zero-mean per period.
  * MomentNet (model.py:87-161): raw macro tiled + individual → (optional FFN,
    default none) → Dense(num_moments) → tanh → [K, T, N].
  * SimpleSDF (model.py:620-694): non-adversarial baseline, FFN-only over
    [macro, individual], zero-mean weights.

TPU-first notes: Dense layers operate directly on the [T, N, D] panel (no
host-side flatten/reshape); the [T·N, D] × [D, H] matmuls are what lands on
the MXU. Initialization matches torch.nn.Linear (kaiming-uniform a=√5 ⇒
U(-1/√fan_in, 1/√fan_in) for both kernel and bias) so training dynamics and
imported reference checkpoints line up.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.pallas_ffn import fused_sdf_ffn, fused_sdf_ffn_sharded
from ..utils.config import ExecutionConfig, GANConfig
from .recurrent import TorchLSTM

_DEFAULT_EXEC = ExecutionConfig()


def _torch_kernel_init(key, shape, dtype=jnp.float32):
    # flax kernel shape is [fan_in, fan_out]
    bound = float(shape[0]) ** -0.5
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def _torch_bias_init(fan_in: int):
    bound = float(fan_in) ** -0.5

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)

    return init


class TorchDense(nn.Module):
    """nn.Dense with torch.nn.Linear's default initialization."""

    features: int

    @nn.compact
    def __call__(self, x):
        fan_in = x.shape[-1]
        return nn.Dense(
            self.features,
            kernel_init=_torch_kernel_init,
            bias_init=_torch_bias_init(fan_in),
        )(x)


class _DenseParams(nn.Module):
    """Bare kernel+bias with torch init, scoped to match nn.Dense's param
    paths (`<name>/kernel`, `<name>/bias`) so checkpoints are interchangeable
    with TorchDense."""

    features: int
    fan_in: int

    @nn.compact
    def __call__(self):
        kernel = self.param(
            "kernel", _torch_kernel_init, (self.fan_in, self.features)
        )
        bias = self.param("bias", _torch_bias_init(self.fan_in), (self.features,))
        return kernel, bias


class TorchDenseSplit(nn.Module):
    """TorchDense over the concat of a per-stock [T, N, Ds] and a per-period
    [T, Dp] input — WITHOUT materializing the [T, N, Ds+Dp] concat.

        concat([stock, period]) @ K  ==  stock @ K[:Ds] + period @ K[Ds:]

    The per-period part is a tiny [T, Dp] x [Dp, H] matmul broadcast over N,
    so the HBM-resident intermediate shrinks from [T, N, Ds+Dp] to [T, H].
    At the real workload (T=240, N=10k, macro=178) this removes a ~2 GB
    buffer per forward from the moment net alone. Param tree and init are
    bit-identical to `TorchDense` on the concatenated input (same param
    paths, same shapes, same RNG folding), so reference checkpoint import
    (checkpoint.py) and weight-transplant parity are unaffected.

    `stock_first` encodes the reference's concat orders: the SDF net
    concatenates [individual, macro_state] (model.py:251-255) while the
    moment net concatenates [macro, individual] (model.py:514-518).
    """

    features: int
    stock_first: bool = True

    @nn.compact
    def __call__(self, x_stock: jnp.ndarray, x_period: jnp.ndarray) -> jnp.ndarray:
        ds, dp = x_stock.shape[-1], x_period.shape[-1]
        kernel, bias = _DenseParams(
            self.features, ds + dp, name="Dense_0"
        )()
        if self.stock_first:
            k_stock, k_period = kernel[:ds], kernel[ds:]
        else:
            k_period, k_stock = kernel[:dp], kernel[dp:]
        per_period = x_period @ k_period  # [T, H] — tiny
        return x_stock @ k_stock + per_period[:, None, :] + bias


class _RawDense(nn.Module):
    """Parameter twin of TorchDense: creates `<name>/Dense_0/{kernel,bias}`
    with the same shapes/init/RNG folding, but returns the raw arrays instead
    of applying them — the fused Pallas path consumes them directly while
    staying checkpoint-interchangeable with the XLA path."""

    features: int
    fan_in: int

    @nn.compact
    def __call__(self):
        return _DenseParams(self.features, self.fan_in, name="Dense_0")()


def _ffn(x, hidden_dims, dropout, deterministic):
    for h in hidden_dims:
        x = TorchDense(h)(x)
        x = nn.relu(x)
        x = nn.Dropout(rate=dropout)(x, deterministic=deterministic)
    return x


def _split_ffn_head(
    x_stock, x_period, hidden_dims, dropout, deterministic,
    stock_first: bool, out_features: int,
):
    """FFN whose FIRST layer consumes the (stock, period) pair concat-free.

    With hidden layers: returns the last hidden activation [T, N, H] (caller
    applies output_proj). With NO hidden layers (the moment net's default),
    the output projection itself is the split layer; returns [T, N, out].
    Param/RNG paths (TorchDense_i/Dense_0, Dropout_i, output_proj/Dense_0)
    are identical to the concat + _ffn formulation.
    """
    if not hidden_dims:
        return TorchDenseSplit(
            out_features, stock_first=stock_first, name="output_proj"
        )(x_stock, x_period)
    x = TorchDenseSplit(
        hidden_dims[0], stock_first=stock_first, name="TorchDense_0"
    )(x_stock, x_period)
    x = nn.relu(x)
    x = nn.Dropout(rate=dropout, name="Dropout_0")(x, deterministic=deterministic)
    for i, h in enumerate(hidden_dims[1:], start=1):
        x = TorchDense(h, name=f"TorchDense_{i}")(x)
        x = nn.relu(x)
        x = nn.Dropout(rate=dropout, name=f"Dropout_{i}")(
            x, deterministic=deterministic
        )
    return x


def masked_zero_mean(weights: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Cross-sectional zero-mean per period over valid stocks (model.py:273-279)."""
    count = jnp.clip(mask.sum(axis=1, keepdims=True), 1, None)
    mean = (weights * mask).sum(axis=1, keepdims=True) / count
    return (weights - mean) * mask


class SDFNet(nn.Module):
    """Generator: per-stock portfolio weights [T, N] from the panel.

    Two execution routes with ONE parameter tree (identical paths/init):
      * XLA: concat-free TorchDenseSplit + Dense stack (default off-TPU);
      * Pallas: the fused single-HBM-pass FFN kernel (ops/pallas_ffn.py),
        fed the feature-major panel `individual_t` [T, F, N] (pass it in —
        the trainer hoists the transpose outside the epoch scan).
    """

    cfg: GANConfig
    exec_cfg: ExecutionConfig = _DEFAULT_EXEC

    @nn.compact
    def __call__(
        self,
        macro: Optional[jnp.ndarray],  # [T, M] or None
        individual: jnp.ndarray,  # [T, N, F]
        mask: jnp.ndarray,  # [T, N] float
        deterministic: bool = True,
        individual_t: Optional[jnp.ndarray] = None,  # [T, F, N] feature-major
        macro_state: Optional[jnp.ndarray] = None,  # [T, H] precomputed
    ) -> jnp.ndarray:
        cfg = self.cfg
        T, N, _ = individual.shape

        if macro_state is not None:
            # caller carries the recurrent state (serving/engine.py keeps it
            # incrementally — models/recurrent.py's cell/carry split); the
            # LSTM is skipped entirely and its params stay untouched
            pass
        elif macro is not None and cfg.use_rnn and cfg.macro_feature_dim > 0:
            macro_state = TorchLSTM(
                cfg.num_units_rnn, dropout=cfg.dropout, name="macro_lstm"
            )(macro, deterministic=deterministic)
        else:
            macro_state = macro  # may be None

        if self.exec_cfg.use_pallas(cfg.hidden_dim):
            w = self._pallas_ffn(macro_state, individual, individual_t,
                                 deterministic)
            w = w * mask
            if cfg.normalize_w:
                w = masked_zero_mean(w, mask)
            return w

        if macro_state is not None:
            # reference concat order: [individual, macro] (model.py:255),
            # realized concat-free via TorchDenseSplit (see its docstring)
            x = _split_ffn_head(
                individual, macro_state, cfg.hidden_dim, cfg.dropout,
                deterministic, stock_first=True, out_features=1,
            )
            if cfg.hidden_dim:
                w = TorchDense(1, name="output_proj")(x)[..., 0]  # [T, N]
            else:
                w = x[..., 0]
        else:
            x = _ffn(individual, cfg.hidden_dim, cfg.dropout, deterministic)
            w = TorchDense(1, name="output_proj")(x)[..., 0]  # [T, N]
        w = w * mask
        if cfg.normalize_w:
            w = masked_zero_mean(w, mask)
        return w

    def ffn_pieces(self, macro_state, individual):
        """(zp, layers, kout, bout) — the fused kernels' parameter inputs.

        Parameters are created through _RawDense under the same module names
        as the XLA route, so both routes share one checkpoint format and one
        init stream."""
        cfg = self.cfg
        ds = cfg.individual_feature_dim
        dp = 0 if macro_state is None else macro_state.shape[-1]
        h1 = cfg.hidden_dim[0]
        k0, b0 = _RawDense(h1, ds + dp, name="TorchDense_0")()
        if macro_state is not None:
            # reference concat order [individual, macro] (model.py:255)
            k_stock, k_period = k0[:ds], k0[ds:]
            zp = macro_state @ k_period + b0  # [T, H1]
        else:
            k_stock = k0
            zp = jnp.broadcast_to(b0, (individual.shape[0], h1))
        layers = [(k_stock, None)]
        for i, h in enumerate(cfg.hidden_dim[1:], start=1):
            k, b = _RawDense(h, cfg.hidden_dim[i - 1],
                             name=f"TorchDense_{i}")()
            layers.append((k, b))
        kout, bout = _RawDense(1, cfg.hidden_dim[-1], name="output_proj")()
        return zp, layers, kout, bout

    def _pallas_ffn(self, macro_state, individual, individual_t,
                    deterministic) -> jnp.ndarray:
        """Fused-kernel route (see ffn_pieces for the parameter layout)."""
        cfg = self.cfg
        zp, layers, kout, bout = self.ffn_pieces(macro_state, individual)
        if deterministic or cfg.dropout == 0.0:
            rate, seed = 0.0, None
        else:
            rate = cfg.dropout
            seed = jax.random.randint(
                self.make_rng("dropout"), (), 0, jnp.iinfo(jnp.int32).max,
                dtype=jnp.int32,
            )
        if individual_t is None:
            individual_t = jnp.transpose(individual, (0, 2, 1))
        kw = dict(
            dropout_rate=rate, seed=seed,
            block_stocks=self.exec_cfg.block_stocks,
            interpret=self.exec_cfg.interpret,
            compute_dtype=self.exec_cfg.compute_dtype,
        )
        if self.exec_cfg.shard_mesh is not None:
            return fused_sdf_ffn_sharded(
                individual_t, zp, layers, kout, bout,
                self.exec_cfg.shard_mesh, self.exec_cfg.shard_axis, **kw,
            )
        return fused_sdf_ffn(individual_t, zp, layers, kout, bout, **kw)


class MomentNet(nn.Module):
    """Discriminator: K bounded moment functions h_k(t, i) in [-1, 1].

    Consumes RAW macro (not the LSTM state) + individual features, concat
    order [macro, individual] (model.py:514-518), concat-free via
    TorchDenseSplit — the [T, N, M+F] tile+concat (2+ GB at the real
    workload) never materializes."""

    cfg: GANConfig
    exec_cfg: ExecutionConfig = _DEFAULT_EXEC

    @nn.compact
    def __call__(
        self,
        macro: Optional[jnp.ndarray],  # [T, M] or None
        individual: jnp.ndarray,  # [T, N, F]
        deterministic: bool = True,
        individual_t: Optional[jnp.ndarray] = None,  # [T, F, N], may be bf16
    ) -> jnp.ndarray:
        cfg = self.cfg
        if (
            individual_t is not None
            and individual_t.dtype == jnp.bfloat16
            and not cfg.hidden_dim_moment
            and macro is not None
        ):
            # feature-major bf16 path (default architecture: no hidden
            # layers): ONE einsum from the bf16 [T, F, N] panel halves the
            # moment net's dominant HBM read. Only taken for a bf16 panel —
            # measured at the real shape, the [T,N,F] f32 route's matmul
            # tiles better, so f32 stays on TorchDenseSplit below. Param
            # tree identical to the TorchDenseSplit route.
            dp = macro.shape[-1]
            k0, b0 = _RawDense(
                cfg.num_condition_moment, dp + cfg.individual_feature_dim,
                name="output_proj",
            )()
            k_period, k_stock = k0[:dp], k0[dp:]  # concat order [macro, indiv]
            zp_m = macro @ k_period + b0  # [T, K]
            # operand dtype follows ExecutionConfig.compute_dtype (same knob
            # as the SDF kernel) where the MXU accumulates in f32 (TPU);
            # CPU's dot thunk has no BF16xBF16=F32 kernel
            cd = (
                jnp.dtype(self.exec_cfg.compute_dtype)
                if jax.default_backend() == "tpu"
                else jnp.float32
            )
            out = jnp.einsum(
                "tfn,fk->ktn", individual_t.astype(cd), k_stock.astype(cd),
                preferred_element_type=jnp.float32,
            ) + zp_m.T[:, :, None]
            return jnp.tanh(out)  # [K, T, N]
        if macro is not None:
            x = _split_ffn_head(
                individual, macro, cfg.hidden_dim_moment, cfg.dropout,
                deterministic, stock_first=False,
                out_features=cfg.num_condition_moment,
            )
            if cfg.hidden_dim_moment:
                x = TorchDense(cfg.num_condition_moment, name="output_proj")(x)
        else:
            x = _ffn(individual, cfg.hidden_dim_moment, cfg.dropout, deterministic)
            x = TorchDense(cfg.num_condition_moment, name="output_proj")(x)
        out = jnp.tanh(x)  # [T, N, K]
        return jnp.transpose(out, (2, 0, 1))  # [K, T, N]


class AssetPricingModule(nn.Module):
    """The GAN pair as one Flax module with separable parameter subtrees.

    params tree: {'sdf_net': ..., 'moment_net': ...} — the training phases
    partition optimizers/gradients on exactly this split (the reference does
    it with two torch optimizers, train.py:210-211).
    """

    cfg: GANConfig
    exec_cfg: ExecutionConfig = _DEFAULT_EXEC

    def setup(self):
        self.sdf_net = SDFNet(self.cfg, self.exec_cfg)
        self.moment_net = MomentNet(self.cfg, self.exec_cfg)

    def __call__(self, macro, individual, mask, deterministic: bool = True,
                 individual_t=None):
        """Returns (weights [T, N], moments [K, T, N])."""
        weights = self.sdf_net(macro, individual, mask, deterministic,
                               individual_t=individual_t)
        moments = self.moment_net(macro, individual, deterministic,
                                  individual_t=individual_t)
        return weights, moments

    def weights(self, macro, individual, mask, deterministic: bool = True,
                individual_t=None, macro_state=None):
        return self.sdf_net(macro, individual, mask, deterministic,
                            individual_t=individual_t,
                            macro_state=macro_state)

    def moments(self, macro, individual, deterministic: bool = True,
                individual_t=None):
        return self.moment_net(macro, individual, deterministic,
                               individual_t=individual_t)


class SimpleSDF(nn.Module):
    """Non-adversarial FFN-only SDF baseline (model.py:620-694)."""

    macro_dim: int
    individual_dim: int
    hidden_dims: Tuple[int, ...] = (64, 64)
    dropout: float = 0.05

    @nn.compact
    def __call__(self, macro, individual, mask, deterministic: bool = True):
        T, N, _ = individual.shape
        if macro is not None:
            tiled = jnp.broadcast_to(macro[:, None, :], (T, N, macro.shape[-1]))
            x = jnp.concatenate([tiled, individual], axis=-1)
        else:
            x = individual
        x = _ffn(x, self.hidden_dims, self.dropout, deterministic)
        w = TorchDense(1)(x)[..., 0] * mask
        return masked_zero_mean(w, mask)


def moment_output_params(params, cfg: GANConfig):
    """(k_period, k_stock, bias) of the default MomentNet output layer.

    THE single place encoding the moment net's parameter layout outside the
    module: path ``moment_net/output_proj/Dense_0`` with the reference's
    [macro, individual] concat order (model.py:514-518) — rows [:M] act on
    macro, rows [M:] on the stock features. MomentNet's in-module routes
    (TorchDenseSplit / the bf16 einsum) encode the same order.
    """
    mp = params["moment_net"]["output_proj"]["Dense_0"]
    M = cfg.macro_feature_dim
    return mp["kernel"][:M], mp["kernel"][M:], mp["bias"]


def simple_sdf_forward(model: SimpleSDF, params, batch, rng=None):
    """SimpleSDF's loss-bearing forward (reference model.py:652-694): weights,
    UNWEIGHTED portfolio returns (no N̄/N_t scaling, unlike the GAN loss),
    the shared unconditional loss, and the (std+1e-8)-guarded monitoring
    sharpe (torch .std() is unbiased, ddof=1)."""
    from ..ops.losses import unconditional_loss
    from ..ops.metrics import sharpe_monitor

    deterministic = rng is None
    rngs = None if deterministic else {"dropout": rng}
    mask = batch["mask"]
    returns = batch["returns"]
    weights = model.apply(
        {"params": params}, batch.get("macro"), batch["individual"], mask,
        deterministic, rngs=rngs,
    )
    loss, port = unconditional_loss(
        weights, returns, mask, weighted=False,
        n_assets=batch.get("n_assets"),
    )
    return {
        "weights": weights,
        "loss": loss,
        "sharpe": sharpe_monitor(port),
        "portfolio_returns": port,
    }
