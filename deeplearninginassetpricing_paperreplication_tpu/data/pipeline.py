"""Overlapped startup pipeline: cache-aware decode → streamed transfer →
early AOT compile.

BENCH_SELF_r04 shows the cold-start path is no longer execute-bound: host
load/decode (25.3 s) and host→device transfer (22.7 s) run back-to-back
before the first train step dispatches, while the phase programs compile
AFTER both. The three stages have no data dependencies beyond "compile needs
shapes" and "transfer needs decoded bytes", so this module runs them as a
pipeline:

  1. **decode** (thread pool, train split first): per split, hit the
     decoded-panel disk cache (:mod:`.diskcache` — memmapped raw arrays plus
     the packed valid-rows rep, skipping npz decompress, mask build, and the
     flatnonzero/gather repack) or decode via :func:`..panel.load_panel` and
     store for next time;
  2. **transfer** (dedicated thread): as each split's decode lands — in
     train/valid/test order — ship it with :func:`stream_batch`, which
     chunks the dominant array into slabs and `device_put`s them through a
     double-buffered prep thread so host packing overlaps DMA (and the
     remaining splits' decodes). Bit-identical to
     :func:`..transfer.device_put_batch` on every route (dense, packed,
     bf16-wire);
  3. **compile** (worker thread, t≈0): :func:`probe_split_shapes` reads the
     npz headers without touching payload bytes, so the three phase-scan
     programs can start their ``.lower().compile()`` immediately
     (:func:`trainer_precompile_fn`) and finish under the load+transfer
     window instead of after it.

Every stage emits ``startup/*`` spans into the run's EventLog;
``python -m ...report`` renders them as the startup breakdown.

**Sharded data plane** (PR 7): for panels too big to materialize per host,
the same pipeline runs against the CHUNKED store (:mod:`.diskcache`
``store_chunked``/``load_chunked``): :func:`load_splits_chunked` loads only
the stock shards a mesh slot owns (``columns=``), digest-verifying each
shard and re-decoding JUST a corrupt one from the npz, and
:func:`stream_batch_sharded` ships each device's stock span directly to its
owning device (double-buffered, assembled with
``jax.make_array_from_single_device_arrays`` under the exact
``parallel.mesh.batch_sharding`` layout — bit-identical to ``shard_batch``).
``StartupPipeline(mesh=...)`` composes both with the overlapped
decode/compile stages, so ``train.py --shard_stocks`` keeps the PR 2
startup win. Shard telemetry rides ``startup/shard_*`` events.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import queue
import threading
import zipfile
from functools import partial
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..observability.events import EventLog
from ..reliability.faults import inject
from . import diskcache
from .panel import (
    PanelDataset,
    load_panel,
    macro_train_stats,
    normalize_macro_with,
)
from .transfer import (
    AUTO_PACK_THRESHOLD,
    _scatter_dense,
    _upcast_f32,
    pack_rows,
)

SPLITS = ("train", "valid", "test")

# transfer slab size: big enough to amortize per-put overhead, small enough
# that the prep(+cast) of slab k+1 genuinely overlaps slab k's DMA
DEFAULT_CHUNK_BYTES = 64 << 20


def split_paths(
    data_dir: Union[str, Path], split: str
) -> Tuple[Path, Optional[Path]]:
    """(char npz, macro npz or None) for one split in the reference layout."""
    data_dir = Path(data_dir)
    char = data_dir / "char" / f"Char_{split}.npz"
    macro = data_dir / "macro" / f"macro_{split}.npz"
    return char, (macro if macro.exists() else None)


# --------------------------------------------------------------------------
# stage 3 input: shape probe from npz headers (no payload bytes)
# --------------------------------------------------------------------------

def npz_member_shape(path: Union[str, Path], member: str = "data"):
    """(shape, dtype) of one .npz member from its .npy header alone — reads
    a few hundred bytes, never the (possibly ~0.5 GB) payload."""
    with zipfile.ZipFile(path) as z:
        with z.open(member + ".npy") as f:
            version = np.lib.format.read_magic(f)
            if version == (1, 0):
                shape, _, dtype = np.lib.format.read_array_header_1_0(f)
            elif version == (2, 0):
                shape, _, dtype = np.lib.format.read_array_header_2_0(f)
            else:
                raise ValueError(f"unsupported .npy format version {version}")
    return shape, dtype


def probe_split_shapes(data_dir: Union[str, Path]) -> Dict[str, Dict[str, tuple]]:
    """Device-batch shapes per split, from headers only:

        {"train": {"individual": (T, N, F), "returns": (T, N),
                   "mask": (T, N), "macro": (T, M)}, ...}

    This is everything the phase-program compiles need, available at t≈0.
    (A ``macro_idx`` selection shrinks M — callers using one must adjust.)
    """
    shapes: Dict[str, Dict[str, tuple]] = {}
    for split in SPLITS:
        char, macro = split_paths(data_dir, split)
        (t, n, c), _ = npz_member_shape(char)
        entry = {
            "individual": (t, n, c - 1),
            "returns": (t, n),
            "mask": (t, n),
        }
        if macro is not None:
            (_, m), _ = npz_member_shape(macro)
            entry["macro"] = (t, m)
        shapes[split] = entry
    return shapes


# --------------------------------------------------------------------------
# stage 1: cache-aware decode
# --------------------------------------------------------------------------

@dataclasses.dataclass
class _RawSplit:
    """One split fresh off stage 1: macro still RAW (normalization needs the
    train split's stats), packed rep present when the coverage packs."""

    ds: PanelDataset
    packed: Optional[tuple]  # (idx [V] i32, rows [V, F] f32, ret [V] f32)
    cache_hit: bool


def _load_split_raw(
    char_path: Path,
    macro_path: Optional[Path],
    use_cache: bool = True,
) -> _RawSplit:
    if use_cache:
        entry = diskcache.load(char_path, macro_path)
        if entry is not None:
            ds = PanelDataset(
                returns=entry.returns,
                individual=entry.individual,
                mask=entry.mask,
                macro=entry.macro,
                dates=entry.dates,
                variable_names=entry.variable_names,
            )
            packed = (
                (entry.idx, entry.rows, entry.ret_packed)
                if entry.idx is not None else None
            )
            return _RawSplit(ds, packed, True)
    ds = load_panel(char_path, macro_path, normalize_macro=False)
    packed = _pack_and_store_monolithic(char_path, macro_path, ds, use_cache)
    return _RawSplit(ds, packed, False)


def _pack_and_store_monolithic(
    char_path: Path,
    macro_path: Optional[Path],
    ds: PanelDataset,
    use_cache: bool,
) -> Optional[tuple]:
    """Pack (when sparse) and persist one freshly decoded split in the
    MONOLITHIC cache format — THE single store call shared by the unsharded
    raw path and a full-span chunked miss, so every later full-span
    consumer zero-copy mmaps instead of re-deriving. Returns the packed
    (idx, rows, ret) triple (None at dense coverage)."""
    mask_f = ds.mask.astype(np.float32)
    coverage = float(mask_f.mean())
    packed = None
    if coverage < AUTO_PACK_THRESHOLD:
        # pay the repack once, here, so every later run mmaps it instead
        packed = pack_rows(mask_f, ds.individual, ds.returns)
    if use_cache:
        diskcache.store(
            char_path, macro_path,
            {
                "returns": ds.returns,
                "individual": ds.individual,
                "mask": ds.mask,
                "dates": ds.dates,
                "variable_names": ds.variable_names,
                "macro": ds.macro,
                "idx": packed[0] if packed else None,
                "rows": packed[1] if packed else None,
                "ret_packed": packed[2] if packed else None,
            },
            extra_meta={"coverage": coverage},
        )
    return packed


def _finalize_macro(ds: PanelDataset, macro_idx, stats=None):
    """Apply macro_idx selection + z-scoring to one RAW split in place,
    using :func:`..panel.macro_train_stats` / `normalize_macro_with` so the
    result is bit-identical to `load_splits`. Returns the (mean, std) used,
    or None when the split has no macro / no stats exist to apply."""
    if ds.macro is None:
        return None
    macro = np.asarray(ds.macro)
    if macro_idx is not None:
        macro = macro[:, list(macro_idx)]
    if stats is None:
        mean, std = macro_train_stats(macro)
    else:
        mean, std = stats
    ds.macro = normalize_macro_with(macro, mean, std)
    ds.mean_macro, ds.std_macro = mean, std
    return mean, std


def load_splits_cached(
    data_dir: Union[str, Path],
    macro_idx: Optional[Sequence[int]] = None,
    events: Optional[EventLog] = None,
) -> Tuple[PanelDataset, PanelDataset, PanelDataset]:
    """Drop-in for :func:`..panel.load_splits` with the decoded-panel disk
    cache in front of the npz decode — bit-identical results either way.

    Big arrays in a cache-hit dataset are read-only memmaps; every existing
    consumer (full_batch, subsample, pad_stocks, device_put_batch) already
    copies where it mutates, so the distinction is invisible downstream.
    """
    ev = events if events is not None else EventLog()
    use_cache = diskcache.cache_enabled()

    def job(split: str) -> _RawSplit:
        char, macro = split_paths(data_dir, split)
        inject("pipeline/decode", split=split)
        with ev.span(f"startup/load/{split}"):
            raw = _load_split_raw(char, macro, use_cache)
        ev.counter("panel_cache", value=1, split=split, hit=raw.cache_hit)
        return raw

    with concurrent.futures.ThreadPoolExecutor(3) as ex:
        futs = {split: ex.submit(job, split) for split in SPLITS}
        raw = {split: futs[split].result() for split in SPLITS}
    stats = _finalize_macro(raw["train"].ds, macro_idx)
    for split in ("valid", "test"):
        if stats is not None:
            _finalize_macro(raw[split].ds, macro_idx, stats)
    return raw["train"].ds, raw["valid"].ds, raw["test"].ds


# --------------------------------------------------------------------------
# stage 1b: chunked store + shard-local loading (the sharded data plane)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class _ChunkedSplit:
    """One split off the chunked reader: `ds` covers only `columns` (full
    split when None); shard accounting feeds the startup/shard_* telemetry."""

    ds: PanelDataset
    cache_hit: bool
    shards_owned: int
    shards_loaded: int      # served straight from verified cache shards
    shards_redecoded: int   # failed the fingerprint check → npz re-decode
    columns: Optional[Tuple[int, int]]
    monolithic: bool = False  # full-span hit served from a monolithic entry


def _slice_columns(ds: PanelDataset, columns) -> PanelDataset:
    if columns is None:
        return ds
    a, b = columns
    return PanelDataset(
        returns=ds.returns[:, a:b],
        individual=ds.individual[:, a:b, :],
        mask=ds.mask[:, a:b],
        macro=ds.macro,
        dates=ds.dates,
        variable_names=ds.variable_names,
    )


def _load_split_chunked(
    char_path: Path,
    macro_path: Optional[Path],
    columns: Optional[Tuple[int, int]] = None,
    use_cache: bool = True,
    shard_width: Optional[int] = None,
    events: Optional[EventLog] = None,
    split: str = "",
    monolithic_ok: bool = True,
) -> _ChunkedSplit:
    """Load one split through the CHUNKED panel store, touching only the
    stock shards intersecting `columns` ([a, b) span; None = all).

    Every shard read fires the ``data/shard_read`` fault site and is
    digest-verified against the entry manifest; a corrupt/torn shard is
    re-decoded from the source npz and re-stored IN PLACE — the other
    shards never re-verify, never re-decode. A corrupt manifest or global
    array invalidates the whole entry and falls back to a fresh decode +
    store. On a cache miss the npz is decoded once in full (a deflate zip
    member cannot be column-sliced) and the chunked entry written for every
    later run to read shard-locally.

    Width-agnostic FULL-span reads (columns None, no explicit width — the
    sweep/evaluate/serve CLIs) serve an existing MONOLITHIC entry first:
    it zero-copy mmaps with no payload hashing, exactly what the
    pre-sharding cache-aware path did — so a default (unsharded) training
    run's decode feeds a later sweep/evaluate/serve startup without a
    second decode. On a miss they store BOTH formats from the one decode
    (monolithic for their own warm reruns and later unsharded trains,
    chunked for later sharded runs), so the chunked read path — per-shard
    verify + one materializing concat — is never on a full-span
    consumer's warm path; only sharded slots and explicit-width callers,
    where the chunked store is the point, pay it.
    """
    import shutil

    ev = events if events is not None else EventLog()
    width = diskcache.shard_width(shard_width)
    decoded: List[Optional[PanelDataset]] = [None]

    def full_decode() -> PanelDataset:
        if decoded[0] is None:
            decoded[0] = load_panel(char_path, macro_path,
                                    normalize_macro=False)
        return decoded[0]

    # width-agnostic full-span reads (the sweep/evaluate/serve CLIs) take
    # the monolithic fast path and maintain both formats; an EXPLICIT width
    # is a chunked-store request (bench seeding, width tests) and must
    # create/serve the width-specific entry, never short-circuit past it —
    # and the mesh route (monolithic_ok=False) always goes chunked, or its
    # warm runs would lose the per-shard verify/repair the route is for
    width_agnostic = (columns is None and shard_width is None
                      and monolithic_ok)
    if use_cache and width_agnostic:
        mono = diskcache.load(char_path, macro_path)
        if mono is not None:
            ds = PanelDataset(
                returns=mono.returns,
                individual=mono.individual,
                mask=mono.mask,
                macro=mono.macro,
                dates=mono.dates,
                variable_names=mono.variable_names,
            )
            return _ChunkedSplit(ds, True, 0, 0, 0, None, monolithic=True)

    entry = (diskcache.load_chunked(char_path, macro_path, width)
             if use_cache else None)
    if entry is not None:
        try:
            out = _read_chunked_entry(entry, columns, full_decode, ev, split)
            if out is not None:
                return out
        except MemoryError:
            raise  # transient pressure — never evict a healthy entry for it
        except Exception:
            pass
        # unusable entry (bad manifest/global, or a shard restore that no
        # longer reproduces the recorded digests): evict and re-store fresh
        shutil.rmtree(entry.dir, ignore_errors=True)

    ds_full = full_decode()
    if use_cache:
        diskcache.store_chunked(
            char_path, macro_path,
            {
                "returns": ds_full.returns,
                "individual": ds_full.individual,
                "mask": ds_full.mask,
                "dates": ds_full.dates,
                "variable_names": ds_full.variable_names,
                "macro": ds_full.macro,
            },
            width=width,
            extra_meta={"coverage": float(ds_full.mask.mean())},
        )
        if width_agnostic:
            # a full-span consumer (sweep/evaluate/serve cold start) also
            # leaves the MONOLITHIC entry behind: its own warm rerun — and
            # any later unsharded train — zero-copy mmaps it instead of
            # paying the chunked format's per-shard verify + concat. The
            # formats coexist under _evict_stale; a sharded slot (or an
            # explicit-width caller) skips this so no mesh host ever
            # writes a full-panel copy.
            _pack_and_store_monolithic(char_path, macro_path, ds_full,
                                       use_cache=True)
    bounds = diskcache.shard_bounds(ds_full.returns.shape[1], width)
    owned = (len(bounds) if columns is None else
             sum(1 for lo, hi in bounds
                 if hi > columns[0] and lo < columns[1]))
    ev.counter("startup/shard_owned", value=owned, split=split)
    return _ChunkedSplit(_slice_columns(ds_full, columns), False,
                         owned, 0, 0, columns)


def _read_chunked_entry(
    entry, columns, full_decode, ev: EventLog, split: str
) -> Optional[_ChunkedSplit]:
    """Serve one split from a chunked entry: verify + memmap each owned
    shard, re-decoding (and repairing) the ones that fail. Returns None when
    a repair cannot reproduce the manifest digests (entry is stale).

    Shard fingerprint checks run on a small thread pool (hashlib releases
    the GIL, so two shards hash on two cores while the in-order consumer
    assembles earlier ones) — the verify pass is on the shard-local load's
    critical path and serial hashing would cost as much as the load
    itself. The ``data/shard_read`` fault site fires inside each shard's
    check, still strictly before that shard's fingerprint verification."""
    bounds = entry.bounds()
    needed = entry.shards_for(columns)
    parts: Dict[str, list] = {name: [] for name in diskcache.SHARD_ARRAYS}
    n_loaded = n_redecoded = 0

    def check(i):
        inject("data/shard_read",
               path=str(entry.shard_path(i, "individual")),
               split=split, shard=i)
        return entry.verify_shard(i)

    pool = concurrent.futures.ThreadPoolExecutor(min(2, max(1, len(needed))))
    checks = {i: pool.submit(check, i) for i in needed}
    pool.shutdown(wait=False)
    for i in needed:
        ok, why = checks[i].result()
        if ok:
            arrs = entry.load_shard(i)
            n_loaded += 1
        else:
            ds_full = full_decode()
            full_arrays = {"returns": ds_full.returns,
                           "individual": ds_full.individual,
                           "mask": ds_full.mask}
            if not entry.restore_shard(i, full_arrays):
                return None  # decode no longer matches the manifest
            a, b = bounds[i]
            arrs = {k: v[:, a:b] for k, v in full_arrays.items()}
            n_redecoded += 1
            ev.counter("startup/shard_redecode", split=split, shard=i,
                       reason=why)
        a, b = bounds[i]
        lo = a if columns is None else max(a, columns[0])
        hi = b if columns is None else min(b, columns[1])
        for name in diskcache.SHARD_ARRAYS:
            parts[name].append(arrs[name][:, lo - a:hi - a])
    assembled = {
        name: (parts[name][0] if len(parts[name]) == 1
               else np.concatenate(parts[name], axis=1))
        for name in diskcache.SHARD_ARRAYS
    }
    ds = PanelDataset(
        returns=assembled["returns"],
        individual=assembled["individual"],
        mask=assembled["mask"],
        macro=entry.load_global("macro"),
        dates=entry.load_global("dates"),
        variable_names=entry.load_global("variable_names"),
    )
    ev.counter("startup/shard_owned", value=len(needed), split=split)
    if n_loaded:
        ev.counter("startup/shard_loaded", value=n_loaded, split=split)
    return _ChunkedSplit(ds, True, len(needed), n_loaded, n_redecoded,
                         columns)


def load_splits_chunked(
    data_dir: Union[str, Path],
    macro_idx: Optional[Sequence[int]] = None,
    events: Optional[EventLog] = None,
    columns: Optional[Tuple[int, int]] = None,
    shard_width: Optional[int] = None,
) -> Tuple[PanelDataset, PanelDataset, PanelDataset]:
    """Drop-in for :func:`..panel.load_splits` through the CHUNKED panel
    store — bit-identical results over the same stock span.

    `columns=(a, b)` restricts every split to that stock span: the
    shard-local path a mesh slot uses so its host materializes only the
    data its devices own (macro/dates stay global — they are tiny and the
    TRAIN macro stats must not depend on the span). This is the reader the
    sweep / evaluate_ensemble / serving CLIs route through (full span).
    """
    ev = events if events is not None else EventLog()
    use_cache = diskcache.cache_enabled()

    def job(split: str) -> _ChunkedSplit:
        char, macro = split_paths(data_dir, split)
        inject("pipeline/decode", split=split)
        with ev.span(f"startup/load/{split}"):
            raw = _load_split_chunked(
                char, macro, columns=columns, use_cache=use_cache,
                shard_width=shard_width, events=ev, split=split)
        ev.counter("panel_cache", value=1, split=split, hit=raw.cache_hit,
                   chunked=not raw.monolithic)
        return raw

    with concurrent.futures.ThreadPoolExecutor(3) as ex:
        futs = {split: ex.submit(job, split) for split in SPLITS}
        raw = {split: futs[split].result() for split in SPLITS}
    stats = _finalize_macro(raw["train"].ds, macro_idx)
    for split in ("valid", "test"):
        if stats is not None:
            _finalize_macro(raw[split].ds, macro_idx, stats)
    return raw["train"].ds, raw["valid"].ds, raw["test"].ds


# --------------------------------------------------------------------------
# stage 2: streamed, double-buffered transfer
# --------------------------------------------------------------------------

def _buffered_puts(n_chunks: int, make_chunk: Callable[[int], np.ndarray],
                   put: Callable[[np.ndarray], Any]) -> list:
    """device_put `n_chunks` host slabs with one-slab-ahead preparation: a
    producer thread gathers/casts slab k+1 while slab k's bytes are on the
    wire (device_put dispatches asynchronously). Bounded queue so at most
    two prepared slabs are ever resident."""
    if n_chunks <= 1:
        return [put(make_chunk(0))]
    q: "queue.Queue" = queue.Queue(maxsize=2)

    def producer():
        try:
            for i in range(n_chunks):
                q.put(("chunk", make_chunk(i)))
        except BaseException as e:  # re-raised on the consumer side
            q.put(("error", e))
        else:
            q.put(("done", None))

    threading.Thread(
        target=producer, daemon=True, name="panel-transfer-prep"
    ).start()
    out = []
    while True:
        kind, payload = q.get()
        if kind == "done":
            return out
        if kind == "error":
            raise payload
        out.append(put(payload))


def buffered_puts(n_chunks: int, make_chunk: Callable[[int], Any],
                  put: Callable[[Any], Any]) -> list:
    """Public surface of the one-slab-ahead transfer discipline (see
    :func:`_buffered_puts`): the serving engine's sharded staging dispatch
    rides the same producer/consumer protocol as
    :func:`stream_batch_sharded` — per-device host spans prepared one
    ahead of the wire, results in device order for
    ``jax.make_array_from_single_device_arrays`` assembly."""
    return _buffered_puts(n_chunks, make_chunk, put)


def _chunk_bounds(n: int, per_chunk: int) -> list:
    per_chunk = max(1, per_chunk)
    return [(a, min(a + per_chunk, n)) for a in range(0, max(n, 1), per_chunk)]


def stream_batch(
    batch: Dict[str, np.ndarray],
    packed: Union[bool, str] = "auto",
    device=None,
    bf16_wire: bool = False,
    packed_rep: Optional[tuple] = None,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
) -> Dict[str, Any]:
    """`..transfer.device_put_batch`, streamed: same routing decision, same
    wire dtypes, same scatter program, bit-identical device arrays — but the
    dominant payload (`individual` dense slabs / packed valid rows) ships in
    `chunk_bytes` slices through :func:`_buffered_puts`, so the host-side
    gather/cast/copy of one slab overlaps the previous slab's DMA.

    `packed_rep`: a precomputed (idx, rows, ret) triple — on a disk-cache
    hit these are memmapped straight from the cache entry and the dense
    `individual` payload is never read at all.

    Memory trade: the multi-chunk routes reassemble with one on-device
    `concatenate`, so the chunks AND the result are briefly co-resident —
    a transient extra copy of the wire payload (~120-240 MB at the real
    shape on the packed route; the dense route only multi-chunks when
    coverage ≥ 0.85 or packing is forced off). Raise `chunk_bytes` past
    the payload size to get `device_put_batch`'s single-allocation
    behavior at the cost of the prep/DMA overlap.
    """
    import jax
    import jax.numpy as jnp

    mask = np.asarray(batch["mask"], np.float32)
    t, n = mask.shape
    ind = np.asarray(batch["individual"])
    if ind.dtype != np.float32:
        raise TypeError(
            "stream_batch expects a float32 panel (loader contract); "
            f"got individual dtype {ind.dtype}"
        )
    f = int(ind.shape[-1])
    coverage = float(mask.mean())
    if packed == "auto":
        packed = coverage < AUTO_PACK_THRESHOLD
    put = partial(jax.device_put, device=device)
    wire = jnp.bfloat16 if bf16_wire else np.float32

    if not packed:
        out = {
            k: put(jnp.asarray(v)) for k, v in batch.items()
            if k != "individual"
        }
        per = chunk_bytes // max(1, t * f * 4)
        bounds = _chunk_bounds(n, per)

        def dense_chunk(i):
            a, b = bounds[i]
            slab = np.ascontiguousarray(ind[:, a:b, :])
            return slab.astype(wire, copy=False) if bf16_wire else slab

        chunks = _buffered_puts(len(bounds), dense_chunk, put)
        ind_d = chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks, axis=1)
        out["individual"] = _upcast_f32(ind_d) if bf16_wire else ind_d
        return out

    if packed_rep is None:
        packed_rep = pack_rows(mask, ind, batch["returns"])
    idx, rows, ret = packed_rep
    v = int(np.asarray(idx).shape[0])
    bounds = _chunk_bounds(v, chunk_bytes // max(1, f * 4))

    def row_chunk(i):
        a, b = bounds[i]
        return np.ascontiguousarray(rows[a:b]).astype(wire, copy=False)

    row_chunks = _buffered_puts(len(bounds), row_chunk, put)
    rows_d = (
        row_chunks[0] if len(row_chunks) == 1
        else jnp.concatenate(row_chunks, axis=0)
    )
    individual, returns, mask_d = _scatter_dense(
        put(np.ascontiguousarray(np.asarray(idx, np.int32))),
        rows_d,
        put(np.ascontiguousarray(np.asarray(ret, np.float32))),
        t, n, f,
    )
    out = {"individual": individual, "returns": returns, "mask": mask_d}
    for k, val in batch.items():
        if k not in out:
            out[k] = put(jnp.asarray(val))
    return out


def stream_batch_sharded(
    batch: Dict[str, np.ndarray],
    mesh,
    axis_name: Optional[str] = None,
    events: Optional[EventLog] = None,
    split: str = "",
    bf16_wire: bool = False,
) -> Dict[str, Any]:
    """`..parallel.partition.shard_batch`, streamed per shard: each device's
    stock span is gathered/copied on the host while the PREVIOUS span's
    bytes are on the wire (the same one-slab-ahead discipline as
    :func:`stream_batch`), `device_put` directly onto its owning device,
    and the global arrays assembled with
    ``jax.make_array_from_single_device_arrays`` under the exact
    rule-matched ``partition.batch_shardings`` layout — bit-identical to
    ``shard_batch`` by construction, without ever staging a second full
    copy of the panel.

    Emits one ``startup/shard_transfer`` span per device shard (dispatch
    window — device_put is async). N must divide the mesh's stock axis;
    pad with ``PanelDataset.pad_stocks`` first (same contract as
    ``shard_batch``). Replicated fields (macro, n_assets) ship with their
    replicated shardings.

    ``bf16_wire``: ship each shard's `individual` span as bfloat16 and
    upcast the assembled global array on device — per-shard halving of the
    dominant host→device payload, values identical to the single-device
    ``device_put_batch(bf16_wire=True)`` route (the cast is elementwise, so
    casting per shard ≡ casting the whole panel; PARITY_BF16.json is the
    end-to-end evidence for the bf16 wire itself).
    """
    import jax
    import jax.numpy as jnp

    from ..parallel import partition

    axis_name = axis_name or partition.STOCK_AXIS
    ev = events if events is not None else EventLog()
    sh = partition.batch_shardings(mesh, axis_name)
    arrs = {k: np.asarray(batch[k])
            for k in ("individual", "returns", "mask") if k in batch}
    n = arrs["returns"].shape[1]
    if n % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"stock axis {n} not divisible by mesh axis "
            f"{mesh.shape[axis_name]}; pad with PanelDataset.pad_stocks()"
        )
    # device → (slice(None), slice(a, b)) for the [T, N] layout; all three
    # big arrays share the stock-axis split, so one map drives them all
    dmap = sh["returns"].devices_indices_map(arrs["returns"].shape)
    devices = list(dmap)

    def make_chunk(i):
        dev = devices[i]
        sl = dmap[dev][1]
        a, b, _ = sl.indices(n)
        slabs = {}
        for k, v in arrs.items():
            if bf16_wire and k == "individual":
                # ONE host copy: astype on the strided view gathers and
                # casts in a single C-contiguous bf16 allocation (half the
                # bytes) — an ascontiguousarray first would pay a full f32
                # copy just to throw it away
                slabs[k] = v[:, sl].astype(jnp.bfloat16)
            else:
                slabs[k] = np.ascontiguousarray(v[:, sl])
        return (i, dev, (a, b), slabs)

    def put(payload):
        i, dev, (a, b), slabs = payload
        with ev.span("startup/shard_transfer", split=split, shard=i,
                     device=str(dev), start=a, stop=b):
            return {k: jax.device_put(v, dev) for k, v in slabs.items()}

    parts = _buffered_puts(len(devices), make_chunk, put)
    out = {}
    for k, a in arrs.items():
        wired_bf16 = bf16_wire and k == "individual"
        parts_k = [p[k] for p in parts]
        assembled = jax.make_array_from_single_device_arrays(
            a.shape, sh[k], parts_k)
        if wired_bf16:
            # elementwise upcast of the sharded global array: no collective,
            # each device upcasts its own span in place
            assembled = _upcast_f32(assembled)
        out[k] = assembled
    for k, v in batch.items():
        if k in out:
            continue
        s = sh.get(k) or partition.replicated(mesh)
        out[k] = jax.device_put(jnp.asarray(v), s)
    return out


def _peak_rss_bytes() -> Optional[int]:
    """This process's high-water RSS (Linux ru_maxrss is KiB) — the host-
    memory number the dataplane bench and report CLI track."""
    try:
        import resource
    except ImportError:  # pragma: no cover — non-POSIX host
        return None
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


# --------------------------------------------------------------------------
# the pipeline orchestrator
# --------------------------------------------------------------------------

@dataclasses.dataclass
class PipelineResult:
    """Everything `StartupPipeline.result()` hands back."""

    datasets: Tuple[PanelDataset, PanelDataset, PanelDataset]
    batches: Tuple[Dict[str, Any], Dict[str, Any], Dict[str, Any]]
    compiled: Any  # compile_fn's return value (e.g. a precompiled Trainer)
    cache_hits: Dict[str, bool]


class StartupPipeline:
    """Run decode, transfer, and compile as three overlapped stages.

    Usage::

        pipe = StartupPipeline(data_dir, bf16_wire=..., events=events,
                               compile_fn=trainer_precompile_fn(...)).start()
        ...                       # anything else the CLI wants to do
        res = pipe.result()       # blocks until batches + compile are done

    `compile_fn(shapes)` — optional — is called on a worker thread at t≈0
    with :func:`probe_split_shapes`'s output; its return value comes back as
    ``PipelineResult.compiled``. Exceptions from any stage are re-raised by
    ``result()``.
    """

    def __init__(
        self,
        data_dir: Union[str, Path],
        *,
        macro_idx: Optional[Sequence[int]] = None,
        packed: Union[bool, str] = "auto",
        bf16_wire: bool = False,
        device=None,
        events: Optional[EventLog] = None,
        compile_fn: Optional[Callable[[Dict], Any]] = None,
        shapes: Optional[Dict] = None,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        cache: Optional[bool] = None,
        mesh=None,
        shard_width: Optional[int] = None,
    ):
        self.data_dir = Path(data_dir)
        self.macro_idx = macro_idx
        self.packed = packed
        self.bf16_wire = bf16_wire
        self.device = device
        self.events = events if events is not None else EventLog()
        self.compile_fn = compile_fn
        self.shapes = shapes
        self.chunk_bytes = chunk_bytes
        self.use_cache = diskcache.cache_enabled() if cache is None else cache
        # sharded data plane: with a mesh, decode goes through the CHUNKED
        # store and each split streams per-shard onto its owning devices
        # (stream_batch_sharded); datasets come back stock-padded to the
        # mesh's stock axis. bf16_wire applies per shard on this route too
        # (each owning device's `individual` span ships bfloat16 and
        # upcasts in place — values identical to the single-device wire).
        self.mesh = mesh
        self.shard_width = shard_width
        self._started = False
        self._compile_thread: Optional[threading.Thread] = None
        self._transfer_thread: Optional[threading.Thread] = None
        self._decode_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._decode_futures: Dict[str, concurrent.futures.Future] = {}
        self._compiled: Any = None
        self._compile_error: Optional[BaseException] = None
        self._transfer_error: Optional[BaseException] = None
        self._datasets: Dict[str, PanelDataset] = {}
        self._batches: Dict[str, Dict[str, Any]] = {}
        self._cache_hits: Dict[str, bool] = {}

    # -- stage bodies --------------------------------------------------------

    def _run_compile(self):
        try:
            with self.events.span("startup/compile"):
                self._compiled = self.compile_fn(self.shapes)
        except BaseException as e:
            self._compile_error = e

    def _decode_one(self, split: str) -> _RawSplit:
        char, macro = split_paths(self.data_dir, split)
        inject("pipeline/decode", split=split)
        with self.events.span(f"startup/load/{split}"):
            if self.mesh is not None:
                chunked = _load_split_chunked(
                    char, macro, use_cache=self.use_cache,
                    shard_width=self.shard_width,
                    events=self.events, split=split,
                    monolithic_ok=False)
                raw = _RawSplit(chunked.ds, None, chunked.cache_hit)
                attrs = {"chunked": not chunked.monolithic}
            else:
                raw = _load_split_raw(char, macro, self.use_cache)
                attrs = {}
        self.events.counter(
            "panel_cache", value=1, split=split, hit=raw.cache_hit, **attrs,
        )
        return raw

    def _run_transfers(self):
        try:
            from ..parallel.mesh import STOCK_AXIS

            stats = None
            for split in SPLITS:
                raw = self._decode_futures[split].result()
                self._cache_hits[split] = raw.cache_hit
                if split == "train":
                    stats = _finalize_macro(raw.ds, self.macro_idx)
                elif stats is not None:
                    _finalize_macro(raw.ds, self.macro_idx, stats)
                ds = raw.ds
                if self.mesh is not None:
                    ds = ds.pad_stocks(int(self.mesh.shape[STOCK_AXIS]))
                self._datasets[split] = ds
                inject("pipeline/transfer", split=split)
                with self.events.span(f"startup/transfer/{split}"):
                    if self.mesh is not None:
                        self._batches[split] = stream_batch_sharded(
                            ds.full_batch(), self.mesh,
                            events=self.events, split=split,
                            bf16_wire=self.bf16_wire,
                        )
                    else:
                        self._batches[split] = stream_batch(
                            ds.full_batch(),
                            packed=self.packed,
                            device=self.device,
                            bf16_wire=self.bf16_wire,
                            packed_rep=raw.packed,
                            chunk_bytes=self.chunk_bytes,
                        )
            rss = _peak_rss_bytes()
            if rss is not None:
                self.events.gauge("startup/peak_rss", value=rss)
        except BaseException as e:
            self._transfer_error = e

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "StartupPipeline":
        if self._started:
            raise RuntimeError("pipeline already started")
        self._started = True
        if self.compile_fn is not None:
            if self.shapes is None:
                with self.events.span("startup/probe"):
                    self.shapes = probe_split_shapes(self.data_dir)
            self._compile_thread = threading.Thread(
                target=self._run_compile, daemon=True, name="startup-compile"
            )
            self._compile_thread.start()
        # train submitted first so its decode (and therefore its transfer,
        # the one the first phase dispatch waits on) leads the queue
        self._decode_pool = concurrent.futures.ThreadPoolExecutor(
            3, thread_name_prefix="panel-decode"
        )
        for split in SPLITS:
            self._decode_futures[split] = self._decode_pool.submit(
                self._decode_one, split
            )
        self._transfer_thread = threading.Thread(
            target=self._run_transfers, daemon=True, name="startup-transfer"
        )
        self._transfer_thread.start()
        return self

    def result(self) -> PipelineResult:
        """Block until every stage completes; re-raise the first failure."""
        if not self._started:
            self.start()
        self._transfer_thread.join()
        if self._decode_pool is not None:
            self._decode_pool.shutdown(wait=True)
        if self._compile_thread is not None:
            self._compile_thread.join()
        if self._transfer_error is not None:
            raise self._transfer_error
        if self._compile_error is not None:
            raise self._compile_error
        return PipelineResult(
            datasets=tuple(self._datasets[s] for s in SPLITS),
            batches=tuple(self._batches[s] for s in SPLITS),
            compiled=self._compiled,
            cache_hits=dict(self._cache_hits),
        )


# --------------------------------------------------------------------------
# stage 3 helper: early AOT compile of the trainer's phase programs
# --------------------------------------------------------------------------

def trainer_precompile_fn(
    cfg,
    tcfg,
    exec_cfg=None,
    seed: int = 42,
    *,
    share_sdf_program: bool = False,
    has_test: bool = True,
    events: Optional[EventLog] = None,
    heartbeat=None,
    device=None,
    checkpoint_every: Optional[int] = None,
    stop_after_epochs: Optional[int] = None,
    divergence_guard: bool = True,
    guard_max_trips: int = 3,
    mesh=None,
    diag_stride: Optional[int] = None,
) -> Callable[[Dict], Any]:
    """A `compile_fn` for :class:`StartupPipeline`: builds the GAN + Trainer
    and AOT-compiles the three phase-scan programs from header-probed shapes
    (`.lower().compile()` via ``Trainer.precompile``), so compilation hides
    under the load+transfer window. Returns the warm Trainer — hand it to
    ``train_3phase(..., trainer=...)`` to dispatch straight into the
    executables.

    The structs carry an explicit degenerate-mesh sharding
    (``partition.device_sharding``) matching what the streamed transfer
    produces; without it the executables would pay a first-call relayout
    of the big arrays (~10 s at the real shape).

    `mesh`: the --shard_stocks route — structs are built with the
    rule-matched ``partition.batch_shardings`` over stock-padded
    shapes (plus the ``n_assets`` scalar a padded ``full_batch`` carries),
    matching what ``stream_batch_sharded`` lands on the devices, so the
    GSPMD phase programs compile under the same window. `exec_cfg` must
    carry the matching ``shard_mesh``.

    `checkpoint_every` / `stop_after_epochs` must mirror what the training
    run will pass to `Trainer.train` — they reshape the dispatched programs
    into segments, and compiling the whole-phase scans instead would both
    waste the early-compile window and leave the real segment compiles to
    run lazily inside the timed phase. (A RESUMED run's program sizes
    depend on on-disk state; callers should skip the early compile there.)
    """

    def compile_fn(shapes: Dict[str, Dict[str, tuple]]):
        import jax

        from ..models.gan import GAN
        from ..training.trainer import Trainer

        gan = GAN(cfg, exec_cfg)
        params = gan.init(jax.random.key(seed))
        trainer = Trainer(
            gan, tcfg, has_test=has_test,
            share_sdf_program=share_sdf_program,
            events=events, heartbeat=heartbeat,
            divergence_guard=divergence_guard,
            guard_max_trips=guard_max_trips,
            diag_stride=diag_stride,
        )
        if mesh is not None:
            from ..parallel import partition

            sh = partition.batch_shardings(mesh)
            axis = int(mesh.shape[partition.STOCK_AXIS])
            structs = []
            for split in SPLITS:
                entry = {}
                for k, shape in shapes[split].items():
                    if k in ("returns", "mask"):
                        t, n = shape
                        shape = (t, n + (-n) % axis)
                    elif k == "individual":
                        t, n, f = shape
                        shape = (t, n + (-n) % axis, f)
                    entry[k] = jax.ShapeDtypeStruct(
                        tuple(shape), np.float32, sharding=sh[k])
                n = shapes[split]["returns"][1]
                if (-n) % axis:
                    # pad_stocks happened → full_batch carries the true N
                    entry["n_assets"] = jax.ShapeDtypeStruct(
                        (), np.float32, sharding=sh["n_assets"])
                structs.append(entry)
        else:
            from ..parallel import partition

            sharding = partition.device_sharding(device)
            structs = [
                {
                    k: jax.ShapeDtypeStruct(tuple(shape), np.float32,
                                            sharding=sharding)
                    for k, shape in shapes[split].items()
                }
                for split in SPLITS
            ]
        trainer.precompile(params, *structs,
                           checkpoint_every=checkpoint_every,
                           stop_after_epochs=stop_after_epochs)
        return trainer

    return compile_fn
