"""Sequence (context) parallelism: the LSTM recurrence over a time-sharded mesh.

The reference consumes its whole macro history in one ``nn.LSTM`` call on one
device (``/root/reference/src/model.py:65-73``) — fine at T≤300, impossible
when the conditioning history is long (intraday panels, decades of daily
data). This module shards the time axis across a mesh dimension and runs the
recurrence as a device pipeline, the TPU-native counterpart of ring/Ulysses
sequence parallelism for attention models (here the sequential state is an
LSTM carry instead of KV blocks):

  * the input projection ``x @ W_ihᵀ`` — all the MXU FLOPs — runs fully in
    parallel on each device's local [T/D, I] shard;
  * the recurrence runs as a pipeline of D stages: stage d scans the local
    chunk on device d (everyone else skips via `lax.cond`), then hands the
    [H] carry to device d+1 over ICI with a single `ppermute`;
  * total recurrent latency is unchanged (it is inherently sequential) but
    activations, inputs, and outputs stay sharded — memory per device is
    O(T/D) — and the per-step work beyond the tiny [H,4H] matmul rides the
    parallel axis.

Works under `shard_map` on any mesh axis; numerically identical to the
single-device `models.recurrent.lstm_layer` (same scan, same carry chain).
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.recurrent import _gates
from .partition import named_sharding

TIME_AXIS = "time"


def _local_scan(zx_local: jnp.ndarray, w_hh_t: jnp.ndarray, carry):
    """Scan the pre-projected local chunk from `carry` (recurrent.lstm_layer's
    loop body, starting from an arbitrary carry instead of zeros)."""

    def step(c, zx_t):
        h, c_ = c
        return _gates(zx_t + h @ w_hh_t, c_)

    return jax.lax.scan(step, carry, zx_local)


def _pipelined_lstm_local(params: Dict[str, jnp.ndarray], x_local: jnp.ndarray,
                          axis_name: str) -> jnp.ndarray:
    """Per-device body (runs under shard_map): project locally, pipeline the
    carry around the mesh axis."""
    H = params["w_hh"].shape[1]
    n_dev = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    # parallel part: one [T/D, I] x [I, 4H] matmul per device
    zx = x_local @ params["w_ih"].T + (params["b_ih"] + params["b_hh"])
    w_hh_t = params["w_hh"].T

    # mark the constants as device-varying so shard_map's varying-manual-axes
    # typing matches the scan outputs (each device's carry/ys genuinely differ)
    varying = lambda v: jax.lax.pcast(v, (axis_name,), to="varying")
    zeros = (
        varying(jnp.zeros((H,), x_local.dtype)),
        varying(jnp.zeros((H,), x_local.dtype)),
    )
    ys0 = varying(jnp.zeros((x_local.shape[0], H), x_local.dtype))

    def stage(s, state):
        carry, ys = state

        def active(_):
            return _local_scan(zx, w_hh_t, carry)

        def passive(_):
            return carry, ys

        carry, ys = jax.lax.cond(s == idx, active, passive, None)
        # hand the boundary carry to the next device; the only inter-device
        # traffic is 2·H floats per stage over ICI
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        carry = jax.tree.map(
            lambda c: jax.lax.ppermute(c, axis_name, perm), carry
        )
        return carry, ys

    _, ys = jax.lax.fori_loop(0, n_dev, stage, (zeros, ys0))
    return ys


def sequence_sharded_lstm(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,  # [T, I], T divisible by the mesh axis size
    mesh: Mesh,
    axis_name: str = TIME_AXIS,
) -> jnp.ndarray:
    """LSTM over a time-sharded sequence: x [T, I] -> h [T, H].

    `params` uses the torch layout of `models.recurrent.TorchLSTM`
    (w_ih [4H, I], w_hh [4H, H], b_ih, b_hh). Output is sharded like the
    input. Bit-identical to `models.recurrent.lstm_layer` up to matmul
    reassociation (same hoisted projection, same carry chain).
    """
    if x.shape[0] % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"sequence length {x.shape[0]} must divide over mesh axis "
            f"{axis_name!r} (size {mesh.shape[axis_name]}); pad the sequence"
        )
    fn = jax.shard_map(
        partial(_pipelined_lstm_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(), P(axis_name, None)),
        out_specs=P(axis_name, None),
    )
    return fn(params, x)


def shard_sequence(x: jnp.ndarray, mesh: Mesh, axis_name: str = TIME_AXIS):
    """device_put a [T, ...] array sharded along time."""
    spec = P(axis_name, *([None] * (x.ndim - 1)))
    return jax.device_put(x, named_sharding(mesh, spec))
