"""Dual roofline accounting: analytic FLOPs + HBM bytes per training pass.

VERDICT r4 asked for the bandwidth story (hbm_utilization) to be joined by a
compute story (achieved FLOP/s, MFU), so the "ensemble compute floor" claim
in docs/ARCHITECTURE.md is settled against hardware, not against the current
kernel structure. Everything here is a pure function of shapes — no device
access — so `bench.py` can attach it to measured epoch times and tests can
pin the formulas.

The FLOP counts are USEFUL flops (true model dimensions, 2·MACs): MFU =
useful / elapsed / peak. The model's matmuls are 64-wide or narrower
([64,46], [64,64], [1,64], [8,224] against the long stock axis), so a naive
whole-peak MFU target is unreachable on a 128×128 MXU — but how much of the
peak these specific shapes CAN sustain is an empirical property of the chip,
not something to hand-model (a 128³ tile-padding model was tried and
falsified: it predicted >100% physical utilization, i.e. the hardware does
not pay full-tile padding on narrow matmuls). `bench.py`'s
`matmul_ceiling` section therefore MEASURES the per-shape ceilings
standalone, and `roofline_summary` accepts that measured ceiling to turn
"the epoch is f% of the shape-ceiling floor" into evidence.

Model structure being counted (paper defaults, `models/networks.py`):
  SDF FFN   : panel rows [F=46] → 64 → 64 → 1, per-period macro bias zp
              (precomputed in XLA from the LSTM state — counted separately)
  Moment net: concat(panel row [F], raw macro [M]) → K=8 moments
  Macro LSTM: M → 4 units, one step per period (negligible but counted)

Backward passes follow the kernels' recompute-based custom_vjp
(`ops/pallas_ffn.py`): bwd = forward recompute + dgrad chain + wgrad, with
no dx (the panel cotangent is never needed — inputs aren't trained).

Hardware peaks are the public TPU v5e spec: 197 TFLOP/s bf16, 819 GB/s HBM.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

# public TPU v5e per-chip peaks
PEAK_BF16_FLOPS = 197e12
HBM_PEAK_GBPS = 819.0


def _matmul_flops(m: int, k: int, n: int) -> float:
    """Useful FLOPs (2·MACs) of an [m,k]×[k,n] matmul."""
    return 2.0 * m * k * n


def ffn_matmul_shapes(F: int, hidden: Sequence[int] = (64, 64)
                      ) -> List[Tuple[int, int]]:
    """The fused FFN's per-period matmul (rows, contract) pairs against the
    stock axis — the shapes whose throughput ceiling bench.py measures."""
    dims = [F, *hidden, 1]
    return [(out, inp) for inp, out in zip(dims[:-1], dims[1:])]


def ffn_flops_per_pass(
    T: int, N: int, F: int, hidden: Sequence[int] = (64, 64),
    mode: str = "fwd",
) -> float:
    """FLOPs of one fused-FFN panel pass (`ops/pallas_ffn.py`).

    fwd: x[F,BN] → h1[H1,BN] → h2[H2,BN] → w[1,BN] per period.
    bwd: recompute of fwd + dgrad (dh_i = W_{i+1}ᵀ dh_{i+1}, no dx to the
    panel) + wgrad ([H,BN]×[BN,H'] contractions over the stock tile).
    """
    layers = ffn_matmul_shapes(F, hidden)
    fwd = sum(_matmul_flops(out, inp, N) for out, inp in layers)
    if mode == "fwd":
        return T * fwd
    if mode != "bwd":
        raise ValueError(f"mode must be fwd|bwd, got {mode!r}")
    dgrad = sum(_matmul_flops(inp, out, N) for out, inp in layers[1:])
    wgrad = sum(_matmul_flops(out, inp, N) for out, inp in layers)
    return T * (fwd + dgrad + wgrad)


def moment_flops_per_pass(
    T: int, N: int, F: int, M: int, K: int = 8, mode: str = "fwd",
) -> float:
    """FLOPs of one fused moment-net pass (`ops/pallas_moment.py`):
    concat(panel row, raw macro) → K moment weights, contracted into the
    [K] empirical means in-kernel (one more K-row MAC per element)."""
    inp = F + M
    fwd = _matmul_flops(K, inp, N) + 2.0 * K * N  # + mean contraction
    if mode == "fwd":
        return T * fwd
    if mode != "bwd":
        raise ValueError(f"mode must be fwd|bwd, got {mode!r}")
    return T * (fwd + _matmul_flops(K, inp, N) + 2.0 * K * N)


def lstm_flops(T: int, M: int, units: Sequence[int] = (4,),
               mode: str = "fwd") -> float:
    """Macro LSTM: 4 gates × (in+U)×U MACs per period per layer; bwd ≈ 2×.
    At M=178, U=4 this is ~0.7 MFLOP/epoch — 5 orders below the panel."""
    flops = 0.0
    inp = M
    for u in units:
        flops += T * 4 * 2.0 * (inp + u) * u
        inp = u
    return flops * (1.0 if mode == "fwd" else 3.0)


def phase_epoch_flops(
    shapes: Dict[str, int],
    hidden: Sequence[int] = (64, 64),
    M: int = 178,
    K: int = 8,
    rnn_units: Sequence[int] = (4,),
    phase: str = "phase3",
) -> float:
    """FLOPs of ONE epoch of a phase, mirroring `bench._bandwidth_accounting`
    pass structure: train fwd+bwd on T_train, plus fwd-only valid AND test
    evaluation every epoch (FFN + moment net both — the eval computes the
    conditional loss)."""
    Tt, Tv, Te = shapes["T_train"], shapes["T_valid"], shapes["T_test"]
    N, F = shapes["N"], shapes["F"]

    def ffn(T, mode):
        return (ffn_flops_per_pass(T, N, F, hidden, mode)
                + lstm_flops(T, M, rnn_units, mode))

    def mom(T, mode):
        return moment_flops_per_pass(T, N, F, M, K, mode)

    eval_flops = ffn(Tv + Te, "fwd") + mom(Tv + Te, "fwd")
    if phase == "phase1":  # unconditional: no moment net in the train step
        return ffn(Tt, "fwd") + ffn(Tt, "bwd") + eval_flops
    if phase == "phase2":  # moment update: SDF frozen, moment net trains
        return (ffn(Tt, "fwd") + mom(Tt, "fwd") + mom(Tt, "bwd")
                + eval_flops)
    if phase == "phase3":  # conditional: FFN + moment net fwd+bwd
        return (ffn(Tt, "fwd") + ffn(Tt, "bwd")
                + mom(Tt, "fwd") + mom(Tt, "bwd") + eval_flops)
    raise ValueError(f"phase must be phase1|phase2|phase3, got {phase!r}")


def schedule_flops(
    shapes: Dict[str, int],
    epochs: Tuple[int, int, int] = (256, 64, 1024),
    hidden: Sequence[int] = (64, 64),
    M: int = 178,
    K: int = 8,
) -> float:
    """Useful FLOPs of the whole 3-phase schedule (per member)."""
    return sum(
        n * phase_epoch_flops(shapes, hidden, M, K, phase=ph)
        for n, ph in zip(epochs, ("phase1", "phase2", "phase3"))
    )


def roofline_summary(
    epoch_seconds: float,
    shapes: Dict[str, int],
    phase: str = "phase3",
    n_members: int = 1,
    panel_bytes_per_epoch: float = None,
    shape_ceiling_tflops: float = None,
    hidden: Sequence[int] = (64, 64),
    M: int = 178,
    K: int = 8,
) -> Dict:
    """Join a MEASURED epoch time with the analytic FLOPs and bytes into the
    dual roofline: which wall (HBM or MXU) the epoch is near, and how near.

    `n_members`: member-fused runs execute n× the FLOPs on ~1× the panel
    bytes (one HBM read serves every member), which is exactly why the
    single-model epoch sits on the bandwidth side of the ridge and the
    9-member epoch on the compute side (intensity scales with n_members).

    `shape_ceiling_tflops`: measured sustained throughput of the model's own
    matmul shapes (bench.py `matmul_ceiling`); when given, the compute
    floor uses it instead of the whole-chip peak these narrow matmuls
    cannot reach.
    """
    useful = n_members * phase_epoch_flops(shapes, hidden, M, K, phase=phase)
    return _summarize(useful, epoch_seconds, panel_bytes_per_epoch,
                      shape_ceiling_tflops, label="per_epoch")


def schedule_roofline_summary(
    wall_seconds: float,
    shapes: Dict[str, int],
    epochs: Tuple[int, int, int] = (256, 64, 1024),
    n_members: int = 1,
    panel_bytes_total: float = None,
    shape_ceiling_tflops: float = None,
    hidden: Sequence[int] = (64, 64),
    M: int = 178,
    K: int = 8,
) -> Dict:
    """Roofline for a full 3-phase run (e.g. the 9-member ensemble's warm
    wall): useful FLOPs of the whole schedule × members vs the measured
    wall — the MFU-backed form of the ensemble compute-floor claim."""
    useful = n_members * schedule_flops(shapes, epochs, hidden, M, K)
    return _summarize(useful, wall_seconds, panel_bytes_total,
                      shape_ceiling_tflops, label="schedule")


def _summarize(useful: float, elapsed: float, nbytes: float,
               shape_ceiling_tflops: float, label: str) -> Dict:
    out = {
        f"useful_gflops_{label}": round(useful / 1e9, 2),
        "achieved_tflops": round(useful / elapsed / 1e12, 2),
        "mfu": round(useful / elapsed / PEAK_BF16_FLOPS, 4),
        "peak_bf16_tflops": PEAK_BF16_FLOPS / 1e12,
    }
    ceiling = (shape_ceiling_tflops * 1e12 if shape_ceiling_tflops
               else PEAK_BF16_FLOPS)
    if shape_ceiling_tflops:
        out["shape_ceiling_tflops"] = round(shape_ceiling_tflops, 2)
        out["fraction_of_shape_ceiling"] = round(
            useful / elapsed / ceiling, 3)
    if nbytes:
        intensity = useful / nbytes
        ridge = ceiling / (HBM_PEAK_GBPS * 1e9)
        out["arithmetic_intensity_flop_per_byte"] = round(intensity, 1)
        out["ridge_intensity_flop_per_byte"] = round(ridge, 1)
        out["bound"] = "hbm" if intensity < ridge else "mxu"
        # roofline bound on elapsed time given both walls
        t_hbm = nbytes / (HBM_PEAK_GBPS * 1e9)
        t_mxu = useful / ceiling
        out["roofline_floor_ms"] = round(max(t_hbm, t_mxu) * 1e3, 3)
        out["floor_components_ms"] = {
            "hbm": round(t_hbm * 1e3, 3),
            ("mxu_at_shape_ceiling" if shape_ceiling_tflops else
             "mxu_at_peak"): round(t_mxu * 1e3, 3),
        }
        out["fraction_of_roofline_floor"] = round(
            max(t_hbm, t_mxu) / elapsed, 3)
    return out
