"""Multi-seed ensembles as a vmapped axis — train 9 models in ONE program.

The reference trains its 9-seed ensemble serially (~6 h CPU,
``demo_full.ipynb`` cell 22) and evaluates it with a serial per-model loop
(``/root/reference/src/evaluate_ensemble.py:112-131``). Here the seed axis is
a `jax.vmap` axis over the whole 3-phase compiled trainer: one XLA program
trains every member simultaneously (the per-member matmuls batch onto the
MXU), and the same axis can be laid out over a ('batch', 'stocks') device
mesh so members and panel shards ride separate mesh dimensions.

Evaluation replicates the paper's protocol exactly
(evaluate_ensemble.py:137-171): average the members' abs-sum-normalized
weights, re-normalize per period, compute portfolio returns, and report the
Sharpe of the NEGATED return series with numpy (ddof=0) std.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gan import GAN
from ..ops.metrics import (
    cross_sectional_r2,
    explained_variation,
    factor_betas,
    normalize_weights_abs,
    sharpe,
)
from ..utils.config import ExecutionConfig, GANConfig, TrainConfig
from ..utils.rng import train_base_key
from ..training.trainer import build_phase_scan, fresh_best
from ..training.steps import make_optimizer, trainable_key

Params = jax.Array
Batch = Dict[str, jax.Array]


def init_ensemble_params(gan: GAN, seeds: Sequence[int]):
    """Stack per-seed init params along a leading ensemble axis [S, ...]."""
    keys = jnp.stack([jax.random.key(int(s)) for s in seeds])
    return jax.vmap(lambda k: gan.init(k))(keys)


def run_member_chunks(run_one, items, chunk):
    """Run `run_one(sub_items)` over `items` split into `chunk`-sized groups
    and concatenate the resulting pytrees of arrays along axis 0.

    THE member-chunking primitive shared by the ensemble and sweep engines:
    caps a vmapped program's member axis so the XLA route's ~2.1 GB/member
    activations (real panel shape) fit the device. Chunks re-trace their
    programs, but equal-size chunks hit the persistent XLA compilation
    cache, so only the first chunk pays a real compile.
    """
    parts = [run_one(items[i:i + chunk]) for i in range(0, len(items), chunk)]

    def cat(*xs):
        if isinstance(xs[0], np.ndarray):
            return np.concatenate(xs, axis=0)
        return jnp.concatenate(xs, axis=0)

    return jax.tree.map(cat, *parts)


def train_ensemble(
    config: GANConfig,
    train_batch: Batch,
    valid_batch: Batch,
    test_batch: Optional[Batch] = None,
    seeds: Sequence[int] = (42, 123, 456, 789, 1000, 2000, 3000, 4000, 5000),
    tcfg: Optional[TrainConfig] = None,
    member_sharding=None,
    verbose: bool = True,
    member_chunk: Optional[int] = None,
) -> Tuple[GAN, Params, Dict[str, np.ndarray]]:
    """Train len(seeds) models with the full 3-phase schedule, vmapped.

    `member_sharding`: optional NamedSharding (e.g. P('batch')) to lay the
    ensemble axis over a mesh dimension — each device group trains its
    members while the panel stays sharded/replicated per the batch arrays.

    `member_chunk`: train at most this many members per vmapped program,
    running chunks sequentially and concatenating. Use when the full member
    axis overflows HBM on a small device count — at the real panel shape the
    XLA route needs ~2.1 GB of activations per member, so one 16 GB chip
    fits ~5 members at once (9 seeds -> member_chunk=5 or 3). Chunks of
    equal size reuse one compiled program.

    Returns (gan, stacked final params [S, ...], history dict [S, E]).
    """
    tcfg = tcfg or TrainConfig()
    if member_chunk is not None and 0 < member_chunk < len(seeds):
        gan_box = []

        def run_one(seed_group):
            gan, vparams, history = train_ensemble(
                config, train_batch, valid_batch, test_batch,
                seeds=seed_group, tcfg=tcfg,
                member_sharding=member_sharding, verbose=verbose,
            )
            gan_box.append(gan)
            return {"params": vparams, "history": history}

        out = run_member_chunks(run_one, list(seeds), member_chunk)
        return gan_box[0], out["params"], out["history"]
    # vmapped training: keep the XLA route (vmap-of-pallas custom_vjp is
    # not supported; the XLA path vmaps cleanly).
    # Measured alternative, rejected: lax.map over members with the fused
    # kernel inside (sequential members at single-model kernel speed would
    # beat vmapped-XLA ~2.6x per member-epoch on one HBM-bound chip — 19.7
    # vs 7.5 ms at the real shape) compiles fine on small panels (~10 s)
    # but the map-of-scan-of-custom_vjp program fails to finish compiling
    # at N=10,000 (>20 min, 2026-07). Revisit if Mosaic compile scaling
    # improves.
    gan = GAN(config, ExecutionConfig(pallas_ffn="off"))
    S = len(seeds)
    has_test = test_batch is not None
    if test_batch is None:
        test_batch = valid_batch

    vparams = init_ensemble_params(gan, seeds)
    if member_sharding is not None:
        vparams = jax.device_put(vparams, member_sharding)
    tx_sdf = make_optimizer(tcfg.lr, tcfg.grad_clip)
    tx_moment = make_optimizer(tcfg.lr, tcfg.grad_clip)
    base_keys = jnp.stack([train_base_key(s) for s in seeds])
    phase_keys = jax.vmap(lambda k: jax.random.split(k, 3))(base_keys)  # [S, 3]

    opt_sdf = jax.vmap(tx_sdf.init)(vparams[trainable_key("unconditional")])
    opt_moment = jax.vmap(tx_moment.init)(vparams[trainable_key("moment")])

    def vrun(phase, tx, num_epochs, params, opt, best, key_idx):
        run = build_phase_scan(gan, phase, tx, num_epochs, tcfg.ignore_epoch, has_test)
        vmapped = jax.vmap(run, in_axes=(0, 0, 0, None, None, None, 0))
        return jax.jit(vmapped)(
            params, opt, best, train_batch, valid_batch, test_batch,
            phase_keys[:, key_idx],
        )

    def log(msg):
        if verbose:
            print(msg, flush=True)

    log(f"Ensemble: {S} seeds × ({tcfg.num_epochs_unc}+{tcfg.num_epochs_moment}"
        f"+{tcfg.num_epochs}) epochs, one vmapped program per phase")

    # Phase 1
    best1 = jax.vmap(fresh_best)(vparams)
    vparams, opt_sdf, best1, h1 = vrun(
        "unconditional", tx_sdf, tcfg.num_epochs_unc, vparams, opt_sdf, best1, 0
    )
    vparams = _vselect(best1["updated_sharpe"], best1["params_sharpe"], vparams)
    params_phase1_best = vparams

    # Phase 2
    if tcfg.num_epochs_moment > 0:
        best2 = jax.vmap(partial(fresh_best, for_moment=True))(vparams)
        vparams, opt_moment, best2, _h2 = vrun(
            "moment", tx_moment, tcfg.num_epochs_moment, vparams, opt_moment, best2, 1
        )

    # Phase 3
    best3 = jax.vmap(fresh_best)(vparams)
    vparams, opt_sdf, best3, h3 = vrun(
        "conditional", tx_sdf, tcfg.num_epochs, vparams, opt_sdf, best3, 2
    )
    final = _vselect(
        best3["updated_sharpe"], best3["params_sharpe"],
        _vselect(best1["updated_sharpe"], params_phase1_best, vparams),
    )

    history = {
        k: np.concatenate([np.asarray(h1[k]), np.asarray(h3[k])], axis=1)
        for k in h1
    }
    log("Ensemble training complete")
    return gan, final, history


def _vselect(pred_vec, new_tree, old_tree):
    """Per-member select: pred [S] broadcast against leading axis of leaves."""
    def sel(a, b):
        pred = pred_vec.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(pred, a, b)

    return jax.tree.map(sel, new_tree, old_tree)


# -- paper-protocol ensemble evaluation -------------------------------------


def _xla_route(gan: GAN) -> GAN:
    """The GAN with the plain-XLA execution route, for vmapped use.

    vmap-of-pallas is avoided everywhere members are mapped (training AND
    evaluation): the custom_vjp has no batching rule, and the XLA route vmaps
    cleanly. This is the single place the vmapped-eval decision lives;
    checkpoint-loaded GANs (default 'auto' route) pass through here too.
    """
    if gan.exec_cfg.pallas_ffn == "off":
        return gan
    from ..utils.config import ExecutionConfig as _EC

    return GAN(gan.cfg, _EC(pallas_ffn="off"))


def member_weights(gan: GAN, vparams, batch: Batch) -> jax.Array:
    """[S, T, N] abs-sum-normalized weights for every member, one vmap."""
    gan = _xla_route(gan)
    return jax.vmap(lambda p: gan.normalized_weights(p, batch))(vparams)


def ensemble_metrics(
    gan: GAN, vparams, batch: Batch
) -> Dict[str, np.ndarray]:
    """The reference's ensemble math (evaluate_ensemble.py:137-171), fused:

    mean member weights → re-normalize |w| to 1 per period (only where the
    abs-sum exceeds 1e-8, matching the reference's guard) → portfolio
    returns → Sharpe of the NEGATED series, ddof=0.

    Also returns each member's individual (negated) Sharpe.
    """

    @jax.jit
    def compute(vparams, batch):
        w = member_weights(gan, vparams, batch)  # [S, T, N]
        return _ensemble_math(w, batch)

    out = compute(vparams, batch)
    return {k: np.asarray(v) for k, v in out.items()}


def _ensemble_math(w: jnp.ndarray, batch: Batch) -> Dict[str, jnp.ndarray]:
    """The shared paper-protocol reduction from stacked member weights
    [S, T, N]: mean → re-normalize (guarded, evaluate_ensemble.py:142-157) →
    portfolio returns → negated ddof=0 Sharpe, plus the paper's Table-1
    EV / XS-R² companions the reference's evaluator lacks."""
    mask, returns = batch["mask"], batch["returns"]
    indiv_port = (w * returns * mask).sum(axis=2)  # [S, T]
    indiv_sharpe = jax.vmap(lambda r: sharpe(-r, ddof=0))(indiv_port)

    avg = w.mean(axis=0)  # [T, N]
    abs_sum = (jnp.abs(avg) * mask).sum(axis=1, keepdims=True)
    avg = jnp.where(abs_sum > 1e-8, avg / abs_sum, avg)
    port = (avg * returns * mask).sum(axis=1)  # [T]
    betas = factor_betas(returns, port, mask)
    return {
        "ensemble_sharpe": sharpe(-port, ddof=0),
        "ensemble_port_returns": port,
        "individual_sharpes": indiv_sharpe,
        "avg_weights": avg,
        "explained_variation": explained_variation(returns, port, mask, betas),
        "cross_sectional_r2": cross_sectional_r2(returns, port, mask, betas),
    }


_jitted_ensemble_math = jax.jit(_ensemble_math)


def ensemble_metrics_from_weights(
    member_w: jnp.ndarray, batch: Batch
) -> Dict[str, np.ndarray]:
    """Same paper-protocol math as :func:`ensemble_metrics`, but starting from
    stacked per-member normalized weights [S, T, N] instead of params.

    This is how members with DIFFERENT architectures ensemble (the reference
    averages [T, N] weight matrices, never params — evaluate_ensemble.py:
    137-139), e.g. the grand ensemble across the sweep's top-k configs.
    """
    out = _jitted_ensemble_math(jnp.asarray(member_w), batch)
    return {k: np.asarray(v) for k, v in out.items()}
