"""Plots, summary statistics, and downloader gating."""

from pathlib import Path

import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.data.download import (
    EXPECTED_SIZES_BYTES,
    check_data_exists,
    validate_sizes,
)

matplotlib = pytest.importorskip("matplotlib")


@pytest.fixture(scope="module")
def trained_ckpts(synthetic_dir, tmp_path_factory):
    """Two tiny trained runs to feed the reporting layer."""
    import jax.numpy as jnp

    from deeplearninginassetpricing_paperreplication_tpu import (
        GANConfig,
        TrainConfig,
        load_splits,
    )
    from deeplearninginassetpricing_paperreplication_tpu.training.trainer import (
        train_3phase,
    )

    train, valid, test = load_splits(synthetic_dir)
    b = lambda ds: {k: jnp.asarray(v) for k, v in ds.full_batch().items()}
    cfg = GANConfig(
        macro_feature_dim=train.macro_feature_dim,
        individual_feature_dim=train.individual_feature_dim,
        hidden_dim=(8,), num_units_rnn=(3,), num_condition_moment=4,
    )
    tcfg = TrainConfig(num_epochs_unc=3, num_epochs_moment=2, num_epochs=4,
                       ignore_epoch=0, seed=0)
    root = tmp_path_factory.mktemp("ckpts")
    dirs = []
    for seed in (1, 2):
        d = root / f"s{seed}"
        train_3phase(cfg, b(train), b(valid), b(test), tcfg=tcfg,
                     save_dir=str(d), seed=seed, verbose=False)
        dirs.append(str(d))
    return dirs


@pytest.mark.slow
def test_generate_all_plots(trained_ckpts, synthetic_dir, tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.plots import (
        generate_all_plots,
    )

    written = generate_all_plots(trained_ckpts, str(synthetic_dir), str(tmp_path))
    assert len(written) == 5
    for f in written:
        assert Path(f).exists() and Path(f).stat().st_size > 5000  # real PNGs


def test_summary_statistics_consistent(trained_ckpts, synthetic_dir):
    from deeplearninginassetpricing_paperreplication_tpu.plots import (
        summary_statistics,
    )

    stats = summary_statistics(trained_ckpts, str(synthetic_dir))
    assert np.isclose(
        stats["sharpe_annual"], stats["sharpe_monthly"] * np.sqrt(12), rtol=1e-6
    )
    assert stats["max_drawdown"] <= 0
    assert stats["min"] <= stats["max"]
    # the table's monthly sharpe must equal the ensemble metric (ddof=0)
    from deeplearninginassetpricing_paperreplication_tpu.evaluate_ensemble import (
        evaluate_ensemble,
    )

    res = evaluate_ensemble(trained_ckpts, str(synthetic_dir), verbose=False)
    assert np.isclose(stats["sharpe_monthly"], res["test_sharpe"], rtol=1e-5)


def test_check_data_exists_and_sizes(tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.data.download import (
        REQUIRED_FILES,
    )

    assert not check_data_exists(tmp_path, verbose=False)
    for sub, name in (("char", "Char_train.npz"), ("macro", "macro_train.npz")):
        (tmp_path / sub).mkdir(exist_ok=True)
        (tmp_path / sub / name).write_bytes(b"x" * 100)
    assert not check_data_exists(tmp_path, verbose=False)  # still 4 missing
    sizes = validate_sizes(tmp_path)
    assert sizes["Char_train.npz"] is False  # 100 bytes << 317 MB
    assert set(EXPECTED_SIZES_BYTES) == {n for _, n in REQUIRED_FILES}


def test_download_requires_gdown(tmp_path):
    """Without gdown, download_all_data must raise the gated ImportError
    pointing at the synthetic generator (not a bare ModuleNotFoundError)."""
    try:
        import gdown  # noqa

        pytest.skip("gdown installed; gate not exercised")
    except ImportError:
        pass
    from deeplearninginassetpricing_paperreplication_tpu.data.download import (
        download_all_data,
    )

    with pytest.raises(ImportError, match="synthetic"):
        download_all_data(tmp_path, force=True)
