"""Tier-1 coverage for elastic sweep orchestration (reliability/ledger.py +
scheduler.py), CPU-only.

Covers the acceptance-criterion fault matrix end to end:
  * the durable bucket ledger: content keys, verified records, generation
    fallback, quarantine markers, reset;
  * the file-locked work queue: claim/complete/drain, no double-claims,
    lease expiry → takeover, retry backoff, poison quarantine after K
    failed claims, lease-keeper renewal and loss detection;
  * fleet-wide fault counters (shared DLAP_FAULT_STATE under a lock) and
    ``persistent`` plan entries;
  * quorum semantics: member_validity / apply_quorum, stack_checkpoints
    ``allow_missing`` with skipped-dir reporting, evaluate-time quorum;
  * verified ranking artifacts: write_ranking sidecars, load_ranking
    digest failure naming the file;
  * supervisor sweep-resume detection (``--resume-from-ledger``);
  * the report CLI's elastic section;
  * the headline fault matrix: a SUPERVISED 2-worker sweep killed at
    ``sweep/claim``, mid-bucket, and ``sweep/ledger_write`` completes with
    a ranking BYTE-identical to an uninterrupted run and zero completed
    buckets re-trained; a poison bucket (persistent raise) quarantines
    after K attempts and the degraded ranking ships with an accurate
    coverage manifest; a supervised single-process sweep resumes from the
    ledger (asserted via ledger-hit counters).

Unit tests are in-process and fast; only the three CLI scenarios pay real
sweep subprocesses (on a deliberately tiny synthetic panel).
"""

import json
import os
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.reliability import (
    faults,
    verified,
)
from deeplearninginassetpricing_paperreplication_tpu.reliability.ledger import (
    SweepLedger,
    bucket_key,
    make_record,
)
from deeplearninginassetpricing_paperreplication_tpu.reliability.scheduler import (
    LeaseKeeper,
    WorkQueue,
)
from deeplearninginassetpricing_paperreplication_tpu.reliability.supervisor import (
    RestartPolicy,
    Supervisor,
)

REPO = Path(__file__).resolve().parents[1]
PKG = "deeplearninginassetpricing_paperreplication_tpu"


@pytest.fixture(autouse=True)
def _fresh_injector(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    monkeypatch.delenv(faults.ENV_EVENTS, raising=False)
    faults.reset_injector()
    yield
    faults.reset_injector()


class _Counters:
    """Stub events sink capturing counter rows (the WorkQueue contract)."""

    def __init__(self):
        self.rows = []

    def counter(self, name, value=1, **attrs):
        self.rows.append(dict(attrs, name=name, value=value))

    def named(self, name):
        return [r for r in self.rows if r["name"] == name]


def _tiny_cfg():
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        GANConfig,
    )

    return GANConfig(macro_feature_dim=0, individual_feature_dim=4,
                     hidden_dim=(4,), use_rnn=False, hidden_dim_moment=(),
                     num_condition_moment=2)


def _items(n, cfg=None):
    config = (cfg or _tiny_cfg()).to_dict()
    return [{"key": f"k{i}", "index": i, "config": config, "lrs": [1e-3]}
            for i in range(n)]


def _record(key, i):
    return make_record(key, i, _tiny_cfg().to_dict(), [1e-3], [7],
                       [[1e-3, 7]], [0.1 * (i + 1)], worker="t")


# --------------------------------------------------------------------------
# bucket keys + ledger records
# --------------------------------------------------------------------------

def test_bucket_key_is_content_addressed():
    cfg = _tiny_cfg().to_dict()
    tcfg = {"num_epochs": 4}
    k = bucket_key(cfg, [1e-3, 5e-4], [7], tcfg)
    assert k == bucket_key(dict(cfg), [1e-3, 5e-4], [7], dict(tcfg))
    # lr ORDER is part of the identity (it fixes the vmapped grid layout)
    assert k != bucket_key(cfg, [5e-4, 1e-3], [7], tcfg)
    assert k != bucket_key(cfg, [1e-3, 5e-4], [8], tcfg)
    assert k != bucket_key(cfg, [1e-3, 5e-4], [7], {"num_epochs": 5})
    assert k != bucket_key(dict(cfg, dropout=0.1), [1e-3, 5e-4], [7], tcfg)


def test_ledger_records_verified_with_generation_fallback(tmp_path):
    led = SweepLedger(tmp_path)
    rec = _record("k1", 0)
    led.write("k1", rec)
    assert led.has("k1")
    assert SweepLedger(tmp_path).load("k1")["best_valid_sharpe"] == [0.1]
    # non-finite Sharpes serialize as null (→ -inf on ranking rebuild)
    assert make_record("k2", 1, {}, [1e-3], [7], [[1e-3, 7]],
                       [float("nan")])["best_valid_sharpe"] == [None]

    led.write("k1", rec)  # rotates the first write to .g1
    path = led.record_path("k1")
    with open(path, "r+b") as f:
        f.truncate(5)
    with pytest.warns(UserWarning, match="fell back"):
        assert led.load("k1")["key"] == "k1"
    with open(verified.generation_path(path, 1), "r+b") as f:
        f.truncate(5)
    with pytest.raises(ValueError, match="k1.json"):
        led.load("k1")


def test_ledger_quarantine_and_reset(tmp_path):
    led = SweepLedger(tmp_path)
    led.write("ka", _record("ka", 0))
    led.quarantine("kb", {"attempts": 2, "index": 1})
    assert led.is_quarantined("kb") and not led.is_quarantined("ka")
    assert led.quarantined()["kb"]["attempts"] == 2
    led.reset()
    assert not led.has("ka") and not led.is_quarantined("kb")
    assert led.keys() == []


# --------------------------------------------------------------------------
# work queue: claims, leases, takeover, quarantine
# --------------------------------------------------------------------------

def _queue(tmp_path, events=None, **kw):
    kw.setdefault("lease_timeout_s", 30.0)
    kw.setdefault("max_attempts", 3)
    kw.setdefault("backoff", RestartPolicy(backoff_base_s=0.0,
                                           backoff_max_s=0.0,
                                           jitter_frac=0.0))
    return WorkQueue(tmp_path, events=events, **kw)


def test_queue_claims_are_exclusive_and_drain(tmp_path):
    q = _queue(tmp_path)
    q.write_manifest(_items(2), {"kind": "sweep_queue"})
    s, a = q.claim("w0")
    assert s == "claimed" and a["index"] == 0 and a["attempt"] == 1
    s, b = q.claim("w1")
    assert s == "claimed" and b["index"] == 1  # never the same bucket twice
    assert q.claim("w2") == ("wait", None)  # all leased, none done

    q.ledger.write(a["key"], _record(a["key"], 0))
    q.complete(a["key"], "w0")
    assert q.claim("w0") == ("wait", None)  # b still leased by w1
    q.ledger.write(b["key"], _record(b["key"], 1))
    q.complete(b["key"], "w1")
    assert q.claim("w0") == ("drained", None)
    assert q.status() == {"total": 2, "completed": 2, "quarantined": 0,
                          "leased": 0, "pending": 0}


def test_queue_lease_expiry_is_taken_over_and_counted(tmp_path):
    ev = _Counters()
    q = _queue(tmp_path, events=ev, lease_timeout_s=0.2)
    q.write_manifest(_items(1), {})
    s, a = q.claim("w0")
    assert s == "claimed"
    assert q.claim("w1") == ("wait", None)  # lease still live
    time.sleep(0.25)  # w0 presumed dead: its lease expired
    s, b = q.claim("w1")
    assert s == "claimed" and b["attempt"] == 2
    assert len(ev.named("sweep/lease_takeover")) == 1
    assert ev.named("sweep/lease_takeover")[0]["from_worker"] == "w0"
    assert len(ev.named("sweep/retry")) == 1
    assert len(ev.named("sweep/claim")) == 2


def test_queue_failed_claims_quarantine_poison_bucket(tmp_path):
    ev = _Counters()
    q = _queue(tmp_path, events=ev, max_attempts=2, lease_timeout_s=30.0)
    q.write_manifest(_items(1), {})
    for attempt in (1, 2):
        s, a = q.claim("w0")
        assert s == "claimed" and a["attempt"] == attempt
        q.fail(a["key"], "w0", error="synthetic poison")
    # third scan: 2 attempts consumed without completing → quarantine
    assert q.claim("w0") == ("drained", None)
    assert q.ledger.is_quarantined("k0")
    marker = q.ledger.quarantined()["k0"]
    assert marker["attempts"] == 2
    assert marker["history"][-1]["error"] == "synthetic poison"
    assert len(ev.named("sweep/quarantine")) == 1
    assert q.status()["quarantined"] == 1


def test_queue_retry_backoff_gates_reclaim(tmp_path):
    q = _queue(tmp_path, max_attempts=5,
               backoff=RestartPolicy(backoff_base_s=0.3, backoff_max_s=0.3,
                                     jitter_frac=0.0))
    q.write_manifest(_items(1), {})
    s, a = q.claim("w0")
    q.fail(a["key"], "w0", error="boom")
    # inside the backoff window the bucket is pending, not claimable
    assert q.claim("w0") == ("wait", None)
    time.sleep(0.35)
    s, b = q.claim("w0")
    assert s == "claimed" and b["attempt"] == 2


def test_lease_keeper_renews_and_flags_loss(tmp_path):
    q = _queue(tmp_path, lease_timeout_s=0.3)
    q.write_manifest(_items(1), {})
    s, a = q.claim("w0")
    with LeaseKeeper(q, a["key"], "w0") as keeper:
        time.sleep(0.5)  # past the timeout: only renewal keeps it alive
        assert q.claim("w1") == ("wait", None)
        assert not keeper.lost
        # another worker takes the lease (as after a presumed death)
        (q.leases_dir / f"{a['key']}.json").write_text(json.dumps(
            {"worker": "w1", "ts": time.time()}))
        deadline = time.time() + 2.0
        while not keeper.lost and time.time() < deadline:
            time.sleep(0.05)
        assert keeper.lost
    # a lost keeper must not have clobbered the new owner's lease
    lease = json.loads((q.leases_dir / f"{a['key']}.json").read_text())
    assert lease["worker"] == "w1"


def test_lease_keeper_beats_heartbeat_until_budget_expires(tmp_path):
    """While a bucket trains, the keeper beats the worker heartbeat (a
    long dispatch must NOT be hang-killed) — until the per-bucket wall
    budget runs out, after which it goes silent (renewals AND beats stop)
    so the watchdog/lease machinery reclaims a genuinely hung bucket."""

    class _Beats:
        def __init__(self):
            self.sections = []

        def beat(self, section, **kw):
            self.sections.append(section)

    hb = _Beats()
    q = _queue(tmp_path, lease_timeout_s=0.3)
    q.write_manifest(_items(1), {})
    s, a = q.claim("w0")
    with LeaseKeeper(q, a["key"], "w0", heartbeat=hb,
                     max_lifetime_s=0.6) as keeper:
        time.sleep(0.45)
        assert hb.sections and set(hb.sections) == {"sweep_bucket"}
        n_before = len(hb.sections)
        # budget (0.6 s) exhausts during this window; POLL instead of a
        # fixed sleep — under full-suite load the keeper thread can be
        # starved past any fixed margin before its loop observes expiry
        deadline = time.monotonic() + 10.0
        while not keeper.expired and time.monotonic() < deadline:
            time.sleep(0.05)
        assert keeper.expired
        n_after = len(hb.sections)
    time.sleep(0.35)
    assert len(hb.sections) == n_after >= n_before  # silent after expiry
    # with renewals stopped the lease expires and the bucket is reclaimable
    s, b = q.claim("w1")
    assert s == "claimed" and b["attempt"] == 2


def test_queue_fail_restamps_backoff_from_failure_time(tmp_path):
    """A failure that surfaces AFTER the claim-time backoff window has
    elapsed (a slow mid-train crash) still waits the exponential delay —
    fail() re-stamps eligibility from the failure, not the claim."""
    q = _queue(tmp_path, max_attempts=5,
               backoff=RestartPolicy(backoff_base_s=0.3, backoff_max_s=0.3,
                                     jitter_frac=0.0))
    q.write_manifest(_items(1), {})
    s, a = q.claim("w0")
    time.sleep(0.35)  # claim-time window (0.3 s) fully elapsed "training"
    q.fail(a["key"], "w0", error="slow crash")
    assert q.claim("w0") == ("wait", None)  # still gated, from fail time
    time.sleep(0.35)
    s, b = q.claim("w0")
    assert s == "claimed" and b["attempt"] == 2


def test_ranking_from_ledger_coverage_manifest(tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.parallel.sweep import (
        ranking_from_ledger,
    )

    q = _queue(tmp_path)
    q.write_manifest(_items(3), {})
    q.ledger.write("k0", _record("k0", 0))
    q.ledger.write("k2", _record("k2", 2))
    q.ledger.quarantine("k1", {"attempts": 2, "index": 1})
    ranked, coverage = ranking_from_ledger(q)
    assert [r["valid_sharpe"] for r in ranked] == [pytest.approx(0.3),
                                                   pytest.approx(0.1)]
    assert coverage["n_buckets"] == 3 and coverage["completed"] == 2
    assert not coverage["complete"] and coverage["coverage"] == 0.6667
    assert [qq["index"] for qq in coverage["quarantined"]] == [1]
    assert coverage["quarantined"][0]["attempts"] == 2
    assert coverage["missing"] == []


# --------------------------------------------------------------------------
# fleet-wide fault counters + persistent entries
# --------------------------------------------------------------------------

def test_fault_persistent_entry_fires_on_every_hit_from_nth():
    inj = faults.FaultInjector(
        [{"site": "s", "action": "raise", "trigger_count": 2,
          "persistent": True}])
    inj.fire("s")  # hit 1: below trigger
    for _ in range(3):  # hits 2, 3, 4: a poison site keeps firing
        with pytest.raises(faults.FaultInjected):
            inj.fire("s")


def test_fault_state_is_fleetwide_across_live_instances(tmp_path):
    """Two LIVE injector instances (two worker processes) sharing one state
    file must see ONE hit stream: the Nth hit fleet-wide fires, not the Nth
    per process (the counters re-read the file under a lock at fire time)."""
    state = tmp_path / "fault_state.json"
    plan = [{"site": "s", "action": "raise", "trigger_count": 2}]
    inj1 = faults.FaultInjector(plan, state_path=state)
    inj2 = faults.FaultInjector(plan, state_path=state)
    inj1.fire("s")  # fleet hit 1
    with pytest.raises(faults.FaultInjected):
        inj2.fire("s")  # fleet hit 2 — fires HERE, not at inj2's own 2nd
    inj1.fire("s")  # fleet hit 3: past the trigger, never again
    inj2.fire("s")


# --------------------------------------------------------------------------
# quorum semantics
# --------------------------------------------------------------------------

def test_member_validity_and_apply_quorum():
    from deeplearninginassetpricing_paperreplication_tpu.parallel.ensemble import (
        QuorumError,
        apply_quorum,
        member_validity,
    )

    vparams = {"layer": {"w": np.ones((3, 2), np.float32),
                         "b": np.zeros((3,), np.float32)}}
    vparams["layer"]["w"][1, 0] = np.nan
    np.testing.assert_array_equal(member_validity(vparams),
                                  [True, False, True])

    kept_params, kept, dropped = apply_quorum(vparams, [7, 8, 9], quorum=2)
    assert kept == [7, 9] and dropped == [8]
    assert np.asarray(kept_params["layer"]["w"]).shape == (2, 2)
    assert np.isfinite(np.asarray(kept_params["layer"]["w"])).all()

    with pytest.raises(QuorumError, match=r"\[8\]"):
        apply_quorum(vparams, [7, 8, 9], quorum=3)

    # all-finite: exact pass-through, seeds normalized to ints
    finite = {"w": np.ones((2, 2), np.float32)}
    out, kept, dropped = apply_quorum(finite, (7, 8), quorum=2)
    assert out is finite and kept == [7, 8] and dropped == []


def test_stack_checkpoints_allow_missing_skips_and_reports(tmp_path):
    import jax

    from deeplearninginassetpricing_paperreplication_tpu.evaluate_ensemble import (
        evaluate_ensemble,
        stack_checkpoints,
    )
    from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
    from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
        save_params,
    )

    cfg = _tiny_cfg()
    gan = GAN(cfg)
    params = gan.init(jax.random.key(0))
    good = tmp_path / "good"
    good.mkdir()
    cfg.save(good / "config.json")
    save_params(good / "best_model_sharpe.msgpack", params)
    corrupt = tmp_path / "corrupt"
    corrupt.mkdir()
    cfg.save(corrupt / "config.json")
    save_params(corrupt / "best_model_sharpe.msgpack", params)
    target = corrupt / "best_model_sharpe.msgpack"
    with open(target, "r+b") as f:
        f.truncate(10)
    torn_cfg = tmp_path / "torn_cfg"
    torn_cfg.mkdir()
    # config.json is a plain (non-atomic) write: a kill mid-save tears it
    (torn_cfg / "config.json").write_text('{"hidden_dim": [4')
    save_params(torn_cfg / "best_model_sharpe.msgpack", params)
    absent = tmp_path / "never_written"
    dirs = [str(good), str(absent), str(corrupt), str(torn_cfg)]

    # strict (default): the first casualty fails the ensemble, as before
    with pytest.raises((FileNotFoundError, ValueError)):
        stack_checkpoints(dirs)

    # allow_missing: one warning LISTING each skipped dir and why
    coverage = {}
    with pytest.warns(UserWarning) as warned:
        gan0, stacked = stack_checkpoints(
            dirs, allow_missing=True, coverage_out=coverage)
    text = "\n".join(str(w.message) for w in warned)
    assert "never_written" in text and "corrupt" in text
    assert "torn_cfg" in text
    assert jax.tree.leaves(stacked)[0].shape[0] == 1
    assert coverage["used"] == [str(good)]
    assert {s["dir"] for s in coverage["skipped"]} == {str(absent),
                                                       str(corrupt),
                                                       str(torn_cfg)}

    # quorum enforcement happens before any data is touched
    with pytest.raises(ValueError, match="quorum is 2"):
        with pytest.warns(UserWarning):
            evaluate_ensemble(dirs, data_dir="/nonexistent", quorum=2)

    # every dir unusable: clear error, not an empty stack
    with pytest.raises(ValueError, match="no usable checkpoint dirs"):
        stack_checkpoints([str(absent)], allow_missing=True)


# --------------------------------------------------------------------------
# verified ranking artifacts
# --------------------------------------------------------------------------

def test_write_ranking_verified_and_load_names_corruption(tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.sweep import (
        load_ranking,
        write_ranking,
    )

    cfg = _tiny_cfg()
    ranked = [
        {"config": cfg, "lr": 1e-3, "seed": 7, "valid_sharpe": 0.5},
        {"config": cfg, "lr": 5e-4, "seed": 7,
         "valid_sharpe": float("-inf")},
    ]
    path = write_ranking(tmp_path, ranked,
                         coverage={"complete": True, "n_buckets": 1})
    assert verified.digest_path(path).exists()
    assert verified.digest_path(tmp_path / "sweep_coverage.json").exists()

    rows = load_ranking(path)
    assert rows[0]["valid_sharpe"] == 0.5 and rows[0]["config"] == cfg
    assert rows[1]["valid_sharpe"] == float("-inf")  # null round-trip

    with open(path, "r+b") as f:  # torn write / bit rot
        f.truncate(20)
    with pytest.raises(ValueError, match="sweep_ranking.json"):
        load_ranking(path)


# --------------------------------------------------------------------------
# supervisor sweep-resume detection
# --------------------------------------------------------------------------

def test_detect_resume_flag_prefers_trainer_state(tmp_path):
    sup = Supervisor(["true"], tmp_path / "heartbeat.json")
    assert sup._detect_resume_flag() is None
    ledger_dir = tmp_path / "sweep_ledger"
    ledger_dir.mkdir()
    (ledger_dir / "queue.json").write_text("{}")
    assert sup._detect_resume_flag() == "--resume-from-ledger"
    (tmp_path / "resume_meta.json").write_text("{}")
    assert sup._detect_resume_flag() == "--resume"


def test_supervisor_appends_resume_from_ledger_for_sweep_child(tmp_path):
    """A restarted sweep child — its run dir holds a ledger, no trainer
    state — gets --resume-from-ledger appended (the sweep-semantics
    satellite), exactly once."""
    stub = tmp_path / "child.py"
    stub.write_text(textwrap.dedent("""
        import json, os, sys, time
        run_dir = sys.argv[1]
        state = {"heartbeat": {"section": "sweep_bucket", "ts": time.time()}}
        with open(os.path.join(run_dir, "heartbeat.json"), "w") as f:
            json.dump(state, f)
        os.makedirs(os.path.join(run_dir, "sweep_ledger"), exist_ok=True)
        qp = os.path.join(run_dir, "sweep_ledger", "queue.json")
        with open(qp, "w") as f:
            f.write("{}")
        spawns_path = os.path.join(run_dir, "spawns")
        n = int(open(spawns_path).read()) if os.path.exists(spawns_path) else 0
        with open(spawns_path, "w") as f:
            f.write(str(n + 1))
        with open(os.path.join(run_dir, f"argv.{n + 1}"), "w") as f:
            json.dump(sys.argv[2:], f)
        sys.exit(0 if n + 1 > 1 else 3)
    """))
    cmd = [sys.executable, "-S", str(stub), str(tmp_path)]
    sup = Supervisor(cmd, tmp_path / "heartbeat.json",
                     policy=RestartPolicy(
                         heartbeat_timeout_s=30.0, poll_s=0.05,
                         min_uptime_s=30.0, max_restarts=3,
                         backoff_base_s=0.05, backoff_max_s=0.1,
                         jitter_frac=0.0))
    summary = sup.run()
    assert summary["outcome"] == "success"
    assert json.loads((tmp_path / "argv.1").read_text()) == []
    assert json.loads(
        (tmp_path / "argv.2").read_text()) == ["--resume-from-ledger"]


# --------------------------------------------------------------------------
# report CLI elastic section
# --------------------------------------------------------------------------

def test_report_elastic_section(tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
        format_summary,
        load_run,
        summarize_run,
    )

    rows = [
        {"kind": "counter", "name": "sweep/claim", "value": 1,
         "worker": "w0", "attempt": 1, "run_id": "a", "seq": 1},
        {"kind": "counter", "name": "sweep/claim", "value": 1,
         "worker": "w1", "attempt": 1, "run_id": "b", "seq": 1},
        {"kind": "counter", "name": "sweep/claim", "value": 1,
         "worker": "w1", "attempt": 2, "run_id": "b", "seq": 2},
        {"kind": "counter", "name": "sweep/retry", "value": 1,
         "worker": "w1", "run_id": "b", "seq": 3},
        {"kind": "counter", "name": "sweep/lease_takeover", "value": 1,
         "worker": "w1", "from_worker": "w0", "run_id": "b", "seq": 4},
        {"kind": "counter", "name": "sweep/ledger_write", "value": 1,
         "worker": "w1", "run_id": "b", "seq": 5},
        {"kind": "counter", "name": "sweep/ledger_hit", "value": 1,
         "run_id": "b", "seq": 6},
        {"kind": "counter", "name": "sweep/quarantine", "value": 1,
         "run_id": "b", "seq": 7},
        {"kind": "counter", "name": "sweep/quorum_drop", "value": 1,
         "rank": 0, "seed": 456, "run_id": "b", "seq": 8},
    ]
    (tmp_path / "events.w1.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows))
    # a ledger dir supplies the authoritative bucket tallies
    q = _queue(tmp_path / "sweep_ledger")
    q.write_manifest(_items(3), {})
    q.ledger.write("k0", _record("k0", 0))
    q.ledger.quarantine("k1", {"attempts": 2})

    summary = summarize_run(load_run(tmp_path))
    el = summary["elastic"]
    assert el["buckets_completed"] == 1
    assert el["ledger_hits"] == 1
    assert el["retries"] == 1
    assert el["lease_takeovers"] == 1
    assert el["quarantined"] == 1
    assert el["claims_by_worker"] == {"w0": 1, "w1": 2}
    assert el["completed_by_worker"] == {"w1": 1}
    assert el["quorum_drops"] == [{"rank": 0, "seed": 456}]
    assert el["ledger"] == {"total_buckets": 3, "records": 1,
                            "quarantined": 1}
    text = format_summary(summary)
    assert "elastic sweep:" in text
    assert "lease takeovers: 1" in text
    assert "rank0:seed456" in text

    plain = tmp_path / "plain"
    plain.mkdir()
    (plain / "events.jsonl").write_text(json.dumps(
        {"kind": "counter", "name": "epochs_dispatched", "value": 4,
         "run_id": "r", "seq": 1}) + "\n")
    assert summarize_run(load_run(plain))["elastic"] is None


# --------------------------------------------------------------------------
# the headline fault matrix: supervised 2-worker sweep CLI
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def elastic_data(tmp_path_factory):
    """A deliberately tiny panel: the elastic tests exercise ORCHESTRATION
    (claims, leases, restarts), so training cost is pure overhead."""
    from deeplearninginassetpricing_paperreplication_tpu.data.synthetic import (
        generate_all_splits,
    )

    out = tmp_path_factory.mktemp("elastic_data")
    generate_all_splits(
        out, n_periods_train=16, n_periods_valid=8, n_periods_test=8,
        n_stocks=24, n_features=5, n_macro=3, seed=7, verbose=False,
    )
    return out


def _sweep_cli(data_dir, save_dir, *extra):
    return [sys.executable, "-m", f"{PKG}.sweep",
            "--data_dir", str(data_dir), "--save_dir", str(save_dir),
            "--quick", "--search_only"] + list(extra)


ELASTIC_ARGS = [
    "--workers", "2", "--lease_timeout", "8",
    "--worker_min_uptime", "0.2", "--worker_backoff", "0.2",
    "--worker_max_restarts", "8", "--retry_backoff", "0.3",
]


def _run_cli(cmd, extra_env=None, timeout=420):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


@pytest.fixture(scope="module")
def quick_ref(elastic_data, tmp_path_factory):
    """The uninterrupted single-process quick search — the byte-level
    reference every elastic/faulted run must reproduce."""
    ref_dir = tmp_path_factory.mktemp("quick_ref")
    out = _run_cli(_sweep_cli(elastic_data, ref_dir))
    assert out.returncode == 0, out.stdout + out.stderr
    return ref_dir, (ref_dir / "sweep_ranking.json").read_bytes()


def _count_events(run_dir, name):
    n = 0
    for p in Path(run_dir).glob("events*.jsonl"):
        for line in p.read_text().splitlines():
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if row.get("kind") == "counter" and row.get("name") == name:
                n += 1
    return n


def test_fault_matrix_2worker_sweep_kills_bit_identical(
        elastic_data, quick_ref, tmp_path):
    """Kill the 2-worker fleet at every NEW fault site — ``sweep/claim``
    (orphans a lease → takeover), mid-bucket (``sweep/bucket``, lease held
    → takeover), and ``sweep/ledger_write`` (bucket trained but not
    recorded → retrained) — with counters shared fleet-wide so each kill
    fires exactly once. The supervised workers restart, the queue drains,
    and the final ranking is BYTE-identical to the uninterrupted run with
    zero completed buckets ever re-trained."""
    _ref_dir, ref_bytes = quick_ref
    plan = [
        {"site": "sweep/claim", "action": "kill", "trigger_count": 1},
        {"site": "sweep/bucket", "action": "kill", "trigger_count": 2},
        {"site": "sweep/ledger_write", "action": "kill", "trigger_count": 2},
    ]
    run_dir = tmp_path / "faulted"
    # each kill consumes one of its bucket's claim attempts, and all three
    # may land on ONE bucket — the attempt budget must exceed that, or the
    # bucket correctly (but unhelpfully here) quarantines as poison
    out = _run_cli(
        _sweep_cli(elastic_data, run_dir, *ELASTIC_ARGS,
                   "--max_bucket_attempts", "6"),
        extra_env={faults.ENV_PLAN: json.dumps(plan)})
    assert out.returncode == 0, out.stdout + out.stderr

    assert (run_dir / "sweep_ranking.json").read_bytes() == ref_bytes

    # every planned kill fired exactly once, fleet-wide
    fault_rows = [json.loads(x) for x in
                  (run_dir / "events.faults.jsonl").read_text().splitlines()]
    assert sorted((r["site"], r["action"]) for r in fault_rows) == [
        ("sweep/bucket", "kill"), ("sweep/claim", "kill"),
        ("sweep/ledger_write", "kill")]

    # zero completed buckets re-trained: exactly one ledger record write
    # per bucket ever succeeded (the quick grid spans 2 buckets)
    assert _count_events(run_dir, "sweep/ledger_write") == 2
    coverage = json.loads((run_dir / "sweep_coverage.json").read_text())
    assert coverage["complete"] and coverage["completed"] == 2

    # the fleet's recovery story is visible to the report CLI
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (
        load_run,
        summarize_run,
    )

    summary = summarize_run(load_run(run_dir))
    el = summary["elastic"]
    assert el["buckets_completed"] == 2
    assert el["lease_takeovers"] >= 1  # claim/mid-bucket kills orphan leases
    assert el["ledger"] == {"total_buckets": 2, "records": 2,
                            "quarantined": 0}
    rel = summary["reliability"]
    assert sum(rel["deaths_by_section"].values()) == 3  # one per kill


def test_poison_bucket_quarantines_and_ships_degraded(
        elastic_data, quick_ref, tmp_path):
    """A bucket that kills every worker that claims it (persistent raise)
    is quarantined after K attempts instead of crash-looping the fleet;
    the ranking ships DEGRADED with a coverage manifest naming the bucket,
    and the surviving bucket's entries match the uninterrupted run."""
    from deeplearninginassetpricing_paperreplication_tpu.parallel.sweep import (
        bucket_work_items,
        grid_configs,
    )
    from deeplearninginassetpricing_paperreplication_tpu.sweep import (
        QUICK_GRID_KW,
        QUICK_SEARCH_SCHEDULE,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        GANConfig,
        TrainConfig,
    )

    _ref_dir, ref_bytes = quick_ref
    # aim the poison at the SECOND quick bucket, keyed exactly as the CLI
    # will key it (same grid constants, same schedule, same data dims)
    base = GANConfig(macro_feature_dim=3, individual_feature_dim=5)
    items = bucket_work_items(
        grid_configs(base, **QUICK_GRID_KW), [42],
        TrainConfig(**QUICK_SEARCH_SCHEDULE, seed=42))
    poison_key = items[1]["key"]
    plan = [{"site": "sweep/bucket", "action": "raise",
             "match": poison_key, "persistent": True}]

    run_dir = tmp_path / "poison"
    out = _run_cli(
        _sweep_cli(elastic_data, run_dir, *ELASTIC_ARGS,
                   "--max_bucket_attempts", "2"),
        extra_env={faults.ENV_PLAN: json.dumps(plan)})
    assert out.returncode == 0, out.stdout + out.stderr  # fleet NOT sunk

    coverage = json.loads((run_dir / "sweep_coverage.json").read_text())
    assert not coverage["complete"]
    assert coverage["completed"] == 1 and coverage["n_buckets"] == 2
    assert [q["key"] for q in coverage["quarantined"]] == [poison_key]
    assert coverage["quarantined"][0]["attempts"] == 2
    assert coverage["missing"] == []

    # the degraded ranking carries exactly the surviving bucket's entries,
    # numerically identical to the uninterrupted run's
    ref_rows = json.loads(ref_bytes)
    poison_cfg = items[1]["config"]
    survivors = [r for r in ref_rows if r["config"] != poison_cfg]
    got = json.loads((run_dir / "sweep_ranking.json").read_text())
    assert ([(r["config"], r["lr"], r["valid_sharpe"]) for r in got]
            == [(r["config"], r["lr"], r["valid_sharpe"])
                for r in survivors])


def test_supervised_single_sweep_resumes_from_ledger(
        elastic_data, quick_ref, tmp_path):
    """A supervised SINGLE-process sweep killed mid-search restarts with
    --resume-from-ledger auto-appended, re-trains only the unfinished
    bucket (asserted via the ledger-hit counter), and finishes with a
    ranking byte-identical to the uninterrupted run."""
    _ref_dir, ref_bytes = quick_ref
    run_dir = tmp_path / "resumed"
    child = _sweep_cli(elastic_data, run_dir)
    cmd = [sys.executable, "-m", f"{PKG}.supervise",
           "--run_dir", str(run_dir), "--timeout", "300", "--poll", "0.2",
           "--backoff", "0.1", "--jitter", "0", "--min_uptime", "0.5",
           "--max_restarts", "8", "--"] + child
    # the 2nd sweep/bucket hit is bucket 2's start: bucket 1 is already in
    # the ledger when the kill lands
    plan = [{"site": "sweep/bucket", "action": "kill", "trigger_count": 2}]
    out = _run_cli(cmd, extra_env={faults.ENV_PLAN: json.dumps(plan)})
    assert out.returncode == 0, out.stdout + out.stderr
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["outcome"] == "success" and summary["restarts"] == 1

    assert (run_dir / "sweep_ranking.json").read_bytes() == ref_bytes
    # the restart replayed NO completed work: bucket 1 was a ledger hit
    assert _count_events(run_dir, "sweep/ledger_hit") == 1
    assert _count_events(run_dir, "sweep/ledger_write") == 2
    # and the supervisor appended the sweep resume flag, not --resume
    sup_rows = [json.loads(x) for x in
                (run_dir / "events.supervisor.jsonl").read_text().splitlines()]
    resumed = [r for r in sup_rows if r.get("kind") == "span_begin"
               and r.get("name") == "supervise/child"]
    assert [(r["attempt"], r["resumed"]) for r in resumed] == [
        (1, False), (2, True)]


# --------------------------------------------------------------------------
# quorum end-to-end through run_protocol (monkeypatched divergence)
# --------------------------------------------------------------------------

def test_run_protocol_quorum_drops_diverged_member(tmp_path, monkeypatch):
    """One ensemble member diverges (its params go NaN after training);
    with --quorum the protocol drops it, records the drop, saves only
    surviving member checkpoints, and the grand ensemble counts only
    survivors — instead of shipping NaN Sharpes or crashing."""
    import jax
    import jax.numpy as jnp

    import deeplearninginassetpricing_paperreplication_tpu.sweep as sweep_cli
    from deeplearninginassetpricing_paperreplication_tpu.parallel import (
        ensemble as ens,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        GANConfig,
        TrainConfig,
    )

    rng = np.random.default_rng(0)
    T, N, F, M = 10, 8, 4, 3
    batch = {
        "returns": jnp.asarray(rng.standard_normal((T, N)), jnp.float32),
        "individual": jnp.asarray(
            rng.standard_normal((T, N, F)), jnp.float32),
        "macro": jnp.asarray(rng.standard_normal((T, M)), jnp.float32),
        "mask": jnp.ones((T, N), jnp.float32),
    }
    cfg = GANConfig(macro_feature_dim=M, individual_feature_dim=F,
                    hidden_dim=(4,), num_units_rnn=(3,),
                    num_condition_moment=2, dropout=0.0)
    tcfg = TrainConfig(num_epochs_unc=1, num_epochs_moment=0, num_epochs=1,
                       ignore_epoch=0)

    real_train_ensemble = ens.train_ensemble

    def poisoned_train_ensemble(*args, **kwargs):
        gan, vparams, hist = real_train_ensemble(*args, **kwargs)
        # member 1 diverged: NaN every leaf of its slice
        vparams = jax.tree.map(
            lambda x: jnp.asarray(np.where(
                np.arange(x.shape[0]).reshape(
                    (-1,) + (1,) * (x.ndim - 1)) == 1,
                np.nan, np.asarray(x, np.float32)), x.dtype), vparams)
        return gan, vparams, hist

    monkeypatch.setattr(sweep_cli, "train_ensemble",
                        poisoned_train_ensemble)

    report = sweep_cli.run_protocol(
        [(cfg, 1e-3)], batch, batch, batch,
        search_tcfg=tcfg, ensemble_tcfg=tcfg,
        search_seeds=[7], ensemble_seeds=[11, 22, 33], top_k=1,
        save_dir=str(tmp_path), verbose=False,
        diagnostic_top=0, quorum=2,
    )
    w = report["winners"][0]
    assert w["dropped_seeds"] == [22]
    assert w["seeds"] == [11, 33]
    assert report["n_grand_members"] == 2
    assert np.isfinite(report["grand_ensemble_test_sharpe"])
    # only surviving members' checkpoint dirs exist
    member_dirs = sorted(p.name for p in tmp_path.glob("rank0_seed*"))
    assert member_dirs == ["rank0_seed11", "rank0_seed33"]
    # below quorum: loud failure naming the dropped seeds
    with pytest.raises(ens.QuorumError, match=r"\[22\]"):
        sweep_cli.run_protocol(
            [(cfg, 1e-3)], batch, batch, batch,
            search_tcfg=tcfg, ensemble_tcfg=tcfg,
            search_seeds=[7], ensemble_seeds=[11, 22, 33], top_k=1,
            verbose=False, diagnostic_top=0, quorum=3,
        )


# --------------------------------------------------------------------------
# lint gate: the new modules stay clean under the pyproject ruff rules
# --------------------------------------------------------------------------

ELASTIC_FILES = [
    REPO / PKG / "reliability" / "ledger.py",
    REPO / PKG / "reliability" / "scheduler.py",
    REPO / PKG / "parallel" / "sweep.py",
    REPO / PKG / "sweep.py",
]


def test_elastic_modules_lint_clean():
    try:
        import ruff  # noqa: F401

        has_ruff = True
    except ImportError:
        has_ruff = False
    if has_ruff:
        out = subprocess.run(
            [sys.executable, "-m", "ruff", "check"]
            + [str(p) for p in ELASTIC_FILES],
            capture_output=True, text=True, cwd=REPO,
        )
        assert out.returncode == 0, out.stdout + out.stderr
    else:
        import ast

        for path in ELASTIC_FILES:
            tree = ast.parse(path.read_text())
            src = path.read_text()
            for node in ast.walk(tree):
                names = []
                if isinstance(node, ast.Import):
                    names = [a.asname or a.name.split(".")[0]
                             for a in node.names]
                elif isinstance(node, ast.ImportFrom):
                    if node.module == "__future__":
                        continue
                    names = [a.asname or a.name for a in node.names]
                for name in names:
                    if name == "*":
                        continue
                    assert src.count(name) > 1, (
                        f"{path.name}: unused import {name}")
