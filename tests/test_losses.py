"""Loss semantics vs hand-computed NumPy on tiny panels.

The expected values are computed here with the formulas from the reference
(model.py:346-483) written directly in NumPy — per-period N_t and per-asset
T_i denominators, N̄ scaling, SDF = 1 + F — so any deviation in the fused
JAX implementations is caught against an independent oracle.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.ops.losses import (
    conditional_loss,
    portfolio_returns,
    residual_loss,
    unconditional_loss,
)
from deeplearninginassetpricing_paperreplication_tpu.ops.metrics import (
    max_drawdown,
    normalize_weights_abs,
    sharpe,
)


def _toy(rng, T=7, N=11, K=3):
    mask = (rng.random((T, N)) > 0.35).astype(np.float32)
    mask[:, 0] = 1.0
    w = rng.standard_normal((T, N)).astype(np.float32) * mask
    R = rng.standard_normal((T, N)).astype(np.float32) * mask
    h = np.tanh(rng.standard_normal((K, T, N))).astype(np.float32)
    return w, R, mask, h


def _np_portfolio(w, R, m, weighted=True):
    wr = (w * R * m).sum(axis=1)
    if weighted:
        n_t = np.maximum(m.sum(axis=1), 1.0)
        return wr / n_t * n_t.mean()
    return wr


def test_portfolio_returns_weighted_scaling(rng):
    w, R, m, _ = _toy(rng)
    np.testing.assert_allclose(
        np.asarray(portfolio_returns(jnp.asarray(w), jnp.asarray(R), jnp.asarray(m))),
        _np_portfolio(w, R, m),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(
            portfolio_returns(jnp.asarray(w), jnp.asarray(R), jnp.asarray(m), weighted=False)
        ),
        (w * R * m).sum(axis=1),
        rtol=1e-5,
    )


def test_unconditional_loss_hand_computed(rng):
    w, R, m, _ = _toy(rng)
    F = _np_portfolio(w, R, m)
    sdf = 1.0 + F
    t_i = np.maximum(m.sum(axis=0), 1.0)
    emp = (R * m * sdf[:, None]).sum(axis=0) / t_i
    expected = (emp**2).mean()
    loss, F_out = unconditional_loss(jnp.asarray(w), jnp.asarray(R), jnp.asarray(m))
    np.testing.assert_allclose(float(loss), expected, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(F_out), F, rtol=1e-5)


def test_conditional_loss_equals_per_moment_loop(rng):
    w, R, m, h = _toy(rng)
    F = _np_portfolio(w, R, m)
    sdf = 1.0 + F
    t_i = np.maximum(m.sum(axis=0), 1.0)
    per_moment = []
    for k in range(h.shape[0]):  # the reference's Python loop, as oracle
        emp = (h[k] * R * m * sdf[:, None]).sum(axis=0) / t_i
        per_moment.append((emp**2).mean())
    expected = np.mean(per_moment)
    loss, _ = conditional_loss(jnp.asarray(w), jnp.asarray(R), jnp.asarray(m), jnp.asarray(h))
    np.testing.assert_allclose(float(loss), expected, rtol=1e-5)


def test_conditional_reduces_to_unconditional_with_unit_moments(rng):
    w, R, m, _ = _toy(rng)
    h1 = np.ones((1,) + w.shape, dtype=np.float32)
    lc, _ = conditional_loss(jnp.asarray(w), jnp.asarray(R), jnp.asarray(m), jnp.asarray(h1))
    lu, _ = unconditional_loss(jnp.asarray(w), jnp.asarray(R), jnp.asarray(m))
    np.testing.assert_allclose(float(lc), float(lu), rtol=1e-6)


def test_residual_loss_hand_computed(rng):
    w, R, m, _ = _toy(rng)
    resid_list, rsq_list = [], []
    for t in range(w.shape[0]):  # the reference's T-loop, as oracle
        valid = m[t] > 0
        if valid.sum() < 2:
            continue
        wv, Rv = w[t, valid], R[t, valid]
        ww = (wv * wv).sum()
        if ww > 1e-8:
            coef = (Rv * wv).sum() / ww
            resid_list.append(((Rv - coef * wv) ** 2).mean())
        rsq_list.append((Rv**2).mean())
    expected = np.mean(resid_list) / max(np.mean(rsq_list), 1e-8)
    got = float(residual_loss(jnp.asarray(w), jnp.asarray(R), jnp.asarray(m)))
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_residual_loss_zero_weights_returns_zero(rng):
    _, R, m, _ = _toy(rng)
    w0 = np.zeros_like(R)
    assert float(residual_loss(jnp.asarray(w0), jnp.asarray(R), jnp.asarray(m))) == 0.0


def test_residual_loss_sparse_periods(rng):
    # periods with <2 valid stocks are excluded entirely
    w, R, m, _ = _toy(rng, T=4, N=6)
    m[1] = 0.0
    m[2] = 0.0
    m[2, 3] = 1.0  # exactly one valid stock → excluded
    w, R = w * m, R * m
    got = float(residual_loss(jnp.asarray(w), jnp.asarray(R), jnp.asarray(m)))
    resid_list, rsq_list = [], []
    for t in (0, 3):
        valid = m[t] > 0
        wv, Rv = w[t, valid], R[t, valid]
        ww = (wv * wv).sum()
        if ww > 1e-8:
            coef = (Rv * wv).sum() / ww
            resid_list.append(((Rv - coef * wv) ** 2).mean())
        rsq_list.append((Rv**2).mean())
    np.testing.assert_allclose(got, np.mean(resid_list) / np.mean(rsq_list), rtol=1e-5)


def test_sharpe_conventions(rng):
    r = rng.standard_normal(50).astype(np.float32)
    # ddof=1 matches torch.Tensor.std() (training/eval path)
    np.testing.assert_allclose(
        float(sharpe(jnp.asarray(r))), r.mean() / r.std(ddof=1), rtol=1e-5
    )
    # ddof=0 matches np.std (ensemble path)
    np.testing.assert_allclose(
        float(sharpe(jnp.asarray(r), ddof=0)), r.mean() / r.std(ddof=0), rtol=1e-5
    )
    assert float(sharpe(jnp.zeros(10))) == 0.0


def test_max_drawdown():
    r = np.array([0.1, -0.5, 0.2, -0.25])
    cum = np.cumprod(1 + r)
    run = np.maximum.accumulate(cum)
    np.testing.assert_allclose(max_drawdown(r), ((cum - run) / run).min())


def test_normalize_weights_abs(rng):
    w, _, m, _ = _toy(rng)
    nw = np.asarray(normalize_weights_abs(jnp.asarray(w), jnp.asarray(m)))
    np.testing.assert_allclose((np.abs(nw) * m).sum(axis=1), 1.0, atol=1e-5)


def test_losses_sharded_equal_unsharded(rng):
    """Stock-axis sharding must not change any loss (masked reductions are
    exact under psum). Runs on the 8-device virtual CPU mesh."""
    import jax
    from jax.sharding import PartitionSpec as P

    from deeplearninginassetpricing_paperreplication_tpu.parallel.partition import (  # noqa: E501
        create_mesh,
        named_sharding,
    )

    w, R, m, h = _toy(rng, T=6, N=32, K=2)
    mesh = create_mesh(8)
    sh2 = named_sharding(mesh, P(None, "stocks"))
    sh3 = named_sharding(mesh, P(None, None, "stocks"))
    wd = jax.device_put(jnp.asarray(w), sh2)
    Rd = jax.device_put(jnp.asarray(R), sh2)
    md = jax.device_put(jnp.asarray(m), sh2)
    hd = jax.device_put(jnp.asarray(h), sh3)

    l_ref, _ = conditional_loss(jnp.asarray(w), jnp.asarray(R), jnp.asarray(m), jnp.asarray(h))
    l_sharded, _ = jax.jit(conditional_loss)(wd, Rd, md, hd)
    np.testing.assert_allclose(float(l_sharded), float(l_ref), rtol=1e-5)


# -- paper Table-1 risk-premium metrics (EV / XS-R²) --------------------------


def _np_risk_premium_oracle(R, F, m, min_obs=1):
    """Loop-based oracle for factor_betas / EV / XS-R² on a masked panel."""
    T, N = R.shape
    betas = np.zeros(N)
    for i in range(N):
        idx = m[:, i] > 0
        t_i = max(idx.sum(), 1)
        fbar = F[idx].sum() / t_i
        rbar = R[idx, i].sum() / t_i
        var = ((F[idx] - fbar) ** 2).sum() / t_i
        cov = ((F[idx] - fbar) * (R[idx, i] - rbar)).sum() / t_i
        betas[i] = cov / max(var, 1e-12) if var > 1e-12 else 0.0
    eps = (R - betas[None, :] * F[:, None]) * m
    ev = 1.0 - (eps**2).sum() / (R**2 * m).sum()
    num = den = 0.0
    for i in range(N):
        t_i = m[:, i].sum()
        if t_i < min_obs:
            continue
        ebar = eps[:, i].sum() / max(t_i, 1)
        rbar = (R[:, i] * m[:, i]).sum() / max(t_i, 1)
        num += t_i * ebar**2
        den += t_i * rbar**2
    xs = 1.0 - num / max(den, 1e-12)
    return betas, ev, xs


def test_risk_premium_metrics_hand_computed(rng):
    from deeplearninginassetpricing_paperreplication_tpu.ops.metrics import (
        cross_sectional_r2,
        explained_variation,
        factor_betas,
    )

    _, R, m, _ = _toy(rng, T=9, N=13)
    # a stock with zero valid months exercises the degenerate-beta guard
    m[:, 5] = 0.0
    R[:, 5] = 0.0
    F = (R * m).sum(axis=1) / np.maximum(m.sum(axis=1), 1.0)
    betas_np, ev_np, xs_np = _np_risk_premium_oracle(R, F, m)

    betas = np.asarray(factor_betas(jnp.asarray(R), jnp.asarray(F), jnp.asarray(m)))
    np.testing.assert_allclose(betas, betas_np, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        float(explained_variation(jnp.asarray(R), jnp.asarray(F), jnp.asarray(m))),
        ev_np, rtol=1e-4,
    )
    np.testing.assert_allclose(
        float(cross_sectional_r2(jnp.asarray(R), jnp.asarray(F), jnp.asarray(m))),
        xs_np, rtol=1e-4,
    )


def test_risk_premium_metrics_sign_invariant_and_perfect_fit(rng):
    """EV/XS-R² must not depend on the sign of F (paper's negation
    convention), and a panel that IS β·F must give EV = XS-R² = 1."""
    from deeplearninginassetpricing_paperreplication_tpu.ops.metrics import (
        cross_sectional_r2,
        explained_variation,
    )

    _, R, m, _ = _toy(rng, T=8, N=10)
    F = (R * m).sum(axis=1) / np.maximum(m.sum(axis=1), 1.0)
    Rj, Fj, mj = jnp.asarray(R), jnp.asarray(F), jnp.asarray(m)
    np.testing.assert_allclose(
        float(explained_variation(Rj, Fj, mj)),
        float(explained_variation(Rj, -Fj, mj)), rtol=1e-5,
    )
    np.testing.assert_allclose(
        float(cross_sectional_r2(Rj, Fj, mj)),
        float(cross_sectional_r2(Rj, -Fj, mj)), rtol=1e-5,
    )

    true_betas = rng.standard_normal(10).astype(np.float32)
    R_exact = (true_betas[None, :] * F[:, None]) * m
    np.testing.assert_allclose(
        float(explained_variation(jnp.asarray(R_exact), Fj, mj)), 1.0, atol=1e-5
    )
    np.testing.assert_allclose(
        float(cross_sectional_r2(jnp.asarray(R_exact), Fj, mj)), 1.0, atol=1e-5
    )
