"""Load-adaptive autoscaling: the closed loop from the metrics plane to
the replica set.

Everything reactive already existed as parts — per-replica metrics
(queue depth, occupancy, latency percentiles, 503/429 tallies), a
supervisor that restarts, a fleet that spawns — but the replica count was
fixed at boot. The :class:`Autoscaler` closes the loop: a control thread
scrapes every live replica's private admin ``/metrics`` endpoint, derives
three pressure signals —

  * **queue depth** — mean pending requests per replica (the same
    ``batcher.pending`` the DAGOR-style admission layer sheds on);
  * **shed/reject rate** — the per-tick delta of 429 + 503 responses over
    the per-tick delta of requests (load the fleet is already refusing);
  * **p99 latency** — the replicas' own served-latency percentiles;

— and grows or shrinks the ``SO_REUSEPORT`` replica set live through
:class:`~.fleet.ReplicaFleet`. Scale-up spawns one supervised replica and
blocks on its ``wait_ready`` heartbeat; scale-down POSTs ``/v1/drain`` to
the victim's admin endpoint (it stops accepting, flushes its lanes, and
exits rc 0 — the supervisor records *success*, not a death) with a
SIGKILL fallback for a replica too wedged to drain. **Hysteresis** (N
consecutive over/under-threshold ticks) plus a post-scale **cooldown**
keep the loop from flapping on a noisy signal, and every scale event
atomically rewrites the fleet run dir's ``fleet.json`` so tooling and the
report CLI always see the live layout.

Decisions are evidence: every tick appends to a bounded ring that the
parent's :class:`~.flight.FlightRecorder` includes in crash dumps (an
overload dump shows *why* the fleet was shedding), and scale actions emit
``fleet/scale`` counters + a ``fleet/replicas`` gauge into the events
plane the report CLI aggregates.

The module is deliberately thin on imports (events + faults only): it
runs inside the fleet PARENT, which supervises replicas but never
initializes a JAX backend.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..observability.events import EventLog
from ..reliability.faults import inject


@dataclasses.dataclass
class AutoscalePolicy:
    """Everything the control loop decides from.

    Scale **up** when ANY pressure signal stays tripped for
    ``up_hysteresis`` consecutive ticks: mean queue depth per replica at or
    above ``up_queue_depth``, shed/reject rate (429+503 per request, per
    tick) at or above ``up_shed_rate``, or p99 above ``up_p99_ms`` (when
    set). Scale **down** when the fleet has been quiet — depth at or below
    ``down_queue_depth`` AND zero sheds — for ``down_hysteresis``
    consecutive ticks. ``cooldown_s`` after any scale event gates the next
    one, so spawn/drain transients can never feed back into the signal
    they changed (the anti-flap guarantee, with hysteresis the second
    half)."""

    min_replicas: int = 1
    max_replicas: int = 4
    poll_s: float = 0.5
    up_queue_depth: float = 8.0
    up_shed_rate: float = 0.02
    up_p99_ms: Optional[float] = None
    down_queue_depth: float = 1.0
    up_hysteresis: int = 2
    down_hysteresis: int = 8
    cooldown_s: float = 5.0
    drain_timeout_s: float = 10.0
    ready_timeout_s: float = 300.0


class FleetController:
    """The Autoscaler's levers over a live :class:`~.fleet.ReplicaFleet`.

    Scrapes per-replica JSON ``/metrics`` over the private admin ports,
    spawns supervised replicas (``make_argv(replica_id, admin_port)``
    builds the child command line), drains victims through ``/v1/drain``,
    and atomically republishes ``fleet.json`` after every change. Split
    from :class:`Autoscaler` so the control loop is unit-testable against
    a fake controller with no processes."""

    def __init__(
        self,
        fleet,
        make_argv: Callable[[int, int], Sequence[str]],
        host: str,
        port: int,
        admin_ports: Optional[Dict[int, int]] = None,
        pointer: Optional[str] = None,
        http_timeout_s: float = 10.0,
        metrics_timeout_s: float = 2.0,
        mesh: Optional[str] = None,
        mesh_slices: Optional[int] = None,
    ):
        self.fleet = fleet
        self.make_argv = make_argv
        self.host, self.port = host, port
        self.admin_ports: Dict[int, int] = dict(admin_ports or {})
        self.pointer = pointer
        # mesh-serving record for fleet.json: the --mesh spec every
        # replica lays out, and the device-slice partition width (replica
        # i serves from disjoint contiguous slice i % mesh_slices)
        self.mesh = mesh
        self.mesh_slices = mesh_slices
        self.http_timeout_s = float(http_timeout_s)
        # the per-tick scrape gets its own SHORT timeout: one wedged-but-
        # accepting replica must not stall the control loop 10 s per poll
        # exactly when the overload needs a fast scale-up (drain/scale
        # operations keep the longer http_timeout_s)
        self.metrics_timeout_s = float(metrics_timeout_s)

    def admin_url(self, rid: int) -> str:
        return f"http://127.0.0.1:{self.admin_ports[rid]}"

    def replica_ids(self) -> List[int]:
        return self.fleet.live_ids()

    def metrics(self, rid: int) -> Optional[Dict[str, Any]]:
        """One replica's JSON ``/metrics`` — None while it is down or
        mid-restart (the loop treats an unreachable replica as
        contributing no signal, not as pressure)."""
        try:
            with urllib.request.urlopen(
                    self.admin_url(rid) + "/metrics",
                    timeout=self.metrics_timeout_s) as r:
                return json.loads(r.read())
        except (OSError, ValueError, KeyError):
            return None

    def scale_up(self, ready_timeout_s: float = 300.0) -> int:
        """Spawn one supervised replica on the shared port and block until
        its heartbeat reaches ``serve/accepting``. Returns the replica id."""
        from .aserver import pick_free_port

        rid = self.fleet.replicas  # ids are never reused
        admin_port = pick_free_port()
        while admin_port in self.admin_ports.values() \
                or admin_port == self.port:
            admin_port = pick_free_port()
        got = self.fleet.add_replica(self.make_argv(rid, admin_port))
        assert got == rid, f"replica id drifted: {got} != {rid}"
        self.admin_ports[rid] = admin_port
        try:
            self.fleet.wait_ready(timeout=ready_timeout_s, indices=[rid])
        except Exception:
            # a replica that cannot come up must not linger half-started
            # (nor keep a stale admin port in the layout)
            self.fleet.stop_replica(rid)
            self.admin_ports.pop(rid, None)
            self.publish_layout()
            raise
        self.publish_layout()
        return rid

    def scale_down(self, rid: int,
                   drain_timeout_s: float = 10.0) -> str:
        """Gracefully remove one replica: POST ``/v1/drain`` (it stops
        accepting, flushes queued lanes, exits rc 0 → supervisor outcome
        ``success``), wait for the clean exit, SIGKILL via the supervisor
        if it never comes. Returns the drain outcome string."""
        outcome = "drained"
        try:
            req = urllib.request.Request(
                self.admin_url(rid) + "/v1/drain",
                data=json.dumps({"timeout_s": drain_timeout_s}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(
                    req, timeout=drain_timeout_s + self.http_timeout_s
                    ) as r:
                json.loads(r.read())
        except (OSError, ValueError):
            outcome = "drain_unreachable"
        # the drained replica closes its listener ~0.5 s after answering
        # and exits; give it that window before falling back to the kill
        deadline = time.monotonic() + drain_timeout_s + 5.0
        while rid in self.fleet.live_ids() \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        if rid in self.fleet.live_ids():
            outcome = "killed"
        self.fleet.stop_replica(rid)
        self.admin_ports.pop(rid, None)
        self.publish_layout()
        return outcome

    def publish_layout(self,
                       replica_ids: Optional[Sequence[int]] = None) -> None:
        """Atomic ``fleet.json`` rewrite: the LIVE layout (current replica
        ids and their admin endpoints). ``replica_ids`` overrides the
        live set for the BOOT publish — the configured layout must be on
        disk (port, admin endpoints) while replicas are still loading, so
        tooling can inspect a slow or wedged startup."""
        from .fleet import write_fleet_json

        live = (self.fleet.live_ids() if replica_ids is None
                else list(replica_ids))
        write_fleet_json(self.fleet.run_dir, {
            "host": self.host, "port": self.port,
            "replicas": len(live),
            "replica_ids": live,
            "admin_ports": {str(r): self.admin_ports[r] for r in live
                            if r in self.admin_ports},
            "admin_urls": [f"http://127.0.0.1:{self.admin_ports[r]}"
                           for r in live if r in self.admin_ports],
            "pointer": str(self.pointer) if self.pointer else None,
            "mesh": self.mesh,
            "mesh_slices": self.mesh_slices,
            "mesh_slice_by_replica": (
                {str(r): f"{r % self.mesh_slices}:{self.mesh_slices}"
                 for r in live}
                if self.mesh and self.mesh_slices else None),
            "total_replicas_ever": self.fleet.replicas,
        })


class Autoscaler:
    """The control loop (see module doc): scrape → signals → hysteresis →
    scale through a :class:`FleetController` (or any object with its
    ``replica_ids``/``metrics``/``scale_up``/``scale_down`` surface).

    ``tick()`` is one full evaluation — exposed so tests drive the loop
    deterministically without the thread."""

    def __init__(
        self,
        controller,
        policy: Optional[AutoscalePolicy] = None,
        events: Optional[EventLog] = None,
        flight: Any = None,
        max_decisions: int = 64,
    ):
        self.controller = controller
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.events = events
        self.flight = flight  # FlightRecorder: decisions ride its dumps
        self.decisions: deque = deque(maxlen=max_decisions)
        self.scale_ups = 0
        self.scale_downs = 0
        self._over_streak = 0
        self._under_streak = 0
        self._last_scale_mono = -float("inf")
        # rid -> (total requests, shed 429+503 total) at the last tick:
        # rates are per-tick deltas, not lifetime averages
        self._last_counts: Dict[int, Any] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signal extraction ----------------------------------------------------

    @staticmethod
    def _totals(metrics: Dict[str, Any]) -> Any:
        """(total responses, shed 429+503 responses) from a replica's
        ``requests`` tally ({"endpoint status": count})."""
        total = shed = 0
        for key, n in (metrics.get("requests") or {}).items():
            status = key.rsplit(" ", 1)[-1]
            if not status.isdigit():
                continue
            total += int(n)
            if int(status) in (429, 503):
                shed += int(n)
        return total, shed

    def signals(self) -> Dict[str, Any]:
        """One scrape across the live fleet → the tick's pressure
        signals. Unreachable replicas are skipped (they contribute no
        signal); per-replica request/shed counters are differenced
        against the previous tick."""
        rids = list(self.controller.replica_ids())
        depths: List[float] = []
        p99s: List[float] = []
        d_req = d_shed = 0
        scraped = 0
        for rid in rids:
            m = self.controller.metrics(rid)
            if m is None:
                continue
            scraped += 1
            batcher = m.get("batcher") or {}
            depths.append(float(batcher.get("pending") or 0))
            p99 = (m.get("latency") or {}).get("p99_ms")
            if isinstance(p99, (int, float)):
                p99s.append(float(p99))
            total, shed = self._totals(m)
            prev = self._last_counts.get(rid)
            # merge, don't replace: a replica that misses ONE scrape must
            # not re-contribute its lifetime totals as a single tick's
            # delta when it reappears. A first-seen replica (boot, or the
            # autoscaler starting against a warm fleet) contributes its
            # baseline, not its history.
            if prev is not None:
                # a restarted replica resets its counters: clamp at 0 so
                # the wrap never reads as negative load
                d_req += max(0, total - prev[0])
                d_shed += max(0, shed - prev[1])
            self._last_counts[rid] = (total, shed)
        return {
            "replicas": len(rids),
            "scraped": scraped,
            "mean_queue_depth": (round(sum(depths) / len(depths), 3)
                                 if depths else 0.0),
            "shed_delta": d_shed,
            "request_delta": d_req,
            "shed_rate": (round(d_shed / d_req, 4) if d_req else
                          (1.0 if d_shed else 0.0)),
            "p99_ms": max(p99s) if p99s else None,
        }

    # -- one evaluation -------------------------------------------------------

    def tick(self) -> Dict[str, Any]:
        pol = self.policy
        sig = self.signals()
        p99_tripped = (pol.up_p99_ms is not None
                       and sig["p99_ms"] is not None
                       and sig["p99_ms"] > pol.up_p99_ms)
        over = sig["scraped"] > 0 and (
            sig["mean_queue_depth"] >= pol.up_queue_depth
            or sig["shed_rate"] >= pol.up_shed_rate
            or p99_tripped)
        # `under` also requires p99 back below the threshold: the replica's
        # p99 is a sliding request window, which goes STALE when traffic
        # stops — without this guard a frozen over-threshold p99 would let
        # over and under trip on alternating branches and flap the fleet
        # up/down once per cooldown forever (conservative: the fleet holds
        # its size until fresh traffic refreshes the window)
        under = (sig["scraped"] > 0
                 and sig["mean_queue_depth"] <= pol.down_queue_depth
                 and sig["shed_delta"] == 0
                 and not p99_tripped)
        self._over_streak = self._over_streak + 1 if over else 0
        self._under_streak = self._under_streak + 1 if under else 0
        now = time.monotonic()
        in_cooldown = now - self._last_scale_mono < pol.cooldown_s
        decision = dict(sig, ts=round(time.time(), 3), action="hold")
        n = sig["replicas"]
        if not in_cooldown and self._over_streak >= pol.up_hysteresis \
                and n < pol.max_replicas:
            decision.update(action="up", reason=self._reason(sig, pol))
            self._act(decision)
        elif not in_cooldown \
                and self._under_streak >= pol.down_hysteresis \
                and n > pol.min_replicas:
            decision.update(action="down", reason="quiet")
            self._act(decision)
        elif in_cooldown:
            decision["cooldown"] = True
        self._record(decision)
        return decision

    @staticmethod
    def _reason(sig: Dict[str, Any], pol: AutoscalePolicy) -> str:
        if sig["mean_queue_depth"] >= pol.up_queue_depth:
            return f"queue_depth {sig['mean_queue_depth']}"
        if sig["shed_rate"] >= pol.up_shed_rate:
            return f"shed_rate {sig['shed_rate']}"
        return f"p99_ms {sig['p99_ms']}"

    def _act(self, decision: Dict[str, Any]) -> None:
        pol = self.policy
        direction = decision["action"]
        try:
            # fault site: a plan can raise/kill exactly as a scale event
            # is about to mutate the fleet — a `raise` fails THIS event
            # (recorded as {direction}_failed), never the control loop
            inject("fleet/scale", direction=direction,
                   path=f"replicas{decision['replicas']}")
            if direction == "up":
                rid = self.controller.scale_up(
                    ready_timeout_s=pol.ready_timeout_s)
                decision["replica"] = rid
                self.scale_ups += 1
            else:
                victim = max(self.controller.replica_ids())
                decision["replica"] = victim
                decision["outcome"] = self.controller.scale_down(
                    victim, drain_timeout_s=pol.drain_timeout_s)
                self.scale_downs += 1
        except Exception as e:
            # a failed spawn/drain must not kill the control loop: record
            # it, stay at current size, let the next tick retry after
            # cooldown
            decision["action"] = f"{direction}_failed"
            decision["error"] = f"{type(e).__name__}: {e}"
        self._over_streak = self._under_streak = 0
        self._last_scale_mono = time.monotonic()
        if self.events is not None:
            live = list(self.controller.replica_ids())
            self.events.counter(
                "fleet/scale", direction=direction,
                action=decision["action"],
                replica=decision.get("replica"),
                replicas=len(live),
                reason=decision.get("reason"),
                queue_depth=decision.get("mean_queue_depth"),
                shed_rate=decision.get("shed_rate"),
                error=decision.get("error"))
            self.events.gauge("fleet/replicas", len(live))

    def _record(self, decision: Dict[str, Any]) -> None:
        self.decisions.append(decision)
        if self.flight is not None:
            try:
                self.flight.record_decision(decision)
            except Exception:
                pass  # evidence, never a failure path

    # -- the control thread ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.policy.poll_s):
                try:
                    self.tick()
                except Exception:
                    # one bad scrape (replica mid-restart, torn JSON) must
                    # not end autoscaling for the fleet's whole life
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-autoscaler")
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
