"""Paper-style figures (reporting layer).

Reproduces the reference's five figures (``/root/reference/src/plots.py``):
cumulative SDF return with split shading, training curves with phase markers,
individual-vs-ensemble Sharpe bars against the paper's 0.75 line, monthly
return histogram + time series, and a summary-statistics table.

Differences from the reference: model evaluation is one vmapped device
program (no per-checkpoint Python loop), and dates come from the panel's own
YYYYMM `date` arrays instead of a hard-coded 1967 start. Matplotlib stays a
host-side, optional dependency — importing this module without it raises a
clear error only when a plot is actually drawn.
"""

from __future__ import annotations

import dataclasses
from datetime import datetime
from pathlib import Path
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from .data.panel import PanelDataset
from .data.pipeline import load_splits_cached
from .evaluate_ensemble import PAPER_TEST_SHARPE, stack_checkpoints
from .parallel.ensemble import ensemble_metrics, member_weights


def _plt():
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "matplotlib is required for plotting: pip install "
            "'deeplearninginassetpricing-paperreplication-tpu[plots]'"
        ) from e
    plt.rcParams.update(
        {
            "figure.figsize": (10, 6),
            "font.size": 12,
            "axes.labelsize": 12,
            "axes.titlesize": 14,
            "legend.fontsize": 10,
            "lines.linewidth": 1.5,
        }
    )
    return plt


def _dates_from_panel(*datasets: PanelDataset) -> List[datetime]:
    """YYYYMM date arrays → datetimes. Panels without a real date column
    (the loader falls back to np.arange) get a synthetic monthly sequence
    starting 1967-03, the reference's convention (plots.py:43-53)."""
    out = []
    counter_year, counter_month = 1967, 3
    for ds in datasets:
        for ymm in np.asarray(ds.dates):
            ymm = int(ymm)
            year, month = ymm // 100, ymm % 100
            if year < 1000 or not 1 <= month <= 12:  # index fallback, not YYYYMM
                year, month = counter_year, counter_month
            out.append(datetime(year, month, 1))
            counter_month += 1
            if counter_month > 12:
                counter_month = 1
                counter_year += 1
    return out


def _batch(ds: PanelDataset) -> Dict[str, jnp.ndarray]:
    return {k: jnp.asarray(v) for k, v in ds.full_batch().items()}


@dataclasses.dataclass
class PlotContext:
    """Checkpoints + panel loaded ONCE and shared by every figure (the
    reference reloads models and data inside each plot function)."""

    gan: object
    vparams: object
    train: PanelDataset
    valid: PanelDataset
    test: PanelDataset

    @classmethod
    def load(cls, checkpoint_dirs: Sequence[str], data_dir: str) -> "PlotContext":
        gan, vparams = stack_checkpoints(list(checkpoint_dirs))
        # cache-aware: figures re-load the panel the training run decoded
        train, valid, test = load_splits_cached(data_dir)
        return cls(gan, vparams, train, valid, test)

    def member_portfolio_returns(self, ds: PanelDataset) -> np.ndarray:
        """[S, T] per-member portfolio returns with normalized weights —
        the quantity the reference's figures average (plots.py:56-71)."""
        w = np.asarray(member_weights(self.gan, self.vparams, _batch(ds)))
        mask = ds.mask.astype(np.float32)
        return (w * ds.returns[None] * mask[None]).sum(axis=2)

    def metrics(self, ds: PanelDataset):
        return ensemble_metrics(self.gan, self.vparams, _batch(ds))


def plot_cumulative_sdf(
    checkpoint_dirs: Sequence[str],
    data_dir: str,
    save_path: Optional[str] = None,
    ctx: Optional[PlotContext] = None,
):
    """Cumulative SDF return across train/valid/test with shaded splits
    (reference plots.py:74-162). SDF return = NEGATED mean of the members'
    raw portfolio returns (the reference averages member returns here, with
    NO ensemble re-normalization — plots.py:118-123)."""
    plt = _plt()
    ctx = ctx or PlotContext.load(checkpoint_dirs, data_dir)
    train, valid, test = ctx.train, ctx.valid, ctx.test

    sdf_ret = -np.concatenate(
        [ctx.member_portfolio_returns(ds).mean(axis=0) for ds in (train, valid, test)]
    )
    cumulative = np.cumprod(1.0 + sdf_ret)
    dates = _dates_from_panel(train, valid, test)

    fig, ax = plt.subplots(figsize=(12, 6))
    ax.plot(dates, cumulative, "b-", label="GAN SDF")
    t_end = dates[train.T - 1]
    v_end = dates[train.T + valid.T - 1]
    ax.axvspan(dates[0], t_end, alpha=0.1, color="blue", label="Train")
    ax.axvspan(t_end, v_end, alpha=0.1, color="green", label="Valid")
    ax.axvspan(v_end, dates[-1], alpha=0.1, color="red", label="Test")
    ax.set_xlabel("Date")
    ax.set_ylabel("Cumulative Return")
    ax.set_title("Cumulative SDF Returns (Ensemble)")
    ax.legend(loc="upper left")
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=150, bbox_inches="tight")
    return fig, ax


def plot_training_curves(checkpoint_dir: str, save_path: Optional[str] = None):
    """Loss (log-scale) + Sharpe curves with phase-boundary markers
    (reference plots.py:165-214; Sharpe negated for the paper convention)."""
    plt = _plt()
    hist = np.load(Path(checkpoint_dir) / "history.npz", allow_pickle=True)
    epochs = np.arange(1, len(hist["train_loss"]) + 1)
    phases = np.asarray(hist["phase"])
    # phase boundary: last 'unc' row (phase 2 adds no rows)
    n_unc = int((phases == "unc").sum())

    fig, axes = plt.subplots(1, 2, figsize=(14, 5))
    axes[0].plot(epochs, hist["train_loss"], "b-", alpha=0.8, label="Train")
    axes[0].plot(epochs, hist["valid_loss"], "g-", alpha=0.8, label="Valid")
    axes[0].set_yscale("log")
    axes[0].set_xlabel("Epoch")
    axes[0].set_ylabel("Loss")
    axes[0].set_title("Training Loss")

    for key, style, label in (
        ("train_sharpe", "b-", "Train"),
        ("valid_sharpe", "g-", "Valid"),
        ("test_sharpe", "r-", "Test"),
    ):
        axes[1].plot(epochs, -np.asarray(hist[key]), style, alpha=0.8, label=label)
    axes[1].set_xlabel("Epoch")
    axes[1].set_ylabel("Sharpe Ratio (Monthly)")
    axes[1].set_title("Sharpe Ratio During Training")

    for ax in axes:
        if 0 < n_unc < len(epochs):
            ax.axvline(n_unc, color="gray", linestyle="--", alpha=0.5)
        ax.legend()
        ax.grid(True, alpha=0.3)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=150, bbox_inches="tight")
    return fig, axes


def plot_moment_violations(checkpoint_dir: str, save_path: Optional[str] = None):
    """Per-moment conditional violation norms over training — the
    model-health view of the no-arbitrage claim ``E[h_j · w·R · M] = 0``
    (one curve per h_j plus the max and the unconditional norm), from the
    ``diag_*`` history fields a ``--diag_stride`` run records. Pre-PR-14
    run dirs (no diag fields) skip gracefully: returns None, draws
    nothing."""
    hist = np.load(Path(checkpoint_dir) / "history.npz", allow_pickle=True)
    if "diag_moment_violations" not in hist.files:
        print(f"Skipping moment-violation panel: {checkpoint_dir} has no "
              "diag_* history fields (train with --diag_stride)")
        return None
    plt = _plt()
    mv = np.asarray(hist["diag_moment_violations"])  # [E, K]
    # the explicit stride sentinel — NOT a value field, so degenerate
    # (all-NaN) computed epochs still plot instead of vanishing.
    # x positions are HISTORY rows (phases 1+3; phase 2 records no rows),
    # the same convention as plot_training_curves — the dashed line marks
    # the phase-1/3 boundary like it does there
    computed = np.nonzero(np.asarray(hist["diag_computed"]))[0]
    n_unc = int((np.asarray(hist["phase"]) == "unc").sum())
    if computed.size == 0:
        print(f"Skipping moment-violation panel: {checkpoint_dir} recorded "
              "no computed diagnostic epochs")
        return None
    epochs = computed + 1

    fig, axes = plt.subplots(1, 2, figsize=(14, 5))
    for k in range(mv.shape[1]):
        axes[0].plot(epochs, mv[computed, k], alpha=0.6, linewidth=1,
                     label=f"h{k}" if mv.shape[1] <= 8 else None)
    axes[0].plot(epochs, np.asarray(hist["diag_moment_violation_max"])[computed],
                 "k-", linewidth=2, label="max")
    axes[0].plot(epochs, np.asarray(hist["diag_unc_violation"])[computed],
                 "k--", linewidth=1.5, label="unconditional")
    axes[0].set_yscale("log")
    axes[0].set_xlabel("Epoch")
    axes[0].set_ylabel("Violation Norm")
    axes[0].set_title("Per-Moment Conditional Violations")
    if mv.shape[1] <= 8:
        axes[0].legend(fontsize=8, ncol=2)

    axes[1].plot(epochs, np.asarray(hist["diag_adv_gap"])[computed], "b-",
                 label="cond − unc loss")
    axes[1].axhline(0, color="black", alpha=0.5)
    axes[1].set_xlabel("Epoch")
    axes[1].set_ylabel("Adversarial Gap")
    axes[1].set_title("Generator vs Discriminator Gap")
    axes[1].legend()
    for ax in axes:
        if 0 < n_unc < mv.shape[0]:
            ax.axvline(n_unc, color="gray", linestyle="--", alpha=0.5)
        ax.grid(True, alpha=0.3)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=150, bbox_inches="tight")
    return fig, axes


def plot_weight_concentration(checkpoint_dir: str,
                              save_path: Optional[str] = None):
    """Portfolio concentration/churn during training: weight HHI and
    max |w| (left), short fraction and month-to-month turnover (right),
    from the ``diag_*`` history fields. Skips gracefully (returns None)
    on run dirs without them."""
    hist = np.load(Path(checkpoint_dir) / "history.npz", allow_pickle=True)
    if "diag_weight_hhi" not in hist.files:
        print(f"Skipping weight-concentration panel: {checkpoint_dir} has "
              "no diag_* history fields (train with --diag_stride)")
        return None
    plt = _plt()
    # history-row x positions + phase-boundary marker: see
    # plot_moment_violations
    computed = np.nonzero(np.asarray(hist["diag_computed"]))[0]
    n_unc = int((np.asarray(hist["phase"]) == "unc").sum())
    n_rows = np.asarray(hist["diag_computed"]).shape[0]
    if computed.size == 0:
        print(f"Skipping weight-concentration panel: {checkpoint_dir} "
              "recorded no computed diagnostic epochs")
        return None
    epochs = computed + 1

    fig, axes = plt.subplots(1, 2, figsize=(14, 5))
    ax2 = axes[0].twinx()
    axes[0].plot(epochs, np.asarray(hist["diag_weight_hhi"])[computed],
                 "b-", label="HHI")
    ax2.plot(epochs, np.asarray(hist["diag_weight_max_abs"])[computed],
             "r-", alpha=0.7, label="max |w|")
    axes[0].set_xlabel("Epoch")
    axes[0].set_ylabel("HHI (Σ w²)", color="b")
    ax2.set_ylabel("max |w|", color="r")
    axes[0].set_title("Weight Concentration")

    axes[1].plot(epochs, np.asarray(hist["diag_short_fraction"])[computed],
                 "g-", label="short fraction")
    axes[1].plot(epochs, np.asarray(hist["diag_turnover"])[computed],
                 "m-", label="turnover")
    axes[1].set_xlabel("Epoch")
    axes[1].set_ylabel("Fraction of Unit Gross Book")
    axes[1].set_title("Short Fraction & Turnover")
    axes[1].legend()
    for ax in axes:
        if 0 < n_unc < n_rows:
            ax.axvline(n_unc, color="gray", linestyle="--", alpha=0.5)
        ax.grid(True, alpha=0.3)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=150, bbox_inches="tight")
    return fig, axes


def plot_sharpe_comparison(
    checkpoint_dirs: Sequence[str],
    data_dir: str,
    save_path: Optional[str] = None,
    ctx: Optional[PlotContext] = None,
):
    """Per-model vs mean vs ensemble test-Sharpe bars against the paper's
    0.75 line (reference plots.py:217-298)."""
    plt = _plt()
    ctx = ctx or PlotContext.load(checkpoint_dirs, data_dir)
    m = ctx.metrics(ctx.test)
    indiv = m["individual_sharpes"]
    values = list(indiv) + [float(indiv.mean()), float(m["ensemble_sharpe"])]
    labels = [f"Model {i+1}" for i in range(len(indiv))] + ["Mean", "Ensemble"]

    fig, ax = plt.subplots(figsize=(12, 6))
    colors = ["steelblue"] * len(indiv) + ["forestgreen", "darkred"]
    bars = ax.bar(np.arange(len(values)), values, color=colors, alpha=0.8,
                  edgecolor="black")
    ax.axhline(PAPER_TEST_SHARPE, color="red", linestyle="--", linewidth=2,
               label=f"Paper ({PAPER_TEST_SHARPE})")
    ax.set_xticks(np.arange(len(values)))
    ax.set_xticklabels(labels, rotation=45, ha="right")
    ax.set_ylabel("Test Sharpe Ratio (Monthly)")
    ax.set_title("Individual vs Ensemble Sharpe Ratio")
    ax.legend()
    ax.grid(True, alpha=0.3, axis="y")
    for bar, val in zip(bars, values):
        ax.text(bar.get_x() + bar.get_width() / 2, bar.get_height() + 0.01,
                f"{val:.3f}", ha="center", va="bottom", fontsize=9)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=150, bbox_inches="tight")
    return fig, ax


def plot_monthly_returns(
    checkpoint_dirs: Sequence[str],
    data_dir: str,
    save_path: Optional[str] = None,
    ctx: Optional[PlotContext] = None,
):
    """Histogram + time series of monthly test SDF returns
    (reference plots.py:301-365; mean of raw member returns, negated)."""
    plt = _plt()
    ctx = ctx or PlotContext.load(checkpoint_dirs, data_dir)
    test = ctx.test
    sdf_ret = -ctx.member_portfolio_returns(test).mean(axis=0)
    dates = _dates_from_panel(test)

    fig, axes = plt.subplots(1, 2, figsize=(14, 5))
    axes[0].hist(sdf_ret, bins=30, density=True, alpha=0.7,
                 color="steelblue", edgecolor="black")
    axes[0].axvline(sdf_ret.mean(), color="red", linestyle="--",
                    label=f"Mean: {sdf_ret.mean():.4f}")
    axes[0].axvline(0, color="black", alpha=0.5)
    axes[0].set_xlabel("Monthly Return")
    axes[0].set_ylabel("Density")
    axes[0].set_title("Distribution of Monthly SDF Returns (Test)")
    axes[0].legend()

    axes[1].plot(dates, sdf_ret, "b-", alpha=0.7, linewidth=1)
    axes[1].axhline(0, color="black", alpha=0.5)
    axes[1].fill_between(dates, sdf_ret, 0, where=sdf_ret > 0, alpha=0.3, color="green")
    axes[1].fill_between(dates, sdf_ret, 0, where=sdf_ret < 0, alpha=0.3, color="red")
    axes[1].set_xlabel("Date")
    axes[1].set_ylabel("Monthly Return")
    axes[1].set_title("Monthly SDF Returns Over Time (Test)")
    for ax in axes:
        ax.grid(True, alpha=0.3)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=150, bbox_inches="tight")
    return fig, axes


def summary_statistics(
    checkpoint_dirs: Sequence[str],
    data_dir: str,
    ctx: Optional[PlotContext] = None,
) -> Dict[str, float]:
    """The summary table's numbers (reference plots.py:368-427): moments,
    monthly+annual Sharpe, cumulative return, max drawdown of the negated
    ensemble (re-normalized averaged-weight) test return."""
    ctx = ctx or PlotContext.load(checkpoint_dirs, data_dir)
    m = ctx.metrics(ctx.test)
    sdf_ret = -m["ensemble_port_returns"]
    mean, std = sdf_ret.mean(), sdf_ret.std()
    cumulative = np.cumprod(1 + sdf_ret)
    running_max = np.maximum.accumulate(cumulative)
    return {
        "mean_monthly": float(mean),
        "std_monthly": float(std),
        "sharpe_monthly": float(mean / std),
        "sharpe_annual": float(mean / std * np.sqrt(12)),
        "min": float(sdf_ret.min()),
        "max": float(sdf_ret.max()),
        "skewness": float(((sdf_ret - mean) ** 3).mean() / std**3),
        "kurtosis": float(((sdf_ret - mean) ** 4).mean() / std**4 - 3),
        "cumulative_return": float(cumulative[-1] - 1),
        "max_drawdown": float(((cumulative - running_max) / running_max).min()),
        "sharpe_vs_paper": float(mean / std / PAPER_TEST_SHARPE),
        # paper Table-1 companions (EV / XS-R²), from the ensemble SDF factor
        "explained_variation": float(m["explained_variation"]),
        "cross_sectional_r2": float(m["cross_sectional_r2"]),
    }


def plot_summary_statistics(
    checkpoint_dirs: Sequence[str],
    data_dir: str,
    save_path: Optional[str] = None,
    ctx: Optional[PlotContext] = None,
):
    """Summary-statistics table rendered as a figure (plots.py:368-472)."""
    plt = _plt()
    stats = summary_statistics(checkpoint_dirs, data_dir, ctx=ctx)
    rows = [
        ["Mean (Monthly)", f"{stats['mean_monthly']:.4f}"],
        ["Std (Monthly)", f"{stats['std_monthly']:.4f}"],
        ["Sharpe (Monthly)", f"{stats['sharpe_monthly']:.4f}"],
        ["Sharpe (Annual)", f"{stats['sharpe_annual']:.2f}"],
        ["Min", f"{stats['min']:.4f}"],
        ["Max", f"{stats['max']:.4f}"],
        ["Skewness", f"{stats['skewness']:.2f}"],
        ["Kurtosis", f"{stats['kurtosis']:.2f}"],
        ["Cumulative Return", f"{stats['cumulative_return']:.2%}"],
        ["Max Drawdown", f"{stats['max_drawdown']:.2%}"],
        ["Explained Variation", f"{stats['explained_variation']:.4f}"],
        ["Cross-Sectional R2", f"{stats['cross_sectional_r2']:.4f}"],
        ["", ""],
        ["Paper Sharpe (Monthly)", f"{PAPER_TEST_SHARPE}"],
        ["Our Sharpe / Paper", f"{stats['sharpe_vs_paper']:.1%}"],
    ]
    fig, ax = plt.subplots(figsize=(10, 6))
    ax.axis("off")
    table = ax.table(cellText=rows, colLabels=["Metric", "Value"],
                     loc="center", cellLoc="center", colWidths=[0.4, 0.3])
    table.auto_set_font_size(False)
    table.set_fontsize(12)
    table.scale(1.2, 1.8)
    for i in range(2):
        table[(0, i)].set_facecolor("#4472C4")
        table[(0, i)].set_text_props(color="white", fontweight="bold")
    ax.set_title("Summary Statistics — Test Period", fontsize=14,
                 fontweight="bold", pad=20)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=150, bbox_inches="tight")
    return fig, ax


def generate_all_plots(
    checkpoint_dirs: Sequence[str],
    data_dir: str,
    output_dir: str = "./plots",
) -> List[str]:
    """All five figures into `output_dir` (reference plots.py:475-512)."""
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    plt = _plt()
    written = []
    ctx = PlotContext.load(checkpoint_dirs, data_dir)  # load once, share
    jobs = [
        ("cumulative_sdf.png", lambda p: plot_cumulative_sdf(checkpoint_dirs, data_dir, p, ctx=ctx)),
        ("training_curves.png", lambda p: plot_training_curves(checkpoint_dirs[0], p)),
        ("sharpe_comparison.png", lambda p: plot_sharpe_comparison(checkpoint_dirs, data_dir, p, ctx=ctx)),
        ("monthly_returns.png", lambda p: plot_monthly_returns(checkpoint_dirs, data_dir, p, ctx=ctx)),
        ("summary_statistics.png", lambda p: plot_summary_statistics(checkpoint_dirs, data_dir, p, ctx=ctx)),
        # model-health panels: these skip (return None, write nothing) on
        # run dirs whose history.npz predates --diag_stride
        ("moment_violations.png", lambda p: plot_moment_violations(checkpoint_dirs[0], p)),
        ("weight_concentration.png", lambda p: plot_weight_concentration(checkpoint_dirs[0], p)),
    ]
    for name, fn in jobs:
        path = str(out / name)
        result = fn(path)
        plt.close("all")
        if result is None:
            continue
        written.append(path)
        print(f"Saved: {path}")
    return written


def main(argv=None):
    from .utils.platform import apply_env_platforms

    apply_env_platforms()
    import argparse

    p = argparse.ArgumentParser(description="Generate paper-style figures")
    p.add_argument("--data_dir", type=str, required=True)
    p.add_argument("--checkpoint_dirs", type=str, nargs="+", required=True)
    p.add_argument("--output_dir", type=str, default="./plots")
    args = p.parse_args(argv)
    generate_all_plots(args.checkpoint_dirs, args.data_dir, args.output_dir)


if __name__ == "__main__":
    main()
