"""Device-memory snapshots aggregated over ALL local devices.

``Trainer.device_memory_stats`` used to read ``memory_stats()`` from device
0 only — on a multi-chip host that under-reports bytes-in-use by the device
count and can miss the one chip that is about to OOM. The aggregation rule:
byte/allocation counts SUM across devices; ``peak_*`` and ``*_limit``
counters take the MAX (a per-device high-water mark or capacity is not
additive evidence of pressure).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .events import EventLog

# keys that are per-device high-water marks or capacities — aggregate by max
_MAX_KEYS = ("peak", "largest", "limit")


def device_memory_snapshot() -> Dict[str, Any]:
    """``{"n_devices", "totals", "per_device"}`` from ``jax.local_devices()``.

    ``totals`` sums count-like stats and maxes peak/limit-like ones;
    ``per_device`` keeps every device's raw counters (tagged with the device
    string). Backends without ``memory_stats`` (CPU) yield empty dicts —
    callers need no gating.
    """
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return {"n_devices": 0, "totals": {}, "per_device": []}
    per_device = []
    totals: Dict[str, int] = {}
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        stats = {k: int(v) for k, v in stats.items()}
        per_device.append({"device": str(d), **stats})
        for k, v in stats.items():
            if any(tag in k for tag in _MAX_KEYS):
                totals[k] = max(totals.get(k, 0), v)
            else:
                totals[k] = totals.get(k, 0) + v
    return {"n_devices": len(devices), "totals": totals, "per_device": per_device}


def log_memory(events: Optional[EventLog], name: str = "device_memory",
               **attrs: Any) -> Dict[str, Any]:
    """Snapshot + emit one ``memory`` event (phase/segment boundaries only —
    ``memory_stats`` is a host-side counter read, never a device sync)."""
    snap = device_memory_snapshot()
    if events is not None:
        events.emit("memory", name, **snap, **attrs)
    return snap
