#!/usr/bin/env python
"""Tier-1 budget gate: validate the repo's BENCH_* trajectory against
``budgets.json`` — a thin wrapper over ``report --budget``.

    python tools/check_budgets.py [--budget budgets.json] [run_dirs...]

Exits non-zero on any regression, missing metric, or malformed budget
file (the gate never silently skips). Run dirs are optional: without
them only the file-scoped entries (the checked-in BENCH_*.json bounds)
are checked — which is exactly what CI wants. (The wrapper pays the
package import like the report CLI, but never touches a device.)
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("run_dirs", nargs="*",
                   help="Optional run dirs for run-scoped budget entries")
    p.add_argument("--budget", default=str(REPO / "budgets.json"),
                   help="Budget file (default: the repo's budgets.json)")
    args = p.parse_args(argv)

    from deeplearninginassetpricing_paperreplication_tpu.observability \
        import report as report_cli

    return report_cli.main(
        ["--budget", args.budget, *args.run_dirs])


if __name__ == "__main__":
    raise SystemExit(main())
