"""Post-hoc run aggregation: ``python -m ...paperreplication_tpu.report``.

Reads what a run directory already contains — ``manifest.json``,
``events.jsonl`` (plus any ``events.proc*.jsonl`` from workers),
``metrics.jsonl``, ``final_metrics.json`` — and prints the questions every
perf PR asks: where did the wall clock go (compile vs execute, per phase),
how fast was each phase (epochs/s), how much device memory did the run
touch, and (optionally) how the final Sharpes compare to a
``PARITY_*.json`` baseline. Pure file reading: nothing here initializes a
JAX backend or touches a device (running it as ``python -m
...paperreplication_tpu.report`` still pays the package import, but no
accelerator needs to be reachable), so it works on live, finished, or
crashed run dirs alike.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, List, Tuple

# ONE definition of the event-file family and the tolerant reader, shared
# with trace assembly — when the file family grows, trace and report can
# never disagree about which processes exist
from .trace import read_jsonl as _read_jsonl
from .trace import trace_file_paths

# the --parity moment-violation column's own tolerance (the 0.02 Sharpe
# bar is a different quantity at a different scale): the run's worst
# per-moment violation may exceed the baseline's by at most this relative
# factor, plus an absolute floor absorbing seed noise near zero
MOMENT_REL_BAR = 0.5
MOMENT_ABS_FLOOR = 1e-3

# metrics.jsonl phase tags → the trainer's phase span/timing labels
PHASE_LABELS = {
    "unc": "phase1_unconditional",
    "moment": "phase2_moment",
    "cond": "phase3_conditional",
}


def _latest_run_rows(rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Scope one file's rows to its most recent run: appended re-runs (and
    resumes) write under a fresh run_id, and only the last run's rows
    describe the run the directory currently holds. Files with no run_id
    anywhere (pre-telemetry writers) are kept whole; once any row carries a
    run_id, id-less legacy rows are dropped too — mixing them back in
    would double-count epochs against the scoped spans."""
    if not rows:
        return rows
    last_id = next(
        (r["run_id"] for r in reversed(rows) if r.get("run_id")), None)
    if last_id is None:
        return rows
    return [r for r in rows if r.get("run_id") == last_id]


def load_run(run_dir) -> Dict[str, Any]:
    """All of one run dir's telemetry artifacts, tolerantly parsed."""
    run_dir = Path(run_dir)
    manifest = None
    mpath = run_dir / "manifest.json"
    if mpath.exists():
        try:
            manifest = json.loads(mpath.read_text())
        except json.JSONDecodeError:
            manifest = None
    # per-file latest-run scoping (NOT a global manifest-run_id filter):
    # multihost workers' events.proc{p}.jsonl rows carry their own run ids,
    # and a manifest-wide filter would silently drop every worker row
    events: List[Dict[str, Any]] = []
    events_all: List[Dict[str, Any]] = []
    # replica*/ subdirs: a replicated serving fleet keeps one run dir per
    # replica under the fleet run dir — the fleet report spans all of them
    for p in trace_file_paths(run_dir):
        rows = _read_jsonl(p)
        events.extend(_latest_run_rows(rows))
        # UNscoped rows feed the reliability summary: a supervised run's
        # children each write under a fresh run_id, and restarts/faults/
        # guard trips must count across ALL of them, not just the last
        # child's (events.supervisor.jsonl and events.faults.jsonl ride the
        # same glob)
        events_all.extend(rows)
    final_metrics = None
    fpath = run_dir / "final_metrics.json"
    if fpath.exists():
        try:
            final_metrics = json.loads(fpath.read_text())
        except json.JSONDecodeError:
            final_metrics = None
    return {
        "run_dir": str(run_dir),
        "manifest": manifest,
        "events": events,
        "events_all": events_all,
        # same latest-run scoping: epoch counts must match the span
        # durations they are divided by (a resumed run reports the resumed
        # segment's throughput, not a mixed-run average)
        "metrics": _latest_run_rows(_read_jsonl(run_dir / "metrics.jsonl")),
        "final_metrics": final_metrics,
    }


def _span_ends(events, prefix: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for e in events:
        if e.get("kind") == "span_end" and str(e.get("name", "")).startswith(prefix):
            name = e["name"][len(prefix):]
            out[name] = out.get(name, 0.0) + float(e.get("duration_s") or 0.0)
    return out


def _compile_wall_seconds(events) -> Any:
    """Wall-clock of the compile stage: earliest compile span begin →
    latest end, per process, max over processes. The trainer compiles
    phase programs CONCURRENTLY (Trainer.precompile), so summing the
    per-program durations would overstate compile wall time ~3×; the
    per-process window uses each process's own monotonic clock (mono
    values are not comparable across processes)."""
    windows: Dict[int, list] = {}
    for e in events:
        if not str(e.get("name", "")).startswith("compile/"):
            continue
        mono = e.get("mono")
        if mono is None:
            continue
        w = windows.setdefault(int(e.get("process_index") or 0), [mono, mono])
        if e.get("kind") == "span_begin":
            w[0] = min(w[0], mono)
        elif e.get("kind") == "span_end":
            w[1] = max(w[1], mono)
    spans = [max(0.0, b - a) for a, b in windows.values()]
    return round(max(spans), 3) if spans else None


def _startup_summary(events) -> Any:
    """The startup pipeline's stage breakdown, when a run carries
    ``startup/*`` spans (data/pipeline.py): per-stage span-duration sums
    plus the OVERLAP-ADJUSTED wall window (earliest begin → latest end per
    process, max over processes — the same logic as the compile wall: the
    stages run concurrently, so summing their durations would overstate the
    startup cost ~3×). Cache hit/miss counts ride along from the
    ``panel_cache`` counters. Runs on the sharded data plane additionally
    carry ``startup/shard_*`` events (data/pipeline.py chunked reader +
    per-shard transfer); those aggregate into a ``dataplane`` subsection:
    shards owned / loaded-from-cache / re-decoded, per-shard transfer span
    count + summed dispatch window, and the peak host RSS gauge. The gauge
    fires on every pipeline run, so unsharded runs report it standalone
    (top-level ``peak_rss_bytes``) with no dataplane subsection. None when
    the run predates the pipeline."""
    stages: Dict[str, float] = {}
    windows: Dict[int, list] = {}
    hits = misses = 0
    shards_owned = shards_loaded = shards_redecoded = 0
    shard_transfers = 0
    shard_transfer_s = 0.0
    peak_rss = None
    for e in events:
        name = str(e.get("name", ""))
        kind = e.get("kind")
        if kind == "counter" and name == "panel_cache":
            if e.get("hit"):
                hits += int(e.get("value") or 0)
            else:
                misses += int(e.get("value") or 0)
            continue
        if not name.startswith("startup/"):
            continue
        if kind == "counter":
            v = int(e.get("value") or 0)
            if name == "startup/shard_owned":
                shards_owned += v
            elif name == "startup/shard_loaded":
                shards_loaded += v
            elif name == "startup/shard_redecode":
                shards_redecoded += v
            continue
        if kind == "gauge" and name == "startup/peak_rss":
            v = e.get("value")
            if v is not None:
                peak_rss = max(peak_rss or 0, int(v))
            continue
        if kind == "span_end":
            stage = name[len("startup/"):]
            stages[stage] = stages.get(stage, 0.0) + float(
                e.get("duration_s") or 0.0)
            if stage == "shard_transfer":
                shard_transfers += 1
                shard_transfer_s += float(e.get("duration_s") or 0.0)
        if kind in ("span_begin", "span_end"):
            mono = e.get("mono")
            if mono is None:
                continue
            w = windows.setdefault(
                int(e.get("process_index") or 0), [mono, mono])
            w[0] = min(w[0], mono)
            w[1] = max(w[1], mono)
    if not stages and not shards_owned:
        return None
    walls = [max(0.0, b - a) for a, b in windows.values()]
    # the subsection asserts the run used the chunked store / shard-local
    # loading, so it only appears when shards were actually in play; the
    # peak-RSS gauge fires on every pipeline run and reports standalone
    dataplane = None
    if shards_owned or shard_transfers:
        dataplane = {
            "shards_owned": shards_owned,
            "shards_loaded": shards_loaded,
            "shards_redecoded": shards_redecoded,
            "shard_transfers": shard_transfers,
            "shard_transfer_s": round(shard_transfer_s, 3),
            "peak_rss_bytes": peak_rss,
        }
    return {
        "wall_s": round(max(walls), 3) if walls else None,
        "stages": {k: round(v, 3) for k, v in sorted(stages.items())},
        "cache": ({"hits": hits, "misses": misses}
                  if (hits or misses) else None),
        "dataplane": dataplane,
        "peak_rss_bytes": peak_rss,
    }


def latency_percentiles_ms(latencies_s, pcts=(50, 95, 99)) -> Any:
    """Nearest-rank percentiles in milliseconds — THE one latency summary
    shared by the serving ``/metrics`` endpoint, the load generator, and
    this report CLI, so the three can never disagree numerically for the
    same labels. Pure stdlib (this module must not import numpy/jax).
    Returns None for an empty series."""
    if not latencies_s:
        return None
    import math

    s = sorted(latencies_s)
    out: Dict[str, Any] = {"count": len(s)}
    for p in pcts:
        idx = min(len(s) - 1, max(0, math.ceil(p / 100 * len(s)) - 1))
        out[f"p{p}_ms"] = round(s[idx] * 1e3, 3)
    return out


def _serving_summary(events) -> Any:
    """A serving run's request-path breakdown, when the run carries
    ``serve/*`` events (serving/server.py + engine.py + batcher.py):
    request counts per endpoint/status (and per replica for a fleet),
    latency percentiles from the ``serve/request`` span durations, cache
    hit rate, dispatch count, continuous-batching occupancy/queue-depth
    aggregates, the 503 rate, and — the steady-state guarantee — the
    recompile count. None for non-serving runs."""
    latencies: List[float] = []
    requests: Dict[str, int] = {}
    by_replica: Dict[str, int] = {}
    occupancy: Dict[str, int] = {}
    traced_rows: List[Dict[str, Any]] = []
    flight_dumps: Dict[str, int] = {}
    cache_hits = cache_misses = 0
    recompiles = dispatches = macro_appends = reloads = 0
    flushes = 0
    n_503 = 0
    queue_depth_sum = 0
    # load-adaptive plane tallies: admission shedding, single-flight
    # coalescing, autoscaler scale events, graceful drains
    shed_by_reason: Dict[str, int] = {}
    shed_by_priority: Dict[str, int] = {}
    coalesce_hits = coalesce_misses = 0
    scale_events: List[Dict[str, Any]] = []
    replicas_gauge: Any = None
    drains = 0
    lat_by_priority: Dict[str, List[float]] = {}
    for e in events:
        name = str(e.get("name", ""))
        kind = e.get("kind")
        if kind in ("span_end", "request") and name == "serve/request" \
                and e.get("priority") is not None:
            lat_by_priority.setdefault(str(e["priority"]), []).append(
                float(e.get("duration_s") or 0.0))
        if kind == "span_end" and name == "serve/request":
            latencies.append(float(e.get("duration_s") or 0.0))
        elif kind == "request" and name == "serve/request":
            # the sampled per-request trace record: same latency stream as
            # the span_end twin, plus segment evidence for the tail section
            latencies.append(float(e.get("duration_s") or 0.0))
            traced_rows.append(e)
        elif kind == "counter" and name == "serve/shed":
            value = int(e.get("value") or 1)
            reason = str(e.get("reason") or "unknown")
            shed_by_reason[reason] = shed_by_reason.get(reason, 0) + value
            pri = str(e.get("priority") or "unknown")
            shed_by_priority[pri] = shed_by_priority.get(pri, 0) + value
        elif kind == "counter" and name == "serve/coalesce":
            if e.get("hit"):
                coalesce_hits += int(e.get("value") or 1)
            else:
                coalesce_misses += int(e.get("value") or 1)
        elif kind == "counter" and name == "fleet/scale":
            scale_events.append({
                "action": e.get("action") or e.get("direction"),
                "replica": e.get("replica"),
                "replicas": e.get("replicas"),
                "reason": e.get("reason"),
                "queue_depth": e.get("queue_depth"),
                "shed_rate": e.get("shed_rate"),
            })
        elif kind == "gauge" and name == "fleet/replicas":
            replicas_gauge = e.get("value")
        elif kind == "counter" and name == "serve/drain":
            drains += int(e.get("value") or 1)
        elif kind == "counter" and name == "serve/flightrecorder":
            reason = str(e.get("reason") or "unknown")
            flight_dumps[reason] = (
                flight_dumps.get(reason, 0) + int(e.get("value") or 1))
        elif kind == "span_end" and name == "serve/dispatch":
            dispatches += 1
        elif kind == "counter" and name == "serve/requests":
            key = f"{e.get('endpoint')} {e.get('status')}"
            value = int(e.get("value") or 0)
            requests[key] = requests.get(key, 0) + value
            if e.get("replica") is not None:
                rep = str(e.get("replica"))
                by_replica[rep] = by_replica.get(rep, 0) + value
            if int(e.get("status") or 0) == 503:
                n_503 += value
        elif kind == "counter" and name == "serve/cache":
            if e.get("hit"):
                cache_hits += int(e.get("value") or 0)
            else:
                cache_misses += int(e.get("value") or 0)
        elif kind == "counter" and name == "serve/recompile":
            recompiles += int(e.get("value") or 0)
        elif kind == "counter" and name == "serve/macro_append":
            macro_appends += int(e.get("value") or 0)
        elif kind == "counter" and name == "serve/reload":
            reloads += int(e.get("value") or 0)
        elif kind == "counter" and name == "serve/flush":
            flushes += 1
            occ = str(e.get("occupancy"))
            occupancy[occ] = occupancy.get(occ, 0) + 1
            queue_depth_sum += int(e.get("queue_depth") or 0)
    if not (latencies or requests or recompiles):
        return None
    lat = latency_percentiles_ms(latencies)
    lookups = cache_hits + cache_misses
    total = sum(requests.values())
    out = {
        "requests": dict(sorted(requests.items())),
        "total_requests": total,
        "latency": lat,
        "cache": ({"hits": cache_hits, "misses": cache_misses,
                   "hit_rate": round(cache_hits / lookups, 4)}
                  if lookups else None),
        "recompiles": recompiles,
        "dispatches": dispatches,
        "macro_appends": macro_appends,
        "rate_503": round(n_503 / total, 4) if total else None,
    }
    if reloads:
        out["reloads"] = reloads
    if by_replica:
        out["requests_by_replica"] = dict(sorted(by_replica.items()))
    if traced_rows:
        out["traced_requests"] = len(traced_rows)
        out["tail_latency"] = _tail_latency(traced_rows)
    if flight_dumps:
        out["flightrecorder_dumps"] = dict(sorted(flight_dumps.items()))
    if shed_by_reason:
        # admission-control evidence: who was deliberately turned away
        out["shed"] = {
            "total": sum(shed_by_reason.values()),
            "by_reason": dict(sorted(shed_by_reason.items())),
            "by_priority": dict(sorted(shed_by_priority.items())),
        }
    if coalesce_hits or coalesce_misses:
        lookups = coalesce_hits + coalesce_misses
        out["coalesce"] = {
            "hits": coalesce_hits,
            "dispatches": coalesce_misses,
            "hit_rate": round(coalesce_hits / lookups, 4),
            # the O(users) → O(distinct queries) ratio: dispatches per
            # coalesce-eligible request (≪ 1 under duplicate-heavy load)
            "dispatch_ratio": round(coalesce_misses / lookups, 4),
        }
    if lat_by_priority:
        out["latency_by_priority"] = {
            p: latency_percentiles_ms(ls)
            for p, ls in sorted(lat_by_priority.items())}
    if scale_events or replicas_gauge is not None:
        ups = sum(1 for s in scale_events if s["action"] == "up")
        downs = sum(1 for s in scale_events if s["action"] == "down")
        out["autoscale"] = {
            "scale_ups": ups,
            "scale_downs": downs,
            "failed": sum(1 for s in scale_events
                          if str(s["action"]).endswith("_failed")),
            "replicas_final": replicas_gauge,
            "events": scale_events[-10:],
        }
    if drains:
        out["drains"] = drains
    if flushes:
        # continuous-batching evidence: how full the device programs ran
        # and how much queueing pressure stood behind each flush
        out["batching"] = {
            "flushes": flushes,
            "occupancy_hist": {
                k: occupancy[k]
                for k in sorted(occupancy, key=lambda s: int(s))},
            "mean_queue_depth": round(queue_depth_sum / flushes, 3),
        }
    return out


# request-row segment fields, in pipeline order, → tail-attribution ms keys
_SEGMENT_FIELDS = (
    ("parse_s", "parse"), ("queue_s", "queue_wait"),
    ("batch_s", "batch_wait"), ("dispatch_share_s", "dispatch_share"),
    ("serialize_s", "serialize"), ("write_s", "write"),
)


def _tail_latency(traced_rows: List[Dict[str, Any]],
                  n: int = 5) -> List[Dict[str, Any]]:
    """The slowest-N traced requests, attributed segment by segment — WHERE
    each slow request spent its time (batcher lane, flush wait, dispatch
    share, serialization, socket write). Deterministic order: duration
    desc, then trace id."""
    rows = sorted(
        traced_rows,
        key=lambda r: (-(float(r.get("duration_s") or 0.0)),
                       str(r.get("trace_id"))))[:n]
    out = []
    for r in rows:
        entry: Dict[str, Any] = {
            "trace_id": r.get("trace_id"),
            "endpoint": r.get("endpoint"),
            "status": r.get("status"),
            "total_ms": round(float(r.get("duration_s") or 0.0) * 1e3, 3),
            "segments_ms": {
                label: round(float(r[field]) * 1e3, 3)
                for field, label in _SEGMENT_FIELDS
                if isinstance(r.get(field), (int, float))
            },
        }
        for key in ("flush", "occupancy", "replica", "wire", "cached"):
            if r.get(key) is not None:
                entry[key] = r[key]
        out.append(entry)
    return out


def _fmt_segments(segments_ms: Dict[str, float]) -> str:
    return "  ".join(f"{k}={v:.2f}" for k, v in segments_ms.items())


def _reliability_summary(events) -> Any:
    """A supervised/fault-injected run's recovery story, when the run
    carries reliability events: deaths with per-section attribution
    (``supervise/death``) and actual restarts (``supervise/restart`` —
    a terminal death is not a restart, so the two can differ by one), the
    supervisor's final outcome, faults injected per site/action
    (``fault/injected``, from the injector's DLAP_FAULT_EVENTS file),
    divergence-guard trips (``guard/trip``), and verified-checkpoint
    generation fallbacks (``checkpoint/fallback`` / ``checkpoint/unusable``).
    Counts run over ALL rows (not latest-run scoped): each restarted child
    logs under its own run_id and every one of them is part of the story.
    None for runs with no reliability events."""
    restarts = hang_kills = guard_trips = fallbacks = unusable = 0
    deaths: Dict[str, int] = {}
    faults: Dict[str, int] = {}
    outcome = None
    for e in events:
        if e.get("kind") != "counter":
            continue
        name = str(e.get("name", ""))
        value = int(e.get("value") or 1)
        if name == "supervise/death":
            section = str(e.get("section") or "setup")
            deaths[section] = deaths.get(section, 0) + value
            if e.get("hang"):
                hang_kills += value
        elif name == "supervise/restart":
            restarts += value
        elif name == "supervise/outcome":
            outcome = {
                "outcome": e.get("outcome"),
                "restarts": e.get("restarts"),
                "returncode": e.get("returncode"),
            }
        elif name == "fault/injected":
            key = f"{e.get('site')}:{e.get('action')}"
            faults[key] = faults.get(key, 0) + value
        elif name == "guard/trip":
            guard_trips += value
        elif name == "checkpoint/fallback":
            fallbacks += value
        elif name == "checkpoint/unusable":
            unusable += value
    if not (restarts or deaths or faults or guard_trips or fallbacks
            or unusable or outcome):
        return None
    return {
        "restarts": restarts,
        "hang_kills": hang_kills,
        "deaths_by_section": dict(sorted(deaths.items())),
        "outcome": outcome,
        "faults_injected": dict(sorted(faults.items())),
        "guard_trips": guard_trips,
        "checkpoint_fallbacks": fallbacks,
        "checkpoint_unusable": unusable,
    }


def _elastic_summary(events, run_dir) -> Any:
    """An elastic sweep's fleet story, when the run carries ``sweep/*``
    elastic events (reliability/scheduler.py + parallel/sweep.py) or a
    ledger directory: buckets completed / retried / quarantined, ledger
    hits (resumed-from-ledger evidence: completed buckets NOT re-trained),
    lease takeovers, per-worker claim and completion counts, and quorum
    drops. Counts run over ALL rows (workers and restarted children each
    log under their own run_id — like the reliability section). The ledger
    directory, when present, supplies the authoritative bucket totals; a
    run with neither returns None."""
    claims_by_worker: Dict[str, int] = {}
    done_by_worker: Dict[str, int] = {}
    hits = writes = retries = takeovers = quarantines = 0
    quorum_drops: List[Dict[str, Any]] = []
    seen_any = False
    for e in events:
        if e.get("kind") != "counter":
            continue
        name = str(e.get("name", ""))
        value = int(e.get("value") or 1)
        if name == "sweep/claim":
            worker = str(e.get("worker") or "?")
            claims_by_worker[worker] = claims_by_worker.get(worker, 0) + value
        elif name == "sweep/ledger_write":
            worker = str(e.get("worker") or "inline")
            done_by_worker[worker] = done_by_worker.get(worker, 0) + value
            writes += value
        elif name == "sweep/ledger_hit":
            hits += value
        elif name == "sweep/retry":
            retries += value
        elif name == "sweep/lease_takeover":
            takeovers += value
        elif name == "sweep/quarantine":
            quarantines += value
        elif name == "sweep/quorum_drop":
            quorum_drops.append(
                {"rank": e.get("rank"), "seed": e.get("seed")})
        else:
            continue
        seen_any = True
    # the ledger dir (stdlib-only module) is the authoritative tally of
    # what the run dir HOLDS — events say what this run DID
    ledger_counts = None
    ledger_root = Path(run_dir) / "sweep_ledger"
    if (ledger_root / "queue.json").exists():
        from ..reliability.ledger import SweepLedger

        ledger = SweepLedger(ledger_root)
        try:
            manifest = json.loads((ledger_root / "queue.json").read_text())
            total = len(manifest.get("items", []))
        except (OSError, json.JSONDecodeError):
            total = None
        ledger_counts = {
            "total_buckets": total,
            "records": len(ledger.keys()),
            "quarantined": len(ledger.quarantined()),
        }
    if not seen_any and ledger_counts is None:
        return None
    return {
        "buckets_completed": writes,
        "ledger_hits": hits,
        "retries": retries,
        "lease_takeovers": takeovers,
        "quarantined": quarantines,
        "claims_by_worker": dict(sorted(claims_by_worker.items())),
        "completed_by_worker": dict(sorted(done_by_worker.items())),
        "quorum_drops": quorum_drops,
        "ledger": ledger_counts,
    }


def _promotion_summary(events, run_dir) -> Any:
    """The promotion control plane's story, when the run carries
    ``promote/*`` or ``serve/generation``/``serve/reload`` events
    (reliability/promotion.py + serving/fleet.RollingUpdater +
    serving/server.py): generations promoted and rolled back, gate
    rejections bucketed by reason, reload swap/no-op counts, and the
    per-replica serving-generation convergence timeline (every
    ``serve/generation`` row is one "replica R began serving fingerprint F"
    transition — boot rows included, so a replica that died mid-promotion
    and converged on restart shows its whole path). Counts run over ALL
    rows (restarted replicas and the refit coordinator each log under
    their own run_id). The pointer file, when the run dir holds one, adds
    the authoritative head. None when the run has no promotion events."""
    promotions = pointer_rollbacks = fleet_rollbacks = fleet_converged = 0
    reloads_swapped = reloads_noop = 0
    rejections: Dict[str, int] = {}
    timeline: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        if e.get("kind") != "counter":
            continue
        name = str(e.get("name", ""))
        value = int(e.get("value") or 1)
        if name == "promote/advance":
            promotions += value
        elif name == "promote/reject":
            reason = str(e.get("reason") or "unknown")
            rejections[reason] = rejections.get(reason, 0) + value
        elif name == "promote/rollback":
            pointer_rollbacks += value
        elif name == "promote/fleet_rollback":
            fleet_rollbacks += value
        elif name == "promote/fleet_converged":
            fleet_converged += value
        elif name == "serve/reload":
            if e.get("swapped") is False:
                reloads_noop += value
            else:
                reloads_swapped += value
        elif name == "serve/generation":
            replica = str(e.get("replica") or "?")
            timeline.setdefault(replica, []).append({
                "ts": e.get("ts"),
                "generation": e.get("generation"),
                "fingerprint": e.get("fingerprint"),
                "pointer_generation": e.get("pointer_generation"),
                "boot": bool(e.get("boot")),
            })
    if not (promotions or rejections or pointer_rollbacks or fleet_rollbacks
            or fleet_converged or reloads_swapped or reloads_noop
            or timeline):
        return None
    for rows in timeline.values():
        rows.sort(key=lambda r: (r["ts"] is None, r["ts"]))
    serving = {r: rows[-1]["fingerprint"] for r, rows in timeline.items()}
    out = {
        "promotions": promotions,
        "pointer_rollbacks": pointer_rollbacks,
        "fleet_rollbacks": fleet_rollbacks,
        "fleet_converged": fleet_converged,
        "rejections_by_reason": dict(sorted(rejections.items())),
        "reloads": {"swapped": reloads_swapped, "noop": reloads_noop},
        "replica_timeline": {r: rows for r, rows in sorted(timeline.items())},
        "serving_fingerprints": dict(sorted(serving.items())),
        "converged": (len(set(serving.values())) == 1 if serving else None),
    }
    # the pointer artifact (stdlib read) is the authoritative CURRENT head
    pointer_path = Path(run_dir) / "serving_current.json"
    if pointer_path.exists():
        try:
            from ..reliability.promotion import read_pointer

            head = read_pointer(pointer_path)
        except (ValueError, OSError):
            head = None
        if head is not None:
            out["pointer"] = {
                "generation": head.get("generation"),
                "fingerprint": str(
                    head.get("params_fingerprint") or "")[:16],
                "source": head.get("source"),
                "valid_sharpe": head.get("valid_sharpe"),
                "history": len(head.get("history") or []),
                "rolled_back_from": head.get("rolled_back_from"),
            }
    return out


def _model_health_summary(run_dir, events) -> Any:
    """The model-health story of one run dir: the verified ``health.json``
    artifact (written by the trainer — per-moment violation norms, SDF /
    portfolio diagnostics, divergence-guard trips), the reference-profile
    presence, and the serving drift monitor's event counters. None when
    the run predates the health plane (no health.json, no drift/health
    events) — old run dirs summarize byte-stably with the section absent
    and the text report printing its "(no health data)" placeholder."""
    from .drift import PROFILE_FILENAME
    from .modelhealth import read_health

    health = read_health(run_dir)
    drift_alerts = drift_scored = canary_swaps = 0
    last_psi = None
    canary_max_delta = None
    for e in events:
        name = str(e.get("name", ""))
        kind = e.get("kind")
        if kind == "counter" and name == "model/drift_alert":
            drift_alerts += int(e.get("value") or 1)
        elif kind == "gauge" and name == "model/drift_psi":
            last_psi = e.get("value")
            drift_scored += 1
        elif kind == "counter" and name == "serve/canary":
            canary_swaps += 1
            d = e.get("max_weight_delta")
            if d is not None:
                canary_max_delta = max(canary_max_delta or 0.0, float(d))
    has_profile = (Path(run_dir) / PROFILE_FILENAME).exists()
    if health is None and not (drift_alerts or drift_scored or canary_swaps
                               or has_profile):
        return None
    out: Dict[str, Any] = {
        "reference_profile": has_profile,
    }
    if health is not None:
        diag = health.get("diagnostics") or {}
        out.update({
            "finite": health.get("finite"),
            "split": health.get("split"),
            "guard_trips": health.get("guard_trips", 0),
            "moment_violation_max": diag.get("moment_violation_max"),
            "moment_violations": diag.get("moment_violations"),
            "unc_violation": diag.get("unc_violation"),
            "adv_gap": diag.get("adv_gap"),
            "sdf": {k: diag.get(k) for k in
                    ("sdf_mean", "sdf_vol", "sdf_min", "sdf_finite_frac")},
            "portfolio": {k: diag.get(k) for k in
                          ("weight_hhi", "weight_max_abs",
                           "short_fraction", "turnover")},
        })
    if drift_scored or drift_alerts:
        out["drift"] = {"scored": drift_scored, "alerts": drift_alerts,
                        "psi_last": last_psi}
    if canary_swaps:
        out["canary"] = {"hot_swaps": canary_swaps,
                         "max_weight_delta": canary_max_delta}
    return out


def _slo_summary(events) -> Any:
    """The SLO/alerting story of one run dir: probe totals (blackbox
    checks, failures, digest changes), alert transitions, and the
    current firing set + last burn-rate/budget gauges. The row semantics
    live in ONE place — ``statusboard.scan_slo_rows`` — shared with the
    ops console, so the report CLI and ``ops status`` can never disagree
    about what the durable ``alert``/``probe`` rows mean. None when the
    run predates the plane (section absent, text report byte-stable)."""
    from .statusboard import scan_slo_rows

    scan = scan_slo_rows(events)
    # the SAME presence gate as statusboard.gather_status: a prober that
    # only ever recorded layout_unreadable (blind on a dead fleet dir)
    # must surface in the report exactly as it does in `ops status`
    if not (scan["last_state"] or scan["burn"] or scan["probe_checks"]
            or scan["probe_failures"] or scan["layout_unreadable"]):
        return None
    firing_now = sorted(
        f"{o} [{w}]" for (o, w), row in scan["last_state"].items()
        if row.get("name") == "alert/firing")
    return {
        "probe": {
            "checks": scan["probe_checks"],
            "failures": scan["probe_failures"],
            "digest_changes": scan["digest_changes"],
            "layout_unreadable": scan["layout_unreadable"],
            "failures_by_target": dict(
                sorted(scan["failure_targets"].items())),
        },
        "alerts": {"firings": scan["firings"],
                   "resolves": scan["resolves"],
                   "firing_now": firing_now},
        "burn_rates": {f"{o} {w}": v
                       for (o, w), v in sorted(scan["burn"].items())},
        "budget_remaining": {
            f"{o} {w}": v
            for (o, w), v in sorted(scan["budget"].items())},
    }


def _xla_programs_summary(manifest, events) -> Any:
    """The run's AOT program cost/memory table: ``manifest.json``'s
    ``xla_programs`` (written by the CLIs after compile), falling back to
    the ``program`` event rows for runs whose manifest predates the patch
    or whose CLI died before writing it. None when the run compiled no
    introspected programs (old run dirs — the section stays absent)."""
    progs = (manifest or {}).get("xla_programs")
    if isinstance(progs, dict) and progs:
        return progs
    from .xla import programs_from_events

    return programs_from_events(events) or None


def _metrics_crosscheck(run_dir, events) -> Any:
    """Cross-check the run dir's final metrics snapshot (``metrics.prom``,
    written by the serving service at clean shutdown) against the events
    plane: request/recompile totals must agree, and the steady-state
    recompile gauge — the zero-recompile guarantee measured by the METRICS
    plane, not just events — must be zero. The snapshot holds only the
    FINAL process incarnation's registry (a supervised restart starts a
    fresh one), so the events side is scoped to the last run_id that
    served — an unscoped comparison would flag every restarted run as
    disagreeing. None when the run left no snapshot (old run dirs: the
    section stays absent)."""
    path = Path(run_dir) / "metrics.prom"
    if not path.exists():
        return None
    from .metrics import parse_prom_text

    try:
        metrics = parse_prom_text(path.read_text())
    except (OSError, ValueError) as e:
        return {"error": f"metrics.prom unreadable: {e}"}
    out: Dict[str, Any] = {
        "requests": int(sum(
            (metrics.get("dlap_serve_requests_total") or {}).values())),
        "recompiles": int(sum(
            (metrics.get("dlap_serve_recompile_total") or {}).values())),
    }
    steady = metrics.get("dlap_serve_steady_state_recompiles")
    if steady:
        n = int(sum(steady.values()))
        out["steady_state_recompiles"] = n
        out["steady_state_ok"] = n == 0
    last_rid = None
    for e in events:
        if str(e.get("name", "")).startswith("serve/"):
            last_rid = e.get("run_id")
    if last_rid is not None:
        ev_requests = ev_recompiles = 0
        for e in events:
            if e.get("run_id") != last_rid or e.get("kind") != "counter":
                continue
            name = e.get("name")
            if name == "serve/requests":
                ev_requests += int(e.get("value") or 0)
            elif name == "serve/recompile":
                ev_recompiles += int(e.get("value") or 0)
        out["requests_agree"] = out["requests"] == ev_requests
        out["recompiles_agree"] = out["recompiles"] == ev_recompiles
    return out


def summarize_run(run: Dict[str, Any]) -> Dict[str, Any]:
    """One run dir → the compile/execute/throughput/memory summary dict."""
    events = run["events"]
    fm = run["final_metrics"] or {}

    compile_s = _span_ends(events, "compile/")
    compile_wall = _compile_wall_seconds(events)
    if not compile_s and fm.get("compile_seconds"):
        compile_s = {k: float(v) for k, v in fm["compile_seconds"].items()}

    phase_s = _span_ends(events, "phase/")
    if not phase_s and fm.get("phase_execute_seconds"):
        phase_s = {k: float(v) for k, v in fm["phase_execute_seconds"].items()}

    # epochs EXECUTED under the measured span, best evidence first:
    #   1. the trainer's `epochs_dispatched` counters — exact for budget
    #      stops (span attrs only know the PLANNED count) and resumes;
    #   2. span attrs (epochs - start_epoch) — planned count of the
    #      measured segment;
    #   3. metrics.jsonl row counts — whole-phase history rows.
    epochs_by_counter: Dict[str, int] = {}
    epochs_by_span: Dict[str, int] = {}
    for e in events:
        if e.get("kind") == "counter" and e.get("name") == "epochs_dispatched":
            label = e.get("phase")
            if label:
                epochs_by_counter[label] = (
                    epochs_by_counter.get(label, 0) + int(e.get("value") or 0))
        elif (e.get("kind") == "span_end"
                and str(e.get("name", "")).startswith("phase/")
                and e.get("epochs") is not None):
            label = e["name"][len("phase/"):]
            n = int(e["epochs"]) - int(e.get("start_epoch") or 0)
            epochs_by_span[label] = epochs_by_span.get(label, 0) + max(n, 0)
    epochs_by_label: Dict[str, int] = {}
    for row in run["metrics"]:
        label = PHASE_LABELS.get(row.get("phase"))
        if label:
            epochs_by_label[label] = epochs_by_label.get(label, 0) + 1
    phases = {}
    for label in sorted(set(phase_s) | set(epochs_by_counter)
                        | set(epochs_by_span) | set(epochs_by_label)):
        secs = phase_s.get(label)
        epochs = epochs_by_counter.get(
            label, epochs_by_span.get(label, epochs_by_label.get(label)))
        phases[label] = {
            "execute_s": round(secs, 3) if secs is not None else None,
            "epochs": epochs,
            "epochs_per_s": (
                round(epochs / secs, 2)
                if secs and epochs is not None else None
            ),
        }

    peak_in_use = 0
    peak_peak = 0
    n_mem_events = 0
    for e in events:
        if e.get("kind") != "memory":
            continue
        totals = e.get("totals") or {}
        n_mem_events += 1
        peak_in_use = max(peak_in_use, int(totals.get("bytes_in_use", 0)))
        peak_peak = max(peak_peak, int(totals.get("peak_bytes_in_use", 0)))
    dm = fm.get("device_memory") or {}
    totals = dm.get("totals", dm if isinstance(dm, dict) else {})
    if isinstance(totals, dict):
        peak_in_use = max(peak_in_use, int(totals.get("bytes_in_use") or 0))
        peak_peak = max(peak_peak, int(totals.get("peak_bytes_in_use") or 0))

    # wall window when span events exist (compiles run concurrently);
    # fall back to the sum only when final_metrics durations are all we have
    total_compile = compile_wall
    if total_compile is None and compile_s:
        total_compile = round(sum(compile_s.values()), 3)
    total_execute = round(sum(phase_s.values()), 3) if phase_s else None
    manifest = run["manifest"] or {}
    serving = _serving_summary(run.get("events_all") or events)
    sharpe = {
        split: fm[split]["sharpe"]
        for split in ("train", "valid", "test")
        if isinstance(fm.get(split), dict)
        and isinstance(fm[split].get("sharpe"), (int, float))
    }
    out = {
        "run_dir": run["run_dir"],
        "run_id": manifest.get("run_id"),
        "kind": manifest.get("kind"),
        "config_hash": manifest.get("config_hash"),
        "git_sha": manifest.get("git_sha"),
        "backend": (manifest.get("devices") or {}).get("backend"),
        "n_devices": (manifest.get("devices") or {}).get("device_count"),
        "wall_clock_s": fm.get("wall_clock_s"),
        "startup": _startup_summary(events),
        # unscoped like reliability: a restarted fleet replica logs under a
        # fresh run_id, and its pre-restart requests are part of the story
        "serving": serving,
        "reliability": _reliability_summary(
            run.get("events_all") or events),
        # unscoped like reliability: every worker and restarted child logs
        # under its own run_id, and the fleet story spans all of them
        "elastic": _elastic_summary(
            run.get("events_all") or events, run["run_dir"]),
        # unscoped too: the convergence timeline must span every replica
        # restart and the promoting coordinator alike
        "promotion": _promotion_summary(
            run.get("events_all") or events, run["run_dir"]),
        "compile_seconds": {k: round(v, 3) for k, v in sorted(compile_s.items())},
        "total_compile_s": total_compile,
        "phases": phases,
        "total_execute_s": total_execute,
        "peak_bytes_in_use": peak_in_use or None,
        "peak_peak_bytes_in_use": peak_peak or None,
        "n_memory_events": n_mem_events,
        "n_events": len(events),
        "sharpe": sharpe or None,
    }
    # new-plane sections only when their artifacts exist: summaries (and
    # the text report) of pre-telemetry run dirs stay byte-stable
    model_health = _model_health_summary(
        run["run_dir"], run.get("events_all") or events)
    if model_health:
        out["model_health"] = model_health
    # unscoped: probe/alert evidence spans prober + engine + replica
    # restarts alike
    slo = _slo_summary(run.get("events_all") or events)
    if slo:
        out["slo"] = slo
    xla_programs = _xla_programs_summary(
        manifest, run.get("events_all") or events)
    if xla_programs:
        out["xla_programs"] = xla_programs
    metrics_check = _metrics_crosscheck(
        run["run_dir"], run.get("events_all") or events)
    if metrics_check:
        out["metrics_check"] = metrics_check
    return out


def compare_parity(summary: Dict[str, Any], parity_path,
                   bar: float = 0.02) -> Dict[str, Any]:
    """Final Sharpes vs a ``PARITY_*.json`` baseline's reference numbers
    (the 0.02 bar is the repo's established parity criterion).

    Never silently absent: an unreadable baseline or a run with no final
    Sharpes returns ``{"error": ...}`` so a CI gate using ``--parity``
    fails loudly instead of passing vacuously (main() exits nonzero)."""
    parity_path = Path(parity_path)
    out: Dict[str, Any] = {"baseline": str(parity_path), "bar": bar}
    try:
        parity = json.loads(parity_path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        out["error"] = f"baseline unreadable: {e}"
        return out
    ref = (parity.get("reference") or {}).get("sharpe") or {}
    sharpe = summary.get("sharpe") or {}
    splits = {}
    for split in ("train", "valid", "test"):
        if split in sharpe and split in ref:
            delta = abs(float(sharpe[split]) - float(ref[split]))
            # the repo's parity criterion gates valid/test only: train-split
            # deltas of 0.07-1.8 are documented selection-equivalence noise
            # (README "training parity"; PARITY.json passes with
            # abs_delta_sharpe.train=0.0827), so train is informational
            gated = split != "train"
            splits[split] = {
                "run": round(float(sharpe[split]), 4),
                "reference": float(ref[split]),
                "abs_delta": round(delta, 4),
                "within_bar": (delta <= bar) if gated else None,
            }
    if not splits:
        out["error"] = ("no overlapping final Sharpes between the run "
                        "(final_metrics.json) and the baseline's "
                        "reference.sharpe")
        return out
    out["splits"] = splits
    # the moment-violation column: a PARITY_* run can be checked for
    # moment-CONDITION health, not just loss/Sharpe agreement. The run
    # side comes from health.json (summary.model_health); baselines that
    # record reference.moment_violation_max additionally get a gated
    # comparison, older baselines an informational reading. The gate uses
    # its OWN tolerance — violation norms live at ~1e-2 scales the 0.02
    # Sharpe bar was never calibrated for: the run's worst violation may
    # exceed the reference's by at most 50% (plus a small absolute floor
    # absorbing seed noise near zero); improvement is always within.
    mh = summary.get("model_health") or {}
    run_mv = mh.get("moment_violation_max")
    ref_mv = (parity.get("reference") or {}).get("moment_violation_max")
    if run_mv is not None or ref_mv is not None:
        entry: Dict[str, Any] = {
            "run": run_mv,
            "reference": ref_mv,
            "finite": (bool(mh.get("finite"))
                       if run_mv is not None else None),
        }
        if run_mv is not None and ref_mv is not None:
            entry["abs_delta"] = round(abs(run_mv - ref_mv), 6)
            entry["rel_bar"] = MOMENT_REL_BAR
            entry["within_bar"] = (
                run_mv <= ref_mv * (1.0 + MOMENT_REL_BAR)
                + MOMENT_ABS_FLOOR)
        else:
            entry["within_bar"] = None
        out["moment_violation"] = entry
    else:
        out["moment_violation"] = None
    return out


def _gib(n) -> str:
    return f"{n / (1 << 30):.3f} GiB" if n else "n/a"


def format_summary(summary: Dict[str, Any]) -> str:
    """Human-readable report for one run."""
    lines = [f"run dir: {summary['run_dir']}"]
    ident = [
        f"kind={summary['kind']}" if summary.get("kind") else None,
        f"run_id={summary['run_id']}" if summary.get("run_id") else None,
        f"backend={summary['backend']}" if summary.get("backend") else None,
        (f"devices={summary['n_devices']}"
         if summary.get("n_devices") is not None else None),
        (f"config={summary['config_hash'][:12]}"
         if summary.get("config_hash") else None),
        (f"git={summary['git_sha'][:12]}" if summary.get("git_sha") else None),
    ]
    ident = [x for x in ident if x]
    if ident:
        lines.append("  " + "  ".join(ident))
    if summary.get("wall_clock_s") is not None:
        lines.append(f"  wall clock: {summary['wall_clock_s']:.1f}s")

    if summary.get("startup"):
        st = summary["startup"]
        wall = (f"{st['wall_s']:.2f}s" if st.get("wall_s") is not None
                else "n/a")
        lines.append("  startup breakdown (stages overlap; wall is the "
                     "begin→end window):")
        lines.append(f"    wall window: {wall}")
        for stage, secs in st["stages"].items():
            lines.append(f"      {stage}: {secs:.2f}s")
        if st.get("cache"):
            c = st["cache"]
            lines.append(f"    panel cache: {c['hits']} hits, "
                         f"{c['misses']} misses")
        if st.get("dataplane"):
            dp = st["dataplane"]
            lines.append("    dataplane (chunked store, shard-local):")
            lines.append(
                f"      shards: {dp['shards_owned']} owned, "
                f"{dp['shards_loaded']} loaded from cache, "
                f"{dp['shards_redecoded']} re-decoded")
            lines.append(
                f"      per-shard transfers: {dp['shard_transfers']} "
                f"({dp['shard_transfer_s']:.2f}s dispatch window)")
            if dp.get("peak_rss_bytes"):
                lines.append(
                    f"      peak host RSS: {_gib(dp['peak_rss_bytes'])}")
        elif st.get("peak_rss_bytes"):
            lines.append(f"    peak host RSS: {_gib(st['peak_rss_bytes'])}")

    if summary.get("serving"):
        sv = summary["serving"]
        lines.append("  serving:")
        lines.append(f"    requests: {sv['total_requests']}")
        for key, n in sv["requests"].items():
            lines.append(f"      {key}: {n}")
        if sv.get("latency"):
            la = sv["latency"]
            lines.append(
                f"    latency: p50 {la['p50_ms']:.3f} ms  "
                f"p95 {la['p95_ms']:.3f} ms  p99 {la['p99_ms']:.3f} ms  "
                f"({la['count']} requests)")
        if sv.get("cache"):
            c = sv["cache"]
            lines.append(f"    result cache: {c['hits']} hits, "
                         f"{c['misses']} misses "
                         f"(hit rate {c['hit_rate']:.1%})")
        if sv.get("requests_by_replica"):
            parts = "  ".join(f"{r}={n}"
                              for r, n in sv["requests_by_replica"].items())
            lines.append(f"    requests by replica: {parts}")
        if sv.get("rate_503"):
            lines.append(f"    503 rate: {sv['rate_503']:.2%}")
        if sv.get("shed"):
            sh = sv["shed"]
            reasons = "  ".join(f"{k}:{v}"
                                for k, v in sh["by_reason"].items())
            pris = "  ".join(f"{k}:{v}"
                             for k, v in sh["by_priority"].items())
            lines.append(f"    shed (429): {sh['total']} "
                         f"[{reasons}] by priority [{pris}]")
        if sv.get("latency_by_priority"):
            for pri, la in sv["latency_by_priority"].items():
                if la:
                    lines.append(
                        f"    latency[{pri}]: p50 {la['p50_ms']:.3f} ms  "
                        f"p99 {la['p99_ms']:.3f} ms  "
                        f"({la['count']} requests)")
        if sv.get("coalesce"):
            co = sv["coalesce"]
            lines.append(
                f"    coalescing: {co['hits']} hits / "
                f"{co['dispatches']} dispatches "
                f"(hit rate {co['hit_rate']:.1%}, dispatch ratio "
                f"{co['dispatch_ratio']:.3f})")
        if sv.get("autoscale"):
            au = sv["autoscale"]
            lines.append(
                f"    autoscale: {au['scale_ups']} up / "
                f"{au['scale_downs']} down"
                + (f" / {au['failed']} failed" if au["failed"] else "")
                + (f"  (replicas now {au['replicas_final']})"
                   if au["replicas_final"] is not None else ""))
            for ev in au["events"]:
                why = f" ({ev['reason']})" if ev.get("reason") else ""
                lines.append(
                    f"      {ev['action']} replica{ev['replica']}"
                    f" -> {ev['replicas']} live{why}")
        if sv.get("drains"):
            lines.append(f"    graceful drains: {sv['drains']}")
        if sv.get("batching"):
            bt = sv["batching"]
            hist = "  ".join(f"{k}:{v}"
                             for k, v in bt["occupancy_hist"].items())
            lines.append(f"    continuous batching: {bt['flushes']} flushes, "
                         f"mean queue depth {bt['mean_queue_depth']:.2f}")
            lines.append(f"      occupancy histogram: {hist}")
        if sv.get("tail_latency"):
            lines.append(
                f"    tail latency attribution "
                f"({sv['traced_requests']} traced requests, slowest "
                f"{len(sv['tail_latency'])}):")
            for t in sv["tail_latency"]:
                where = (f" flush={t['flush']}" if "flush" in t else "")
                lines.append(
                    f"      {str(t['trace_id'])[:16]}… {t['endpoint']} "
                    f"{t['total_ms']:.2f} ms{where}")
                if t["segments_ms"]:
                    lines.append(
                        f"        {_fmt_segments(t['segments_ms'])} (ms)")
        if sv.get("flightrecorder_dumps"):
            dumps = "  ".join(f"{k}:{v}" for k, v in
                              sv["flightrecorder_dumps"].items())
            lines.append(f"    flight recorder dumps: {dumps}")
        lines.append(f"    dispatches: {sv['dispatches']}  "
                     f"recompiles: {sv['recompiles']}  "
                     f"macro appends: {sv['macro_appends']}"
                     + (f"  reloads: {sv['reloads']}"
                        if sv.get("reloads") else ""))

    if summary.get("metrics_check"):
        mc = summary["metrics_check"]
        lines.append("  metrics cross-check (metrics.prom vs events):")
        if mc.get("error"):
            lines.append(f"    ERROR: {mc['error']}")
        else:
            if "requests_agree" not in mc:
                # no serve/ event rows at all (e.g. a zero-request run):
                # nothing was compared, which must not read as a regression
                verdict = "(no serve events to compare)"
            elif mc["requests_agree"] and mc.get("recompiles_agree"):
                verdict = "(agrees with events)"
            else:
                verdict = "(DISAGREES with events)"
            lines.append(
                f"    requests: {mc['requests']}  recompiles: "
                f"{mc['recompiles']}  " + verdict)
            if "steady_state_recompiles" in mc:
                ok = "OK" if mc["steady_state_ok"] else "VIOLATED"
                lines.append(
                    "    steady-state recompiles (from metrics): "
                    f"{mc['steady_state_recompiles']}  [{ok}]")

    if summary.get("reliability"):
        rel = summary["reliability"]
        lines.append("  reliability:")
        out = rel.get("outcome") or {}
        if out:
            lines.append(f"    outcome: {out.get('outcome')} "
                         f"(restarts={out.get('restarts')}, "
                         f"rc={out.get('returncode')})")
        lines.append(f"    restarts: {rel['restarts']}  "
                     f"(hang kills: {rel['hang_kills']})")
        for section, n in rel["deaths_by_section"].items():
            lines.append(f"      died in {section}: {n}")
        if rel["faults_injected"]:
            lines.append("    faults injected:")
            for key, n in rel["faults_injected"].items():
                lines.append(f"      {key}: {n}")
        lines.append(f"    guard trips: {rel['guard_trips']}  "
                     f"checkpoint fallbacks: {rel['checkpoint_fallbacks']}"
                     + (f"  unusable: {rel['checkpoint_unusable']}"
                        if rel["checkpoint_unusable"] else ""))

    if summary.get("elastic"):
        el = summary["elastic"]
        lines.append("  elastic sweep:")
        led = el.get("ledger")
        if led:
            total = (str(led["total_buckets"])
                     if led.get("total_buckets") is not None else "?")
            lines.append(f"    ledger: {led['records']}/{total} buckets "
                         f"recorded, {led['quarantined']} quarantined")
        lines.append(f"    buckets completed: {el['buckets_completed']}  "
                     f"ledger hits (not re-trained): {el['ledger_hits']}")
        lines.append(f"    retries: {el['retries']}  lease takeovers: "
                     f"{el['lease_takeovers']}  quarantined: "
                     f"{el['quarantined']}")
        for worker, n in el["claims_by_worker"].items():
            done = el["completed_by_worker"].get(worker, 0)
            lines.append(f"      {worker}: {n} claims, {done} completed")
        inline = el["completed_by_worker"].get("inline")
        if inline and "inline" not in el["claims_by_worker"]:
            lines.append(f"      inline (single-process): {inline} completed")
        if el["quorum_drops"]:
            drops = ", ".join(
                f"rank{d.get('rank')}:seed{d.get('seed')}"
                for d in el["quorum_drops"])
            lines.append(f"    quorum drops: {drops}")

    if summary.get("promotion"):
        pm = summary["promotion"]
        lines.append("  promotion:")
        head = pm.get("pointer")
        if head:
            sharpe = head.get("valid_sharpe")
            lines.append(
                f"    pointer: generation {head['generation']} "
                f"({head['fingerprint']}…, source={head.get('source')}, "
                f"valid Sharpe "
                f"{sharpe if sharpe is not None else 'n/a'}, "
                f"{head['history']} retained)"
                + (f" ROLLED BACK from g{head['rolled_back_from']}"
                   if head.get("rolled_back_from") is not None else ""))
        lines.append(
            f"    promoted: {pm['promotions']}  rolled back: "
            f"{pm['pointer_rollbacks']} pointer / {pm['fleet_rollbacks']} "
            f"fleet  fleet converged: {pm['fleet_converged']}")
        if pm["rejections_by_reason"]:
            rej = "  ".join(f"{k}:{v}" for k, v
                            in pm["rejections_by_reason"].items())
            lines.append(f"    gate rejections: {rej}")
        rl = pm["reloads"]
        lines.append(f"    reloads: {rl['swapped']} swapped, "
                     f"{rl['noop']} no-op")
        for replica, rows in pm["replica_timeline"].items():
            path = " -> ".join(
                f"{'boot:' if r['boot'] else ''}g{r['generation']}"
                f"({str(r['fingerprint'])[:8]})" for r in rows)
            lines.append(f"      {replica}: {path}")
        if pm.get("converged") is not None:
            fps = set(pm["serving_fingerprints"].values())
            lines.append(
                "    replicas CONVERGED on one generation"
                if pm["converged"]
                else f"    replicas DIVERGED: {sorted(fps)}")

    mh = summary.get("model_health")
    if not mh:
        # deliberate placeholder (not silence): a pre-health-plane run dir
        # renders deterministically with the section present but empty
        lines.append("  model health: (no health data)")
    else:
        lines.append("  model health:")
        if mh.get("moment_violation_max") is not None:
            finite = "finite" if mh.get("finite") else "NON-FINITE"
            lines.append(
                f"    moment violations ({mh.get('split')}): max "
                f"{mh['moment_violation_max']:.6f}  unconditional "
                f"{(mh.get('unc_violation') or 0):.6f}  [{finite}]")
            per = mh.get("moment_violations") or []
            if per:
                vals = "  ".join(f"h{j}={v:.4f}" if v is not None else
                                 f"h{j}=n/a" for j, v in enumerate(per))
                lines.append(f"      per moment: {vals}")
            if mh.get("adv_gap") is not None:
                lines.append(
                    f"    adversarial gap (cond − unc loss): "
                    f"{mh['adv_gap']:.6g}")
            sdf = mh.get("sdf") or {}
            if sdf.get("sdf_mean") is not None:
                lines.append(
                    f"    SDF series: mean {sdf['sdf_mean']:.4f}  vol "
                    f"{(sdf.get('sdf_vol') or 0):.4f}  min "
                    f"{(sdf.get('sdf_min') or 0):.4f}  finite "
                    f"{(sdf.get('sdf_finite_frac') or 0):.1%}")
            pf = mh.get("portfolio") or {}
            if pf.get("weight_hhi") is not None:
                lines.append(
                    f"    portfolio: HHI {pf['weight_hhi']:.4f}  max|w| "
                    f"{(pf.get('weight_max_abs') or 0):.4f}  short "
                    f"{(pf.get('short_fraction') or 0):.1%}  turnover "
                    f"{(pf.get('turnover') or 0):.4f}")
            if mh.get("guard_trips"):
                lines.append(
                    f"    divergence-guard trips: {mh['guard_trips']}")
        if mh.get("reference_profile"):
            lines.append("    reference profile: present")
        if mh.get("drift"):
            dr = mh["drift"]
            psi = (f"{dr['psi_last']:.4f}"
                   if dr.get("psi_last") is not None else "n/a")
            lines.append(f"    drift monitor: {dr['scored']} scored, "
                         f"{dr['alerts']} alerts (last PSI {psi})")
        if mh.get("canary"):
            ca = mh["canary"]
            delta = (f"{ca['max_weight_delta']:.6f}"
                     if ca.get("max_weight_delta") is not None else "n/a")
            lines.append(f"    reload canary: {ca['hot_swaps']} hot-swaps "
                         f"replayed (max |Δw| {delta})")

    slo = summary.get("slo")
    if slo:
        lines.append("  slo:")
        al = slo.get("alerts") or {}
        if al.get("firing_now"):
            for a in al["firing_now"]:
                lines.append(f"    ALERT FIRING: {a}")
        lines.append(
            f"    alerts: {al.get('firings', 0)} fired, "
            f"{al.get('resolves', 0)} resolved")
        for key, v in (slo.get("budget_remaining") or {}).items():
            if isinstance(v, (int, float)):
                lines.append(f"    budget remaining {key}: {v:.4g}")
        pr = slo.get("probe") or {}
        lines.append(
            f"    probes: {pr.get('checks', 0)} checks, "
            f"{pr.get('failures', 0)} failures, "
            f"{pr.get('digest_changes', 0)} digest changes")
        for target, n in (pr.get("failures_by_target") or {}).items():
            lines.append(f"      {target}: {n} failures")

    lines.append("  compile vs execute:")
    tc, te = summary.get("total_compile_s"), summary.get("total_execute_s")
    lines.append(f"    compile total (wall): {tc:.2f}s" if tc is not None
                 else "    compile total (wall): n/a")
    # per-program latencies; they sum past the wall when compiles overlap
    for name, secs in (summary.get("compile_seconds") or {}).items():
        lines.append(f"      {name}: {secs:.2f}s")
    lines.append(f"    execute total: {te:.2f}s" if te is not None
                 else "    execute total: n/a")

    if summary.get("xla_programs"):
        lines.append("  AOT programs (XLA cost/memory analysis):")
        lines.append("    program                          GFLOPs   "
                     "GB accessed   peak MiB")
        for name, a in sorted(summary["xla_programs"].items()):
            flops = (f"{a['flops'] / 1e9:8.3f}" if a.get("flops") is not None
                     else "     n/a")
            acc = (f"{a['bytes_accessed'] / 1e9:8.3f}"
                   if a.get("bytes_accessed") is not None else "     n/a")
            peak = (f"{a['peak_memory_bytes'] / (1 << 20):8.1f}"
                    if a.get("peak_memory_bytes") is not None else "     n/a")
            lines.append(f"    {name:<32} {flops}      {acc}   {peak}")
            for flag, reason in (("cost_available", "cost_reason"),
                                 ("memory_available", "memory_reason")):
                if a.get(flag) is False and a.get(reason):
                    lines.append(f"      ({flag.split('_')[0]} analysis "
                                 f"unavailable: {a[reason]})")

    if summary.get("phases"):
        lines.append("  per-phase throughput:")
        for label, p in summary["phases"].items():
            secs = f"{p['execute_s']:.2f}s" if p["execute_s"] is not None else "n/a"
            eps = (f"{p['epochs_per_s']:.2f} epochs/s"
                   if p["epochs_per_s"] is not None else "n/a")
            epochs = p["epochs"] if p["epochs"] is not None else "?"
            lines.append(f"    {label}: {epochs} epochs in {secs} ({eps})")

    lines.append("  device memory (aggregated over local devices):")
    lines.append(f"    peak bytes in use: {_gib(summary.get('peak_bytes_in_use'))}")
    lines.append(
        f"    peak high-water:   {_gib(summary.get('peak_peak_bytes_in_use'))}"
        f"  ({summary.get('n_memory_events', 0)} snapshots)")

    if summary.get("sharpe"):
        parts = "  ".join(f"{k}={v:.4f}" for k, v in summary["sharpe"].items())
        lines.append(f"  final sharpe: {parts}")
    if summary.get("parity"):
        par = summary["parity"]
        lines.append(f"  parity vs {par['baseline']} (bar {par['bar']}):")
        if par.get("error"):
            lines.append(f"    PARITY COMPARISON FAILED: {par['error']}")
        else:
            for split, d in par["splits"].items():
                if d["within_bar"] is None:
                    ok = "(informational; train is not gated)"
                else:
                    ok = "OK" if d["within_bar"] else "EXCEEDS BAR"
                lines.append(
                    f"    {split}: run {d['run']:+.4f} vs ref "
                    f"{d['reference']:+.4f}  |d|={d['abs_delta']:.4f}  {ok}")
            mv = par.get("moment_violation")
            if mv is None:
                lines.append(
                    "    moment violation: (no moment-condition data)")
            else:
                run = (f"{mv['run']:.6f}" if mv.get("run") is not None
                       else "n/a")
                ref = (f"{mv['reference']:.6f}"
                       if mv.get("reference") is not None else "n/a")
                if mv.get("within_bar") is None:
                    ok = ("(informational; baseline records no "
                          "moment reference)")
                else:
                    ok = "OK" if mv["within_bar"] else "EXCEEDS BAR"
                finite = ("" if mv.get("finite") in (None, True)
                          else "  NON-FINITE")
                lines.append(
                    f"    moment violation: run {run} vs ref {ref}  "
                    f"{ok}{finite}")
    return "\n".join(lines)


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearninginassetpricing_paperreplication_tpu.report",
        description="Aggregate run-dir telemetry (manifest.json + "
                    "events.jsonl + metrics.jsonl) into a compile/execute/"
                    "memory report",
    )
    p.add_argument("run_dirs", nargs="*", help="Run directories (optional "
                   "when --budget checks only file-scoped entries)")
    p.add_argument("--parity", type=str, default=None, metavar="JSON",
                   help="PARITY_*.json baseline to compare final Sharpes "
                        "against (0.02 bar)")
    p.add_argument("--trace", type=str, default=None, metavar="OUT.json",
                   help="Assemble the run dirs' full event-file families "
                        "(events.jsonl + proc/supervisor/worker/replica "
                        "files) into ONE Chrome trace JSON with request "
                        "flow arrows — open in Perfetto or "
                        "chrome://tracing. Multiple run dirs merge into "
                        "one timeline (e.g. the loadgen client dir next "
                        "to the fleet dir: every retried request is one "
                        "arrowed trace across replicas)")
    p.add_argument("--budget", type=str, default=None, metavar="JSON",
                   help="Check declarative perf budgets (observability/"
                        "budgets.py schema): file-scoped entries against "
                        "their BENCH_*.json artifacts, run-scoped entries "
                        "against each run dir's summary; exits non-zero on "
                        "any regression or missing metric")
    p.add_argument("--bench-trend", type=str, default=None,
                   dest="bench_trend", nargs="?", const="benches/"
                   "history.jsonl", metavar="HISTORY.jsonl",
                   help="Render the checked-in bench trajectory from an "
                        "append-only benches/history.jsonl (written by "
                        "tools/bench_history.py); run dirs optional")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="Emit the machine-readable summary instead of text")
    return p


def _render_bench_trend(history_path) -> Tuple[int, str]:
    """Load tools/bench_history.py (one source of truth for the history
    format) from the repo the history file lives in and render the
    trajectory; returns (rc, text)."""
    import importlib.util

    history_path = Path(history_path)
    tool = history_path.resolve().parent.parent / "tools" / \
        "bench_history.py"
    if not tool.exists():
        return 2, (f"bench-trend: no tools/bench_history.py next to "
                   f"{history_path} (expected {tool})")
    spec = importlib.util.spec_from_file_location("_dlap_bench_history",
                                                  tool)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # stdlib-only module
    rows = mod.read_history(history_path)
    return 0, mod.format_trend(rows)


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if not args.run_dirs and not args.budget and not args.bench_trend:
        print("report: at least one run dir is required (except with "
              "--budget / --bench-trend)", file=sys.stderr)
        return 2
    if args.trace and not args.run_dirs:
        print("report: --trace requires at least one run dir",
              file=sys.stderr)
        return 2
    summaries = []
    rc = 0
    for d in args.run_dirs:
        summary = summarize_run(load_run(d))
        if args.parity:
            summary["parity"] = compare_parity(summary, args.parity)
            if summary["parity"].get("error"):
                # an impossible comparison must not look like a pass
                print(f"warning: {d}: parity comparison failed: "
                      f"{summary['parity']['error']}", file=sys.stderr)
                rc = 1
        summaries.append(summary)

    budget_result = None
    if args.budget:
        from .budgets import BudgetSpecError, check_budgets

        try:
            budget_result = check_budgets(
                args.budget,
                {s["run_dir"]: s for s in summaries})
        except BudgetSpecError as e:
            print(f"budget gate: {e}", file=sys.stderr)
            return 2
        if not budget_result["ok"]:
            rc = 1

    trend_text = None
    if args.bench_trend:
        trend_rc, trend_text = _render_bench_trend(args.bench_trend)
        if trend_rc:
            print(trend_text, file=sys.stderr)
            return trend_rc

    if args.trace:
        from .trace import write_trace

        try:
            info = write_trace(args.run_dirs, args.trace)
        except FileNotFoundError as e:
            print(f"trace: {e}", file=sys.stderr)
            return 2
        print(f"trace written to {args.trace}: {info['n_files']} event "
              f"files, {info['n_span_events']} spans "
              f"({info['n_synthesized_ends']} synthesized ends), "
              f"{info['n_instant_events']} instants, "
              f"{info['n_request_events']} request rows in "
              f"{info['n_traces']} traces "
              f"({info['n_flow_events']} flow events)",
              # --json owns stdout (a consumer pipes it to a parser); the
              # human-facing status line must not corrupt the document
              file=sys.stderr if args.as_json else sys.stdout)

    if args.as_json:
        out: Any = summaries if len(summaries) > 1 else (
            summaries[0] if summaries else [])
        if budget_result is not None:
            out = {"runs": summaries, "budget": budget_result}
        if trend_text is not None:
            # the human-facing trend stays off the JSON document
            print(trend_text, file=sys.stderr)
        print(json.dumps(out, indent=2))
        return rc
    if trend_text is not None:
        print(trend_text)
        if summaries:
            print()
    for i, s in enumerate(summaries):
        if i:
            print()
        print(format_summary(s))
    if len(summaries) > 1:
        print("\ncomparison (headline numbers):")
        for s in summaries:
            wall = (f"{s['wall_clock_s']:.1f}s"
                    if s.get("wall_clock_s") is not None else "n/a")
            tc = (f"{s['total_compile_s']:.1f}s"
                  if s.get("total_compile_s") is not None else "n/a")
            te = (f"{s['total_execute_s']:.1f}s"
                  if s.get("total_execute_s") is not None else "n/a")
            test = (s.get("sharpe") or {}).get("test")
            test = f"{test:.4f}" if test is not None else "n/a"
            print(f"  {s['run_dir']}: wall={wall} compile={tc} "
                  f"execute={te} test_sharpe={test}")
    if budget_result is not None:
        from .budgets import format_budget_report

        if summaries:
            print()
        print(format_budget_report(budget_result))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
