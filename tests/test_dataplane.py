"""Sharded data plane: chunked panel store, shard-local loading, streamed
per-shard transfer (data/diskcache.py store_chunked + data/pipeline.py).

The acceptance contract, tier-1 on CPU (8 virtual devices):
  * the chunked store round-trips BIT-IDENTICALLY vs `load_splits` — at the
    fixture shape AND at a shard width that leaves a ragged last shard, on
    both the store (miss) and mmap (hit) rounds;
  * changing the shard width changes the cache key (never mis-slices an
    existing entry), and same-source entries of different formats coexist;
  * `columns=` spans load only the intersecting shards;
  * a truncated shard (`data/shard_read` truncate_file fault) fails its
    manifest fingerprint, re-decodes from the npz ALONE, is repaired on
    disk, and the final batches stay bit-identical;
  * `stream_batch_sharded` ≡ `shard_batch` bitwise, same shardings;
  * `StartupPipeline(mesh=...)` runs decode→per-shard transfer→early GSPMD
    compile end-to-end, and `train.py --shard_stocks` runs THROUGH the
    pipeline with final metrics identical to the sequential shard path;
  * the report CLI renders the dataplane subsection from startup/shard_*;
  * `bench.py --dataplane` produces a well-formed BENCH_DATAPLANE.json
    (tiny shape tier-1; the 100k-stock acceptance run is `slow`).
"""

import json
import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.data import (
    diskcache,
    pipeline,
)
from deeplearninginassetpricing_paperreplication_tpu.data.panel import (
    load_splits,
)
from deeplearninginassetpricing_paperreplication_tpu.observability import (
    EventLog,
)
from deeplearninginassetpricing_paperreplication_tpu.parallel.mesh import (
    create_mesh,
    shard_batch,
)
from deeplearninginassetpricing_paperreplication_tpu.reliability.faults import (
    reset_injector,
)

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    """Every test gets a private, empty panel cache."""
    d = tmp_path / "panel_cache"
    monkeypatch.setenv("DLAP_PANEL_CACHE_DIR", str(d))
    monkeypatch.delenv("DLAP_PANEL_CACHE", raising=False)
    return d


def _assert_splits_equal(ref, got, columns=None):
    for r, g, name in zip(ref, got, ("train", "valid", "test")):
        a, b = columns if columns is not None else (0, r.N)
        np.testing.assert_array_equal(r.returns[:, a:b], g.returns,
                                      err_msg=name)
        np.testing.assert_array_equal(r.individual[:, a:b, :], g.individual,
                                      err_msg=name)
        np.testing.assert_array_equal(np.asarray(r.mask)[:, a:b],
                                      np.asarray(g.mask), err_msg=name)
        np.testing.assert_array_equal(r.macro, g.macro, err_msg=name)
        np.testing.assert_array_equal(r.dates, g.dates, err_msg=name)


# --------------------------------------------------------------------------
# chunked store: round-trip bit-identity, key invalidation, spans
# --------------------------------------------------------------------------

def test_chunked_roundtrip_bit_identical_ragged_width(
        synthetic_dir, splits, cache_dir):
    # width 24 over N=64 → shards (0,24)(24,48)(48,64): ragged last shard
    for round_name in ("store", "hit"):
        got = pipeline.load_splits_chunked(synthetic_dir, shard_width=24)
        _assert_splits_equal(splits, got)
    # the hit round really was a hit: entry exists with the right geometry
    char, macro = pipeline.split_paths(synthetic_dir, "train")
    entry = diskcache.load_chunked(char, macro, width=24)
    assert entry is not None
    assert entry.bounds() == [(0, 24), (24, 48), (48, 64)]
    assert all(entry.verify_shard(i)[0] for i in range(entry.n_shards))


def test_chunked_macro_stats_match_load_splits(synthetic_dir, splits,
                                               cache_dir):
    got = pipeline.load_splits_chunked(synthetic_dir, shard_width=32)
    for r, g in zip(splits, got):
        np.testing.assert_array_equal(r.mean_macro, g.mean_macro)
        np.testing.assert_array_equal(r.std_macro, g.std_macro)


def test_shard_width_changes_cache_key(synthetic_dir, cache_dir):
    char, macro = pipeline.split_paths(synthetic_dir, "train")
    pipeline.load_splits_chunked(synthetic_dir, shard_width=32)
    # a different width is a MISS, never a mis-slice of the 32-wide entry
    assert diskcache.load_chunked(char, macro, width=16) is None
    pipeline.load_splits_chunked(synthetic_dir, shard_width=16)
    # both widths now coexist (same live source → no cross-eviction) ...
    assert diskcache.load_chunked(char, macro, width=32) is not None
    assert diskcache.load_chunked(char, macro, width=16) is not None
    # ... and the monolithic entry for the same source survives alongside
    pipeline.load_splits_cached(synthetic_dir)
    assert diskcache.load(char, macro) is not None
    assert diskcache.load_chunked(char, macro, width=32) is not None


def test_env_knob_sets_default_width(monkeypatch):
    monkeypatch.setenv(diskcache.ENV_SHARD_WIDTH, "123")
    assert diskcache.shard_width() == 123
    monkeypatch.delenv(diskcache.ENV_SHARD_WIDTH)
    assert diskcache.shard_width() == diskcache.DEFAULT_SHARD_WIDTH
    assert diskcache.shard_width(7) == 7


def test_columns_span_loads_only_owned_shards(synthetic_dir, splits,
                                              cache_dir, tmp_path):
    pipeline.load_splits_chunked(synthetic_dir, shard_width=16)  # seed
    run = tmp_path / "run"
    ev = EventLog(run, process_index=0)
    got = pipeline.load_splits_chunked(
        synthetic_dir, columns=(16, 48), shard_width=16, events=ev)
    ev.close()
    _assert_splits_equal(splits, got, columns=(16, 48))
    rows = [json.loads(line)
            for line in (run / "events.jsonl").read_text().splitlines()]
    owned = [r for r in rows if r.get("name") == "startup/shard_owned"]
    loaded = [r for r in rows if r.get("name") == "startup/shard_loaded"]
    # N=64 @ width 16 → 4 shards; [16, 48) intersects exactly 2, per split
    assert {r["value"] for r in owned} == {2} and len(owned) == 3
    assert {r["value"] for r in loaded} == {2} and len(loaded) == 3


def test_corrupt_manifest_falls_back_to_fresh_store(synthetic_dir, splits,
                                                    cache_dir):
    pipeline.load_splits_chunked(synthetic_dir, shard_width=32)
    char, macro = pipeline.split_paths(synthetic_dir, "train")
    entry = diskcache.load_chunked(char, macro, width=32)
    # torn manifest (and its rotated generation): entry must be evicted and
    # the next load re-decode + re-store, bit-identically
    for p in (entry.dir / "meta.json", entry.dir / "meta.json.g1"):
        if p.exists():
            p.write_text("{not json")
    got = pipeline.load_splits_chunked(synthetic_dir, shard_width=32)
    _assert_splits_equal(splits, got)
    entry = diskcache.load_chunked(char, macro, width=32)
    assert entry is not None
    assert all(entry.verify_shard(i)[0] for i in range(entry.n_shards))


# --------------------------------------------------------------------------
# fault injection: a torn shard re-decodes ALONE, batches bit-identical
# --------------------------------------------------------------------------

def test_shard_read_fault_redecodes_only_that_shard(
        synthetic_dir, splits, cache_dir, tmp_path, monkeypatch):
    pipeline.load_splits_chunked(synthetic_dir, shard_width=16)  # seed
    plan = [{"site": "data/shard_read", "action": "truncate_file",
             "match": "s00002", "trigger_count": 1}]
    monkeypatch.setenv("DLAP_FAULT_PLAN", json.dumps(plan))
    reset_injector()
    run = tmp_path / "run"
    ev = EventLog(run, process_index=0)
    try:
        got = pipeline.load_splits_chunked(
            synthetic_dir, shard_width=16, events=ev)
    finally:
        monkeypatch.delenv("DLAP_FAULT_PLAN")
        reset_injector()
    ev.close()
    # final batches bit-identical to load_splits despite the torn shard
    _assert_splits_equal(splits, got)
    rows = [json.loads(line)
            for line in (run / "events.jsonl").read_text().splitlines()]
    redecodes = [r for r in rows
                 if r.get("name") == "startup/shard_redecode"]
    # exactly ONE shard re-decoded (the truncate fired once, on one split's
    # shard 2); everything else served from the verified cache
    assert len(redecodes) == 1
    assert redecodes[0]["shard"] == 2
    loaded = sum(r["value"] for r in rows
                 if r.get("name") == "startup/shard_loaded")
    assert loaded == 3 * 4 - 1  # 4 shards × 3 splits, minus the torn one
    # and the shard was REPAIRED on disk: a fresh load verifies clean
    char, macro = pipeline.split_paths(synthetic_dir, "train")
    for split in pipeline.SPLITS:
        c, m = pipeline.split_paths(synthetic_dir, split)
        entry = diskcache.load_chunked(c, m, width=16)
        assert all(entry.verify_shard(i)[0] for i in range(entry.n_shards)), (
            split)


# --------------------------------------------------------------------------
# streamed per-shard transfer ≡ shard_batch (the tier-1 parity criterion)
# --------------------------------------------------------------------------

def test_stream_batch_sharded_bit_identical(splits):
    mesh = create_mesh()
    ds = splits[0].pad_stocks(mesh.devices.size)
    batch = ds.full_batch()
    ref = shard_batch({k: jnp.asarray(v) for k, v in batch.items()}, mesh)
    got = pipeline.stream_batch_sharded(batch, mesh)
    assert set(ref) == set(got)
    for k in ref:
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(got[k]), err_msg=k)
        assert ref[k].sharding == got[k].sharding, k


def test_stream_batch_sharded_padded_n_assets(splits):
    mesh = create_mesh()
    ds = splits[0].subsample(splits[0].T, 60).pad_stocks(mesh.devices.size)
    batch = ds.full_batch()
    assert "n_assets" in batch  # 60 → 64 padded: true count rides along
    ref = shard_batch({k: jnp.asarray(v) for k, v in batch.items()}, mesh)
    got = pipeline.stream_batch_sharded(batch, mesh)
    for k in ref:
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(got[k]), err_msg=k)
        assert ref[k].sharding == got[k].sharding, k


def test_stream_batch_sharded_rejects_indivisible_n(splits):
    mesh = create_mesh()
    ds = splits[0].subsample(splits[0].T, 63)  # 63 % 8 != 0, unpadded
    with pytest.raises(ValueError, match="pad_stocks"):
        pipeline.stream_batch_sharded(ds.full_batch(), mesh)


def test_stream_batch_sharded_emits_shard_spans(splits, tmp_path):
    mesh = create_mesh()
    ds = splits[0].pad_stocks(mesh.devices.size)
    run = tmp_path / "run"
    ev = EventLog(run, process_index=0)
    pipeline.stream_batch_sharded(ds.full_batch(), mesh, events=ev,
                                  split="train")
    ev.close()
    rows = [json.loads(line)
            for line in (run / "events.jsonl").read_text().splitlines()]
    spans = [r for r in rows if r["kind"] == "span_end"
             and r["name"] == "startup/shard_transfer"]
    assert len(spans) == mesh.devices.size
    assert {(r["start"], r["stop"]) for r in spans} == {
        (i * ds.N // 8, (i + 1) * ds.N // 8) for i in range(8)}


# --------------------------------------------------------------------------
# StartupPipeline(mesh=...): decode ∥ per-shard transfer ∥ early compile
# --------------------------------------------------------------------------

def test_pipeline_mesh_end_to_end(synthetic_dir, splits, cache_dir,
                                  tmp_path):
    mesh = create_mesh()
    run = tmp_path / "run"
    ev = EventLog(run, process_index=0)
    res = pipeline.StartupPipeline(
        synthetic_dir, events=ev, mesh=mesh, shard_width=24,
    ).start().result()
    ev.close()
    for ds, ref in zip(res.datasets, splits):
        assert ds.N % mesh.devices.size == 0
    # batches ≡ shard_batch of the load_splits datasets (padded)
    for batch, ref in zip(res.batches, splits):
        padded = ref.pad_stocks(mesh.devices.size)
        want = shard_batch(
            {k: jnp.asarray(v) for k, v in padded.full_batch().items()},
            mesh)
        assert set(want) == set(batch)
        for k in want:
            np.testing.assert_array_equal(
                np.asarray(want[k]), np.asarray(batch[k]), err_msg=k)
    rows = [json.loads(line)
            for line in (run / "events.jsonl").read_text().splitlines()]
    names = {r["name"] for r in rows if r["kind"] == "span_end"}
    assert "startup/shard_transfer" in names
    assert "startup/transfer/train" in names
    gauges = [r for r in rows if r.get("kind") == "gauge"
              and r["name"] == "startup/peak_rss"]
    assert gauges and gauges[0]["value"] > 0


# --------------------------------------------------------------------------
# train CLI: --shard_stocks runs THROUGH the pipeline, metrics identical
# to the sequential shard path
# --------------------------------------------------------------------------

TRAIN_ARGS = ["--epochs_unc", "2", "--epochs_moment", "1", "--epochs", "2",
              "--ignore_epoch", "0", "--print_freq", "4",
              "--no_lstm", "--hidden_dim", "4", "--rnn_dim", "2"]


def test_train_cli_shard_stocks_through_pipeline(synthetic_dir, cache_dir,
                                                 tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.train import main

    metrics = {}
    for label, extra in (("pipe", []), ("seq", ["--no_pipeline"])):
        run = tmp_path / label
        main(["--data_dir", str(synthetic_dir), "--save_dir", str(run),
              "--shard_stocks"] + TRAIN_ARGS + extra)
        metrics[label] = json.loads((run / "final_metrics.json").read_text())
    # the pipeline's per-shard streamed transfer is bit-identical to
    # shard_batch, so the two sharded routes must agree EXACTLY
    for split in ("train", "valid", "test"):
        assert metrics["pipe"][split] == metrics["seq"][split], split
    manifest = json.loads((tmp_path / "pipe" / "manifest.json").read_text())
    assert manifest["startup_pipeline"] is True
    rows = [json.loads(line) for line in
            (tmp_path / "pipe" / "events.jsonl").read_text().splitlines()]
    names = {r["name"] for r in rows if r["kind"] == "span_end"}
    # the sharding run kept the overlapped pipeline: early compile AND the
    # per-shard transfer spans are both present
    assert "startup/compile" in names
    assert "startup/shard_transfer" in names


# --------------------------------------------------------------------------
# report CLI: dataplane subsection from startup/shard_* events
# --------------------------------------------------------------------------

def test_report_dataplane_subsection(synthetic_dir, cache_dir, tmp_path,
                                     capsys):
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (  # noqa: E501
        load_run,
        main as report_main,
        summarize_run,
    )

    mesh = create_mesh()
    run = tmp_path / "run"
    ev = EventLog(run, process_index=0)
    pipeline.StartupPipeline(
        synthetic_dir, events=ev, mesh=mesh, shard_width=24,
    ).start().result()
    ev.close()
    st = summarize_run(load_run(run))["startup"]
    dp = st["dataplane"]
    assert dp is not None
    assert dp["shards_owned"] == 3 * 3  # 3 shards (width 24, N 64) × splits
    assert dp["shards_redecoded"] == 0
    assert dp["shard_transfers"] == 3 * mesh.devices.size
    assert dp["peak_rss_bytes"] and dp["peak_rss_bytes"] > 0
    assert report_main([str(run)]) == 0
    out = capsys.readouterr().out
    assert "dataplane (chunked store, shard-local)" in out
    assert "per-shard transfers" in out
    assert "peak host RSS" in out


# --------------------------------------------------------------------------
# bench.py --dataplane: tiny tier-1 e2e; the 100k acceptance run is slow
# --------------------------------------------------------------------------

def _run_dataplane_bench(tmp_path, extra):
    out = tmp_path / "BENCH_DATAPLANE.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--dataplane",
         "--out", str(out)] + extra,
        capture_output=True, text=True, cwd=REPO, timeout=1200,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-4000:]
    return json.loads(out.read_text())


def test_bench_dataplane_tiny_end_to_end(tmp_path):
    got = _run_dataplane_bench(tmp_path, [
        "--dp_stocks", "800", "--dp_periods", "6", "--dp_features", "5",
        "--dp_shard_width", "128", "--dp_parity_stocks", "200"])
    assert got["parity"]["bit_identical"] is True
    assert set(got["shard_local"]) == {"1", "2", "8"}
    assert got["full_chunked"]["cache_hit"] is True
    assert got["full_monolithic"]["cache_hit"] is True  # pre-shard baseline
    assert got["shard_local"]["8"]["n_cols"] == 100
    assert got["shard_local"]["8"]["shards_owned"] == 1
    assert got["full_chunked"]["shards_owned"] == 7  # ceil(800/128)
    assert got["full_monolithic"]["shards_owned"] == 0  # monolithic mmap
    for row in (got["full_chunked"], got["full_monolithic"],
                *got["shard_local"].values()):
        assert row["peak_delta_bytes"] >= 0
        assert row["wall_s"] > 0
        assert row["shards_redecoded"] == 0
    # no bars asserted at toy scale: fixed per-process overheads dominate


@pytest.mark.slow
def test_bench_dataplane_100k_meets_bars(tmp_path):
    """The acceptance run: 100k-stock panel, shard-local ≥4× faster and
    ≥4× less peak host memory than full materialization at 8-way."""
    got = _run_dataplane_bench(tmp_path, [])
    assert got["parity"]["bit_identical"] is True
    assert got["bars"]["met"] is True
    assert got["value"] >= 4.0
    assert got["host_mem_ratio_8way"] >= 4.0


# --------------------------------------------------------------------------
# shipped BENCH_DATAPLANE.json stays honest
# --------------------------------------------------------------------------

def test_bench_dataplane_artifact_bars():
    art = json.loads((REPO / "BENCH_DATAPLANE.json").read_text())
    assert art["panel"]["n_stocks"] == 100_000
    assert art["parity"]["bit_identical"] is True
    assert art["bars"]["met"] is True
    assert art["value"] >= art["bars"]["speedup_min"]
    assert art["host_mem_ratio_8way"] >= art["bars"]["mem_ratio_min"]
    # the headline is measured against the honest pre-sharding baseline
    # (monolithic mmap hit), not the chunked reader's own full read
    assert art["full_monolithic"]["shards_owned"] == 0
    assert art["full_chunked"]["shards_owned"] > 0


# --------------------------------------------------------------------------
# lint gate: the data-plane modules stay clean under the pyproject rules
# --------------------------------------------------------------------------

PKG = REPO / "deeplearninginassetpricing_paperreplication_tpu"
LINTED_DATAPLANE = [
    PKG / "data" / "diskcache.py",
    PKG / "data" / "pipeline.py",
    PKG / "data" / "synthetic.py",
    PKG / "parallel" / "ensemble.py",
    PKG / "parallel" / "sweep.py",
    PKG / "train.py",
    PKG / "sweep.py",
    PKG / "evaluate_ensemble.py",
    PKG / "observability" / "report.py",
    REPO / "bench.py",
]


def test_dataplane_modules_lint_clean():
    from test_observability import _ast_unused_imports

    try:
        import ruff  # noqa: F401

        proc = subprocess.run(
            [sys.executable, "-m", "ruff", "check",
             *[str(p) for p in LINTED_DATAPLANE]],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
    except ImportError:
        problems = {}
        for path in LINTED_DATAPLANE:
            unused = _ast_unused_imports(path)
            if unused:
                problems[path.name] = unused
        assert not problems, f"unused imports: {problems}"
