"""Replicated serving: R supervised engine processes on one shared port.

Each replica is a full ``serving.server --server async`` process — its own
engine, AOT programs, continuous batcher, and per-process result-cache
shard — bound to the SAME (host, port) via ``SO_REUSEPORT``: the kernel
spreads incoming connections across live listeners, so R replicas give R×
the GIL-bound parse/dispatch capacity with no userspace load balancer. Each
replica runs under its own :class:`~..reliability.supervisor.Supervisor`
(one watch thread per replica in this parent): a crash or hang is detected
by heartbeat staleness, the process group is killed, and the replica is
restarted with backoff — during which the fleet keeps serving at R-1
capacity (clients see dropped connections, retry onto survivors, and zero
requests go unserved; asserted by the tier-1 fault matrix).

Artifact layout under the fleet run dir::

    run_dir/
      replica0/  heartbeat.json, events.jsonl, manifest.json, supervised.log
      replica1/  ...
      events.supervisor.replica{i}.jsonl   (supervise/* spans + counters)

The report CLI aggregates across all of these (per-replica request counts,
occupancy, restarts) from the one fleet run dir.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from ..observability.events import EventLog
from ..observability.heartbeat import read_state
from ..reliability.faults import ENV_EVENTS, ENV_PLAN, ENV_STATE
from ..reliability.supervisor import RestartPolicy, Supervisor

_ROOT_PKG = __name__.rsplit(".", 2)[0]

# serving replicas restart much faster than training jobs: there is no
# resume state to protect, and every second down is lost capacity. The
# watchdog flare (SIGUSR1 before SIGKILL) gives a stale replica one grace
# window to dump its flight recorder — the server CLI installs the handler
REPLICA_POLICY = RestartPolicy(
    heartbeat_timeout_s=120.0,
    poll_s=0.5,
    max_restarts=5,
    min_uptime_s=10.0,
    backoff_base_s=0.5,
    backoff_max_s=10.0,
    prekill_signal=signal.SIGUSR1,
    prekill_grace_s=0.75,
)


def server_child_argv(args, replica_id: int, replica_run_dir,
                      port: int, admin_port: Optional[int] = None
                      ) -> List[str]:
    """The ``serving.server`` command line for one replica, rebuilt from
    the parsed parent args (explicit field-by-field: the parent's
    ``--replicas`` and ``--run_dir`` must not leak through).

    ``admin_port``: the replica's PRIVATE per-replica endpoint (the
    rolling-update path targets it); the shared ``port`` stays the
    SO_REUSEPORT serving socket. With a ``--pointer`` the replica boots
    from the promotion pointer instead of a fixed ``--checkpoint_dirs``
    list — so a replica restarted mid-promotion converges to the
    pointer's generation on its own."""
    argv = [sys.executable, "-m", f"{_ROOT_PKG}.serving.server",
            "--server", "async",
            "--host", args.host, "--port", str(port), "--reuse_port",
            "--replica_id", str(replica_id),
            "--run_dir", str(replica_run_dir),
            "--max_queue", str(args.max_queue),
            "--bulk_threshold", str(getattr(args, "bulk_threshold", 0.5)),
            "--cache_size", str(args.cache_size)]
    if getattr(args, "no_coalesce", False):
        argv += ["--no_coalesce"]
    if getattr(args, "pointer", None):
        argv += ["--pointer", str(args.pointer)]
    else:
        argv += ["--checkpoint_dirs", *args.checkpoint_dirs]
    if admin_port is not None:
        argv += ["--admin_port", str(admin_port)]
    if args.data_dir:
        argv += ["--data_dir", args.data_dir,
                 "--macro_split", args.macro_split]
    if args.macro_npy:
        argv += ["--macro_npy", args.macro_npy]
    if args.stock_buckets:
        argv += ["--stock_buckets", args.stock_buckets]
    if args.batch_buckets:
        argv += ["--batch_buckets", args.batch_buckets]
    if getattr(args, "mesh", None):
        argv += ["--mesh", args.mesh]
        n_slices = getattr(args, "mesh_slices", None)
        if n_slices:
            # replica↔device-slice lease: replica i of a co-hosted fleet
            # lays its mesh over disjoint contiguous slice i % N. The
            # parent never imports jax, so it stamps the INDEX and the
            # replica resolves its own devices via partition.slice_devices
            argv += ["--mesh_slice", f"{replica_id % n_slices}:{n_slices}"]
    if args.max_batch is not None:
        argv += ["--max_batch", str(args.max_batch)]
    if args.no_warmup:
        argv += ["--no_warmup"]
    if getattr(args, "reference_profile", None):
        argv += ["--reference_profile", str(args.reference_profile)]
    if getattr(args, "drift_every", None) is not None:
        argv += ["--drift_every", str(args.drift_every)]
    if getattr(args, "drift_psi_threshold", None) is not None:
        argv += ["--drift_psi_threshold", str(args.drift_psi_threshold)]
    return argv


def write_fleet_json(run_dir, layout: Dict[str, Any]) -> Path:
    """Atomically (tmp + ``os.replace``) rewrite the fleet run dir's
    ``fleet.json`` live-layout record. The autoscaler rewrites it on every
    scale event, so tooling and the report CLI always read a complete
    document describing the CURRENT replica set — never a torn one."""
    path = Path(run_dir) / "fleet.json"
    tmp = path.with_suffix(f".tmp.{os.getpid()}")
    tmp.write_text(json.dumps(layout, indent=2))
    os.replace(tmp, path)
    return path


def read_fleet_json(run_dir) -> Optional[Dict[str, Any]]:
    """Read a fleet run dir's live layout; missing/torn → None (the
    atomic writer makes torn unreachable in practice)."""
    try:
        return json.loads((Path(run_dir) / "fleet.json").read_text())
    except (OSError, json.JSONDecodeError):
        return None


class ReplicaFleet:
    """Supervised replica processes + their watch threads — a DYNAMIC set.

    Boots with the construction-time argvs; :meth:`add_replica` grows the
    set live (the autoscaler's scale-up) and :meth:`stop_replica` stops
    one member (scale-down — graceful when the replica already drained
    itself to a clean exit, SIGKILL otherwise). Replica ids are never
    reused within one fleet object: a scaled-down slot keeps its summary,
    and the next scale-up gets a fresh id — so per-replica run dirs and
    event files stay attributable."""

    def __init__(
        self,
        child_argvs: Sequence[Sequence[str]],
        run_dir,
        policy: Optional[RestartPolicy] = None,
        env: Optional[Dict[str, str]] = None,
    ):
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.policy = policy if policy is not None else REPLICA_POLICY
        # fault-plan plumbing (same default as the supervise CLI): a plan
        # without persistent state would re-kill a restarted replica at the
        # same site forever; one fleet-shared state file makes a kill fire
        # exactly once ACROSS the fleet
        self.env = dict(os.environ if env is None else env)
        if self.env.get(ENV_PLAN):
            self.env.setdefault(
                ENV_STATE, str(self.run_dir / "fault_state.json"))
            self.env.setdefault(
                ENV_EVENTS, str(self.run_dir / "events.faults.jsonl"))
        self.replica_dirs: List[Path] = []
        self.supervisors: List[Supervisor] = []
        self._events: List[EventLog] = []
        self._threads: List[Optional[threading.Thread]] = []
        self.summaries: List[Optional[Dict[str, Any]]] = []
        self._started = False
        self._lock = threading.Lock()
        for argv in child_argvs:
            self.add_replica(argv)

    @property
    def replicas(self) -> int:
        return len(self.supervisors)

    def add_replica(self, argv: Sequence[str]) -> int:
        """Register one more supervised replica (id = the next slot); when
        the fleet is already running, its watch thread starts immediately
        (the autoscaler's scale-up path). Returns the replica id."""
        with self._lock:
            i = len(self.supervisors)
            rdir = self.run_dir / f"replica{i}"
            rdir.mkdir(parents=True, exist_ok=True)
            events = EventLog(
                self.run_dir, process_index=0,
                filename=f"events.supervisor.replica{i}.jsonl")
            sup = Supervisor(
                list(argv),
                heartbeat_path=rdir / "heartbeat.json",
                policy=self.policy,
                events=events,
                log_path=rdir / "supervised.log",
                env=self.env,
            )
            self.replica_dirs.append(rdir)
            self.supervisors.append(sup)
            self._events.append(events)
            self._threads.append(None)
            self.summaries.append(None)
            if self._started:
                self._start_one(i)
        return i

    def _start_one(self, i: int) -> None:
        sup = self.supervisors[i]

        def run(i=i, sup=sup):
            self.summaries[i] = sup.run()

        t = threading.Thread(target=run, daemon=True,
                             name=f"supervise-replica{i}")
        t.start()
        self._threads[i] = t

    def start(self) -> None:
        self._started = True
        for i in range(len(self.supervisors)):
            if self._threads[i] is None:
                self._start_one(i)

    def live_ids(self) -> List[int]:
        """Replica ids whose watch thread is still running (the replica is
        being served/supervised — not drained, crash-looped, or stopped)."""
        return [i for i, t in enumerate(self._threads)
                if t is not None and t.is_alive()]

    def replica_pid(self, i: int) -> Optional[int]:
        """Replica ``i``'s live child pid (None between incarnations) —
        the SLO detection drill signals a replica directly (SIGKILL for
        dead, SIGSTOP for wedged-but-accepting) and measures seconds to
        the firing alert."""
        return self.supervisors[i].child_pid

    def wait_ready(self, timeout: float = 300.0,
                   section: str = "serve/accepting",
                   indices: Optional[Sequence[int]] = None) -> None:
        """Block until every replica in ``indices`` (default: all live
        slots) reaches heartbeat `section` (written once its socket
        accepts). Raises on timeout or a crash-looped replica, with the
        dead replica's log tail in the message."""
        deadline = time.monotonic() + timeout
        pending = set(range(self.replicas) if indices is None
                      else indices)
        while pending:
            for i in sorted(pending):
                hb = read_state(
                    self.replica_dirs[i] / "heartbeat.json"
                ).get("heartbeat") or {}
                if hb.get("section") == section:
                    pending.discard(i)
                    continue
                summary = self.summaries[i]
                if summary is not None:
                    raise RuntimeError(
                        f"replica{i} ended during startup "
                        f"({summary.get('outcome')}): "
                        + self._log_tail(i))
            if pending and time.monotonic() > deadline:
                raise TimeoutError(
                    f"replicas {sorted(pending)} not ready after "
                    f"{timeout:.0f}s: " + self._log_tail(min(pending)))
            if pending:
                time.sleep(0.1)

    def _log_tail(self, i: int, n: int = 12) -> str:
        try:
            lines = (self.replica_dirs[i] / "supervised.log").read_text(
                errors="replace").splitlines()
            return "\n".join(lines[-n:])
        except OSError:
            return "(no log)"

    def stop_replica(self, i: int, timeout: float = 30.0
                     ) -> Optional[Dict[str, Any]]:
        """Stop supervising replica ``i`` and end its process. When the
        replica already exited cleanly (a graceful drain: rc 0 →
        supervisor outcome ``success``), this just joins the watch
        thread; otherwise the supervisor SIGKILLs the process group.
        Closes the slot's supervisor EventLog too — a long-running
        autoscaled fleet must not leak one open fd per scale cycle
        (``close()`` is idempotent, so a later ``stop()`` is safe)."""
        t = self._threads[i]
        if t is not None and t.is_alive():
            self.supervisors[i].request_stop()
            t.join(timeout=timeout)
        self._events[i].close()
        return self.summaries[i]

    def stop(self, timeout: float = 30.0) -> List[Optional[Dict[str, Any]]]:
        for sup in self.supervisors:
            sup.request_stop()
        for t in self._threads:
            if t is not None:
                t.join(timeout=timeout)
        for ev in self._events:
            ev.close()
        return self.summaries


class RollingUpdater:
    """Health-gated rolling hot-swap of a replica fleet to the promotion
    pointer's current generation, with automatic rollback.

    Replicas are reloaded ONE at a time through their private admin
    endpoints (``--admin_port``): the fleet never drops below R-1
    serving capacity, and a request in flight during a swap lands either
    fully pre-swap or fully post-swap (the engine swaps under its
    dispatch lock). After each reload the replica must pass a health
    window over its OWN ``/metrics``:

      * its params fingerprint matches the pointer's (a torn candidate
        whose reload fell back — or errored — fails here);
      * ``steady_state_recompiles`` stayed 0 (a hot-swap must never
        recompile);
      * no new 5xx responses beyond the pre-swap baseline;
      * p99 latency under ``p99_budget_ms`` when configured.

    Any failed or regressed swap triggers automatic rollback: the pointer
    reverts (``reliability.promotion.rollback``) and every
    already-swapped replica is re-reloaded — converging the fleet back
    on the incumbent generation. A replica that DIES mid-reload (the
    ``serve/reload`` kill site) is restarted by its supervisor and boots
    from the pointer; the updater polls its admin endpoint until the
    fingerprint converges instead of failing the roll.

    Stdlib-only (urllib over the loopback admin ports): the updater runs
    in thin parents that never touch jax.
    """

    def __init__(
        self,
        admin_urls: Sequence[str],
        pointer_root,
        events: Optional[EventLog] = None,
        health_polls: int = 4,
        health_interval_s: float = 0.25,
        p99_budget_ms: Optional[float] = None,
        reload_timeout_s: float = 120.0,
        http_timeout_s: float = 30.0,
    ):
        self.admin_urls = [u.rstrip("/") for u in admin_urls]
        self.pointer_root = pointer_root
        self.events = events
        self.health_polls = int(health_polls)
        self.health_interval_s = float(health_interval_s)
        self.p99_budget_ms = p99_budget_ms
        self.reload_timeout_s = float(reload_timeout_s)
        self.http_timeout_s = float(http_timeout_s)

    # -- tiny loopback HTTP (stdlib; admin ports are local) ------------------

    def _get_json(self, url: str, path: str):
        import json as _json
        import urllib.request

        with urllib.request.urlopen(url + path,
                                    timeout=self.http_timeout_s) as r:
            return _json.loads(r.read())

    def _post_json(self, url: str, path: str, payload):
        import json as _json
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            url + path, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.http_timeout_s) as r:
                return r.status, _json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, _json.loads(e.read())
            except (ValueError, OSError):
                return e.code, {"error": "unreadable error body"}

    def _try_metrics(self, url: str):
        try:
            return self._get_json(url, "/metrics")
        except (OSError, ValueError):
            return None  # replica down / mid-restart

    @staticmethod
    def _count_5xx(metrics) -> int:
        n = 0
        for key, value in (metrics or {}).get("requests", {}).items():
            status = key.rsplit(" ", 1)[-1]
            if status.isdigit() and int(status) >= 500:
                n += int(value)
        return n

    def _counter(self, name: str, **attrs) -> None:
        if self.events is not None:
            self.events.counter(name, **attrs)

    # -- the roll ------------------------------------------------------------

    def roll(self) -> Dict[str, Any]:
        """Read the pointer, swap every replica one at a time, health-gate
        each; rollback on the first failure. Returns
        ``{"status": "promoted"|"rolled_back", ...}``."""
        from ..reliability.promotion import read_pointer
        from ..reliability.promotion import rollback as pointer_rollback

        pointer = read_pointer(self.pointer_root)
        if pointer is None:
            raise ValueError(f"no promotion pointer under "
                             f"{self.pointer_root}")
        target_fp = str(pointer.get("params_fingerprint") or "")[:16]
        replicas: List[Dict[str, Any]] = []
        swapped: List[str] = []
        for url in self.admin_urls:
            verdict = self._swap_one(url, pointer, target_fp)
            replicas.append(verdict)
            if verdict["ok"]:
                swapped.append(url)
                continue
            # rollback: revert the pointer FIRST (so restarting replicas
            # boot onto the incumbent), then re-reload everyone already
            # swapped — and the failed replica, in case it half-advanced
            from ..reliability.promotion import PromotionError

            try:
                reverted = pointer_rollback(
                    self.pointer_root, reason=verdict["reason"],
                    events=self.events)
            except PromotionError as e:
                # nothing to revert to (the first-ever promoted
                # generation failed its roll): the pointer stays put —
                # re-reloading swapped replicas would just re-swap them
                # onto the same failed generation, so report the
                # divergence instead of masking it
                self._counter("promote/fleet_rollback_failed",
                              reason=verdict["reason"], error=str(e))
                return {"status": "rollback_failed",
                        "reason": verdict["reason"],
                        "failed_replica": url, "replicas": replicas,
                        "rollback_error": str(e),
                        "swapped": list(swapped)}
            rolled: List[str] = []
            for u in swapped + [url]:
                status, _body = self._reload_until_converged(
                    u, str(reverted.get("params_fingerprint") or "")[:16])
                rolled.append(f"{u}: {status}")
            self._counter("promote/fleet_rollback",
                          reason=verdict["reason"],
                          generation=reverted["generation"])
            return {"status": "rolled_back", "reason": verdict["reason"],
                    "failed_replica": url, "replicas": replicas,
                    "pointer_generation": reverted["generation"],
                    "rolled": rolled}
        self._counter("promote/fleet_converged",
                      generation=pointer["generation"],
                      fingerprint=target_fp, replicas=len(self.admin_urls))
        return {"status": "promoted",
                "pointer_generation": pointer["generation"],
                "fingerprint": target_fp, "replicas": replicas}

    def _reload_until_converged(self, url: str, target_fp: str):
        """POST /v1/reload; if the replica dies mid-reload (connection
        drop), poll its admin endpoint until the supervisor's restart
        converges it to the pointer on boot. Returns (status, body) —
        status "converged"/"reloaded"/HTTP code/"timeout"."""
        deadline = time.monotonic() + self.reload_timeout_s
        while time.monotonic() < deadline:
            try:
                status, body = self._post_json(url, "/v1/reload", {})
            except (OSError, ValueError):
                # died mid-reload (or still restarting): give the
                # supervisor time, then check whether the boot already
                # converged to the pointer's generation
                time.sleep(0.5)
                m = self._try_metrics(url)
                fp = ((m or {}).get("engine") or {}).get(
                    "params_fingerprint")
                if fp is not None and fp == target_fp:
                    return "converged", m
                continue
            if status == 200:
                return "reloaded", body
            return status, body
        return "timeout", None

    def _swap_one(self, url: str, pointer, target_fp: str
                  ) -> Dict[str, Any]:
        baseline = self._try_metrics(url)
        errors_before = self._count_5xx(baseline)
        status, body = self._reload_until_converged(url, target_fp)
        verdict: Dict[str, Any] = {"replica": url, "reload": str(status),
                                   "ok": False}
        if status == "timeout":
            verdict["reason"] = "reload_timeout"
            return verdict
        if status not in ("reloaded", "converged"):
            verdict["reason"] = (
                f"reload_error_{status}: "
                f"{(body or {}).get('error', '')}"[:300])
            return verdict
        # post-reload health window over THIS replica's own metrics
        checks: Dict[str, Any] = {}
        metrics = None
        for _ in range(max(1, self.health_polls)):
            time.sleep(self.health_interval_s)
            metrics = self._try_metrics(url) or metrics
        if metrics is None:
            verdict["reason"] = "health_unreachable"
            return verdict
        engine = metrics.get("engine") or {}
        checks["fingerprint"] = engine.get("params_fingerprint") == target_fp
        steady = engine.get("steady_state_recompiles")
        checks["steady_state_recompiles"] = steady in (0, None)
        new_5xx = max(0, self._count_5xx(metrics) - errors_before)
        checks["no_new_5xx"] = new_5xx == 0
        if self.p99_budget_ms is not None:
            p99 = (metrics.get("latency") or {}).get("p99_ms")
            checks["p99_under_budget"] = (
                p99 is None or p99 <= self.p99_budget_ms)
        verdict["checks"] = checks
        verdict["new_5xx"] = new_5xx
        failed = [k for k, v in checks.items() if not v]
        if failed:
            verdict["reason"] = "health_" + ",".join(failed)
            return verdict
        verdict["ok"] = True
        return verdict


def main_from_server_args(args) -> int:
    """The ``serving.server --replicas R`` parent: spawn, supervise, park.

    Never initializes a JAX backend — replicas do all the serving; the
    parent only watches heartbeats and restarts the dead.
    """
    from .aserver import pick_free_port

    if not args.run_dir:
        print("--replicas requires --run_dir (per-replica heartbeats and "
              "supervision live there)", file=sys.stderr)
        return 2
    if args.server != "async":
        print("--replicas requires --server async (the threaded path is "
              "deprecated and single-process only)", file=sys.stderr)
        return 2
    run_dir = Path(args.run_dir)
    port = args.port if args.port else pick_free_port(args.host)
    # every replica gets a private admin endpoint: the rolling-update
    # path must be able to target ONE replica, which the shared
    # SO_REUSEPORT port cannot do. Explicit --admin_port P → P, P+1, …;
    # default → free ports. Recorded in fleet.json for tooling.
    if args.admin_port:
        admin_ports = [args.admin_port + i for i in range(args.replicas)]
    else:
        admin_ports = []
        for _ in range(args.replicas):
            p = pick_free_port()
            while p in admin_ports or p == port:
                p = pick_free_port()
            admin_ports.append(p)
    argvs = [
        server_child_argv(args, i, run_dir / f"replica{i}", port,
                          admin_port=admin_ports[i])
        for i in range(args.replicas)
    ]
    fleet = ReplicaFleet(argvs, run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)

    def make_argv(replica_id: int, admin_port: int) -> List[str]:
        # the autoscaler's scale-up path: one more child on the SAME
        # shared port, its own run dir + private admin endpoint
        return server_child_argv(args, replica_id,
                                 run_dir / f"replica{replica_id}", port,
                                 admin_port=admin_port)

    from .autoscale import FleetController

    controller = FleetController(
        fleet, make_argv, args.host, port,
        admin_ports={i: p for i, p in enumerate(admin_ports)},
        pointer=getattr(args, "pointer", None),
        mesh=getattr(args, "mesh", None),
        mesh_slices=getattr(args, "mesh_slices", None))
    # the CONFIGURED layout, on disk before any replica is up: a slow or
    # wedged boot is still inspectable (port + admin endpoints); the
    # post-ready publish below and every scale event rewrite it live
    controller.publish_layout(replica_ids=range(args.replicas))
    autoscaler = None
    events = None
    flight = None
    if getattr(args, "autoscale", False):
        from ..observability.events import EventLog
        from .autoscale import AutoscalePolicy, Autoscaler
        from .flight import FlightRecorder

        events = EventLog(run_dir, process_index=0,
                          filename="events.autoscaler.jsonl")
        # the parent's own recorder: the decision ring must actually
        # reach disk — autosave while dirty, final dump at shutdown —
        # so an overload post-mortem shows WHY the fleet was shedding
        flight = FlightRecorder(run_dir=run_dir, events=events)
        flight.start_autosave()
        policy = AutoscalePolicy(
            min_replicas=args.min_replicas or 1,
            max_replicas=args.max_replicas or max(4, args.replicas),
            poll_s=args.autoscale_poll_s,
            up_queue_depth=args.autoscale_up_depth,
            down_queue_depth=args.autoscale_down_depth,
            up_hysteresis=args.autoscale_up_hysteresis,
            down_hysteresis=args.autoscale_down_hysteresis,
            cooldown_s=args.autoscale_cooldown_s,
        )
        autoscaler = Autoscaler(controller, policy, events=events,
                                flight=flight)
    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — signal-handler shape
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    try:
        fleet.start()
        fleet.wait_ready()
        # the boot layout, published once every replica accepts (live ids
        # are only meaningful after start); every scale event rewrites it
        controller.publish_layout()
        if autoscaler is not None:
            autoscaler.start()
            print(f"autoscaler live: {autoscaler.policy.min_replicas}.."
                  f"{autoscaler.policy.max_replicas} replicas, "
                  f"poll {autoscaler.policy.poll_s}s", flush=True)
        print(f"fleet of {fleet.replicas} replicas serving on "
              f"http://{args.host}:{port} (SO_REUSEPORT)", flush=True)
        while not stop.is_set():
            stop.wait(1.0)
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        if flight is not None:
            flight.stop_autosave()
            flight.dump("shutdown")
        fleet.stop()
        if events is not None:
            events.close()
    return 0
