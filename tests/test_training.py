"""Trainer semantics: optimizer partitioning, best-model selection, phases,
checkpoint round-trips, and end-to-end smoke on synthetic data."""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu import GAN, GANConfig, TrainConfig
from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
    load_checkpoint_dir,
    load_params,
    save_params,
)
from deeplearninginassetpricing_paperreplication_tpu.training.steps import (
    make_eval_step,
    make_optimizer,
    make_train_step,
)
from deeplearninginassetpricing_paperreplication_tpu.training.trainer import (
    Trainer,
    carry_donate_argnums,
    train_3phase,
)


def _batch_from(ds):
    return {k: jnp.asarray(v) for k, v in ds.full_batch().items()}


@pytest.fixture(scope="module")
def small_cfg():
    return GANConfig(
        macro_feature_dim=6, individual_feature_dim=10,
        hidden_dim=(8, 8), num_units_rnn=(3,), num_condition_moment=4,
    )


@pytest.mark.slow
def test_train_step_updates_only_trainable_subtree(small_cfg, splits):
    gan = GAN(small_cfg)
    params = gan.init(jax.random.key(0))
    batch = _batch_from(splits[0])
    tx = make_optimizer(1e-3)

    for phase, moving, frozen in (
        ("unconditional", "sdf_net", "moment_net"),
        ("conditional", "sdf_net", "moment_net"),
        ("moment", "moment_net", "sdf_net"),
    ):
        step = make_train_step(gan, phase, tx)
        opt = tx.init(params[moving])
        new_params, _, metrics = step(params, opt, batch, jax.random.key(1))
        # frozen subtree bit-identical
        assert jax.tree.all(
            jax.tree.map(lambda a, b: bool((a == b).all()),
                         new_params[frozen], params[frozen])
        ), phase
        # trainable subtree actually moved
        moved = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                             new_params[moving], params[moving])
        assert max(jax.tree.leaves(moved)) > 0, phase
        assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_moment_phase_ascends_conditional_loss(small_cfg, splits):
    """Phase 2 maximizes E[h·w·R·M]²: after several discriminator steps the
    conditional loss must increase (train.py:304-321)."""
    gan = GAN(small_cfg)
    params = gan.init(jax.random.key(0))
    batch = _batch_from(splits[0])
    tx = make_optimizer(1e-2)
    step = make_train_step(gan, "moment", tx)
    opt = tx.init(params["moment_net"])
    first = None
    for i in range(25):
        params, opt, m = step(params, opt, batch, jax.random.key(100 + i))
        if first is None:
            first = float(m["loss_cond"])
    assert float(m["loss_cond"]) > first


def test_grad_clip_bounds_global_norm(small_cfg, splits):
    """The step's applied update must equal Adam on the hand-clipped gradient
    (clip-by-global-norm to `grad_clip`, the torch clip_grad_norm_ semantics
    of /root/reference/src/train.py:88-92)."""
    import optax

    gan = GAN(small_cfg)
    params = gan.init(jax.random.key(0))
    batch = _batch_from(splits[0])
    clip = 1e-5  # far below the raw grad norm (~2e-4 at init) so it binds
    tx = make_optimizer(1e-3, grad_clip=clip)
    step = make_train_step(gan, "unconditional", tx)
    opt = tx.init(params["sdf_net"])
    rng = jax.random.key(1)
    new_params, _, metrics = step(params, opt, batch, rng)

    # reproduce the step's raw gradients exactly (same loss, same dropout rng)
    def loss_fn(trainable):
        out = gan.forward(
            {"sdf_net": trainable, "moment_net": params["moment_net"]},
            batch, phase="unconditional", rng=rng,
        )
        return out["loss"]

    grads = jax.grad(loss_fn)(params["sdf_net"])
    gnorm = float(optax.global_norm(grads))
    assert gnorm > clip, "clip must be binding for this test to mean anything"

    # the clip transform actually bounds the global norm
    clip_tx = optax.clip_by_global_norm(clip)
    clipped, _ = clip_tx.update(grads, clip_tx.init(params["sdf_net"]))
    assert float(optax.global_norm(clipped)) <= clip * (1 + 1e-5)

    # Adam on the clipped grads reproduces the applied update exactly; Adam on
    # the RAW grads must NOT (proves the step routes grads through the clip)
    adam = optax.adam(1e-3, b1=0.9, b2=0.999, eps=1e-8)
    upd, _ = adam.update(clipped, adam.init(params["sdf_net"]), params["sdf_net"])
    expected = optax.apply_updates(params["sdf_net"], upd)
    for a, b in zip(jax.tree.leaves(expected), jax.tree.leaves(new_params["sdf_net"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)

    upd_raw, _ = adam.update(grads, adam.init(params["sdf_net"]), params["sdf_net"])
    unclipped = optax.apply_updates(params["sdf_net"], upd_raw)
    diffs = jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), unclipped, new_params["sdf_net"]
    )
    assert max(jax.tree.leaves(diffs)) > 1e-6, "raw-Adam result should differ"
    # and the reported grad_norm is the RAW (pre-clip) norm
    np.testing.assert_allclose(float(metrics["grad_norm"]), gnorm, rtol=1e-5)


def test_eval_step_deterministic_and_normalized(small_cfg, splits):
    gan = GAN(small_cfg)
    params = gan.init(jax.random.key(0))
    batch = _batch_from(splits[1])
    ev = make_eval_step(gan)
    a = ev(params, batch)
    b = ev(params, batch)
    assert float(a["sharpe"]) == float(b["sharpe"])
    assert np.isfinite(float(a["loss_cond"]))


@pytest.mark.slow
def test_train_3phase_end_to_end(small_cfg, splits, tmp_path):
    train, valid, test = splits
    tcfg = TrainConfig(num_epochs_unc=6, num_epochs_moment=3, num_epochs=10,
                       ignore_epoch=1, seed=0)
    gan, final_params, history, _trainer = train_3phase(
        small_cfg, _batch_from(train), _batch_from(valid), _batch_from(test),
        tcfg=tcfg, save_dir=str(tmp_path / "run"), verbose=False,
    )
    # history shape: phases 1 and 3 only (reference appends no phase-2 rows)
    assert len(history["train_loss"]) == 16
    assert list(history["phase"]) == ["unc"] * 6 + ["cond"] * 10
    assert np.all(np.isfinite(history["train_loss"]))
    # artifacts
    run = tmp_path / "run"
    for f in ("config.json", "best_model_loss.msgpack", "best_model_sharpe.msgpack",
              "final_model.msgpack", "history.npz"):
        assert (run / f).exists(), f
    # checkpoint round-trip reproduces the final params
    gan2, params2 = load_checkpoint_dir(run, "final_model")
    for a, b in zip(jax.tree.leaves(final_params), jax.tree.leaves(params2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # final model == best-by-valid-sharpe over eligible phase-3 epochs
    hist_sharpe = np.asarray(history["valid_sharpe"][6:])
    ev = make_eval_step(gan)
    final_sharpe = float(ev(final_params, _batch_from(valid))["sharpe"])
    np.testing.assert_allclose(final_sharpe, hist_sharpe[2:].max(), rtol=1e-5)


@pytest.mark.slow
def test_best_selection_ignores_early_epochs(small_cfg, splits, tmp_path):
    """With ignore_epoch >= num_epochs no phase ever updates its best tracker,
    so the final params must equal the LAST-epoch running params (the
    reference's `if best_model_state is not None` guard, train.py:289-292,
    398-400). Verified by replaying the exact same schedule as serial
    un-scanned train steps with the trainer's rng stream."""
    train, valid, test = splits
    tb, vb, teb = _batch_from(train), _batch_from(valid), _batch_from(test)
    tcfg = TrainConfig(num_epochs_unc=3, num_epochs_moment=2, num_epochs=3,
                       ignore_epoch=99, seed=0)
    gan, final_params, history, _trainer = train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg, verbose=False,
    )
    assert len(history["train_loss"]) == 6

    # serial replay: same init, same rng folding as build_phase_scan
    from deeplearninginassetpricing_paperreplication_tpu.utils.rng import (
        train_base_key,
    )

    params = gan.init(jax.random.key(tcfg.seed))
    tx_sdf = make_optimizer(tcfg.lr, tcfg.grad_clip)
    tx_m = make_optimizer(tcfg.lr, tcfg.grad_clip)
    opt_sdf = tx_sdf.init(params["sdf_net"])
    opt_m = tx_m.init(params["moment_net"])
    r1, r2, r3 = jax.random.split(train_base_key(tcfg.seed), 3)
    step_unc = make_train_step(gan, "unconditional", tx_sdf)
    step_m = make_train_step(gan, "moment", tx_m)
    step_cond = make_train_step(gan, "conditional", tx_sdf)
    for e in range(tcfg.num_epochs_unc):
        params, opt_sdf, _ = step_unc(params, opt_sdf, tb, jax.random.fold_in(r1, e))
    for e in range(tcfg.num_epochs_moment):
        params, opt_m, _ = step_m(params, opt_m, tb, jax.random.fold_in(r2, e))
    for e in range(tcfg.num_epochs):
        params, opt_sdf, _ = step_cond(params, opt_sdf, tb, jax.random.fold_in(r3, e))

    # scan-compiled vs unrolled float32 programs reassociate; tolerance covers
    # the tiny accumulation drift over the 8 epochs, not a semantic gap
    for a, b in zip(jax.tree.leaves(final_params), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("kill_after", [1, 2])
def test_resume_after_phase_kill(small_cfg, splits, tmp_path, kill_after):
    """Kill-between-phases: a run stopped after phase k and resumed with
    --resume must land on exactly the same final params and history as an
    uninterrupted run (phase dropout streams derive from the seed per phase,
    so the continuation is bit-identical)."""
    train, valid, test = splits
    tb, vb, teb = _batch_from(train), _batch_from(valid), _batch_from(test)
    tcfg = TrainConfig(num_epochs_unc=4, num_epochs_moment=2, num_epochs=5,
                       ignore_epoch=1, seed=3)

    # uninterrupted reference run
    _, final_full, hist_full, _ = train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg,
        save_dir=str(tmp_path / "full"), verbose=False,
    )

    # interrupted: stop after phase `kill_after`, then resume
    run_dir = tmp_path / f"killed_{kill_after}"
    train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg, save_dir=str(run_dir),
        verbose=False, stop_after_phase=kill_after,
    )
    assert (run_dir / "resume_state.msgpack").exists()
    assert (run_dir / "resume_meta.json").exists()
    meta = json.loads((run_dir / "resume_meta.json").read_text())
    assert meta["completed_phase"] == kill_after

    _, final_resumed, hist_resumed, _ = train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg, save_dir=str(run_dir),
        verbose=False, resume=True,
    )
    for a, b in zip(jax.tree.leaves(final_full), jax.tree.leaves(final_resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(hist_full["train_loss"]), np.asarray(hist_resumed["train_loss"])
    )
    assert list(hist_full["phase"]) == list(hist_resumed["phase"])

    # schedule mismatch must be loud
    import dataclasses

    bad = dataclasses.replace(tcfg, num_epochs=7)
    train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg, save_dir=str(run_dir),
        verbose=False, stop_after_phase=1,
    )
    with pytest.raises(ValueError, match="does not match"):
        train_3phase(
            small_cfg, tb, vb, teb, tcfg=bad, save_dir=str(run_dir),
            verbose=False, resume=True,
        )


@pytest.mark.slow
def test_segmented_run_bit_identical(small_cfg, splits, tmp_path):
    """checkpoint_every segments must not change anything: same final params
    and history as the whole-phase scans (segments scan the same absolute
    epoch indices, so dropout streams and best tracking are identical —
    dropout is ON here to prove the rng claim)."""
    train, valid, test = splits
    tb, vb, teb = _batch_from(train), _batch_from(valid), _batch_from(test)
    tcfg = TrainConfig(num_epochs_unc=5, num_epochs_moment=2, num_epochs=7,
                       ignore_epoch=1, seed=11)

    _, final_a, hist_a, _ = train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg,
        save_dir=str(tmp_path / "whole"), verbose=False,
    )
    _, final_b, hist_b, _ = train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg,
        save_dir=str(tmp_path / "segmented"), verbose=False,
        checkpoint_every=3,  # 5→3+2, 2→2, 7→3+3+1
    )
    for a, b in zip(jax.tree.leaves(final_a), jax.tree.leaves(final_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in ("train_loss", "valid_sharpe", "test_sharpe"):
        np.testing.assert_array_equal(
            np.asarray(hist_a[k]), np.asarray(hist_b[k]))
    # a completed run leaves nothing to resume
    assert not (tmp_path / "segmented" / "resume_state.msgpack").exists()


@pytest.mark.slow
@pytest.mark.parametrize("stop_at", [3, 8, 12])
def test_midphase_stop_and_resume_bit_identical(small_cfg, splits, tmp_path,
                                                stop_at):
    """Stop INSIDE a phase (stop_after_epochs at a segment boundary), resume,
    and land exactly on the uninterrupted run's final params and history.
    stop_at=3 stops mid-phase-1, 8 mid-phase-3 (after 5+2=7), 12 deeper into
    phase 3."""
    train, valid, test = splits
    tb, vb, teb = _batch_from(train), _batch_from(valid), _batch_from(test)
    tcfg = TrainConfig(num_epochs_unc=5, num_epochs_moment=2, num_epochs=7,
                       ignore_epoch=1, seed=11)

    _, final_full, hist_full, _ = train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg,
        save_dir=str(tmp_path / "full"), verbose=False,
    )

    run_dir = tmp_path / f"stopped_{stop_at}"
    train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg, save_dir=str(run_dir),
        verbose=False, checkpoint_every=2, stop_after_epochs=stop_at,
    )
    meta = json.loads((run_dir / "resume_meta.json").read_text())
    assert meta["in_phase"] > 0  # genuinely stopped inside a phase
    _, final_resumed, hist_resumed, _ = train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg, save_dir=str(run_dir),
        verbose=False, resume=True, checkpoint_every=2,
    )
    for a, b in zip(jax.tree.leaves(final_full), jax.tree.leaves(final_resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in ("train_loss", "valid_sharpe", "test_sharpe"):
        np.testing.assert_array_equal(
            np.asarray(hist_full[k]), np.asarray(hist_resumed[k]))
    assert list(hist_full["phase"]) == list(hist_resumed["phase"])
    assert not (run_dir / "resume_state.msgpack").exists()


@pytest.mark.slow
def test_midphase_resume_without_checkpoint_every(small_cfg, splits, tmp_path):
    """A mid-phase state resumes correctly even when the resuming invocation
    passes no checkpoint_every (the remainder runs as one segment)."""
    train, valid, test = splits
    tb, vb, teb = _batch_from(train), _batch_from(valid), _batch_from(test)
    tcfg = TrainConfig(num_epochs_unc=5, num_epochs_moment=2, num_epochs=7,
                       ignore_epoch=1, seed=11)
    _, final_full, _, _ = train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg,
        save_dir=str(tmp_path / "full"), verbose=False,
    )
    run_dir = tmp_path / "stopped"
    train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg, save_dir=str(run_dir),
        verbose=False, checkpoint_every=2, stop_after_epochs=9,
    )
    _, final_resumed, _, _ = train_3phase(
        small_cfg, tb, vb, teb, tcfg=tcfg, save_dir=str(run_dir),
        verbose=False, resume=True,
    )
    for a, b in zip(jax.tree.leaves(final_full), jax.tree.leaves(final_resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_save_load_params_roundtrip(small_cfg, tmp_path):
    gan = GAN(small_cfg)
    params = gan.init(jax.random.key(3))
    save_params(tmp_path / "p.msgpack", params)
    loaded = load_params(tmp_path / "p.msgpack", gan.init(jax.random.key(4)))
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_adam_matches_torch_on_quadratic():
    """optax.adam(eps=1e-8) must track torch.optim.Adam step-for-step —
    the trainer's update rule parity in isolation."""
    torch = pytest.importorskip("torch")
    import optax

    w0 = np.array([1.5, -2.0, 0.5], dtype=np.float32)
    tw = torch.tensor(w0.copy(), requires_grad=True)
    topt = torch.optim.Adam([tw], lr=1e-2)
    jw = jnp.asarray(w0.copy())
    tx = optax.adam(1e-2, b1=0.9, b2=0.999, eps=1e-8)
    jstate = tx.init(jw)
    for _ in range(20):
        loss_t = (tw**2).sum()
        topt.zero_grad(); loss_t.backward(); topt.step()
        g = jax.grad(lambda w: (w**2).sum())(jw)
        upd, jstate = tx.update(g, jstate, jw)
        jw = optax.apply_updates(jw, upd)
    np.testing.assert_allclose(np.asarray(jw), tw.detach().numpy(), atol=1e-5)


def test_joint_plateau_matches_torch_scheduler():
    """_plateau_update replicates torch ReduceLROnPlateau(mode='max',
    factor, patience, rel threshold) step-for-step on a metric trace."""
    import numpy as np
    import jax.numpy as jnp
    import torch
    from deeplearninginassetpricing_paperreplication_tpu.training.joint import (
        _plateau_update,
    )

    factor, patience = 0.5, 3
    rng = np.random.default_rng(0)
    metrics = np.cumsum(rng.standard_normal(60)).astype(np.float32) * 0.1

    opt = torch.optim.SGD([torch.nn.Parameter(torch.zeros(1))], lr=1.0)
    sched = torch.optim.lr_scheduler.ReduceLROnPlateau(
        opt, mode="max", factor=factor, patience=patience
    )
    lr_scale = jnp.float32(1.0)
    best = jnp.float32(-np.inf)
    bad = jnp.int32(0)
    for m in metrics:
        sched.step(float(m))
        lr_scale, best, bad = _plateau_update(
            lr_scale, best, bad, jnp.float32(m), factor, patience, 1e-4
        )
        torch_lr = opt.param_groups[0]["lr"]
        assert abs(float(lr_scale) - torch_lr) < 1e-9, (m, float(lr_scale), torch_lr)


@pytest.mark.slow
def test_joint_train_runs_and_decays_lr():
    import numpy as np
    import jax
    from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
    from deeplearninginassetpricing_paperreplication_tpu.training.joint import (
        joint_train,
        train_simple_sdf,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import GANConfig

    rng = np.random.default_rng(0)
    T, N, F, M = 10, 24, 4, 3
    mask = (rng.random((T, N)) > 0.3).astype(np.float32)
    batch = {
        "individual": (rng.standard_normal((T, N, F)) * mask[:, :, None]).astype(np.float32),
        "returns": (rng.standard_normal((T, N)) * 0.05 * mask).astype(np.float32),
        "mask": mask,
        "macro": rng.standard_normal((T, M)).astype(np.float32),
    }
    cfg = GANConfig(macro_feature_dim=M, individual_feature_dim=F, hidden_dim=(6,))
    gan = GAN(cfg)
    params = gan.init(jax.random.key(0))
    p2, hist = joint_train(gan, params, batch, batch, num_epochs=25,
                           plateau_patience=4)
    assert np.all(np.isfinite(hist["train_loss"]))
    assert hist["lr"][0] == 1e-3
    # params actually moved
    moved = jax.tree.map(lambda a, b: float(np.abs(np.asarray(a - b)).max()),
                         params, p2)
    assert max(jax.tree.leaves(moved)) > 0
    _, _, shist = train_simple_sdf(M, F, batch, batch, num_epochs=10)
    assert np.all(np.isfinite(shist["valid_sharpe"]))


def test_torch_checkpoint_export_roundtrip_and_reference_load(small_cfg, tmp_path):
    """Export to the reference's .pt format: params → state_dict → params is
    exact, and the exported dict loads into the reference's own
    AssetPricingGAN with strict=True (key names and shapes all match)."""
    import sys

    torch = pytest.importorskip("torch")
    if not Path("/root/reference/src/model.py").exists():
        pytest.skip("reference repo not mounted")

    from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
        params_from_torch_state_dict,
        save_torch_checkpoint,
        torch_state_dict_from_params,
    )

    gan = GAN(small_cfg)
    params = gan.init(jax.random.key(9))
    sd = torch_state_dict_from_params(params, small_cfg)
    back = params_from_torch_state_dict(sd, small_cfg)
    for (ka, a), (kb, b) in zip(
        jax.tree.leaves_with_path(params), jax.tree.leaves_with_path(back),
        strict=True,
    ):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(ka))

    save_torch_checkpoint(tmp_path / "export.pt", params, small_cfg)
    reloaded = torch.load(tmp_path / "export.pt", map_location="cpu",
                          weights_only=True)

    sys.path.insert(0, "/root/reference")
    try:
        from src.model import AssetPricingGAN
    finally:
        sys.path.pop(0)
    ref_model = AssetPricingGAN({
        "macro_feature_dim": small_cfg.macro_feature_dim,
        "individual_feature_dim": small_cfg.individual_feature_dim,
        "hidden_dim": list(small_cfg.hidden_dim),
        "use_rnn": small_cfg.use_rnn,
        "num_units_rnn": list(small_cfg.num_units_rnn),
        "hidden_dim_moment": list(small_cfg.hidden_dim_moment),
        "num_condition_moment": small_cfg.num_condition_moment,
        "dropout": 0.0,
    })
    ref_model.load_state_dict(reloaded, strict=True)  # raises on any mismatch


def test_load_checkpoint_dir_accepts_reference_pt(small_cfg, tmp_path):
    """A reference-style run directory (config.json + best_model_sharpe.pt)
    loads through the same load_checkpoint_dir the ensemble/plots CLIs use."""
    pytest.importorskip("torch")
    from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
        save_torch_checkpoint,
    )

    gan = GAN(small_cfg)
    params = gan.init(jax.random.key(12))
    save_torch_checkpoint(tmp_path / "best_model_sharpe.pt", params, small_cfg)
    assert (tmp_path / "config.json").exists()  # written alongside

    gan2, loaded = load_checkpoint_dir(tmp_path, "best_model_sharpe")
    assert gan2.cfg == small_cfg
    # jax.tree_util spelling: jax.tree.leaves_with_path needs jax >= 0.5
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(loaded),
        strict=True,
    ):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7,
                                   err_msg=str(ka))


# -- load_checkpoint_dir candidate-fallback chain ----------------------------
# requested .msgpack → reference .pt → final_model.{msgpack,pt}; the exact
# order the docstring promises, with a warning IFF a best_model request
# degrades to final_model.


def _params_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


@pytest.fixture()
def ckpt_dir(small_cfg, tmp_path):
    """A run dir with config.json and two DISTINCT param sets on disk."""
    gan = GAN(small_cfg)
    small_cfg.save(tmp_path / "config.json")
    return {
        "dir": tmp_path,
        "gan": gan,
        "best": gan.init(jax.random.key(21)),
        "final": gan.init(jax.random.key(22)),
    }


def test_fallback_requested_msgpack_wins_over_final(ckpt_dir):
    save_params(ckpt_dir["dir"] / "best_model_sharpe.msgpack", ckpt_dir["best"])
    save_params(ckpt_dir["dir"] / "final_model.msgpack", ckpt_dir["final"])
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # no degradation warning here
        _, loaded = load_checkpoint_dir(ckpt_dir["dir"], "best_model_sharpe")
    assert _params_equal(loaded, ckpt_dir["best"])
    assert not _params_equal(loaded, ckpt_dir["final"])


def test_fallback_to_final_model_warns(ckpt_dir):
    save_params(ckpt_dir["dir"] / "final_model.msgpack", ckpt_dir["final"])
    with pytest.warns(UserWarning, match="best_model_sharpe absent"):
        _, loaded = load_checkpoint_dir(ckpt_dir["dir"], "best_model_sharpe")
    assert _params_equal(loaded, ckpt_dir["final"])


def test_fallback_final_model_direct_request_no_warning(ckpt_dir):
    save_params(ckpt_dir["dir"] / "final_model.msgpack", ckpt_dir["final"])
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _, loaded = load_checkpoint_dir(ckpt_dir["dir"], "final_model")
    assert _params_equal(loaded, ckpt_dir["final"])


def test_fallback_no_final_for_non_best_request(ckpt_dir):
    """Only best_model* requests may degrade to final_model; a custom
    artifact name must not silently load someone else's params."""
    save_params(ckpt_dir["dir"] / "final_model.msgpack", ckpt_dir["final"])
    with pytest.raises(FileNotFoundError):
        load_checkpoint_dir(ckpt_dir["dir"], "some_other_artifact")


def test_fallback_empty_dir_raises_with_candidates_named(ckpt_dir):
    with pytest.raises(FileNotFoundError, match="best_model_sharpe"):
        load_checkpoint_dir(ckpt_dir["dir"], "best_model_sharpe")


def test_fallback_reference_pt_preferred_over_final_msgpack(ckpt_dir):
    """The reference's torch format for the REQUESTED artifact outranks the
    final_model fallback."""
    torch = pytest.importorskip("torch")
    from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
        torch_state_dict_from_params,
    )

    torch.save(
        torch_state_dict_from_params(ckpt_dir["best"], ckpt_dir["gan"].cfg),
        ckpt_dir["dir"] / "best_model_sharpe.pt")
    save_params(ckpt_dir["dir"] / "final_model.msgpack", ckpt_dir["final"])
    _, loaded = load_checkpoint_dir(ckpt_dir["dir"], "best_model_sharpe")
    # .pt round-trip is float32-exact (transpose + copy, no arithmetic)
    assert _params_equal(loaded, ckpt_dir["best"])


@pytest.mark.slow
def test_shared_sdf_program_matches_dedicated(splits):
    """The shared phase-1/3 program (traced use_cond switch, K-epoch
    segments) runs the same math as the dedicated per-phase programs; the
    program shapes differ (lax.cond wrapping changes XLA fusion), so
    equality is to tight tolerance rather than bitwise — measured max
    relative difference ~1e-7 on this workload. Bitwise reproducibility is
    guaranteed WITHIN a route (see test_segmented_run_bit_identical, which
    runs the default shared route on both sides)."""
    import jax

    from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
    from deeplearninginassetpricing_paperreplication_tpu.training.trainer import (
        Trainer,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        GANConfig,
        TrainConfig,
    )

    train_ds, valid_ds, test_ds = splits
    batch = lambda ds: {k: jnp.asarray(v) for k, v in ds.full_batch().items()}
    tb, vb, teb = batch(train_ds), batch(valid_ds), batch(test_ds)
    cfg = GANConfig(
        macro_feature_dim=train_ds.macro_feature_dim,
        individual_feature_dim=train_ds.individual_feature_dim,
    )
    tcfg = TrainConfig(num_epochs_unc=8, num_epochs_moment=4, num_epochs=16,
                       ignore_epoch=2)
    gan = GAN(cfg)
    params = gan.init(jax.random.key(0))

    outs = []
    for share in (True, False):
        tr = Trainer(gan, tcfg, has_test=True, share_sdf_program=share)
        if share:
            assert tr._switched_seg_len() == 8  # 16 % 8 == 0
        final, hist = tr.train(params, tb, vb, teb, verbose=False)
        outs.append((jax.device_get(final), hist))

    (p_sw, h_sw), (p_ded, h_ded) = outs
    for (path, a), b in zip(
        jax.tree.leaves_with_path(p_sw), jax.tree.leaves(p_ded)
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6,
                                   err_msg=str(path))
    assert set(h_sw) == set(h_ded)
    for k in h_sw:
        a, b = np.asarray(h_sw[k]), np.asarray(h_ded[k])
        if a.dtype.kind in "US":  # the per-epoch phase labels
            np.testing.assert_array_equal(a, b, err_msg=k)
        else:
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5, err_msg=k)


# --------------------------------------------------------------------------
# PR 17: segment-boundary carry donation (double-buffered trainer carry)
# --------------------------------------------------------------------------


def _trees_equal(a, b, msg=""):
    for (path, x), y in zip(jax.tree_util.tree_leaves_with_path(a),
                            jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"{msg} {path}")


def test_carry_donate_argnums_resolves_off_on_cpu():
    """The donation site follows the repo-wide rule: resolved OFF on the
    CPU backend, (opt, best) = argnums (1, 2) anywhere else."""
    assert jax.default_backend() == "cpu"
    assert carry_donate_argnums() == ()


@pytest.mark.slow
@pytest.mark.parametrize("guard", [True, False])
def test_forced_carry_donation_segmented_bit_identical(small_cfg, splits,
                                                       tmp_path, guard):
    """Forcing ``Trainer.carry_donate = (1, 2)`` on CPU runs the full
    donation bookkeeping (one-time best↔params alias-breaking copy, guard
    rollback copies, donated segment dispatches) and the segmented run
    stays bit-identical to the undonated one — with the divergence guard
    on AND explicitly off. Also asserts the metrics-plane counter records
    the forced resolution."""
    from deeplearninginassetpricing_paperreplication_tpu.observability.events import (  # noqa: E501
        EventLog,
    )

    train_ds, valid_ds, test_ds = splits
    tb, vb, teb = (_batch_from(train_ds), _batch_from(valid_ds),
                   _batch_from(test_ds))
    tcfg = TrainConfig(num_epochs_unc=5, num_epochs_moment=2, num_epochs=7,
                       ignore_epoch=1, seed=11)
    gan = GAN(small_cfg)
    params = gan.init(jax.random.key(0))

    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    ref = Trainer(gan, tcfg)
    assert ref.carry_donate == ()  # CPU default: donation resolved off
    final_ref, hist_ref = ref.train(
        params, tb, vb, teb, verbose=False,
        save_dir=str(ref_dir), checkpoint_every=3)

    ev_dir = tmp_path / f"ev_guard{guard}"
    ev = EventLog(ev_dir)
    don_dir = tmp_path / f"don_guard{guard}"
    don_dir.mkdir()
    tr = Trainer(gan, tcfg, divergence_guard=guard, events=ev)
    tr.carry_donate = (1, 2)  # force the off-CPU resolution
    final_d, hist_d = tr.train(
        params, tb, vb, teb, verbose=False,
        save_dir=str(don_dir), checkpoint_every=3)
    ev.close()

    _trees_equal(final_d, final_ref, msg=f"guard={guard}")
    for k in ("train_loss", "valid_sharpe", "test_sharpe", "grad_norm"):
        np.testing.assert_array_equal(
            np.asarray(hist_d[k]), np.asarray(hist_ref[k]), err_msg=k)

    rows = [json.loads(ln) for ln in
            (ev_dir / "events.jsonl").read_text().splitlines()]
    don = [r for r in rows if r.get("name") == "trainer/carry_donation"]
    assert len(don) == 3  # one resolution record per phase
    assert all(r["active"] is True and r["argnums"] == [1, 2] for r in don)


@pytest.mark.slow
def test_forced_carry_donation_stop_resume_bit_identical(small_cfg, splits,
                                                         tmp_path):
    """A donated run stopped mid-phase resumes (also donated) onto exactly
    the uninterrupted UNDONATED run's final params and history — donation
    must not leak into the persisted resume state or the rng streams."""
    train_ds, valid_ds, test_ds = splits
    tb, vb, teb = (_batch_from(train_ds), _batch_from(valid_ds),
                   _batch_from(test_ds))
    tcfg = TrainConfig(num_epochs_unc=5, num_epochs_moment=2, num_epochs=7,
                       ignore_epoch=1, seed=11)
    gan = GAN(small_cfg)
    params = gan.init(jax.random.key(0))

    ref = Trainer(gan, tcfg)
    final_ref, hist_ref = ref.train(params, tb, vb, teb, verbose=False)

    run_dir = tmp_path / "donated"
    run_dir.mkdir()
    tr1 = Trainer(gan, tcfg)
    tr1.carry_donate = (1, 2)
    tr1.train(params, tb, vb, teb, verbose=False, save_dir=str(run_dir),
              checkpoint_every=2, stop_after_epochs=8)
    assert tr1.stopped_midphase
    meta = json.loads((run_dir / "resume_meta.json").read_text())
    assert meta["in_phase"] > 0  # genuinely stopped inside a phase

    tr2 = Trainer(gan, tcfg)
    tr2.carry_donate = (1, 2)
    final_res, hist_res = tr2.train(
        params, tb, vb, teb, verbose=False, save_dir=str(run_dir),
        resume=True, checkpoint_every=2)
    _trees_equal(final_res, final_ref, msg="stop/resume")
    for k in ("train_loss", "valid_sharpe", "test_sharpe"):
        np.testing.assert_array_equal(
            np.asarray(hist_res[k]), np.asarray(hist_ref[k]), err_msg=k)
    assert not (run_dir / "resume_state.msgpack").exists()


@pytest.mark.slow
def test_forced_carry_donation_switched_route(small_cfg, splits):
    """Donation on the shared phase-1/3 switched program: the nested
    schedule (8 = 2×4) dispatches the one K-epoch program repeatedly, so
    every interior boundary takes the donated path; outputs are bitwise
    equal to the undonated switched run (same route → bitwise)."""
    train_ds, valid_ds, test_ds = splits
    tb, vb, teb = (_batch_from(train_ds), _batch_from(valid_ds),
                   _batch_from(test_ds))
    tcfg = TrainConfig(num_epochs_unc=4, num_epochs_moment=2, num_epochs=8,
                       ignore_epoch=1, seed=11)
    gan = GAN(small_cfg)
    params = gan.init(jax.random.key(0))

    outs = []
    for donate in (False, True):
        tr = Trainer(gan, tcfg, share_sdf_program=True)
        assert tr._switched_seg_len() == 4
        if donate:
            tr.carry_donate = (1, 2)
        final, hist = tr.train(params, tb, vb, teb, verbose=False)
        outs.append((jax.device_get(final), hist))
    (p_ref, h_ref), (p_don, h_don) = outs
    _trees_equal(p_don, p_ref, msg="switched donated")
    for k in ("train_loss", "valid_sharpe", "test_sharpe"):
        np.testing.assert_array_equal(
            np.asarray(h_don[k]), np.asarray(h_ref[k]), err_msg=k)
