"""Replicated serving: R supervised engine processes on one shared port.

Each replica is a full ``serving.server --server async`` process — its own
engine, AOT programs, continuous batcher, and per-process result-cache
shard — bound to the SAME (host, port) via ``SO_REUSEPORT``: the kernel
spreads incoming connections across live listeners, so R replicas give R×
the GIL-bound parse/dispatch capacity with no userspace load balancer. Each
replica runs under its own :class:`~..reliability.supervisor.Supervisor`
(one watch thread per replica in this parent): a crash or hang is detected
by heartbeat staleness, the process group is killed, and the replica is
restarted with backoff — during which the fleet keeps serving at R-1
capacity (clients see dropped connections, retry onto survivors, and zero
requests go unserved; asserted by the tier-1 fault matrix).

Artifact layout under the fleet run dir::

    run_dir/
      replica0/  heartbeat.json, events.jsonl, manifest.json, supervised.log
      replica1/  ...
      events.supervisor.replica{i}.jsonl   (supervise/* spans + counters)

The report CLI aggregates across all of these (per-replica request counts,
occupancy, restarts) from the one fleet run dir.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from ..observability.events import EventLog
from ..observability.heartbeat import read_state
from ..reliability.faults import ENV_EVENTS, ENV_PLAN, ENV_STATE
from ..reliability.supervisor import RestartPolicy, Supervisor

_ROOT_PKG = __name__.rsplit(".", 2)[0]

# serving replicas restart much faster than training jobs: there is no
# resume state to protect, and every second down is lost capacity
REPLICA_POLICY = RestartPolicy(
    heartbeat_timeout_s=120.0,
    poll_s=0.5,
    max_restarts=5,
    min_uptime_s=10.0,
    backoff_base_s=0.5,
    backoff_max_s=10.0,
)


def server_child_argv(args, replica_id: int, replica_run_dir,
                      port: int) -> List[str]:
    """The ``serving.server`` command line for one replica, rebuilt from
    the parsed parent args (explicit field-by-field: the parent's
    ``--replicas`` and ``--run_dir`` must not leak through)."""
    argv = [sys.executable, "-m", f"{_ROOT_PKG}.serving.server",
            "--checkpoint_dirs", *args.checkpoint_dirs,
            "--server", "async",
            "--host", args.host, "--port", str(port), "--reuse_port",
            "--replica_id", str(replica_id),
            "--run_dir", str(replica_run_dir),
            "--max_queue", str(args.max_queue),
            "--cache_size", str(args.cache_size)]
    if args.data_dir:
        argv += ["--data_dir", args.data_dir,
                 "--macro_split", args.macro_split]
    if args.macro_npy:
        argv += ["--macro_npy", args.macro_npy]
    if args.stock_buckets:
        argv += ["--stock_buckets", args.stock_buckets]
    if args.batch_buckets:
        argv += ["--batch_buckets", args.batch_buckets]
    if args.max_batch is not None:
        argv += ["--max_batch", str(args.max_batch)]
    if args.no_warmup:
        argv += ["--no_warmup"]
    return argv


class ReplicaFleet:
    """R supervised replica processes + their watch threads."""

    def __init__(
        self,
        child_argvs: Sequence[Sequence[str]],
        run_dir,
        policy: Optional[RestartPolicy] = None,
        env: Optional[Dict[str, str]] = None,
    ):
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.policy = policy if policy is not None else REPLICA_POLICY
        # fault-plan plumbing (same default as the supervise CLI): a plan
        # without persistent state would re-kill a restarted replica at the
        # same site forever; one fleet-shared state file makes a kill fire
        # exactly once ACROSS the fleet
        self.env = dict(os.environ if env is None else env)
        if self.env.get(ENV_PLAN):
            self.env.setdefault(
                ENV_STATE, str(self.run_dir / "fault_state.json"))
            self.env.setdefault(
                ENV_EVENTS, str(self.run_dir / "events.faults.jsonl"))
        self.replica_dirs: List[Path] = []
        self.supervisors: List[Supervisor] = []
        self._events: List[EventLog] = []
        self._threads: List[threading.Thread] = []
        self.summaries: List[Optional[Dict[str, Any]]] = []
        for i, argv in enumerate(child_argvs):
            rdir = self.run_dir / f"replica{i}"
            rdir.mkdir(parents=True, exist_ok=True)
            events = EventLog(
                self.run_dir, process_index=0,
                filename=f"events.supervisor.replica{i}.jsonl")
            sup = Supervisor(
                list(argv),
                heartbeat_path=rdir / "heartbeat.json",
                policy=self.policy,
                events=events,
                log_path=rdir / "supervised.log",
                env=self.env,
            )
            self.replica_dirs.append(rdir)
            self.supervisors.append(sup)
            self._events.append(events)
            self.summaries.append(None)

    @property
    def replicas(self) -> int:
        return len(self.supervisors)

    def start(self) -> None:
        for i, sup in enumerate(self.supervisors):
            def run(i=i, sup=sup):
                self.summaries[i] = sup.run()

            t = threading.Thread(target=run, daemon=True,
                                 name=f"supervise-replica{i}")
            t.start()
            self._threads.append(t)

    def wait_ready(self, timeout: float = 300.0,
                   section: str = "serve/accepting") -> None:
        """Block until every replica's heartbeat reaches `section` (written
        once its socket accepts). Raises on timeout or a crash-looped
        replica, with the dead replica's log tail in the message."""
        deadline = time.monotonic() + timeout
        pending = set(range(self.replicas))
        while pending:
            for i in sorted(pending):
                hb = read_state(
                    self.replica_dirs[i] / "heartbeat.json"
                ).get("heartbeat") or {}
                if hb.get("section") == section:
                    pending.discard(i)
                    continue
                summary = self.summaries[i]
                if summary is not None:
                    raise RuntimeError(
                        f"replica{i} ended during startup "
                        f"({summary.get('outcome')}): "
                        + self._log_tail(i))
            if pending and time.monotonic() > deadline:
                raise TimeoutError(
                    f"replicas {sorted(pending)} not ready after "
                    f"{timeout:.0f}s: " + self._log_tail(min(pending)))
            if pending:
                time.sleep(0.1)

    def _log_tail(self, i: int, n: int = 12) -> str:
        try:
            lines = (self.replica_dirs[i] / "supervised.log").read_text(
                errors="replace").splitlines()
            return "\n".join(lines[-n:])
        except OSError:
            return "(no log)"

    def stop(self, timeout: float = 30.0) -> List[Optional[Dict[str, Any]]]:
        for sup in self.supervisors:
            sup.request_stop()
        for t in self._threads:
            t.join(timeout=timeout)
        for ev in self._events:
            ev.close()
        return self.summaries


def main_from_server_args(args) -> int:
    """The ``serving.server --replicas R`` parent: spawn, supervise, park.

    Never initializes a JAX backend — replicas do all the serving; the
    parent only watches heartbeats and restarts the dead.
    """
    from .aserver import pick_free_port

    if not args.run_dir:
        print("--replicas requires --run_dir (per-replica heartbeats and "
              "supervision live there)", file=sys.stderr)
        return 2
    if args.server != "async":
        print("--replicas requires --server async (the threaded path is "
              "deprecated and single-process only)", file=sys.stderr)
        return 2
    run_dir = Path(args.run_dir)
    port = args.port if args.port else pick_free_port(args.host)
    argvs = [
        server_child_argv(args, i, run_dir / f"replica{i}", port)
        for i in range(args.replicas)
    ]
    fleet = ReplicaFleet(argvs, run_dir)
    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — signal-handler shape
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    try:
        fleet.start()
        fleet.wait_ready()
        print(f"fleet of {fleet.replicas} replicas serving on "
              f"http://{args.host}:{port} (SO_REUSEPORT)", flush=True)
        while not stop.is_set():
            stop.wait(1.0)
    finally:
        fleet.stop()
    return 0
