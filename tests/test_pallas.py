"""Fused Pallas SDF-FFN kernel: equivalence with the XLA route.

Runs the kernel in the Pallas interpreter on the CPU test mesh, so the same
tests validate the kernel logic everywhere; on-TPU behavior differs only in
matmul precision class (bf16 operands, f32 accumulation — the same class as
JAX's default TPU matmul).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
from deeplearninginassetpricing_paperreplication_tpu.ops.losses import (
    conditional_loss,
    unconditional_loss,
)
from deeplearninginassetpricing_paperreplication_tpu.ops.pallas_ffn import (
    choose_block_stocks,
    fused_sdf_ffn,
)
from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
    ExecutionConfig,
    GANConfig,
)

INTERP = ExecutionConfig(
    pallas_ffn="on", interpret=True, compute_dtype="float32", block_stocks=16,
    bf16_panel=False,  # bit-level f32 comparisons against the XLA route
)
OFF = ExecutionConfig(pallas_ffn="off")

# -- jax-version gates -------------------------------------------------------
# TRACKING: long-standing failures on the image's jax (0.4.37 at the time of
# writing), which predates these APIs. Each gate probes the capability (not a
# version string, so a backport or rename resolves it automatically); remove
# the marker when the toolchain moves to a jax that ships the API.
try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_COMPILER_PARAMS = hasattr(pltpu, "CompilerParams")
except ImportError:  # pallas not importable at all: same skip
    _HAS_COMPILER_PARAMS = False

# pallas_ffn.py builds pltpu.CompilerParams (the post-0.4 spelling of
# TPUCompilerParams) for every kernel call
needs_pallas_compiler_params = pytest.mark.skipif(
    not _HAS_COMPILER_PARAMS,
    reason="jax.experimental.pallas.tpu.CompilerParams not in this jax "
           "(0.4.x ships TPUCompilerParams); the kernel route needs it",
)
# jax.tree.leaves_with_path is the jax>=0.5 tree-path API
needs_tree_paths = pytest.mark.skipif(
    not hasattr(jax.tree, "leaves_with_path"),
    reason="jax.tree.leaves_with_path needs jax >= 0.5",
)
# jax.shard_map (top-level) replaced jax.experimental.shard_map in jax 0.6
needs_jax_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="top-level jax.shard_map needs jax >= 0.6; the sharded kernel "
           "route calls it directly",
)


def _batch(T=6, N=37, F=5, M=3, seed=0):
    rng = np.random.default_rng(seed)
    mask = (rng.random((T, N)) > 0.3).astype(np.float32)
    return {
        "individual": jnp.asarray(
            rng.standard_normal((T, N, F)).astype(np.float32) * mask[:, :, None]
        ),
        "returns": jnp.asarray(
            rng.standard_normal((T, N)).astype(np.float32) * mask
        ),
        "mask": jnp.asarray(mask),
        "macro": jnp.asarray(rng.standard_normal((T, M)).astype(np.float32)),
    }


@pytest.fixture(scope="module")
def cfg():
    return GANConfig(
        macro_feature_dim=3, individual_feature_dim=5,
        hidden_dim=(8, 7), num_units_rnn=(4,), dropout=0.05,
    )


@needs_pallas_compiler_params
def test_kernel_matches_xla_route_forward(cfg):
    """Same params, dropout off: pallas route == XLA route exactly (fp32)."""
    batch = _batch()
    gan_x = GAN(cfg, OFF)
    gan_p = GAN(cfg, INTERP)
    params = gan_x.init(jax.random.key(0))
    w_x = gan_x.weights(params, batch)
    w_p = gan_p.weights(params, gan_p.prepare_batch(batch))
    np.testing.assert_allclose(np.asarray(w_x), np.asarray(w_p), atol=2e-6)


@needs_tree_paths
def test_param_trees_identical(cfg):
    """Both routes create the identical parameter tree (paths + shapes +
    values for the same init key) — one checkpoint format."""
    gan_x, gan_p = GAN(cfg, OFF), GAN(cfg, INTERP)
    px = jax.tree.leaves_with_path(gan_x.init(jax.random.key(3)))
    pp = jax.tree.leaves_with_path(gan_p.init(jax.random.key(3)))
    assert [k for k, _ in px] == [k for k, _ in pp]
    for (kx, vx), (_, vp) in zip(px, pp):
        np.testing.assert_array_equal(np.asarray(vx), np.asarray(vp), err_msg=str(kx))


@pytest.mark.slow
def test_kernel_gradients_match_xla_route(cfg):
    batch = _batch()
    gan_x, gan_p = GAN(cfg, OFF), GAN(cfg, INTERP)
    batch_p = gan_p.prepare_batch(batch)
    params = gan_x.init(jax.random.key(1))

    def loss(gan, batch):
        return lambda p: gan.forward(p, batch, phase="conditional")["loss"]

    gx = jax.grad(loss(gan_x, batch))(params)
    gp = jax.grad(loss(gan_p, batch_p))(params)
    flat_x = jax.tree.leaves_with_path(gx)
    flat_p = jax.tree.leaves(gp)
    for (path, a), b in zip(flat_x, flat_p):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6, err_msg=str(path)
        )


@needs_pallas_compiler_params
def test_kernel_no_macro_route(cfg):
    cfg2 = GANConfig(
        macro_feature_dim=0, individual_feature_dim=5, hidden_dim=(8,),
        use_rnn=False, dropout=0.0,
    )
    batch = _batch()
    batch = {k: v for k, v in batch.items() if k != "macro"}
    gan_x, gan_p = GAN(cfg2, OFF), GAN(cfg2, INTERP)
    params = gan_x.init(jax.random.key(2))
    w_x = gan_x.weights(params, batch)
    w_p = gan_p.weights(params, gan_p.prepare_batch(batch))
    np.testing.assert_allclose(np.asarray(w_x), np.asarray(w_p), atol=2e-6)


@needs_pallas_compiler_params
def test_kernel_ragged_edge_blocks():
    """N not a multiple of the stock tile: edge lanes must not pollute
    outputs or gradients (explicit lane masking in the bwd kernels)."""
    rng = np.random.default_rng(5)
    T, F, N, H = 3, 4, 21, 6  # block 16 -> ragged second block of 5
    x_t = jnp.asarray(rng.standard_normal((T, F, N)).astype(np.float32))
    zp = jnp.asarray(rng.standard_normal((T, H)).astype(np.float32))
    k1 = jnp.asarray(rng.standard_normal((F, H)).astype(np.float32))
    ko = jnp.asarray(rng.standard_normal((H, 1)).astype(np.float32))
    bo = jnp.asarray(rng.standard_normal((1,)).astype(np.float32))
    g = jnp.asarray(rng.standard_normal((T, N)).astype(np.float32))

    def pal(k1, zp):
        return fused_sdf_ffn(
            x_t, zp, [(k1, None)], ko, bo, block_stocks=16, interpret=True,
            compute_dtype="float32",
        )

    def ref(k1, zp):
        h = jnp.maximum(jnp.einsum("tfn,fh->tnh", x_t, k1) + zp[:, None, :], 0)
        return (h @ ko)[..., 0] + bo[0]

    np.testing.assert_allclose(
        np.asarray(pal(k1, zp)), np.asarray(ref(k1, zp)), atol=1e-6
    )
    gp = jax.grad(lambda k, z: jnp.sum(pal(k, z) * g), argnums=(0, 1))(k1, zp)
    gr = jax.grad(lambda k, z: jnp.sum(ref(k, z) * g), argnums=(0, 1))(k1, zp)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_choose_block_stocks_lane_aligned():
    for n in (500, 10000, 128, 131072):
        bn = choose_block_stocks(n, 46, [64, 64])
        assert bn % 128 == 0
        assert bn >= 128


def test_padded_losses_bit_equal_with_n_assets():
    """pad_stocks + n_assets keeps both losses bit-equal to unpadded."""
    from deeplearninginassetpricing_paperreplication_tpu.data.panel import (
        PanelDataset,
    )

    rng = np.random.default_rng(7)
    T, N, F, K = 5, 11, 3, 4
    mask = (rng.random((T, N)) > 0.4)
    ds = PanelDataset(
        returns=(rng.standard_normal((T, N)) * mask).astype(np.float32),
        individual=(rng.standard_normal((T, N, F)) * mask[:, :, None]).astype(np.float32),
        mask=mask,
        macro=None,
        dates=np.arange(T),
    )
    padded = ds.pad_stocks(8)  # 11 -> 16
    assert padded.N == 16 and padded.n_assets == 11
    b0, b1 = ds.full_batch(), padded.full_batch()
    assert "n_assets" in b1 and float(b1["n_assets"]) == 11.0
    w0 = jnp.asarray(rng.standard_normal((T, N)).astype(np.float32))
    w1 = jnp.pad(w0, ((0, 0), (0, 5)))
    h0 = jnp.asarray(rng.standard_normal((K, T, N)).astype(np.float32))
    h1 = jnp.pad(h0, ((0, 0), (0, 0), (0, 5)))
    l0, _ = unconditional_loss(w0, jnp.asarray(b0["returns"]), jnp.asarray(b0["mask"]))
    l1, _ = unconditional_loss(
        w1, jnp.asarray(b1["returns"]), jnp.asarray(b1["mask"]),
        n_assets=jnp.asarray(b1["n_assets"]),
    )
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    c0, _ = conditional_loss(w0, jnp.asarray(b0["returns"]), jnp.asarray(b0["mask"]), h0)
    c1, _ = conditional_loss(
        w1, jnp.asarray(b1["returns"]), jnp.asarray(b1["mask"]), h1,
        n_assets=jnp.asarray(b1["n_assets"]),
    )
    np.testing.assert_allclose(float(c0), float(c1), rtol=1e-6)


@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="pltpu PRNG has no interpret-mode implementation; the dropout "
    "path is exercised on TPU (bench/parity runs use dropout=0.05)",
)
def test_dropout_kernel_statistics():
    """Dropout path: correct keep-rate scaling in expectation (TPU only)."""
    rng = np.random.default_rng(9)
    T, F, N, H = 4, 3, 64, 16
    x_t = jnp.asarray(np.abs(rng.standard_normal((T, F, N))).astype(np.float32))
    zp = jnp.asarray(np.full((T, H), 1.0, np.float32))
    k1 = jnp.asarray(np.abs(rng.standard_normal((F, H))).astype(np.float32))
    ko = jnp.asarray(np.ones((H, 1), np.float32))
    bo = jnp.asarray(np.zeros((1,), np.float32))
    det = fused_sdf_ffn(x_t, zp, [(k1, None)], ko, bo, block_stocks=64,
                        compute_dtype="float32")
    outs = []
    for s in range(20):
        outs.append(fused_sdf_ffn(
            x_t, zp, [(k1, None)], ko, bo, dropout_rate=0.3,
            seed=jnp.asarray(s, jnp.int32), block_stocks=64,
            compute_dtype="float32",
        ))
    mean = np.mean([np.asarray(o) for o in outs], axis=0)
    # inverted dropout: E[drop(h)] = h (all inputs positive => relu inert)
    ratio = mean.sum() / float(det.sum())
    assert 0.9 < ratio < 1.1, ratio


@pytest.mark.slow
def test_sharded_kernel_matches_unsharded():
    """shard_map-wrapped kernel on the 8-device mesh == single-device kernel
    == XLA route, forward AND gradients (replicated-param psum transpose)."""
    from deeplearninginassetpricing_paperreplication_tpu.parallel.mesh import (
        create_mesh,
        shard_batch,
    )

    mesh = create_mesh()
    cfg = GANConfig(
        macro_feature_dim=3, individual_feature_dim=5,
        hidden_dim=(8, 7), num_units_rnn=(4,), dropout=0.05,
    )
    batch = _batch(N=40)  # divisible by 8
    gan_x = GAN(cfg, OFF)
    gan_s = GAN(
        cfg,
        ExecutionConfig(
            pallas_ffn="on", interpret=True, compute_dtype="float32",
            block_stocks=16, shard_mesh=mesh, bf16_panel=False,
        ),
    )
    params = gan_x.init(jax.random.key(0))
    sbatch = shard_batch({k: jnp.asarray(v) for k, v in batch.items()}, mesh)
    sbatch = gan_s.prepare_batch(sbatch)

    w_x = gan_x.weights(params, batch)
    w_s = jax.jit(lambda p, b: gan_s.weights(p, b))(params, sbatch)
    np.testing.assert_allclose(np.asarray(w_x), np.asarray(w_s), atol=2e-6)

    def loss(gan, batch):
        return lambda p: gan.forward(p, batch, phase="conditional")["loss"]

    gx = jax.grad(loss(gan_x, batch))(params)
    gs = jax.jit(jax.grad(loss(gan_s, sbatch)))(params)
    for (path, a), b in zip(
        jax.tree.leaves_with_path(gx), jax.tree.leaves(gs)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-6, err_msg=str(path)
        )


@pytest.mark.slow
def test_bf16_panel_route_close_to_f32():
    """bf16_panel (experimental): kernel + bf16 moment einsum path stay
    within bf16 rounding of the f32 route; param tree unchanged."""
    batch = _batch()
    cfg = GANConfig(
        macro_feature_dim=3, individual_feature_dim=5,
        hidden_dim=(8, 7), num_units_rnn=(4,), dropout=0.0,
    )
    gan_f = GAN(cfg, OFF)
    gan_b = GAN(
        cfg,
        ExecutionConfig(pallas_ffn="on", interpret=True,
                        compute_dtype="float32", block_stocks=16,
                        bf16_panel=True),
    )
    params = gan_f.init(jax.random.key(0))
    bb = gan_b.prepare_batch(batch)
    assert bb["individual_t"].dtype == jnp.bfloat16
    out_f = gan_f.forward(params, batch, phase="conditional")
    out_b = gan_b.forward(params, bb, phase="conditional")
    # weights scale ~1e-1; bf16 has ~3 decimal digits
    np.testing.assert_allclose(
        np.asarray(out_f["weights"]), np.asarray(out_b["weights"]), atol=5e-3
    )
    # the fused conditional route never materializes h; compare it via the
    # explicit moments() entry point instead
    assert out_b["moments"] is None
    np.testing.assert_allclose(
        np.asarray(gan_f.moments(params, batch)),
        np.asarray(gan_b.moments(params, bb)),
        atol=5e-3,
    )
    assert abs(float(out_f["loss"] - out_b["loss"])) < 5e-3
    # backward through the bf16 route (regression: the dx kernel must write
    # its cotangent in the panel's storage dtype)
    gf = jax.grad(lambda p: gan_f.forward(p, batch, phase="conditional")["loss"])(params)
    gb = jax.grad(lambda p: gan_b.forward(p, bb, phase="conditional")["loss"])(params)
    for (path, a), b in zip(jax.tree.leaves_with_path(gf), jax.tree.leaves(gb)):
        scale = float(np.abs(np.asarray(a)).max())
        err = float(np.abs(np.asarray(a - b)).max())
        # rel for real gradients, abs floor for ~zero ones (e.g. the output
        # bias, which the zero-mean normalization annihilates)
        assert err < max(0.05 * scale, 1e-6), (path, err, scale)


@needs_jax_shard_map
def test_bf16_panel_sharded_close_to_f32():
    """The DEFAULT TPU route under --shard_stocks is now shard_mesh +
    bf16_panel; its weights must stay within bf16 rounding of the unsharded
    f32 XLA route."""
    from deeplearninginassetpricing_paperreplication_tpu.parallel.mesh import (
        create_mesh,
        shard_batch,
    )

    mesh = create_mesh()
    cfg = GANConfig(
        macro_feature_dim=3, individual_feature_dim=5,
        hidden_dim=(8, 7), num_units_rnn=(4,), dropout=0.0,
    )
    batch = _batch(N=40)
    gan_x = GAN(cfg, OFF)
    gan_b = GAN(
        cfg,
        ExecutionConfig(
            pallas_ffn="on", interpret=True, compute_dtype="float32",
            block_stocks=16, shard_mesh=mesh, bf16_panel=True,
        ),
    )
    params = gan_x.init(jax.random.key(0))
    sbatch = shard_batch({k: jnp.asarray(v) for k, v in batch.items()}, mesh)
    sbatch = gan_b.prepare_batch(sbatch)
    assert sbatch["individual_t"].dtype == jnp.bfloat16
    w_x = gan_x.weights(params, batch)
    w_b = jax.jit(lambda p, b: gan_b.weights(p, b))(params, sbatch)
    np.testing.assert_allclose(np.asarray(w_x), np.asarray(w_b), atol=5e-3)


@pytest.mark.slow
def test_vmapped_kernel_matches_serial_members():
    """vmap over a member axis ≡ a per-member Python loop, forward AND grads
    (fp32, interpret, dropout off).

    This is the route `parallel.ensemble`/`parallel.sweep` train on: JAX's
    pallas_call batching rule prepends the member axis to the kernel grid
    (unbatched operands — the shared panel — are NOT copied). Exercises both
    fused kernels (SDF-FFN and conditional-EM) through the full conditional
    forward.
    """
    cfg0 = GANConfig(
        macro_feature_dim=3, individual_feature_dim=5,
        hidden_dim=(8, 7), num_units_rnn=(4,), dropout=0.0,
    )
    batch = _batch(N=37)
    gan = GAN(cfg0, INTERP)
    batch_p = gan.prepare_batch(batch)
    vparams = jax.vmap(lambda k: gan.init(k))(
        jnp.stack([jax.random.key(s) for s in (0, 1, 2)])
    )

    def loss(p):
        return gan.forward(p, batch_p, phase="conditional")["loss"]

    v_loss = jax.vmap(loss)(vparams)
    v_grads = jax.vmap(jax.grad(loss))(vparams)
    for i in range(3):
        p_i = jax.tree.map(lambda x, i=i: x[i], vparams)
        np.testing.assert_allclose(
            np.asarray(v_loss[i]), np.asarray(loss(p_i)), atol=1e-6
        )
        g_i = jax.grad(loss)(p_i)
        for (path, a), b in zip(
            jax.tree.leaves_with_path(g_i),
            jax.tree.leaves(jax.tree.map(lambda x, i=i: x[i], v_grads)),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, err_msg=str(path)
            )


@pytest.mark.parametrize("T", [12, 7])
@needs_pallas_compiler_params
def test_multi_period_cells_match_xla(T):
    """Multi-period blocking with MULTIPLE period cells per pass (T=12 →
    tb=6 → 2 cells: the cross-cell accumulator branches actually run) and
    the tb=1 fallback (T=7, prime): forward and grads match the XLA route.
    The module-level suite shapes all have tb == T (one period cell), which
    leaves the tbi>0 accumulation paths unexercised — this test is the
    coverage for them."""
    from deeplearninginassetpricing_paperreplication_tpu.ops.pallas_ffn import (
        choose_period_block,
    )

    tb = choose_period_block(T, 5, 16, 4)
    assert (T, tb) in ((12, 6), (7, 1))

    cfg0 = GANConfig(
        macro_feature_dim=3, individual_feature_dim=5,
        hidden_dim=(8, 7), num_units_rnn=(4,), dropout=0.0,
    )
    batch = _batch(T=T, N=37)
    gan_x, gan_p = GAN(cfg0, OFF), GAN(cfg0, INTERP)
    params = gan_x.init(jax.random.key(0))
    bp = gan_p.prepare_batch(batch)

    def loss(g, b):
        return lambda p: g.forward(p, b, phase="conditional")["loss"]

    np.testing.assert_allclose(
        float(loss(gan_p, bp)(params)), float(loss(gan_x, batch)(params)),
        atol=1e-6,
    )
    g_p = jax.grad(loss(gan_p, bp))(params)
    g_x = jax.grad(loss(gan_x, batch))(params)
    for (path, a), b in zip(jax.tree.leaves_with_path(g_p),
                            jax.tree.leaves(g_x)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5,
                                   err_msg=str(path))


@needs_pallas_compiler_params
def test_member_fused_kernels_fire_under_vmap(monkeypatch):
    """A vmapped conditional train step must dispatch the MEMBER-FUSED
    kernels (one panel read for all members), not pallas_call's default
    grid-prepending batching rule — forward AND backward, both kernels.

    The batching rules call the member entry points by module-global name,
    so instrumenting those globals observes exactly the dispatch decision.
    """
    from deeplearninginassetpricing_paperreplication_tpu.ops import (
        pallas_ffn as pf,
        pallas_moment as pm,
    )

    calls = []

    def spy(mod, name):
        orig = getattr(mod, name)
        tag = f"{mod.__name__.rsplit('.', 1)[-1]}.{name}"

        def wrapper(*a, **k):
            calls.append(tag)
            return orig(*a, **k)

        monkeypatch.setattr(mod, name, wrapper)

    spy(pf, "_fwd_call_members")
    spy(pf, "_bwd_call_members")
    spy(pm, "_fwd_call_members")
    spy(pm, "_bwd_call_members")

    cfg0 = GANConfig(
        macro_feature_dim=3, individual_feature_dim=5,
        hidden_dim=(8, 7), num_units_rnn=(4,), dropout=0.0,
    )
    batch = _batch(N=37)
    gan = GAN(cfg0, INTERP)
    batch_p = gan.prepare_batch(batch)
    vparams = jax.vmap(lambda k: gan.init(k))(
        jnp.stack([jax.random.key(s) for s in (0, 1, 2)])
    )

    def loss(p):
        return gan.forward(p, batch_p, phase="conditional")["loss"]

    jax.vmap(jax.grad(loss))(vparams)  # trace fires the batching rules
    # per-module: a silent fallback in EITHER kernel family must fail
    assert calls.count("pallas_ffn._fwd_call_members") >= 1
    assert calls.count("pallas_ffn._bwd_call_members") >= 1
    assert calls.count("pallas_moment._fwd_call_members") >= 1
    assert calls.count("pallas_moment._bwd_call_members") >= 1


@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="pltpu PRNG has no interpret-mode implementation; the dropout "
    "path of the vmapped kernel only runs on TPU",
)
def test_member_fused_dropout_bit_identical_to_serial():
    """With dropout ON, the member-fused route must be bit-identical to S
    serial single-member runs: the member kernel seeds per (member seed,
    grid cell) with the same formula and block size as the single kernel,
    so the dropout streams coincide exactly (compiled path, real TPU)."""
    cfg0 = GANConfig(
        macro_feature_dim=3, individual_feature_dim=5,
        hidden_dim=(8, 7), num_units_rnn=(4,), dropout=0.3,
    )
    batch = _batch(N=300)
    gan = GAN(cfg0, ExecutionConfig(
        pallas_ffn="on", compute_dtype="float32", bf16_panel=False,
    ))
    batch_p = gan.prepare_batch(batch)
    vparams = jax.vmap(lambda k: gan.init(k))(
        jnp.stack([jax.random.key(s) for s in (0, 1, 2)])
    )
    rngs = jax.random.split(jax.random.key(7), 3)
    fwd = lambda p, r: gan.forward(
        p, batch_p, phase="conditional", rng=r)["weights"]
    w_v = jax.jit(jax.vmap(fwd))(vparams, rngs)
    for i in range(3):
        p_i = jax.tree.map(lambda x, i=i: x[i], vparams)
        w_i = jax.jit(fwd)(p_i, rngs[i])
        np.testing.assert_array_equal(np.asarray(w_v[i]), np.asarray(w_i))


@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="pltpu PRNG has no interpret-mode implementation; the dropout "
    "path of the vmapped kernel only runs on TPU",
)
def test_vmapped_kernel_batched_seed_compiles():
    """Dropout on under vmap: the per-member SMEM seed must batch (the seed
    is rank-2 (1, 1) precisely so its batched block keeps legal last-two
    dims). Statistical check only — kernel dropout draws its own stream.
    Compiled path (no interpret): the pltpu PRNG only exists on real TPUs."""
    cfg0 = GANConfig(
        macro_feature_dim=3, individual_feature_dim=5,
        hidden_dim=(8, 7), num_units_rnn=(4,), dropout=0.3,
    )
    batch = _batch(N=37)
    # block_stocks stays auto: a 16-lane tile is interpret-only (real TPU
    # blocks need a 128-divisible lane dim)
    gan = GAN(cfg0, ExecutionConfig(
        pallas_ffn="on", compute_dtype="float32", bf16_panel=False,
    ))
    batch_p = gan.prepare_batch(batch)
    vparams = jax.vmap(lambda k: gan.init(k))(
        jnp.stack([jax.random.key(s) for s in (0, 1)])
    )
    rngs = jax.random.split(jax.random.key(7), 2)
    w = jax.vmap(
        lambda p, r: gan.forward(p, batch_p, phase="conditional", rng=r)["weights"]
    )(vparams, rngs)
    assert w.shape == (2,) + batch["returns"].shape
    assert np.isfinite(np.asarray(w)).all()
    # distinct member rngs must yield distinct dropout realizations
    assert not np.allclose(np.asarray(w[0]), np.asarray(w[1]))


@needs_pallas_compiler_params
def test_sharded_fused_cond_em_active_and_exact():
    """Under stock sharding the fused conditional-EM kernel must be ACTIVE
    (moments is None in the forward output — no silent XLA fallback) and its
    loss must equal the unsharded kernel route exactly (fp32, interpret)."""
    from deeplearninginassetpricing_paperreplication_tpu.parallel.mesh import (
        create_mesh,
        shard_batch,
    )

    mesh = create_mesh()
    cfg = GANConfig(
        macro_feature_dim=3, individual_feature_dim=5,
        hidden_dim=(8, 7), num_units_rnn=(4,), dropout=0.0,
    )
    batch = _batch(N=40)
    gan_u = GAN(cfg, INTERP)
    gan_s = GAN(
        cfg,
        ExecutionConfig(
            pallas_ffn="on", interpret=True, compute_dtype="float32",
            block_stocks=16, shard_mesh=mesh, bf16_panel=False,
        ),
    )
    params = gan_u.init(jax.random.key(0))
    ubatch = gan_u.prepare_batch(batch)
    sbatch = shard_batch({k: jnp.asarray(v) for k, v in batch.items()}, mesh)
    sbatch = gan_s.prepare_batch(sbatch)

    out_u = gan_u.forward(params, ubatch, phase="conditional")
    out_s = jax.jit(
        lambda p, b: gan_s.forward(p, b, phase="conditional"),
    )(params, sbatch)
    assert out_u["moments"] is None  # fused route taken, unsharded
    assert out_s["moments"] is None  # fused route taken, SHARDED
    np.testing.assert_allclose(
        float(out_u["loss_conditional"]), float(out_s["loss_conditional"]),
        atol=1e-6,
    )


@needs_pallas_compiler_params
def test_eval_step_kernel_route_matches_xla(cfg):
    """make_eval_step on the kernel route (multi-period-blocked fused
    kernels) must match the XLA route's eval metrics."""
    from deeplearninginassetpricing_paperreplication_tpu.training.steps import (
        make_eval_step,
    )

    batch = _batch(N=37)
    gan_x, gan_p = GAN(cfg, OFF), GAN(cfg, INTERP)
    params = gan_x.init(jax.random.key(1))
    ev_x = make_eval_step(gan_x)(params, batch)
    ev_p = make_eval_step(gan_p)(params, gan_p.prepare_batch(batch))
    for k in ev_x:
        np.testing.assert_allclose(
            float(ev_x[k]), float(ev_p[k]), atol=5e-6, err_msg=k
        )
