"""SLO & alerting plane (PR 15): burn-rate engine, blackbox prober,
cross-plane ops console, process gauges, bench history — and the tier-1
detection drill: a replica SIGKILLed (and separately SIGSTOPped =
wedged-but-accepting) under the live prober + SLO engine produces a
firing availability alert, and ``ops status``/``timeline`` tell the story
byte-deterministically."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.observability import (
    statusboard,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.events import (  # noqa: E501
    _DURABLE_KINDS,
    EventLog,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.metrics import (  # noqa: E501
    MetricsSidecar,
    parse_prom_text,
    process_stats,
    render_process_prom,
)
from deeplearninginassetpricing_paperreplication_tpu.observability.slo import (
    FileAlertSink,
    SLOEngine,
    SLOSpecError,
    WebhookAlertSink,
    default_slo,
    drill_spec,
    load_slo,
    validate_slo,
    write_slo,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.fleet import (
    read_fleet_json,
    write_fleet_json,
)
from deeplearninginassetpricing_paperreplication_tpu.serving.probe import (
    Prober,
    build_sources,
    fixture_payload,
)

REPO = Path(__file__).resolve().parents[1]
PKG = "deeplearninginassetpricing_paperreplication_tpu"


# --------------------------------------------------------------------------
# slo.json spec: validation + verified write/load
# --------------------------------------------------------------------------


def test_slo_spec_validation_names_the_field():
    validate_slo(default_slo())
    validate_slo(drill_spec())
    cases = [
        ({"schema": 2, "objectives": []}, "schema"),
        ({"schema": 1, "objectives": []}, "objectives"),
        ({"schema": 1, "objectives": [{"name": "", "kind": "ratio",
                                       "source": "s"}]}, "name"),
        ({"schema": 1, "objectives": [{"name": "a", "kind": "nope",
                                       "source": "s"}]}, "kind"),
        ({"schema": 1, "objectives": [
            {"name": "a", "kind": "ratio", "source": "s", "target": 1.2,
             "windows": [{"long_s": 10, "short_s": 1, "burn_rate": 2}]}]},
         "target"),
        ({"schema": 1, "objectives": [
            {"name": "a", "kind": "ratio", "source": "s", "target": 0.9,
             "windows": [{"long_s": 1, "short_s": 10, "burn_rate": 2}]}]},
         "short_s"),
        ({"schema": 1, "objectives": [
            {"name": "a", "kind": "value", "source": "s", "max": -1,
             "sustain_s": 5}]}, "max"),
        ({"schema": 1, "objectives": [
            {"name": "a", "kind": "ratio", "source": "s", "target": 0.9,
             "windows": [{"long_s": 10, "short_s": 1, "burn_rate": 2,
                          "severity": "sms"}]}]}, "severity"),
    ]
    for doc, needle in cases:
        with pytest.raises(SLOSpecError) as ei:
            validate_slo(doc)
        assert needle in str(ei.value), (doc, ei.value)
    # duplicate names
    dup = {"schema": 1, "objectives": [
        {"name": "a", "kind": "value", "source": "s", "max": 1,
         "sustain_s": 5},
        {"name": "a", "kind": "value", "source": "s", "max": 1,
         "sustain_s": 5}]}
    with pytest.raises(SLOSpecError, match="duplicate"):
        validate_slo(dup)


def test_slo_spec_verified_roundtrip_and_tamper(tmp_path):
    p = write_slo(tmp_path / "slo.json", drill_spec())
    assert load_slo(p)["objectives"][0]["name"] == "availability"
    assert (tmp_path / "slo.json.sha256").exists()
    # tampered bytes fail the sidecar check
    p.write_text(p.read_text() + " ")
    with pytest.raises(SLOSpecError, match="sha256"):
        load_slo(p)
    # a malformed-on-disk spec (no sidecar) fails validation loudly
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"schema": 1, "objectives": [{}]}))
    with pytest.raises(SLOSpecError):
        load_slo(bad)


def test_shipped_slo_json_verifies():
    """The repo-root slo.json contract must load, digest-verify, and
    only reference sources the standard wiring provides."""
    from deeplearninginassetpricing_paperreplication_tpu.observability.slo import (  # noqa: E501
        KNOWN_SOURCES,
    )

    doc = load_slo(REPO / "slo.json")
    for obj in doc["objectives"]:
        assert obj["source"] in KNOWN_SOURCES, obj


# --------------------------------------------------------------------------
# burn-rate engine: window math + state machine + sinks + gauges
# --------------------------------------------------------------------------


def _fake_clock():
    now = [0.0]

    def clock():
        return now[0]

    def advance(dt):
        now[0] += dt

    return clock, advance


def test_burn_rate_multi_window_fire_and_resolve(tmp_path):
    clock, advance = _fake_clock()
    counts = {"bad": 0, "total": 0}
    events = EventLog(tmp_path, filename="events.slo.jsonl",
                      process_index=0)
    sink = FileAlertSink(tmp_path / "alerts.jsonl")
    eng = SLOEngine(drill_spec(long_s=8, short_s=2, burn_rate=6.0),
                    {"probe": lambda: (counts["bad"], counts["total"])},
                    events=events, sinks=(sink,), clock=clock)
    # healthy: never fires, gauges refresh anyway
    for _ in range(40):
        advance(0.25)
        counts["total"] += 4
        assert eng.tick() == []
    assert eng.firing() == []
    # 50% outage: burn = 0.5 / 0.01 = 50 >> 6 on both windows
    fired_at = None
    for i in range(64):
        advance(0.25)
        counts["total"] += 4
        counts["bad"] += 2
        if eng.tick():
            fired_at = i * 0.25
            break
    assert fired_at is not None and fired_at <= 4.0
    assert [f["objective"] for f in eng.firing()] == ["availability"]
    # a second bad tick does NOT re-fire (state machine, not a spammer)
    advance(0.25)
    counts["total"] += 4
    counts["bad"] += 2
    assert eng.tick() == []
    # recovery: resolves once both windows drop under threshold
    resolved = None
    for i in range(120):
        advance(0.25)
        counts["total"] += 4
        t = eng.tick()
        if t:
            resolved = t
            break
    assert resolved and resolved[0]["state"] == "resolved"
    assert resolved[0]["firing_duration_s"] > 0
    assert eng.firing() == []
    events.close()
    # transitions reached the file sink, durably
    lines = [json.loads(x) for x in
             (tmp_path / "alerts.jsonl").read_text().splitlines()]
    assert [x["state"] for x in lines] == ["firing", "resolved"]
    assert sink.delivered == 2 and sink.failed == 0
    # durable alert rows + dlap_alert_* gauges in the metrics twin
    rows = [json.loads(x) for x in
            (tmp_path / "events.slo.jsonl").read_text().splitlines()]
    alert_rows = [r for r in rows if r["kind"] == "alert"]
    assert [r["name"] for r in alert_rows] == ["alert/firing",
                                               "alert/resolved"]
    assert alert_rows[0]["objective"] == "availability"
    assert alert_rows[0]["severity"] == "page"
    prom = events.metrics.render_prom()
    parsed = parse_prom_text(prom)
    assert "dlap_alert_firing" in parsed
    assert "dlap_alert_burn_rate" in parsed
    assert "dlap_alert_budget_remaining" in parsed
    assert "dlap_alert_firing_total" in parsed  # the durable rows count


def test_no_data_means_no_alert_decision():
    """Empty windows (no traffic) must neither fire nor resolve: a fleet
    with zero probes/requests is UNKNOWN, not healthy."""
    clock, advance = _fake_clock()
    eng = SLOEngine(drill_spec(long_s=8, short_s=2),
                    {"probe": lambda: None}, clock=clock)
    for _ in range(100):
        advance(0.25)
        assert eng.tick() == []
    assert eng.firing() == []
    # a source that raises is counted, never propagated
    def boom():
        raise RuntimeError("scrape died")

    eng2 = SLOEngine(drill_spec(), {"probe": boom}, clock=clock)
    eng2.tick()
    assert eng2.source_errors >= 1


def test_engine_rejects_unwired_sources():
    """An objective whose source has no wired callable would silently
    never evaluate — the engine must refuse the spec, naming the source
    (the probe CLI pre-filters with a printed warning instead)."""
    with pytest.raises(SLOSpecError, match="probe"):
        SLOEngine(drill_spec(), {})
    with pytest.raises(SLOSpecError, match="requests"):
        SLOEngine(default_slo(), {"probe": lambda: (0, 0)})


def test_series_ring_sized_for_the_longest_window():
    """The sample ring must HOLD the longest window at the engine's poll
    cadence: the shipped 6-hour availability window at 1 s polls needs
    ~43k samples — a fixed 4096-deep ring would silently shrink the
    window to ~68 minutes."""
    eng = SLOEngine(
        default_slo(),
        {"probe": lambda: (0, 0), "requests": lambda: (0, 0),
         "drift": lambda: (0, 0), "latency_p99_ms": lambda: None,
         "freshness_months": lambda: None},
        poll_s=1.0)
    ring = eng._series["availability"]._ring
    assert ring.maxlen >= 2 * 21600  # two 6-hour windows of 1 s samples


def test_fleet_scraper_monotone_across_dropouts_and_restarts(tmp_path):
    """The summed whitebox series must stay monotone exactly during
    incidents: an unreachable replica keeps contributing its last-seen
    counts (flat sum → the window reads 'no new data', never
    'recovered'), and a restart's counter reset folds the previous
    incarnation's totals into a base instead of dipping the sum."""
    from deeplearninginassetpricing_paperreplication_tpu.serving.probe import (  # noqa: E501
        FleetScraper,
    )

    state = {"requests": {"POST /v1/weights 200": 90,
                          "POST /v1/weights 500": 10}}

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            b = json.dumps(state).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(b)))
            self.end_headers()
            self.wfile.write(b)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_address[1]
    dead_port = 1
    try:
        write_fleet_json(tmp_path, {
            "host": "127.0.0.1", "port": port, "replicas": 2,
            "replica_ids": [0, 1], "admin_ports": {"0": port,
                                                   "1": dead_port},
            "admin_urls": [f"http://127.0.0.1:{port}",
                           f"http://127.0.0.1:{dead_port}"],
            "pointer": None, "total_replicas_ever": 2})
        scraper = FleetScraper(tmp_path, timeout_s=0.5)
        bad0, total0 = scraper.sample()["requests"]
        assert (bad0, total0) == (10, 100)
        # more traffic: monotone growth (the dead replica never
        # subtracts anything)
        state["requests"]["POST /v1/weights 200"] = 150
        bad1, total1 = scraper.sample()["requests"]
        assert total1 == 160 and bad1 == 10
        # restart reset: counters drop to a small fresh count — the sum
        # must NOT dip (previous incarnation folds into the base)
        state["requests"] = {"POST /v1/weights 200": 5}
        bad2, total2 = scraper.sample()["requests"]
        assert total2 == 165 and bad2 == 10
        # the layout file dying does not zero the held series either
        (tmp_path / "fleet.json").unlink()
        bad3, total3 = scraper.sample()["requests"]
        assert (bad3, total3) == (bad2, total2)
    finally:
        srv.shutdown()
        srv.server_close()


def test_value_objective_sustained_breach():
    clock, advance = _fake_clock()
    value = {"v": 100.0}
    spec = {"schema": 1, "objectives": [
        {"name": "p99_latency", "kind": "value",
         "source": "latency_p99_ms", "max": 250.0, "sustain_s": 2.0,
         "severity": "ticket"}]}
    eng = SLOEngine(spec, {"latency_p99_ms": lambda: value["v"]},
                    clock=clock)
    for _ in range(20):
        advance(0.25)
        assert eng.tick() == []
    # one spike does not fire (not sustained)
    value["v"] = 400.0
    advance(0.25)
    assert eng.tick() == []
    value["v"] = 100.0
    for _ in range(10):
        advance(0.25)
        assert eng.tick() == []
    # sustained breach fires; recovery resolves
    value["v"] = 400.0
    fired = False
    for _ in range(20):
        advance(0.25)
        if eng.tick():
            fired = True
            break
    assert fired
    value["v"] = 100.0
    resolved = False
    for _ in range(20):
        advance(0.25)
        t = eng.tick()
        if t:
            assert t[0]["state"] == "resolved"
            resolved = True
            break
    assert resolved


def test_webhook_sink_delivers_and_survives_dead_receiver(tmp_path):
    got = []

    class H(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            got.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        sink = WebhookAlertSink(
            f"http://127.0.0.1:{srv.server_address[1]}/alert")
        sink.deliver({"state": "firing", "objective": "availability"})
        assert sink.delivered == 1 and sink.failed == 0
        assert got[0]["objective"] == "availability"
    finally:
        srv.shutdown()
        srv.server_close()
    dead = WebhookAlertSink("http://127.0.0.1:1/alert", timeout_s=0.5)
    dead.deliver({"state": "firing"})  # must not raise
    assert dead.failed == 1


def test_alert_ring_rides_flightrecorder_dump(tmp_path):
    from deeplearninginassetpricing_paperreplication_tpu.serving.flight import (  # noqa: E501
        FlightRecorder,
    )

    clock, advance = _fake_clock()
    counts = {"bad": 0, "total": 0}
    flight = FlightRecorder(run_dir=tmp_path)
    eng = SLOEngine(drill_spec(long_s=8, short_s=2),
                    {"probe": lambda: (counts["bad"], counts["total"])},
                    flight=flight, clock=clock)
    for _ in range(40):
        advance(0.25)
        counts["total"] += 4
        eng.tick()
    for _ in range(40):
        advance(0.25)
        counts["total"] += 4
        counts["bad"] += 4
        if eng.tick():
            break
    assert eng.firing()
    path = flight.dump("test")
    doc = json.loads(path.read_text())
    assert doc["alerts"] and doc["alerts"][-1]["state"] == "firing"


# --------------------------------------------------------------------------
# durability + trace rendering of the new kinds
# --------------------------------------------------------------------------


def test_alert_probe_kinds_are_durable_and_instant(tmp_path):
    assert "alert" in _DURABLE_KINDS and "probe" in _DURABLE_KINDS
    from deeplearninginassetpricing_paperreplication_tpu.observability.trace import (  # noqa: E501
        INSTANT_NAMES,
        assemble_trace,
    )

    assert {"alert/firing", "alert/resolved",
            "probe/failure"} <= INSTANT_NAMES
    ev = EventLog(tmp_path, process_index=0)
    ev.emit("alert", "alert/firing", objective="availability",
            window="8s/2s", severity="page", burn_long=50.0)
    ev.emit("probe", "probe/failure", target="replica0_healthz",
            error="URLError", consecutive=3)
    ev.emit("alert", "alert/resolved", objective="availability",
            window="8s/2s", severity="page")
    ev.close()
    trace = assemble_trace(tmp_path)
    instants = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    names = [e["name"] for e in instants]
    assert names == ["alert/firing", "probe/failure", "alert/resolved"]
    args = instants[0]["args"]
    assert args["objective"] == "availability"
    assert args["burn_long"] == 50.0
    assert instants[1]["args"]["target"] == "replica0_healthz"
    # byte-deterministic like every trace
    a = json.dumps(assemble_trace(tmp_path), sort_keys=True)
    b = json.dumps(assemble_trace(tmp_path), sort_keys=True)
    assert a == b


# --------------------------------------------------------------------------
# process gauges (dlap_process_*) on every scrape surface
# --------------------------------------------------------------------------


def test_process_stats_and_prom_block():
    stats = process_stats()
    assert stats["peak_rss_bytes"] and stats["peak_rss_bytes"] > 1e6
    assert stats["cpu_seconds"] is not None and stats["cpu_seconds"] >= 0
    assert stats["threads"] is not None and stats["threads"] >= 1
    parsed = parse_prom_text(render_process_prom())
    assert parsed["dlap_process_peak_rss_bytes"][()] > 1e6
    assert "dlap_process_cpu_seconds" in parsed
    assert "dlap_process_open_fds" in parsed


def test_metrics_sidecar_scrape_carries_process_gauges():
    ev = EventLog()
    sidecar = MetricsSidecar([ev.metrics])
    port = sidecar.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
    finally:
        sidecar.stop()
    parsed = parse_prom_text(text)
    assert parsed["dlap_process_peak_rss_bytes"][()] > 1e6
    assert "dlap_process_rss_bytes" in parsed or True  # /proc may vary


# --------------------------------------------------------------------------
# fleet.json consumers vs torn/partial writes and dead-fleet layouts
# --------------------------------------------------------------------------


def _stub_http(body=b"ok", status=200):
    class H(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            self.rfile.read(n)
            self._answer()

        def do_GET(self):
            self._answer()

        def _answer(self):
            self.send_response(status)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_read_fleet_json_torn_and_missing(tmp_path):
    assert read_fleet_json(tmp_path) is None  # missing
    (tmp_path / "fleet.json").write_text('{"replicas": 2, "admin_')
    assert read_fleet_json(tmp_path) is None  # torn
    (tmp_path / "fleet.json").write_text("")  # zero-byte partial
    assert read_fleet_json(tmp_path) is None


def test_prober_survives_torn_layout_and_dead_fleet(tmp_path):
    srv = _stub_http()
    port = srv.server_address[1]
    dead_port = 1
    try:
        write_fleet_json(tmp_path, {
            "host": "127.0.0.1", "port": port, "replicas": 2,
            "replica_ids": [0, 1],
            "admin_ports": {"0": port, "1": dead_port},
            "admin_urls": [f"http://127.0.0.1:{port}",
                           f"http://127.0.0.1:{dead_port}"],
            "pointer": None, "total_replicas_ever": 2})
        ev = EventLog(tmp_path, filename="events.probe.jsonl",
                      process_index=0)
        prober = Prober(ev, fleet_dir=tmp_path, timeout_s=0.5)
        res = prober.probe_once()
        # dead replica1 recorded as failures, live replica0 as successes
        by = {r["target"]: r["ok"] for r in res}
        assert by["replica0_healthz"] and by["replica0_metrics"]
        assert not by["replica1_healthz"]
        failures0, checks0 = prober.counts()
        assert failures0 == 2 and checks0 == 4
        # torn layout mid-flight: counted, last-known layout keeps probing
        (tmp_path / "fleet.json").write_text('{"replicas": 2, "adm')
        res2 = prober.probe_once()
        assert len(res2) == len(res)
        assert prober.stats()["layout_unreadable"] == 1
        # layout DELETED (dead fleet cleanup): same story
        (tmp_path / "fleet.json").unlink()
        res3 = prober.probe_once()
        assert len(res3) == len(res)
        assert prober.stats()["layout_unreadable"] == 2
        ev.close()
        rows = [json.loads(x) for x in
                (tmp_path / "events.probe.jsonl").read_text().splitlines()]
        probe_rows = [r for r in rows if r["kind"] == "probe"]
        assert probe_rows and all(
            r["name"] == "probe/failure" for r in probe_rows)
        assert any(r.get("consecutive", 0) >= 3 for r in probe_rows)
    finally:
        srv.shutdown()
        srv.server_close()


def test_probe_wire_constant_matches_server():
    """probe.py duplicates the raw-f32 content type as a literal so the
    standalone CLI never imports the engine (and jax) for a header
    string — the two constants must never drift."""
    from deeplearninginassetpricing_paperreplication_tpu.serving import (
        probe as probe_mod,
    )
    from deeplearninginassetpricing_paperreplication_tpu.serving import (
        server as server_mod,
    )

    assert probe_mod.BINARY_CONTENT_TYPE == server_mod.BINARY_CONTENT_TYPE


def test_prober_with_no_layout_at_all(tmp_path):
    """A prober pointed at a run dir a dead fleet never wrote to probes
    nothing, records the unreadable layout, and does not crash."""
    ev = EventLog(tmp_path, filename="events.probe.jsonl",
                  process_index=0)
    prober = Prober(ev, fleet_dir=tmp_path, timeout_s=0.5)
    assert prober.probe_once() == []
    assert prober.stats()["layout_unreadable"] == 1
    ev.close()


def test_ops_console_on_dead_fleet_layouts(tmp_path):
    """The ops console renders placeholders (never crashes, never lies)
    over missing/torn fleet.json and a layout whose processes are gone."""
    run_dir = tmp_path / "r"
    run_dir.mkdir()
    # no artifacts at all
    s = statusboard.gather_status(run_dir)
    text = statusboard.format_status(s)
    assert "(no fleet.json)" in text
    assert "(no probe/alert telemetry)" in text
    assert statusboard.gather_timeline(run_dir) == []
    # torn layout → same placeholder (read_fleet_json → None)
    (run_dir / "fleet.json").write_text('{"replicas":')
    assert "(no fleet.json)" in statusboard.format_status(
        statusboard.gather_status(run_dir))
    # a dead fleet's intact layout still renders (ports point nowhere —
    # status is file-derived, so nothing hangs)
    write_fleet_json(run_dir, {
        "host": "127.0.0.1", "port": 9, "replicas": 1,
        "replica_ids": [0], "admin_ports": {"0": 1},
        "admin_urls": ["http://127.0.0.1:1"], "pointer": None,
        "total_replicas_ever": 3})
    text = statusboard.format_status(statusboard.gather_status(run_dir))
    assert "1 live" in text and "ever=3" in text


# --------------------------------------------------------------------------
# ops console: canned run dir, byte determinism, --json purity
# --------------------------------------------------------------------------


def _canned_ops_dir(tmp_path) -> Path:
    run_dir = tmp_path / "fleet_run"
    run_dir.mkdir(parents=True, exist_ok=True)
    write_fleet_json(run_dir, {
        "host": "127.0.0.1", "port": 8787, "replicas": 2,
        "replica_ids": [0, 1], "admin_ports": {"0": 9001, "1": 9002},
        "admin_urls": ["http://127.0.0.1:9001", "http://127.0.0.1:9002"],
        "pointer": None, "total_replicas_ever": 2})
    rdir = run_dir / "replica0"
    rdir.mkdir()
    rev = EventLog(rdir, process_index=0)
    rev.counter("serve/generation", replica="replica0", generation=2,
                fingerprint="feedbeef" * 2)
    rev.close()
    ev = EventLog(run_dir, filename="events.probe.jsonl",
                  process_index=0)
    ev.counter("probe/check", target="public", outcome="ok")
    ev.counter("probe/check", target="replica0_healthz", outcome="ok")
    ev.emit("probe", "probe/failure", target="replica1_healthz",
            error="URLError", latency_ms=2.0, consecutive=1)
    ev.emit("alert", "alert/firing", objective="availability",
            window="8s/2s", severity="page", burn_long=50.0,
            burn_short=50.0)
    ev.gauge("alert/burn_rate", 50.0, objective="availability",
             window="8s/2s")
    ev.gauge("alert/budget_remaining", 0.0, objective="availability",
             window="8s/2s")
    ev.counter("fleet/scale", direction="up", reason="queue_depth")
    ev.counter("serve/canary", replica="replica0",
               max_weight_delta=0.0, max_sdf_delta=0.0, finite=True)
    ev.close()
    return run_dir


def test_ops_status_timeline_deterministic_and_complete(tmp_path, capsys):
    run_dir = _canned_ops_dir(tmp_path)
    s = statusboard.gather_status(run_dir)
    assert s["fleet"]["replicas"] == 2
    assert s["replicas"][0]["generation"] == 2
    assert s["slo"]["firing"][0]["objective"] == "availability"
    assert s["slo"]["probe"]["checks"] == 2
    assert s["slo"]["probe"]["failures"] == 1
    assert s["autoscaler"]["scale_ups"] == 1
    assert s["model_health"]["canary_swaps"] == 1
    text = statusboard.format_status(s)
    assert "ALERT FIRING: availability" in text
    rows = statusboard.gather_timeline(run_dir)
    names = [r["name"] for r in rows]
    assert "probe/failure" in names and "alert/firing" in names
    assert "fleet/scale" in names and "serve/canary" in names
    assert "serve/generation" in names
    # `--limit` keeps the newest
    limited = statusboard.gather_timeline(run_dir, limit=2)
    assert len(limited) == 2 and limited == rows[-2:]

    # byte determinism of BOTH commands, via the real CLI surface
    for argv in (["status", str(run_dir)],
                 ["status", str(run_dir), "--json"],
                 ["timeline", str(run_dir)],
                 ["timeline", str(run_dir), "--json"]):
        outs = []
        for _ in range(2):
            assert statusboard.main(argv) == 0
            outs.append(capsys.readouterr().out)
        assert outs[0] == outs[1], argv
        if "--json" in argv:
            json.loads(outs[0])  # --json owns stdout: pure document


def test_ops_module_entrypoint(tmp_path):
    """``python -m ….ops`` (the ISSUE-named console) reaches the
    statusboard through the ops package shim."""
    run_dir = _canned_ops_dir(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", f"{PKG}.ops", "status", str(run_dir)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "ALERT FIRING: availability" in r.stdout


def test_report_slo_section(tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (  # noqa: E501
        format_summary,
        load_run,
        summarize_run,
    )

    run_dir = _canned_ops_dir(tmp_path)
    summary = summarize_run(load_run(run_dir))
    slo = summary["slo"]
    assert slo["probe"]["checks"] == 2
    assert slo["probe"]["failures"] == 1
    assert slo["alerts"]["firings"] == 1
    assert slo["alerts"]["firing_now"] == ["availability [8s/2s]"]
    text = format_summary(summary)
    assert "ALERT FIRING: availability [8s/2s]" in text
    assert "probes: 2 checks, 1 failures" in text
    # pre-SLO run dirs keep their summaries byte-stable: section absent
    old = tmp_path / "old_run"
    old.mkdir()
    ev = EventLog(old, process_index=0)
    ev.counter("epochs_dispatched", value=1, phase="phase1_unconditional")
    ev.close()
    s_old = summarize_run(load_run(old))
    assert "slo" not in s_old


# --------------------------------------------------------------------------
# bench history + report --bench-trend
# --------------------------------------------------------------------------


def test_bench_history_idempotent_append_and_trend(tmp_path, capsys):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import bench_history
    finally:
        sys.path.pop(0)
    repo = tmp_path / "repo"
    (repo / "artifacts").mkdir(parents=True)
    (repo / "BENCH_X.json").write_text(json.dumps(
        {"throughput_rps": 100.0, "nested": {"p99_ms": 9.5},
         "note": "text ignored", "deep": {"a": {"b": 1.0}}}))
    (repo / "artifacts" / "DRILL.json").write_text(json.dumps(
        {"detection_s": 2.5}))
    out = repo / "benches" / "history.jsonl"
    appended = bench_history.update_history(repo, out)
    assert [e["file"] for e in appended] == ["BENCH_X.json",
                                             "artifacts/DRILL.json"]
    m = appended[0]["metrics"]
    assert m["throughput_rps"] == 100.0 and m["nested.p99_ms"] == 9.5
    assert "deep.a.b" not in m  # depth-bounded
    # idempotent: unchanged artifacts append nothing
    assert bench_history.update_history(repo, out) == []
    assert len(bench_history.read_history(out)) == 2
    # a CHANGED artifact appends exactly one new line
    (repo / "BENCH_X.json").write_text(json.dumps(
        {"throughput_rps": 120.0}))
    again = bench_history.update_history(repo, out)
    assert [e["file"] for e in again] == ["BENCH_X.json"]
    trend = bench_history.format_trend(bench_history.read_history(out))
    assert "BENCH_X.json" in trend and "throughput_rps" in trend
    # the changed artifact renders as a 2-point trajectory, old -> new
    line = next(ln for ln in trend.splitlines()
                if "throughput_rps" in ln)
    assert "100" in line and "120" in line and "->" in line

    # report --bench-trend renders through the same module
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (  # noqa: E501
        main as report_main,
    )

    # the tool must sit next to the history's repo for the path-load
    (repo / "tools").mkdir()
    (repo / "tools" / "bench_history.py").write_text(
        (REPO / "tools" / "bench_history.py").read_text())
    rc = report_main(["--bench-trend", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "bench trend" in text and "throughput_rps" in text


def test_repo_bench_history_checked_in_and_renders(capsys):
    """The perf trajectory artifact exists and covers the checked-in
    BENCH_* family (satellite: the trajectory was empty before PR 15)."""
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import bench_history
    finally:
        sys.path.pop(0)
    rows = bench_history.read_history(REPO / "benches" / "history.jsonl")
    assert rows, "benches/history.jsonl must be checked in and non-empty"
    files = {r["file"] for r in rows}
    assert "BENCH_SERVING.json" in files
    assert "BENCH_SLO.json" in files
    assert "artifacts/BENCH_OUTAGE_DRILL_r05.json" in files
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (  # noqa: E501
        main as report_main,
    )

    rc = report_main(
        ["--bench-trend", str(REPO / "benches" / "history.jsonl")])
    assert rc == 0
    assert "BENCH_SLO.json" in capsys.readouterr().out


# --------------------------------------------------------------------------
# tier-1 detection drill: live fleet + prober + engine, kill then wedge
# --------------------------------------------------------------------------


def test_detection_drill_kill_then_wedge(tmp_path):
    """THE acceptance path. A supervised 2-replica fleet serves under the
    live blackbox prober + burn-rate SLO engine. Replica0 is SIGKILLed →
    a firing availability alert (durable alert/firing row, file sink,
    flight ring); the supervisor restarts it and the alert RESOLVES.
    Replica1 is then SIGSTOPped — wedged-but-accepting: its sockets
    accept, nothing answers, whitebox metrics freeze mid-healthy — and
    the probe timeouts fire the alert again; SIGCONT resolves it. The
    ops console then tells the whole story byte-deterministically."""
    import dataclasses

    import jax

    from deeplearninginassetpricing_paperreplication_tpu.models.gan import (
        GAN,
    )
    from deeplearninginassetpricing_paperreplication_tpu.serving.aserver import (  # noqa: E501
        pick_free_port,
    )
    from deeplearninginassetpricing_paperreplication_tpu.serving.fleet import (  # noqa: E501
        REPLICA_POLICY,
        ReplicaFleet,
        server_child_argv,
    )
    from deeplearninginassetpricing_paperreplication_tpu.serving.flight import (  # noqa: E501
        FlightRecorder,
    )
    from deeplearninginassetpricing_paperreplication_tpu.serving.server import (  # noqa: E501
        build_arg_parser,
    )
    from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (  # noqa: E501
        save_params,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        GANConfig,
    )

    T, N, F, M = 12, 64, 10, 6
    cfg = GANConfig(macro_feature_dim=M, individual_feature_dim=F,
                    hidden_dim=(8, 8), num_units_rnn=(4,))
    mdir = tmp_path / "m1"
    mdir.mkdir()
    cfg.save(mdir / "config.json")
    save_params(mdir / "best_model_sharpe.msgpack",
                GAN(cfg).init(jax.random.key(1)))
    rng = np.random.default_rng(11)
    np.save(tmp_path / "macro.npy",
            rng.standard_normal((T, M)).astype(np.float32))
    run_dir = tmp_path / "fleet_run"
    args = build_arg_parser().parse_args([
        "--checkpoint_dirs", str(mdir),
        "--macro_npy", str(tmp_path / "macro.npy"),
        "--stock_buckets", "64", "--batch_buckets", "1,4",
        "--max_queue", "32", "--cache_size", "0",
        "--run_dir", str(run_dir)])
    port = pick_free_port()
    admin_ports = {}
    for i in range(2):
        p = pick_free_port()
        while p == port or p in admin_ports.values():
            p = pick_free_port()
        admin_ports[i] = p
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    policy = dataclasses.replace(
        REPLICA_POLICY, backoff_base_s=2.0, backoff_max_s=2.0,
        jitter_frac=0.0, min_uptime_s=0.5, poll_s=0.2)

    def make_argv(rid, admin_port):
        return server_child_argv(args, rid, run_dir / f"replica{rid}",
                                 port, admin_port=admin_port)

    fleet = ReplicaFleet([make_argv(i, admin_ports[i]) for i in range(2)],
                         run_dir, policy=policy, env=env)
    from deeplearninginassetpricing_paperreplication_tpu.serving.autoscale import (  # noqa: E501
        FleetController,
    )

    controller = FleetController(fleet, make_argv, "127.0.0.1", port,
                                 admin_ports=dict(admin_ports))
    events = EventLog(run_dir, filename="events.probe.jsonl",
                      process_index=0)
    flight = FlightRecorder(run_dir=run_dir, events=events)
    prober = Prober(events, public_url=f"http://127.0.0.1:{port}",
                    fixture=fixture_payload(F, month=0),
                    fleet_dir=run_dir, interval_s=0.25, timeout_s=1.0)
    engine = SLOEngine(
        drill_spec(long_s=8, short_s=2),
        build_sources(prober=prober),
        events=events, flight=flight,
        sinks=(FileAlertSink(run_dir / "alerts.jsonl"),), poll_s=0.1)

    def wait_for(predicate, timeout_s, what):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.05)
        raise AssertionError(
            f"timed out waiting for {what}: {engine.state()} / "
            f"{prober.stats()}")

    try:
        fleet.start()
        fleet.wait_ready(timeout=300)
        controller.publish_layout()
        prober.start()
        engine.start()
        # settle: clean probes across the full target set, no alert (a
        # transient startup blip is allowed to fire-and-resolve first)
        wait_for(lambda: prober.counts()[1] >= 10, 60,
                 "probes flowing")
        wait_for(lambda: engine.firing() == [], 60, "clean baseline")
        failures_before, _ = prober.counts()

        # -- drill 1: SIGKILL replica0 (dead: connections refused)
        pid0 = fleet.replica_pid(0)
        assert pid0 is not None
        os.kill(pid0, signal.SIGKILL)
        wait_for(lambda: engine.firing(), 60, "kill-drill firing alert")
        assert engine.firing()[0]["objective"] == "availability"
        failures_mid, _ = prober.counts()
        assert failures_mid > failures_before  # blackbox saw it
        # supervised restart → probes clean → alert resolves
        wait_for(lambda: not engine.firing(), 120,
                 "kill-drill alert resolve")

        # -- drill 2: SIGSTOP replica1 (wedged-but-accepting)
        pid1 = fleet.replica_pid(1)
        assert pid1 is not None
        os.kill(pid1, signal.SIGSTOP)
        try:
            wait_for(lambda: engine.firing(), 60,
                     "wedge-drill firing alert")
        finally:
            os.kill(pid1, signal.SIGCONT)
        wait_for(lambda: not engine.firing(), 120,
                 "wedge-drill alert resolve")
    finally:
        engine.stop()
        prober.stop()
        summaries = fleet.stop()
        events.close()

    # the kill really went through the supervisor (one restart, attributed)
    assert sum((s or {}).get("restarts", 0) for s in summaries) == 1
    # durable evidence: 2 firing + 2 resolved transitions, in order, in
    # BOTH the event log and the file sink
    rows = [json.loads(x) for x in
            (run_dir / "events.probe.jsonl").read_text().splitlines()]
    alert_names = [r["name"] for r in rows if r["kind"] == "alert"]
    # the two drills are the LAST two fire/resolve pairs (a transient
    # startup blip may add an earlier pair on a loaded runner); every
    # firing resolved, strictly alternating
    assert len(alert_names) >= 4
    assert alert_names[-4:] == ["alert/firing", "alert/resolved",
                                "alert/firing", "alert/resolved"]
    assert alert_names[0::2] == ["alert/firing"] * (len(alert_names) // 2)
    assert alert_names[1::2] == (["alert/resolved"]
                                 * (len(alert_names) // 2))
    sink_states = [json.loads(x)["state"] for x in
                   (run_dir / "alerts.jsonl").read_text().splitlines()]
    assert sink_states == [
        {"alert/firing": "firing", "alert/resolved": "resolved"}[n]
        for n in alert_names]
    assert any(r["kind"] == "probe" for r in rows)

    # the ops console tells the story, byte-deterministically
    s = statusboard.gather_status(run_dir)
    assert s["slo"]["firing"] == []  # both drills resolved
    assert s["slo"]["alerts_resolved"] >= 1
    assert s["slo"]["probe"]["failures"] >= 2
    assert [r["replica"] for r in s["replicas"]] == ["replica0",
                                                     "replica1"]
    tl = statusboard.gather_timeline(run_dir)
    names = [r["name"] for r in tl]
    assert names.count("alert/firing") >= 2
    assert names.count("alert/firing") == names.count("alert/resolved")
    assert "probe/failure" in names
    assert "supervise/death" in names and "supervise/restart" in names
    # the firing alert precedes its resolve, and the kill-drill firing
    # follows the supervisor-observed death on the merged clock
    assert names.index("alert/firing") < names.index("alert/resolved")
    two_status = [json.dumps(statusboard.gather_status(run_dir),
                             sort_keys=True) for _ in range(2)]
    assert two_status[0] == two_status[1]
    two_tl = [statusboard.format_timeline(
        statusboard.gather_timeline(run_dir)) for _ in range(2)]
    assert two_tl[0] == two_tl[1]

    # report CLI: the slo section aggregates the same evidence
    from deeplearninginassetpricing_paperreplication_tpu.observability.report import (  # noqa: E501
        load_run,
        summarize_run,
    )

    summary = summarize_run(load_run(run_dir))
    assert summary["slo"]["alerts"]["firings"] >= 2
    assert summary["slo"]["alerts"]["firing_now"] == []
    assert summary["slo"]["probe"]["failures"] >= 2


# --------------------------------------------------------------------------
# BENCH_SLO.json artifact bars (budgets.json gates the same numbers)
# --------------------------------------------------------------------------


def test_bench_slo_artifact_bars():
    path = REPO / "BENCH_SLO.json"
    assert path.exists(), "BENCH_SLO.json must be checked in"
    d = json.loads(path.read_text())
    po = d["probe_overhead"]
    assert po["rps_ratio"] >= 0.95, po
    assert d["kill_drill"]["detection_s"] is not None
    assert d["kill_drill"]["detection_s"] <= 20.0
    assert d["kill_drill"]["resolve_s"] is not None
    assert d["wedge_drill"]["detection_s"] is not None
    assert d["wedge_drill"]["detection_s"] <= 20.0
    assert d["steady_state_recompiles_max"] == 0
    assert d["alerts_file_transitions"] >= 4
    assert d["probe"]["checks"] > 0 and d["probe"]["failures"] > 0
    # the drill spec that produced the numbers ships inside the artifact
    validate_slo(d["slo_spec"])


# --------------------------------------------------------------------------
# lint gate over the SLO plane's new/changed modules
# --------------------------------------------------------------------------


def test_slo_modules_lint_clean():
    targets = [
        REPO / PKG / "observability" / "slo.py",
        REPO / PKG / "observability" / "statusboard.py",
        REPO / PKG / "observability" / "events.py",
        REPO / PKG / "observability" / "metrics.py",
        REPO / PKG / "observability" / "trace.py",
        REPO / PKG / "observability" / "report.py",
        REPO / PKG / "serving" / "probe.py",
        REPO / PKG / "serving" / "fleet.py",
        REPO / PKG / "serving" / "flight.py",
        REPO / PKG / "serving" / "loadgen.py",
        REPO / PKG / "serving" / "server.py",
        REPO / PKG / "reliability" / "supervisor.py",
        REPO / PKG / "ops" / "__main__.py",
        REPO / "tools" / "bench_history.py",
        REPO / "bench.py",
        Path(__file__),
    ]
    try:
        import ruff  # noqa: F401
    except ImportError:
        pytest.skip("ruff not installed in this container")
    out = subprocess.run(
        [sys.executable, "-m", "ruff", "check"] + [str(t) for t in targets],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, f"ruff findings:\n{out.stdout}{out.stderr}"
