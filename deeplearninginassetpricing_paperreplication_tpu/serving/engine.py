"""InferenceEngine: an ensemble of run directories as a long-lived,
low-latency query object.

Turns K trained checkpoints (``training/checkpoint.load_checkpoint_dir`` via
``evaluate_ensemble.stack_checkpoints``) into the queryable SDF of
Chen–Pelger–Zhu: conditional portfolio weights ``w(I_t, I_{t,i})`` and the
factor ``F_{t+1}`` for any month of firm characteristics, online. Three
design points keep steady-state latency flat:

  * **AOT compile per bucket, donated inputs, pinned staging** — the stock
    axis is padded to a small fixed set of buckets and each (stock bucket,
    batch bucket) forward program is ``.lower().compile()``d once (the same
    AOT pattern as ``data/pipeline.trainer_precompile_fn``) with its
    per-flush inputs donated (device buffers recycle into the outputs;
    resolved off on CPU, where XLA cannot donate) and a reusable zeroed
    host staging set per bucket — so after :meth:`warmup` the serve path
    performs ZERO recompiles and ZERO per-flush host allocations
    regardless of request shapes. :meth:`reload` hot-swaps params in place
    (same shapes, re-derived macro state, bumped fingerprint/generation)
    without ever recompiling.
  * **Incremental macro state** — the macro LSTM's carry is precomputed
    ONCE over the historical macro series at load (``lax.scan``), and every
    new month is an O(1) cell step (``models/recurrent.stacked_lstm_step``)
    instead of an O(T) re-scan.
  * **Member-vmapped ensemble math** — the per-request program vmaps the K
    members and applies the exact paper-protocol reduction of
    ``parallel.ensemble._ensemble_math`` (mean member normalized weights →
    guarded re-normalize → portfolio return), so served outputs are
    bit-identical to the offline ``evaluate_ensemble`` batch path.

Requests batch along the module's TIME axis: B month-queries with injected
per-month macro states [B, H] are exactly a T=B panel forward, so
micro-batched requests ride the same program as single ones.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..evaluate_ensemble import stack_checkpoints
from ..models.gan import GAN
from ..models.recurrent import stacked_lstm_scan, stacked_lstm_step
from ..observability import EventLog, config_hash
from ..observability.xla import record_program
from ..ops.metrics import normalize_weights_abs
from ..parallel import partition
from ..reliability.faults import inject

# Stock-axis buckets: requests are padded (mask 0) up to the smallest bucket
# ≥ N, bounding the compile count while keeping steady-state pad waste low.
# Powers of two from 64 to 16384 cover 500-stock synthetic through the
# ~10k-stock real panel with ≤ 2× padding.
DEFAULT_STOCK_BUCKETS = tuple(64 * 2**i for i in range(9))  # 64 .. 16384
# Batch-axis buckets for micro-batched requests (batcher.py lanes flush at
# most max(batch_buckets) items into one program call).
DEFAULT_BATCH_BUCKETS = (1, 4)


def params_digest(tree) -> str:
    """sha256 over a params pytree's leaf bytes — the served-weights
    identity. Result caches key on it so a checkpoint hot-swap
    (:meth:`InferenceEngine.reload`) can never serve a stale entry."""
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(tree):
        a = np.asarray(leaf)
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket ≥ n; loud error when the request exceeds every bucket
    (the server maps it to a 4xx instead of compiling an unbounded shape)."""
    for b in sorted(buckets):
        if n <= b:
            return b
    raise ValueError(
        f"request size {n} exceeds the largest configured bucket "
        f"{max(buckets)}; raise stock_buckets/batch_buckets at engine load"
    )


@dataclasses.dataclass
class InferenceRequest:
    """One month-query: firm characteristics (+ optional mask / realized
    next-month returns) against the macro state of `month` (-1 = latest)."""

    individual: np.ndarray  # [N, F] float32
    mask: Optional[np.ndarray] = None  # [N]; default all-valid
    returns: Optional[np.ndarray] = None  # [N]; enables the SDF factor
    month: int = -1


@dataclasses.dataclass
class InferenceResult:
    weights: np.ndarray  # [N] ensemble portfolio weights (Σ|w| = 1)
    sdf: Optional[float]  # F_{t+1} = Σ w·R·mask, None without returns
    member_sdf: Optional[np.ndarray]  # [K] per-member factors
    month: int
    n: int
    bucket: int
    batch_bucket: int


class InferenceEngine:
    """K stacked checkpoints + macro history → compiled month-query object.

    Thread-safety: :meth:`infer` may be called from any thread; compile
    bookkeeping and macro-state appends are lock-guarded. The intended
    deployment serializes dispatches through ``batcher.MicroBatcher``.
    """

    def __init__(
        self,
        checkpoint_dirs: Sequence[str],
        macro_history: Optional[np.ndarray] = None,  # [T, M], NORMALIZED
        macro_stats: Optional[Tuple[np.ndarray, np.ndarray]] = None,
        stock_buckets: Optional[Sequence[int]] = None,
        batch_buckets: Sequence[int] = DEFAULT_BATCH_BUCKETS,
        events: Optional[EventLog] = None,
        which: str = "best_model_sharpe",
        device=None,
        donate: bool = True,
        mesh=None,
    ):
        self.checkpoint_dirs = [str(d) for d in checkpoint_dirs]
        self.events = events if events is not None else EventLog()
        self._which = which
        gan, vparams = self._load_stacked()
        self.gan = gan
        self.cfg = gan.cfg
        self.config_hash = config_hash(self.cfg)
        self.n_members = len(self.checkpoint_dirs)
        self.stock_buckets = tuple(sorted(
            stock_buckets if stock_buckets is not None
            else DEFAULT_STOCK_BUCKETS))
        self.batch_buckets = tuple(sorted(batch_buckets))
        # the member-stacked forward's placement comes from the partition
        # layer like every other compute surface. `mesh` (a built Mesh, a
        # MeshConfig, or a CLI spec string like "stocks=4") lays the served
        # forward over a device grid: the stock axis of every bucket is cut
        # along the mesh's 'stocks' axis, members replicate — or shard over
        # a 'members' axis when the mesh has one. Default: the degenerate
        # 1-device mesh (replicated spec) — the single-device engine is the
        # smallest mesh, not a different code path.
        if mesh is None:
            self._device = device if device is not None else jax.devices()[0]
            self._mesh = partition.device_mesh(self._device)
        else:
            if isinstance(mesh, str):
                mesh = partition.parse_mesh_spec(mesh)
            if isinstance(mesh, partition.MeshConfig):
                mesh = mesh.build()
            self._mesh = mesh
            self._device = list(self._mesh.devices.flat)[0]
        self._devices = list(self._mesh.devices.flat)
        self._stock_shards = int(self._mesh.shape.get(partition.STOCK_AXIS,
                                                      1))
        for nb in self.stock_buckets:
            if nb % self._stock_shards != 0:
                raise ValueError(
                    f"stock bucket {nb} is not divisible by the mesh's "
                    f"{self._stock_shards}-way '{partition.STOCK_AXIS}' "
                    "axis — every bucket shards evenly or the padded "
                    "spans would straddle devices")
        # member placement: replicated by default; a mesh that carries a
        # member-ish axis > 1 shards the stacked-params leading axis over
        # it (members x stocks 2-D serving) and must divide the ensemble
        self._member_axis = None
        try:
            axis = partition.member_axis_name(self._mesh)
        except ValueError:
            axis = None
        if axis is not None and int(self._mesh.shape[axis]) > 1:
            if self.n_members % int(self._mesh.shape[axis]) != 0:
                raise ValueError(
                    f"mesh '{axis}' axis size {self._mesh.shape[axis]} "
                    f"does not divide the {self.n_members}-member ensemble")
            self._member_axis = axis
        self._sharding = partition.replicated(self._mesh)
        self._stack_sh = (
            partition.member_sharding(self._mesh, self._member_axis)
            if self._member_axis is not None else self._sharding)
        # per-key shardings of the per-flush inputs: stock axis cut along
        # the mesh, batch/feature axes replicated (partition.batch_rules —
        # the serving [B, Nb, F] ranks match the training [T, N, F] ones)
        if partition.STOCK_AXIS in self._mesh.shape:
            bsh = partition.batch_shardings(self._mesh)
            self._batch_sh = {k: bsh[k]
                              for k in ("individual", "mask", "returns")}
        else:
            self._batch_sh = {k: self._sharding
                              for k in ("individual", "mask", "returns")}
        # sharded staging dispatch: per-device stock spans assembled with
        # make_array_from_single_device_arrays (the stream_batch_sharded
        # discipline). The default-device degenerate mesh keeps the
        # monolithic jnp.asarray staging path — bit-for-bit the pre-mesh
        # engine
        self._sharded_dispatch = (
            len(self._devices) > 1 or self._device != jax.devices()[0])
        # donation is a no-op on the CPU backend (XLA warns "donated
        # buffers were not usable" per dispatch); resolve it against the
        # actual device so CPU loopback serves warning-free while TPU/GPU
        # deployments recycle their per-flush input buffers
        self.donate = bool(donate) and self._device.platform != "cpu"
        self.params_fingerprint = params_digest(vparams)
        self.params_generation = 0
        self._param_sh = self._member_shardings(vparams)
        self.vparams = jax.device_put(vparams, self._param_sh)
        self._lock = threading.Lock()
        # serializes staging-buffer fill + device dispatch: flushes are
        # device-serialized by design (the batcher's single dispatch lane),
        # and the pre-pinned host staging arrays are reused across them
        self._infer_lock = threading.Lock()
        self._staging: Dict[Tuple[int, int], Tuple[np.ndarray, ...]] = {}
        # sharded-dispatch staging: per-(stock bucket, batch bucket) span
        # plan (device order, per-device stock spans, reusable pinned host
        # buffers per UNIQUE span — member-replicated devices share one)
        self._span_plans: Dict[Tuple[int, int], Dict[str, Any]] = {}
        self._programs: Dict[Tuple[int, int], Any] = {}
        self._compiles = 0
        self._dispatches = 0
        # XLA introspection per AOT program (observability/xla.py):
        # folded into manifest.json by the serving service after warmup
        self.program_analyses: Dict[str, Dict[str, Any]] = {}
        # compile count at the end of warmup(): everything past this marker
        # is a steady-state recompile — the zero-recompile guarantee the
        # metrics plane exports (stats()["steady_state_recompiles"])
        self._warmup_compiles: Optional[int] = None
        # per-GENERATION served-output quality (the dlap_model_* gauges):
        # finite fraction / SDF running moments / weight-norm aggregates of
        # everything this params generation has served; reset on every
        # swapped reload so the scrape always describes the weights
        # currently serving
        self._gen_quality: Dict[str, float] = self._fresh_gen_quality()
        # macro-state machinery (None-state engines skip all of it)
        self._macro_stats = macro_stats
        self._uses_state = self.cfg.macro_feature_dim > 0
        self._uses_lstm = self._uses_state and self.cfg.use_rnn
        self._step_compiled = None
        self._carries = None
        self._macro_raw: Optional[np.ndarray] = None  # [T, M] normalized
        self._hs_host: Optional[np.ndarray] = None  # [K, T, Dp]
        if self._uses_state:
            if macro_history is None:
                raise ValueError(
                    "config has macro_feature_dim "
                    f"{self.cfg.macro_feature_dim} > 0: pass macro_history "
                    "([T, M], normalized with the TRAIN split's stats)"
                )
            self._init_macro_state(np.asarray(macro_history, np.float32))

    @staticmethod
    def _fresh_gen_quality() -> Dict[str, float]:
        return {"outputs": 0, "nonfinite_outputs": 0,
                "sdf_n": 0, "sdf_sum": 0.0, "sdf_sumsq": 0.0,
                "weight_norm_sum": 0.0, "weight_max_abs": 0.0}

    def _observe_outputs(self, requests, out) -> None:
        """Fold one micro-batch's served outputs into the generation-
        quality aggregates (host numpy over the already-fetched result —
        no extra device work)."""
        q = self._fresh_gen_quality()
        for i, r in enumerate(requests):
            n = np.asarray(r.individual).shape[0]
            w = out["weights"][i, :n]
            finite = bool(np.isfinite(w).all())
            q["outputs"] += 1
            q["weight_norm_sum"] += float(np.abs(w).sum())
            if w.size:
                q["weight_max_abs"] = max(q["weight_max_abs"],
                                          float(np.abs(w).max()))
            if r.returns is not None:
                s = float(out["sdf"][i])
                if np.isfinite(s):
                    q["sdf_n"] += 1
                    q["sdf_sum"] += s
                    q["sdf_sumsq"] += s * s
                else:
                    finite = False
            if not finite:
                q["nonfinite_outputs"] += 1
        with self._lock:
            g = self._gen_quality
            for k, v in q.items():
                g[k] = max(g[k], v) if k == "weight_max_abs" else g[k] + v

    def generation_quality(self) -> Dict[str, Any]:
        """Summary of what the CURRENT params generation has served — the
        ``dlap_model_*`` gauge source. ``finite_fraction`` is 1.0 for a
        generation that has served nothing (no evidence ≠ bad evidence)."""
        with self._lock:
            g = dict(self._gen_quality)
            generation = self.params_generation
        n = g["outputs"]
        sdf_mean = sdf_vol = None
        if g["sdf_n"]:
            sdf_mean = g["sdf_sum"] / g["sdf_n"]
            var = g["sdf_sumsq"] / g["sdf_n"] - sdf_mean * sdf_mean
            sdf_vol = float(np.sqrt(max(var, 0.0)))
        return {
            "generation": generation,
            "outputs": n,
            "nonfinite_outputs": g["nonfinite_outputs"],
            "finite_fraction": (round(1.0 - g["nonfinite_outputs"] / n, 6)
                                if n else 1.0),
            "weight_norm_mean": (round(g["weight_norm_sum"] / n, 6)
                                 if n else None),
            "weight_max_abs": round(g["weight_max_abs"], 6) if n else None,
            "sdf_mean": round(sdf_mean, 6) if sdf_mean is not None else None,
            "sdf_vol": round(sdf_vol, 6) if sdf_vol is not None else None,
        }

    def _load_stacked(self, checkpoint_dirs: Optional[Sequence[str]] = None):
        """Stack the checkpoint dirs on the evaluation route: f32 panel
        regardless of the training-side bf16_panel optimization (same
        convention as ensemble.member_weights — a checkpoint must serve
        identically on any host)."""
        dirs = (self.checkpoint_dirs if checkpoint_dirs is None
                else [str(d) for d in checkpoint_dirs])
        gan, vparams = stack_checkpoints(dirs, self._which)
        if gan.exec_cfg.bf16_panel:
            gan = GAN(gan.cfg, dataclasses.replace(
                gan.exec_cfg, bf16_panel=False))
        return gan, vparams

    def _member_shardings(self, tree):
        """Sharding tree for member-stacked values (stacked params, LSTM
        carries, per-month macro states): leading-K axis over the mesh's
        member axis when it has one (with the stack_tree_shardings
        non-divisible fallback), fully replicated otherwise — including
        the degenerate 1-device mesh, where this is exactly the pre-mesh
        placement."""
        if self._member_axis is None:
            return jax.tree.map(lambda _: self._sharding, tree)
        return partition.stack_tree_shardings(
            self._mesh, tree, self._member_axis)

    def reload(self, checkpoint_dirs: Optional[Sequence[str]] = None
               ) -> Dict[str, Any]:
        """Hot-swap params in place — from the SAME checkpoint dirs (e.g.
        after a rolling re-estimation wrote new verified checkpoints) or
        from `checkpoint_dirs` (a promotion pointer's candidate set) —
        without dropping traffic or recompiling: the AOT programs are
        shape-keyed, and a reload never changes shapes — an architecture
        or member-count change raises instead. The macro state is
        params-dependent, so it is re-derived over the full (initial +
        appended) normalized series. Bumps ``params_generation`` and
        ``params_fingerprint``; result caches keyed on the fingerprint
        drop every stale entry.

        The reload is ALL-OR-NOTHING: any failure (a member dir whose
        every generation is corrupt, an architecture mismatch, a
        macro-state re-scan error) leaves the engine serving its current
        params untouched. A reload whose loaded bytes hash to the
        CURRENT fingerprint — e.g. a torn newest write fell back to the
        ``.g1`` generation already serving (``reliability.verified``) —
        is a no-op: no generation bump, no macro re-scan, the engine keeps
        serving the old generation bit-identically (``swapped: False``)."""
        dirs = (self.checkpoint_dirs if checkpoint_dirs is None
                else [str(d) for d in checkpoint_dirs])
        if len(dirs) != self.n_members:
            raise ValueError(
                f"reload got {len(dirs)} checkpoint dirs but the compiled "
                f"programs serve a {self.n_members}-member ensemble — "
                "start a fresh engine to change the member count")
        gan, vparams = self._load_stacked(dirs)
        if config_hash(gan.cfg) != self.config_hash:
            raise ValueError(
                "reload found a different architecture (config hash "
                f"{config_hash(gan.cfg)[:12]} != {self.config_hash[:12]}); "
                "the compiled programs only serve the architecture they "
                "were lowered for — start a fresh engine instead")
        fingerprint = params_digest(vparams)
        if fingerprint == self.params_fingerprint:
            # nothing actually changed on disk (or the verified loader
            # fell back to the generation already serving): keep params,
            # macro state, and generation exactly as they are
            self.checkpoint_dirs = dirs
            self.events.counter("serve/reload",
                                generation=self.params_generation,
                                fingerprint=fingerprint[:16],
                                swapped=False)
            return {"params_fingerprint": fingerprint,
                    "params_generation": self.params_generation,
                    "swapped": False}
        with self._infer_lock:
            # the WHOLE swap — params AND the re-derived macro state —
            # happens under the dispatch lock: a flush either runs fully
            # pre-swap or fully post-swap, never new params against old
            # LSTM state (which would then be cached under the new
            # fingerprint); concurrent flushes/appends queue briefly
            old = (self.gan, self.vparams, self.params_fingerprint,
                   self._carries, self._hs_host)
            with self._lock:
                self.gan = gan
                self.vparams = jax.device_put(vparams, self._param_sh)
                self.params_fingerprint = fingerprint
            try:
                if self._uses_state:
                    self._init_macro_state(self._macro_raw)
            except BaseException:
                # a failed re-scan must not leave new params serving old
                # LSTM state: restore the pre-swap engine whole
                with self._lock:
                    (self.gan, self.vparams, self.params_fingerprint,
                     self._carries, self._hs_host) = old
                raise
            with self._lock:
                self.params_generation += 1
                # the quality gauges describe ONE generation's outputs
                self._gen_quality = self._fresh_gen_quality()
        self.checkpoint_dirs = dirs
        self.events.counter("serve/reload",
                            generation=self.params_generation,
                            fingerprint=fingerprint[:16], swapped=True)
        return {"params_fingerprint": fingerprint,
                "params_generation": self.params_generation,
                "swapped": True}

    # -- canary revert (in-memory, never a disk re-read) --------------------

    def snapshot_params(self) -> Tuple:
        """Opaque in-memory snapshot of the serving generation (gan,
        params, fingerprint, FULL macro state incl. the raw series, dirs).
        JAX arrays are immutable and the host arrays are replaced (never
        mutated in place) on every transition, so this is a tuple of
        references — free. Exists for the post-reload canary's REVERT: an
        in-place reload (new bytes under the SAME dirs) cannot be undone
        by reloading those dirs — the old params may exist nowhere on
        disk anymore — so the revert must restore the held state."""
        with self._infer_lock:
            return (self.gan, self.vparams, self.params_fingerprint,
                    self._carries, self._hs_host, self._macro_raw,
                    list(self.checkpoint_dirs))

    def restore_params(self, snapshot: Tuple) -> None:
        """Swap a :meth:`snapshot_params` state back in, atomically under
        the dispatch lock (the counterpart of :meth:`reload`'s swap).
        The WHOLE macro state (carries, per-month states, raw series)
        restores together, so a month appended inside the snapshot→
        restore window is dropped consistently — never a half-state a
        later reload's re-scan would silently resurrect. Bumps the
        generation and emits ``serve/restore`` (NOT ``serve/reload``:
        promotion tooling counts swapped reloads, and a revert is not a
        new hot-swap). The reverted-from generation's cache entries
        become unreachable via its fingerprint, while the restored
        fingerprint revalidates the pre-swap ones."""
        gan, vparams, fingerprint, carries, hs_host, macro_raw, dirs = \
            snapshot
        with self._infer_lock:
            with self._lock:
                self.gan = gan
                self.vparams = vparams
                self.params_fingerprint = fingerprint
                self._carries = carries
                self._hs_host = hs_host
                self._macro_raw = macro_raw
                self.params_generation += 1
                self._gen_quality = self._fresh_gen_quality()
        self.checkpoint_dirs = dirs
        self.events.counter("serve/restore",
                            generation=self.params_generation,
                            fingerprint=fingerprint[:16])

    # -- macro state ---------------------------------------------------------

    @property
    def state_dim(self) -> int:
        """Per-month macro-state width the forward consumes."""
        if not self._uses_state:
            return 0
        return (self.cfg.num_units_rnn[-1] if self._uses_lstm
                else self.cfg.macro_feature_dim)

    @property
    def months(self) -> int:
        """Number of macro months the engine currently holds state for."""
        return 0 if self._hs_host is None else self._hs_host.shape[1]

    def _lstm_tree(self, vparams):
        return vparams["sdf_net"]["macro_lstm"]

    def _init_macro_state(self, macro: np.ndarray) -> None:
        if macro.ndim != 2 or macro.shape[1] != self.cfg.macro_feature_dim:
            raise ValueError(
                f"macro_history must be [T, {self.cfg.macro_feature_dim}]; "
                f"got {macro.shape}"
            )
        self._macro_raw = np.array(macro, np.float32)  # kept for reload()
        if not self._uses_lstm:
            # no recurrence: the 'state' is the raw (normalized) macro row,
            # identical across members
            self._hs_host = np.broadcast_to(
                macro[None], (self.n_members, *macro.shape)).copy()
            return
        n_layers = len(self.cfg.num_units_rnn)

        def scan_all(lstm_tree):
            def one(tree):
                return stacked_lstm_scan(tree, jnp.asarray(macro), n_layers)

            return jax.vmap(one)(lstm_tree)

        with self.events.span("serve/macro_scan", months=int(macro.shape[0])):
            hs, carries = jax.jit(scan_all)(self._lstm_tree(self.vparams))
            hs = jax.block_until_ready(hs)
        self._hs_host = np.asarray(hs)  # [K, T, H]
        # pin the carry to the mesh layout the AOT step program lowers
        # with: the scan's inferred output sharding must never drift from
        # the compiled step's input contract (a mismatch is a recompile)
        self._carries = jax.device_put(
            carries, self._member_shardings(carries))

        def step_all(lstm_tree, carries, x_t):
            def one(tree, carry):
                return stacked_lstm_step(tree, carry, x_t, n_layers)

            return jax.vmap(one, in_axes=(0, 0))(lstm_tree, carries)

        def struct(x, sh_tree):
            return jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                  sharding=s), x, sh_tree)

        if self._step_compiled is None:
            # a reload() re-enters with identical shapes: the compiled step
            # program stays valid, hot-swaps never recompile
            lstm = self._lstm_tree(self.vparams)
            with self.events.span("serve/compile", program="macro_step"):
                self._step_compiled = (
                    jax.jit(step_all)
                    .lower(struct(lstm, self._lstm_tree(self._param_sh)),
                           struct(self._carries,
                                  self._member_shardings(self._carries)),
                           jax.ShapeDtypeStruct(
                               (self.cfg.macro_feature_dim,), np.float32,
                               sharding=self._sharding))
                    .compile()
                )
            record_program(self.events, "macro_step", self._step_compiled,
                           analyses_out=self.program_analyses,
                           program="macro_step")
            self._count_compile("macro_step")

    def append_month(self, macro_row: np.ndarray, raw: bool = False) -> int:
        """Advance the macro state by one month — an O(1) cell step per
        layer, never a re-scan. `raw=True` z-scores the row with the train
        stats the engine was constructed with. Returns the new month index.
        """
        if not self._uses_state:
            raise ValueError("this config consumes no macro series")
        row = np.asarray(macro_row, np.float32).reshape(-1)
        if row.shape[0] != self.cfg.macro_feature_dim:
            raise ValueError(
                f"macro row must have {self.cfg.macro_feature_dim} series; "
                f"got {row.shape[0]}"
            )
        if raw:
            if self._macro_stats is None:
                raise ValueError(
                    "raw=True requires macro_stats=(mean, std) at engine "
                    "construction"
                )
            mean, std = self._macro_stats
            row = ((row - np.asarray(mean).reshape(-1))
                   / np.asarray(std).reshape(-1)).astype(np.float32)
        # _infer_lock (not _lock): the macro state must not advance while
        # reload() is mid-rescan — both mutate _carries/_hs_host/_macro_raw
        with self._infer_lock:
            if not self._uses_lstm:
                new_h = np.broadcast_to(row, (self.n_members, row.shape[0]))
            else:
                x = jax.device_put(jnp.asarray(row), self._sharding)
                h, self._carries = self._step_compiled(
                    self._lstm_tree(self.vparams), self._carries, x)
                new_h = np.asarray(h)
            with self._lock:
                self._dispatches += 1
            self._hs_host = np.concatenate(
                [self._hs_host, new_h[:, None, :]], axis=1)
            # the appended normalized row joins the series reload() rescans
            self._macro_raw = np.concatenate(
                [self._macro_raw, row[None]], axis=0)
            month = self._hs_host.shape[1] - 1
        self.events.counter("serve/macro_append", month=month)
        return month

    def macro_state_for_month(self, month: int) -> np.ndarray:
        """[K, Dp] per-member macro state at `month` (negative = from end)."""
        if self._hs_host is None:
            raise ValueError("this config consumes no macro series")
        return self._hs_host[:, month]

    # -- the per-bucket forward program --------------------------------------

    def _fwd(self, vparams, state, individual, mask, returns):
        """state [K, B, Dp] or None; individual [B, Nb, F]; mask/returns
        [B, Nb] → the paper-protocol ensemble reduction per month."""
        batch = self.gan.prepare_batch(
            {"individual": individual, "mask": mask})

        def member(p, s):
            w = self.gan.weights(p, batch, macro_state=s)  # [B, Nb]
            return normalize_weights_abs(w, mask)

        if state is None:
            w = jax.vmap(lambda p: member(p, None))(vparams)
        else:
            w = jax.vmap(member)(vparams, state)  # [K, B, Nb]
        # ensemble math exactly as parallel.ensemble._ensemble_math
        avg = w.mean(axis=0)  # [B, Nb]
        abs_sum = (jnp.abs(avg) * mask).sum(axis=1, keepdims=True)
        avg = jnp.where(abs_sum > 1e-8, avg / abs_sum, avg)
        member_sdf = (w * returns[None] * mask[None]).sum(axis=2)  # [K, B]
        sdf = (avg * returns * mask).sum(axis=1)  # [B]
        return {"weights": avg, "sdf": sdf, "member_sdf": member_sdf}

    def _get_program(self, nb: int, b: int):
        key = (nb, b)
        with self._lock:
            prog = self._programs.get(key)
        if prog is not None:
            return prog
        f = self.cfg.individual_feature_dim

        def sds(shape, sharding):
            return jax.ShapeDtypeStruct(shape, np.float32,
                                        sharding=sharding)

        pstruct = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            self.vparams, self._param_sh)
        state_struct = (
            sds((self.n_members, b, self.state_dim), self._stack_sh)
            if self._uses_state else None
        )
        # donate the per-flush inputs (state, individual, mask, returns):
        # their device buffers are consumed into the outputs, so steady
        # state recycles one buffer set per program instead of allocating
        # fresh ones every flush. vparams (arg 0) are long-lived — never
        # donated.
        donate = (1, 2, 3, 4) if self.donate else ()
        with self.events.span("serve/compile", bucket=nb, batch=b):
            prog = (
                jax.jit(self._fwd, donate_argnums=donate)
                .lower(pstruct, state_struct,
                       sds((b, nb, f), self._batch_sh["individual"]),
                       sds((b, nb), self._batch_sh["mask"]),
                       sds((b, nb), self._batch_sh["returns"]))
                .compile()
            )
        with self._lock:
            # a concurrent compile of the same key keeps the first program
            prog = self._programs.setdefault(key, prog)
        record_program(self.events, f"fwd_{nb}x{b}", prog,
                       analyses_out=self.program_analyses,
                       program=f"fwd_{nb}x{b}", bucket=nb, batch=b)
        self._count_compile(f"fwd_{nb}x{b}", bucket=nb, batch=b)
        return prog

    def _count_compile(self, program: str, **attrs) -> None:
        with self._lock:
            self._compiles += 1
        self.events.counter("serve/recompile", program=program, **attrs)

    def _staging_arrays(self, nb: int, b: int):
        """Pre-pinned host staging for one (stock bucket, batch bucket):
        (individual, mask, returns), zeroed and reused across flushes so
        steady state allocates no per-flush host memory. Callers hold
        ``_infer_lock`` for the fill + dispatch window."""
        key = (nb, b)
        stage = self._staging.get(key)
        if stage is None:
            f = self.cfg.individual_feature_dim
            stage = (np.zeros((b, nb, f), np.float32),
                     np.zeros((b, nb), np.float32),
                     np.zeros((b, nb), np.float32))
            self._staging[key] = stage
        else:
            for a in stage:
                a.fill(0.0)
        return stage

    def _span_staging(self, nb: int, b: int) -> Dict[str, Any]:
        """Sharded-dispatch staging for one (stock bucket, batch bucket):
        the device order and per-device stock span of the bucket's
        ``partition.batch_shardings`` layout, plus one reusable zeroed
        (individual, mask, returns) host buffer triple per UNIQUE span —
        devices that replicate a span across the member axis share its
        buffers. Callers hold ``_infer_lock`` for the fill + dispatch
        window; buffers are re-zeroed on reuse, so steady state allocates
        no per-flush host memory (the sharded counterpart of
        :meth:`_staging_arrays`)."""
        key = (nb, b)
        plan = self._span_plans.get(key)
        if plan is None:
            f = self.cfg.individual_feature_dim
            # one map drives all three arrays: they share the stock-axis
            # split (the stream_batch_sharded convention)
            dmap = self._batch_sh["returns"].devices_indices_map((b, nb))
            devices = list(dmap)
            spans = []
            for dev in devices:
                a0, a1, _ = dmap[dev][1].indices(nb)
                spans.append((a0, a1))
            unique = sorted(set(spans))
            plan = {
                "devices": devices,
                "span_ix": [unique.index(s) for s in spans],
                "spans": unique,
                "buffers": [
                    (np.zeros((b, a1 - a0, f), np.float32),
                     np.zeros((b, a1 - a0), np.float32),
                     np.zeros((b, a1 - a0), np.float32))
                    for a0, a1 in unique
                ],
            }
            self._span_plans[key] = plan
        else:
            for triple in plan["buffers"]:
                for a in triple:
                    a.fill(0.0)
        return plan

    @staticmethod
    def _fill_spans(plan: Dict[str, Any],
                    requests: List[InferenceRequest]) -> None:
        """Write each request's rows into the per-span staging buffers —
        the same clamped fill as the monolithic path, cut at span
        boundaries (padded tails stay the zeros the re-zeroed buffers
        already hold)."""
        for i, r in enumerate(requests):
            ind = np.asarray(r.individual, np.float32)
            n = ind.shape[0]
            m = None if r.mask is None else np.asarray(r.mask, np.float32)
            ret = (None if r.returns is None
                   else np.asarray(r.returns, np.float32))
            for (a0, a1), (bi, bm, br) in zip(plan["spans"],
                                              plan["buffers"]):
                hi = min(n, a1)
                if hi <= a0:
                    break  # spans are sorted: nothing of this request left
                w = hi - a0
                bi[i, :w] = ind[a0:hi]
                bm[i, :w] = 1.0 if m is None else m[a0:hi]
                if ret is not None:
                    br[i, :w] = ret[a0:hi]

    def _put_spans(self, plan: Dict[str, Any], nb: int, b: int):
        """device_put each span's reusable host buffers onto their owning
        devices through the ``stream_batch_sharded`` discipline
        (one-span-ahead ``data/pipeline.buffered_puts``) and assemble the
        global [B, Nb(, F)] arrays with
        ``jax.make_array_from_single_device_arrays`` under the exact
        shardings the AOT programs were lowered with — steady-state
        dispatch can never trigger a resharding or a recompile."""
        from ..data.pipeline import buffered_puts

        devices, span_ix, buffers = (plan["devices"], plan["span_ix"],
                                     plan["buffers"])

        def make_chunk(i):
            return devices[i], buffers[span_ix[i]]

        def put(payload):
            dev, (bi, bm, br) = payload
            return (jax.device_put(bi, dev), jax.device_put(bm, dev),
                    jax.device_put(br, dev))

        parts = buffered_puts(len(devices), make_chunk, put)
        f = self.cfg.individual_feature_dim
        individual = jax.make_array_from_single_device_arrays(
            (b, nb, f), self._batch_sh["individual"],
            [p[0] for p in parts])
        mask = jax.make_array_from_single_device_arrays(
            (b, nb), self._batch_sh["mask"], [p[1] for p in parts])
        returns = jax.make_array_from_single_device_arrays(
            (b, nb), self._batch_sh["returns"], [p[2] for p in parts])
        return individual, mask, returns

    def warmup(self) -> int:
        """Compile every (stock bucket, batch bucket) program now AND
        allocate its host staging arrays; returns the number of compiled
        forward programs. After this, steady-state serving performs zero
        recompiles (asserted in tier-1) and zero per-flush host staging
        allocations."""
        for nb in self.stock_buckets:
            for b in self.batch_buckets:
                self._get_program(nb, b)
                with self._infer_lock:
                    if self._sharded_dispatch:
                        self._span_staging(nb, b)
                    else:
                        self._staging_arrays(nb, b)
        with self._lock:
            self._warmup_compiles = self._compiles
        return len(self._programs)

    # -- inference -----------------------------------------------------------

    def infer(self, requests: List[InferenceRequest],
              flush: Optional[int] = None,
              observe: bool = True) -> List[InferenceResult]:
        """Serve a micro-batch (same-bucket coalescing is the batcher's job;
        mixed sizes here simply pad to the largest request's bucket).
        ``flush``: the batcher flush id this micro-batch serves — stamped
        onto the ``serve/dispatch`` span so the request trace links each
        request row → its flush → the device dispatch by one id.
        ``observe=False`` keeps the outputs out of the generation-quality
        gauges — the canary replay's route, so ``dlap_model_*`` describes
        only LIVE traffic, never synthetic replays."""
        if not requests:
            return []
        # fault-injection site: one hit per served micro-batch (the server
        # maps an injected raise to a 5xx; kill/hang exercise the watchdog)
        inject("serving/infer", n_requests=len(requests))
        b = bucket_for(len(requests), self.batch_buckets)
        f = self.cfg.individual_feature_dim
        n_max = 0
        for r in requests:
            ind = np.asarray(r.individual, np.float32)
            if ind.ndim != 2 or ind.shape[1] != f:
                raise ValueError(
                    f"individual must be [N, {f}]; got {ind.shape}")
            n_max = max(n_max, ind.shape[0])
        nb = bucket_for(n_max, self.stock_buckets)

        months = []
        for i, r in enumerate(requests):
            months.append(r.month if r.month >= 0
                          else (self.months + r.month
                                if self._uses_state else -1))
        if self._uses_state:
            for i, m in enumerate(months):
                if not 0 <= m < self.months:
                    raise ValueError(
                        f"request {i}: month {requests[i].month} outside the "
                        f"engine's {self.months} macro months")

        prog = self._get_program(nb, b)
        with self._infer_lock:
            plan = None
            if self._sharded_dispatch:
                plan = self._span_staging(nb, b)
                self._fill_spans(plan, requests)
            else:
                individual, mask, returns = self._staging_arrays(nb, b)
                for i, r in enumerate(requests):
                    ind = np.asarray(r.individual, np.float32)
                    n = ind.shape[0]
                    individual[i, :n] = ind
                    mask[i, :n] = (1.0 if r.mask is None
                                   else np.asarray(r.mask, np.float32))
                    if r.returns is not None:
                        returns[i, :n] = np.asarray(r.returns, np.float32)
            state = None
            if self._uses_state:
                # padded batch slots reuse the first request's state (inert
                # — their outputs are discarded below)
                month_idx = months + [months[0]] * (b - len(requests))
                state_host = self._hs_host[:, month_idx]  # [K, B, Dp]
                # the sharded route pins the state to the exact lowered
                # member layout; the default-device route keeps the
                # historical jnp.asarray placement bit-for-bit
                state = (jax.device_put(state_host, self._stack_sh)
                         if self._sharded_dispatch
                         else jnp.asarray(state_host))
            span_attrs: Dict[str, Any] = dict(
                bucket=nb, batch=b, n_requests=len(requests))
            if plan is not None:
                span_attrs["shards"] = len(plan["devices"])
            if flush is not None:
                span_attrs["flush"] = flush
            with self.events.span("serve/dispatch", **span_attrs):
                # `state` is None for stateless configs — the same (empty-
                # pytree) structure the program was lowered with. The
                # staging copies move to fresh device buffers (monolithic
                # jnp.asarray, or per-device spans assembled under the
                # lowered shardings), which the donated program consumes
                # into its outputs.
                if plan is not None:
                    ind_d, mask_d, ret_d = self._put_spans(plan, nb, b)
                else:
                    ind_d, mask_d, ret_d = (jnp.asarray(individual),
                                            jnp.asarray(mask),
                                            jnp.asarray(returns))
                out = prog(self.vparams, state, ind_d, mask_d, ret_d)
                out = jax.device_get(out)
            # merge INSIDE the dispatch lock: a reload's quality reset
            # also runs under it, so a pre-swap batch can never leak its
            # stats into the post-swap generation's gauges
            if observe:
                self._observe_outputs(requests, out)
        with self._lock:
            self._dispatches += 1

        results = []
        for i, r in enumerate(requests):
            n = np.asarray(r.individual).shape[0]
            has_ret = r.returns is not None
            results.append(InferenceResult(
                weights=out["weights"][i, :n],
                sdf=float(out["sdf"][i]) if has_ret else None,
                member_sdf=out["member_sdf"][:, i] if has_ret else None,
                month=months[i],
                n=n,
                bucket=nb,
                batch_bucket=b,
            ))
        return results

    def infer_one(self, request: InferenceRequest,
                  observe: bool = True) -> InferenceResult:
        return self.infer([request], observe=observe)[0]

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "n_members": self.n_members,
                "config_hash": self.config_hash,
                "params_fingerprint": self.params_fingerprint[:16],
                "params_generation": self.params_generation,
                "stock_buckets": list(self.stock_buckets),
                "batch_buckets": list(self.batch_buckets),
                "months": self.months,
                "compiles": self._compiles,
                # None before warmup() establishes the steady-state marker
                "steady_state_recompiles": (
                    self._compiles - self._warmup_compiles
                    if self._warmup_compiles is not None else None),
                "compiled_programs": len(self._programs)
                + (1 if self._step_compiled is not None else 0),
                "dispatches": self._dispatches,
                "donate_inputs": self.donate,
                "staging_buffers": len(self._staging)
                + len(self._span_plans),
                # the serving mesh: axes as laid out, device count, and
                # whether dispatch assembles per-device spans (False only
                # on the default-device degenerate mesh)
                "mesh": partition.mesh_spec_str(self._mesh),
                "mesh_devices": len(self._devices),
                "stock_shards": self._stock_shards,
                "member_axis": self._member_axis,
                "sharded_dispatch": self._sharded_dispatch,
            }
