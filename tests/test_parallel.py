"""Mesh/sharding, vmapped ensemble, and sweep bucketing on the 8-dev CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearninginassetpricing_paperreplication_tpu import GAN, GANConfig, TrainConfig
from deeplearninginassetpricing_paperreplication_tpu.parallel.ensemble import (
    ensemble_metrics,
    ensemble_metrics_from_weights,
    member_weights,
    train_ensemble,
)
from deeplearninginassetpricing_paperreplication_tpu.parallel.mesh import (
    create_2d_mesh,
    create_mesh,
    replicate,
    shard_batch,
)
from deeplearninginassetpricing_paperreplication_tpu.parallel.partition import (
    member_sharding,
)
from deeplearninginassetpricing_paperreplication_tpu.parallel.sweep import (
    architecture_signature,
    grid_configs,
    run_sweep,
)
from deeplearninginassetpricing_paperreplication_tpu.training.steps import (
    make_optimizer,
    make_train_step,
)


def _batch_from(ds):
    return {k: jnp.asarray(v) for k, v in ds.full_batch().items()}


@pytest.fixture(scope="module")
def cfg():
    return GANConfig(
        macro_feature_dim=6, individual_feature_dim=10,
        hidden_dim=(8,), num_units_rnn=(3,), num_condition_moment=4,
    )


def test_mesh_creation_and_validation():
    mesh = create_mesh(8)
    assert mesh.shape["stocks"] == 8
    mesh2 = create_2d_mesh(2, 4)
    assert mesh2.shape == {"batch": 2, "stocks": 4}
    with pytest.raises(ValueError):
        create_2d_mesh(16)  # 16 > 8 devices → degenerate, must raise
    with pytest.raises(ValueError):
        create_2d_mesh(3, 4)  # 12 > 8


def test_shard_batch_divisibility(cfg, splits):
    mesh = create_mesh(8)
    train = splits[0]  # N=64, divisible by 8
    sharded = shard_batch(_batch_from(train), mesh)
    assert sharded["returns"].sharding.spec == P(None, "stocks")
    bad = {k: v[:, :63] if k != "macro" else v for k, v in _batch_from(train).items()}
    with pytest.raises(ValueError, match="not divisible"):
        shard_batch(bad, mesh)


@pytest.mark.slow
def test_sharded_train_step_matches_unsharded(cfg, splits):
    """One full train step under stock-axis GSPMD == single-device step."""
    gan = GAN(cfg)
    params = gan.init(jax.random.key(0))
    batch = _batch_from(splits[0])
    tx = make_optimizer(1e-3)
    step = make_train_step(gan, "conditional", tx)
    opt = tx.init(params["sdf_net"])

    ref_params, _, ref_m = jax.jit(step)(params, opt, batch, jax.random.key(5))

    mesh = create_mesh(8)
    sharded = shard_batch(batch, mesh)
    p_r = replicate(params, mesh)
    opt_r = replicate(opt, mesh)
    sh_params, _, sh_m = jax.jit(step)(p_r, opt_r, sharded, jax.random.key(5))

    np.testing.assert_allclose(float(sh_m["loss"]), float(ref_m["loss"]), rtol=2e-5)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(sh_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.slow
def test_ensemble_matches_serial_training(cfg, splits):
    """The vmapped 3-phase ensemble must reproduce per-seed serial training —
    through ALL three phases, down to the final selected params.

    Exact parity is asserted with dropout=0: the training-stream PRNG (rbg,
    utils/rng.py) generates hardware bits whose batched-vs-unbatched draws
    legitimately differ under vmap, so dropout masks are an implementation
    detail the vmap transform does not preserve bit-for-bit. With dropout
    off, every member must land on the same final params as a full serial
    run. A dropout-on ensemble is still trained to assert finiteness."""
    import dataclasses

    from deeplearninginassetpricing_paperreplication_tpu.training.trainer import (
        Trainer,
    )

    train, valid, test = splits
    tb, vb, teb = _batch_from(train), _batch_from(valid), _batch_from(test)
    tcfg = TrainConfig(num_epochs_unc=4, num_epochs_moment=2, num_epochs=6,
                       ignore_epoch=1, seed=0)
    cfg0 = dataclasses.replace(cfg, dropout=0.0)
    seeds = [11, 22]
    gan, vfinal, vhist = train_ensemble(
        cfg0, tb, vb, teb, seeds=seeds, tcfg=tcfg, verbose=False
    )
    assert vhist["train_loss"].shape == (2, 10)

    for i, seed in enumerate(seeds):
        params = gan.init(jax.random.key(seed))
        trainer = Trainer(gan, tcfg, has_test=True)
        final_serial, hist_serial = trainer.train(
            params, tb, vb, teb, seed=seed, verbose=False, precompile=False
        )
        # per-epoch history parity across phases 1 and 3
        np.testing.assert_allclose(
            np.asarray(hist_serial["train_loss"]), vhist["train_loss"][i],
            rtol=2e-4, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(hist_serial["valid_sharpe"]), vhist["valid_sharpe"][i],
            rtol=2e-4, atol=1e-5,
        )
        # final selected params parity (the docstring's actual claim)
        member_final = jax.tree.map(lambda x: x[i], vfinal)
        for a, b in zip(jax.tree.leaves(final_serial), jax.tree.leaves(member_final)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )

    # dropout on: streams differ between vmapped and serial, but training
    # must still be sane
    _, vfinal_d, vhist_d = train_ensemble(
        cfg, tb, vb, teb, seeds=seeds, tcfg=tcfg, verbose=False
    )
    assert np.all(np.isfinite(vhist_d["train_loss"]))


@pytest.mark.slow
def test_ensemble_metrics_protocol(cfg, splits):
    """Weight-averaged ensemble math vs a NumPy re-derivation."""
    gan = GAN(cfg)
    vparams = jax.vmap(lambda k: gan.init(k))(
        jnp.stack([jax.random.key(i) for i in (1, 2, 3)])
    )
    batch = _batch_from(splits[2])
    out = ensemble_metrics(gan, vparams, batch)

    w = np.asarray(member_weights(gan, vparams, batch))  # [S, T, N]
    mask = np.asarray(batch["mask"])
    ret = np.asarray(batch["returns"])
    avg = w.mean(axis=0)
    for t in range(avg.shape[0]):
        s = np.abs(avg[t] * mask[t]).sum()
        if s > 1e-8:
            avg[t] = avg[t] / s
    port = (avg * ret * mask).sum(axis=1)
    expected = (-port).mean() / (-port).std()  # ddof=0 numpy convention
    np.testing.assert_allclose(float(out["ensemble_sharpe"]), expected, rtol=1e-4)
    assert out["individual_sharpes"].shape == (3,)
    # paper Table-1 companions ride every ensemble evaluation (both the
    # from-params and from-weights paths share _ensemble_math)
    for k in ("explained_variation", "cross_sectional_r2"):
        assert np.isfinite(float(out[k])), k
    out_w = ensemble_metrics_from_weights(w, batch)
    np.testing.assert_allclose(
        float(out_w["explained_variation"]), float(out["explained_variation"]),
        rtol=1e-5,
    )


@pytest.mark.slow
def test_sweep_bucketing_and_ranking(cfg, splits):
    base = cfg
    configs = grid_configs(
        base,
        hidden_dims=((8,), (4, 4)),
        rnn_units=((3,),),
        num_moments=(4,),
        dropouts=(0.05,),
        lrs=(1e-3, 1e-2),
    )
    assert len(configs) == 4  # 2 archs × 2 lrs
    sigs = {architecture_signature(c) for c, _ in configs}
    assert len(sigs) == 2  # lr does not split buckets

    train, valid = splits[0], splits[1]
    tcfg = TrainConfig(num_epochs_unc=2, num_epochs_moment=1, num_epochs=3,
                       ignore_epoch=0, seed=0)
    top = run_sweep(
        configs, seeds=[5, 6], train_batch=_batch_from(train),
        valid_batch=_batch_from(valid), tcfg=tcfg, top_k=3, verbose=False,
    )
    assert len(top) == 3
    assert top[0]["valid_sharpe"] >= top[1]["valid_sharpe"] >= top[2]["valid_sharpe"]
    assert {"config", "lr", "seed", "valid_sharpe"} <= set(top[0])


@pytest.mark.slow
def test_ensemble_member_sharding(cfg, splits):
    """Ensemble axis laid over the 'batch' mesh dimension still trains."""
    mesh = create_2d_mesh(2, 4)
    train, valid = splits[0], splits[1]
    tb = shard_batch(_batch_from(train), mesh)
    vb = shard_batch(_batch_from(valid), mesh)
    tcfg = TrainConfig(num_epochs_unc=2, num_epochs_moment=1, num_epochs=2,
                       ignore_epoch=0, seed=0)
    gan, vfinal, hist = train_ensemble(
        cfg, tb, vb, None, seeds=[7, 8], tcfg=tcfg,
        member_sharding=member_sharding(mesh), verbose=False,
    )
    assert np.all(np.isfinite(hist["train_loss"]))


# -- jax-version gates (TRACKING: the image's jax 0.4.37 predates these
# APIs; capability-probed so a toolchain bump un-skips them automatically;
# remove the markers once the jax release shipping each API lands) --------
needs_jax_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="top-level jax.shard_map needs jax >= 0.6; "
           "parallel/sequence.py calls it directly",
)
needs_distributed_probe = pytest.mark.skipif(
    not hasattr(jax.distributed, "is_initialized"),
    reason="jax.distributed.is_initialized (the idempotency probe in "
           "parallel/multihost.py) needs jax >= 0.5",
)


# ---------------------------------------------------------------------------
# sequence (context) parallelism
# ---------------------------------------------------------------------------


@needs_jax_shard_map
def test_sequence_sharded_lstm_matches_single_device():
    """Time-sharded pipelined LSTM == single-device lax.scan LSTM."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearninginassetpricing_paperreplication_tpu.models.recurrent import (
        lstm_layer,
    )
    from deeplearninginassetpricing_paperreplication_tpu.parallel.mesh import (
        create_mesh,
    )
    from deeplearninginassetpricing_paperreplication_tpu.parallel.sequence import (
        sequence_sharded_lstm,
        shard_sequence,
    )

    rng = np.random.default_rng(11)
    T, I, H = 64, 6, 5
    x = jnp.asarray(rng.standard_normal((T, I)).astype(np.float32))
    k = 1.0 / np.sqrt(H)
    params = {
        name: jnp.asarray(
            rng.uniform(-k, k, shape).astype(np.float32)
        )
        for name, shape in (
            ("w_ih", (4 * H, I)), ("w_hh", (4 * H, H)),
            ("b_ih", (4 * H,)), ("b_hh", (4 * H,)),
        )
    }
    ref = lstm_layer(params, x)
    mesh = create_mesh(axis_name="time")
    assert mesh.devices.size == 8
    x_sharded = shard_sequence(x, mesh)
    out = jax.jit(
        lambda p, xs: sequence_sharded_lstm(p, xs, mesh)
    )(params, x_sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_sequence_sharded_lstm_rejects_ragged():
    import jax.numpy as jnp
    import numpy as np
    import pytest
    from deeplearninginassetpricing_paperreplication_tpu.parallel.mesh import (
        create_mesh,
    )
    from deeplearninginassetpricing_paperreplication_tpu.parallel.sequence import (
        sequence_sharded_lstm,
    )

    mesh = create_mesh(axis_name="time")
    params = {
        "w_ih": jnp.zeros((8, 3)), "w_hh": jnp.zeros((8, 2)),
        "b_ih": jnp.zeros(8), "b_hh": jnp.zeros(8),
    }
    with pytest.raises(ValueError, match="must divide"):
        sequence_sharded_lstm(params, jnp.zeros((13, 3)), mesh)


@needs_distributed_probe
def test_hybrid_mesh_single_slice_fallback():
    """create_hybrid_mesh on the CPU mesh: contiguous (batch, stocks) grid,
    all devices used, trainable end-to-end via shard_batch."""
    import jax
    import numpy as np
    from deeplearninginassetpricing_paperreplication_tpu.parallel.multihost import (
        create_hybrid_mesh,
        initialize_distributed,
        process_local_summary,
    )

    assert initialize_distributed() is False  # single host, nothing to do
    mesh = create_hybrid_mesh(members_per_host_group=2)
    assert mesh.shape == {"batch": 2, "stocks": 4}
    assert mesh.devices.size == len(jax.devices())
    info = process_local_summary()
    assert info["process_count"] == 1 and info["global_devices"] == 8
    import pytest
    with pytest.raises(ValueError, match="member groups"):
        create_hybrid_mesh(members_per_host_group=3)


@pytest.mark.slow
def test_ensemble_member_chunking_equivalent():
    """member_chunk splits the vmapped training into sequential groups with
    identical results (per-member streams are seed-derived, not shared)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearninginassetpricing_paperreplication_tpu.parallel.ensemble import (
        train_ensemble,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        GANConfig,
        TrainConfig,
    )

    rng = np.random.default_rng(1)
    T, N, F, M = 8, 24, 4, 3
    mask = (rng.random((T, N)) > 0.3).astype(np.float32)
    batch = {
        "individual": jnp.asarray((rng.standard_normal((T, N, F)) * mask[:, :, None]).astype(np.float32)),
        "returns": jnp.asarray((rng.standard_normal((T, N)) * 0.05 * mask).astype(np.float32)),
        "mask": jnp.asarray(mask),
        "macro": jnp.asarray(rng.standard_normal((T, M)).astype(np.float32)),
    }
    cfg = GANConfig(macro_feature_dim=M, individual_feature_dim=F,
                    hidden_dim=(6,), dropout=0.0)
    tcfg = TrainConfig(num_epochs_unc=3, num_epochs_moment=2, num_epochs=4,
                       ignore_epoch=0)
    seeds = [42, 123, 456, 789, 1000]
    _, full, hist_full = train_ensemble(cfg, batch, batch, seeds=seeds,
                                        tcfg=tcfg, verbose=False)
    _, chunked, hist_chunk = train_ensemble(cfg, batch, batch, seeds=seeds,
                                            tcfg=tcfg, verbose=False,
                                            member_chunk=2)
    for a, b in zip(jax.tree.leaves(full), jax.tree.leaves(chunked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_allclose(hist_full["train_loss"], hist_chunk["train_loss"],
                               atol=1e-5)


@pytest.mark.slow
def test_sweep_bucket_chunking_equivalent():
    """train_bucket(member_chunk) == unchunked over the same (lr, seed) grid."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearninginassetpricing_paperreplication_tpu.parallel.sweep import (
        train_bucket,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        GANConfig,
        TrainConfig,
    )

    rng = np.random.default_rng(2)
    T, N, F, M = 6, 16, 3, 2
    mask = (rng.random((T, N)) > 0.3).astype(np.float32)
    batch = {
        "individual": jnp.asarray((rng.standard_normal((T, N, F)) * mask[:, :, None]).astype(np.float32)),
        "returns": jnp.asarray((rng.standard_normal((T, N)) * 0.05 * mask).astype(np.float32)),
        "mask": jnp.asarray(mask),
        "macro": jnp.asarray(rng.standard_normal((T, M)).astype(np.float32)),
    }
    cfg = GANConfig(macro_feature_dim=M, individual_feature_dim=F,
                    hidden_dim=(4,), dropout=0.0)
    tcfg = TrainConfig(num_epochs_unc=2, num_epochs_moment=1, num_epochs=3,
                       ignore_epoch=0)
    kw = dict(lrs=[1e-3, 5e-4], seeds=[42, 7], train_batch=batch,
              valid_batch=batch, tcfg=tcfg)
    full = train_bucket(cfg, **kw)
    chunked = train_bucket(cfg, **kw, member_chunk=3)
    np.testing.assert_array_equal(full["grid"], chunked["grid"])
    np.testing.assert_allclose(full["best_valid_sharpe"],
                               chunked["best_valid_sharpe"], atol=1e-6)
    for a, b in zip(jax.tree.leaves(full["params"]), jax.tree.leaves(chunked["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_sweep_warm_programs_equivalent():
    """train_bucket dispatching warm-compiled executables (the sweep's
    compile-ahead pipeline) == inline-compiled, bit for bit: the executables
    are lowered from ShapeDtypeStruct avals, so this also locks the
    aval/sharding compatibility of the struct→array handoff."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearninginassetpricing_paperreplication_tpu.parallel.sweep import (
        train_bucket,
        warm_bucket_programs,
    )
    from deeplearninginassetpricing_paperreplication_tpu.utils.config import (
        GANConfig,
        TrainConfig,
    )

    rng = np.random.default_rng(5)
    T, N, F, M = 6, 16, 3, 2
    mask = (rng.random((T, N)) > 0.3).astype(np.float32)
    batch = {
        "individual": jnp.asarray(
            (rng.standard_normal((T, N, F)) * mask[:, :, None]).astype(np.float32)),
        "returns": jnp.asarray(
            (rng.standard_normal((T, N)) * 0.05 * mask).astype(np.float32)),
        "mask": jnp.asarray(mask),
        "macro": jnp.asarray(rng.standard_normal((T, M)).astype(np.float32)),
    }
    cfg = GANConfig(macro_feature_dim=M, individual_feature_dim=F,
                    hidden_dim=(4,), dropout=0.0)
    tcfg = TrainConfig(num_epochs_unc=2, num_epochs_moment=1, num_epochs=3,
                       ignore_epoch=0)
    kw = dict(lrs=[1e-3, 5e-4], seeds=[42], train_batch=batch,
              valid_batch=batch, tcfg=tcfg)
    progs = warm_bucket_programs(cfg, kw["lrs"], kw["seeds"], batch, batch,
                                 tcfg)
    assert set(progs) == {("unconditional", 2), ("moment", 1),
                          ("conditional", 3)}
    warm = train_bucket(cfg, **kw, programs=progs)
    inline = train_bucket(cfg, **kw)
    np.testing.assert_array_equal(warm["grid"], inline["grid"])
    np.testing.assert_array_equal(np.asarray(warm["best_valid_sharpe"]),
                                  np.asarray(inline["best_valid_sharpe"]))
    for a, b in zip(jax.tree.leaves(warm["params"]),
                    jax.tree.leaves(inline["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_two_process_distributed_train_step():
    """REAL multi-process execution of parallel.multihost: two OS processes
    coordinate via jax.distributed.initialize (localhost TCP), build the
    hybrid DCN-outer mesh (process granules on the outer 'batch' axis), and
    run one jitted conditional train step whose member axis crosses the
    process boundary. Both workers must print the SAME finite losses — only
    possible if the cross-process collectives ran."""
    import json
    import os
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    def env_for(pid):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env.pop("JAX_COORDINATOR_ADDRESS", None)
        return env

    def run_pair(port):
        procs = [
            subprocess.Popen(
                [sys.executable, "-m",
                 "deeplearninginassetpricing_paperreplication_tpu.parallel."
                 "multihost_worker",
                 "--coordinator", f"localhost:{port}",
                 "--num_processes", "2", "--process_id", str(i),
                 "--n_stocks_per_device", "8"],
                cwd=repo, env=env_for(i),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            for i in range(2)
        ]
        outs = []
        for i, p in enumerate(procs):
            out, err = p.communicate(timeout=600)
            assert p.returncode == 0, f"worker {i} failed:\n{err[-3000:]}"
            # the result is the LAST parseable JSON line (runtime warnings
            # may interleave on stdout)
            for line in reversed(out.strip().splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        outs.append(json.loads(line))
                        break
                    except json.JSONDecodeError:
                        continue
            else:
                raise AssertionError(
                    f"no JSON line from worker {i}:\n{out[-2000:]}")
        return outs

    try:
        outs = run_pair(port)
    except (AssertionError, subprocess.TimeoutExpired):
        # one retry: on a saturated single-CPU host (the full suite plus two
        # extra JAX processes) the TCP coordination handshake can time out —
        # a host-load flake, not a product failure; a second pair on a fresh
        # port must succeed
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        outs = run_pair(port)

    for i, o in enumerate(outs):
        assert o["summary"]["process_count"] == 2
        assert o["summary"]["process_index"] == i
        assert o["n_global_devices"] == 4
        assert o["mesh_shape"] == [2, 2]
    # the losses are global collectives' outputs — identical across workers
    assert outs[0]["losses"] == outs[1]["losses"]
    assert all(np.isfinite(v) for v in outs[0]["losses"])


@pytest.mark.slow
def test_midphase_resume_under_stock_sharding(cfg, splits, tmp_path):
    """Mid-phase checkpoint/resume with the panel GSPMD-sharded along
    stocks: the resumed sharded run must reach the same final params as an
    uninterrupted sharded run (resume state round-trips sharded arrays
    through host msgpack)."""
    from deeplearninginassetpricing_paperreplication_tpu.training.trainer import (
        train_3phase,
    )

    train, valid, test = splits
    mesh = create_mesh(8)
    tb = shard_batch(_batch_from(train), mesh)
    vb = shard_batch(_batch_from(valid), mesh)
    teb = shard_batch(_batch_from(test), mesh)
    tcfg = TrainConfig(num_epochs_unc=4, num_epochs_moment=2, num_epochs=5,
                       ignore_epoch=1, seed=7)

    _, final_full, _, _ = train_3phase(
        cfg, tb, vb, teb, tcfg=tcfg,
        save_dir=str(tmp_path / "full"), verbose=False,
    )
    run_dir = tmp_path / "cut"
    train_3phase(
        cfg, tb, vb, teb, tcfg=tcfg, save_dir=str(run_dir),
        verbose=False, checkpoint_every=2, stop_after_epochs=7,
    )
    import json as _json

    meta = _json.loads((run_dir / "resume_meta.json").read_text())
    assert meta["in_phase"] == 3  # 4+2+1: stopped inside phase 3
    _, final_resumed, _, _ = train_3phase(
        cfg, tb, vb, teb, tcfg=tcfg, save_dir=str(run_dir),
        verbose=False, resume=True, checkpoint_every=2,
    )
    for a, b in zip(jax.tree.leaves(final_full), jax.tree.leaves(final_resumed)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_ensemble_zero_epoch_phase(splits):
    """A zero-epoch phase must yield an empty history slice, not crash the
    chunked dispatcher (regression: sizes=[] left hists empty)."""
    train_ds, valid_ds, _ = splits
    batch = lambda ds: {k: jnp.asarray(v) for k, v in ds.full_batch().items()}
    cfg = GANConfig(
        macro_feature_dim=train_ds.macro_feature_dim,
        individual_feature_dim=train_ds.individual_feature_dim,
        hidden_dim=(8,), num_units_rnn=(3,), num_condition_moment=4,
    )
    tcfg = TrainConfig(num_epochs_unc=0, num_epochs_moment=2, num_epochs=3,
                       ignore_epoch=0)
    gan, vparams, hist = train_ensemble(
        cfg, batch(train_ds), batch(valid_ds), seeds=(0, 1), tcfg=tcfg,
        verbose=False,
    )
    assert hist["train_loss"].shape == (2, 3)  # phase-1 contributes 0 epochs
    assert np.all(np.isfinite(hist["train_loss"]))


def test_sweep_ranking_resume_roundtrip(tmp_path):
    """--resume_ranking: a written sweep_ranking.json reconstructs the exact
    winner selection THROUGH the real loader (config round-trip via
    GANConfig.from_dict, None valid_sharpe mapped back to -inf)."""
    import dataclasses
    import json

    from deeplearninginassetpricing_paperreplication_tpu.parallel.sweep import (
        architecture_signature,
    )
    from deeplearninginassetpricing_paperreplication_tpu.sweep import (
        load_ranking,
        select_winners,
    )

    base = GANConfig(macro_feature_dim=3, individual_feature_dim=5)
    ranked = [
        {"config": dataclasses.replace(base, hidden_dim=(16, 16)), "lr": 1e-3,
         "seed": 42, "valid_sharpe": 0.5},
        {"config": dataclasses.replace(base, hidden_dim=(16, 16)), "lr": 1e-3,
         "seed": 7, "valid_sharpe": 0.4},  # same setting, other seed
        {"config": dataclasses.replace(base, hidden_dim=(8,)), "lr": 5e-4,
         "seed": 42, "valid_sharpe": 0.3},
        {"config": dataclasses.replace(base, hidden_dim=(4,)), "lr": 5e-4,
         "seed": 42, "valid_sharpe": None},  # never-updated tracker
    ]
    path = tmp_path / "sweep_ranking.json"
    path.write_text(json.dumps([
        {"rank": i, "config": r["config"].to_dict(), "lr": r["lr"],
         "seed": r["seed"], "valid_sharpe": r["valid_sharpe"]}
        for i, r in enumerate(ranked)
    ]))

    loaded = load_ranking(path)  # the CLI's actual loader
    assert loaded[3]["valid_sharpe"] == float("-inf")
    for orig, got in zip(ranked, loaded):
        assert architecture_signature(got["config"]) == \
            architecture_signature(orig["config"])
    winners = select_winners(loaded, top_k=2)
    assert [w["config"].hidden_dim for w in winners] == [(16, 16), (8,)]
    assert winners[0]["valid_sharpe"] == 0.5
