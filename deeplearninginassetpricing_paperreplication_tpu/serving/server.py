"""HTTP serving layer: the transport-agnostic :class:`ServingService` JSON
API over the :class:`~.engine.InferenceEngine`, with two front ends — the
production asyncio server (:mod:`.aserver`, continuous batching) and the
DEPRECATED stdlib ``ThreadingHTTPServer`` (``--server threaded``).

Endpoints::

    POST /v1/weights  {"individual": [[...]], "mask": [...]?, "month": t?}
                      → {"weights": [...], "month": t, "n": N, ...}
    POST /v1/sdf      same + {"returns": [...]} → {"sdf": F, "member_sdf": [..]}
    POST /v1/macro    {"macro": [...], "raw": false?} — O(1) incremental
                      macro-state advance; → {"month": new index}
    POST /v1/reload   hot-swap params: from an explicit
                      {"checkpoint_dirs": [...]} payload, from the
                      configured promotion pointer (--pointer: the
                      pointer is re-read, digest-verified, and each
                      member's on-disk bytes checked against the digests
                      the gate recorded — a member torn after promotion
                      fails the reload whole instead of half-swapping a
                      mixed ensemble), or from the engine's current dirs;
                      → {"params_fingerprint", "params_generation",
                         "swapped", "pointer_generation"?, "converged"?}
    GET  /v1/models   ensemble manifest (members, config hash, buckets, ...)
    GET  /healthz     liveness; mirrors the run dir's heartbeat.json
    GET  /metrics     request counts, latency percentiles, cache, engine stats

Compact wire format: ``/v1/weights`` and ``/v1/sdf`` also accept
``"individual_b64"`` (base64 of row-major float32 bytes, with ``"n"`` rows)
plus optional ``"mask_b64"``/``"returns_b64"``, and ``"encoding": "b64"``
returns ``weights_b64``/``member_sdf_b64`` the same way — identical numerics
to the JSON-list route (both decode to float32) at a fraction of the parse
cost, which is what high-rate production clients should send.

Every request emits ``observability`` events into the run dir's
``events.jsonl`` (``serve/request`` rows carry the latency the report CLI
aggregates), liveness reuses the shared bench-format heartbeat writer, and
results are cached in a per-process LRU shard keyed by (config hash, params
fingerprint, request fingerprint) — replicated deployments shard the cache
per process, and a checkpoint hot-swap rotates the fingerprint so no shard
can serve a stale entry. Request execution goes through the
:class:`~.batcher.ContinuousBatcher` (async mode) or the legacy
:class:`~.batcher.MicroBatcher` (threaded mode); a full queue surfaces as
HTTP 503, not an unbounded backlog.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import binascii
import functools
import hashlib
import json
import struct
import sys
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability import (
    EventLog,
    Heartbeat,
    read_state,
    update_manifest,
    write_manifest,
)
from ..observability.metrics import PROM_CONTENT_TYPE
from ..observability.tracecontext import TraceContext
from ..reliability import faults
from .batcher import ContinuousBatcher, MicroBatcher, QueueFull, Shed
from .engine import InferenceEngine, InferenceRequest, bucket_for
from .flight import FlightRecorder

HEARTBEAT_INTERVAL_S = 5.0
DISPATCH_TIMEOUT_S = 30.0
# the JSON-free hot wire for /v1/weights: request body is
# [i32 month][u32 n][n*F f32 row-major characteristics], response body is
# [n f32 weights] — no JSON parse, no base64, no per-float boxing
BINARY_CONTENT_TYPE = "application/x-dlap-f32"

# priority-lane request contract (batcher.PRIORITIES): the header wins,
# the path decides the default — single-month weight/SDF queries are
# interactive; grid-shaped endpoints (the scenario workload) default bulk
PRIORITY_HEADER = "x-dlap-priority"
DEADLINE_HEADER = "x-dlap-deadline-ms"
BULK_DEFAULT_PREFIXES = ("/v1/scenarios", "/v1/bulk")


def priority_for(endpoint: str, header: Optional[str]) -> str:
    """Resolve a request's priority class: a valid ``x-dlap-priority``
    header value wins; otherwise the path-based default (bulk for
    ``BULK_DEFAULT_PREFIXES``, interactive for everything else). Unknown
    header values fall back to the path default — a typo must not turn a
    bulk sweep into interactive traffic."""
    if header:
        value = header.strip().lower()
        if value in ("interactive", "bulk"):
            return value
    if any(endpoint.startswith(p) for p in BULK_DEFAULT_PREFIXES):
        return "bulk"
    return "interactive"


def deadline_from_header(header: Optional[str],
                         t0: float) -> Optional[float]:
    """``x-dlap-deadline-ms`` (a client latency budget in milliseconds)
    → an absolute ``time.monotonic()`` deadline anchored at request
    arrival ``t0``. Malformed or non-positive values mean no deadline —
    a bad header must not shed the request."""
    if not header:
        return None
    try:
        budget_ms = float(header)
    except (TypeError, ValueError):
        return None
    if budget_ms <= 0:
        return None
    return t0 + budget_ms / 1e3


class BadRequest(ValueError):
    """Client-side payload problem → HTTP 400."""


class LRUCache:
    """Tiny thread-safe LRU for response dicts."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key):
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self.hits += 1
                return self._d[key]
            self.misses += 1
            return None

    def put(self, key, value) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def __len__(self):
        with self._lock:
            return len(self._d)


def request_fingerprint(endpoint: str, payload: Dict[str, Any]) -> str:
    """Canonical-JSON sha256 of one request — the cache key's second half."""
    blob = json.dumps([endpoint, payload], sort_keys=True,
                      separators=(",", ":"), default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


class ServingService:
    """Engine + micro-batcher + LRU cache + telemetry, transport-agnostic.

    The HTTP handler below is a thin shim over :meth:`handle`; tests drive
    the service directly (loopback-only semantics, no sockets needed).
    """

    def __init__(
        self,
        engine: InferenceEngine,
        run_dir: Optional[str] = None,
        max_batch: Optional[int] = None,
        max_delay_s: float = 0.002,
        max_queue: int = 256,
        cache_size: int = 256,
        events: Optional[EventLog] = None,
        mode: str = "threaded",
        replica_id: Optional[int] = None,
        pointer_root: Optional[str] = None,
        coalesce: bool = True,
        bulk_threshold: float = 0.5,
        reference_profile: Optional[Any] = None,
        drift_every: int = 64,
        drift_psi_threshold: float = 0.25,
        canary_size: int = 4,
    ):
        if mode not in ("threaded", "async"):
            raise ValueError(f"mode must be threaded|async: {mode!r}")
        self.engine = engine
        self.mode = mode
        self.replica_id = replica_id
        # promotion control plane: when set, /v1/reload with no explicit
        # dirs re-reads this pointer and hot-swaps to ITS generation
        # (digest-verified, member bytes checked) — the rolling-update
        # path (serving/fleet.RollingUpdater)
        self.pointer_root = Path(pointer_root) if pointer_root else None
        self.replica_label = (f"replica{replica_id}"
                              if replica_id is not None else None)
        if events is not None:
            self.events = events
        elif run_dir is not None:
            # a run dir implies a sink; rebind the engine too so its
            # compile/dispatch telemetry lands in the same events.jsonl
            # (construct the engine with events=EventLog(run_dir) to also
            # capture its load-time macro_scan/compile spans)
            self.events = EventLog(run_dir)
        else:
            self.events = engine.events
        engine.events = self.events
        self.run_dir = Path(run_dir) if run_dir else None
        self.heartbeat: Optional[Heartbeat] = None
        if self.run_dir is not None:
            self.heartbeat = Heartbeat(
                self.run_dir / "heartbeat.json", events=self.events)
            write_manifest(
                self.run_dir, "serve", events=self.events,
                config=engine.cfg,
                extra={
                    "checkpoint_dirs": engine.checkpoint_dirs,
                    "stock_buckets": list(engine.stock_buckets),
                    "batch_buckets": list(engine.batch_buckets),
                    "mesh": engine.stats().get("mesh"),
                    "mesh_devices": engine.stats().get("mesh_devices"),
                },
            )
            self.heartbeat.beat("serve/start")
        self.cache = LRUCache(cache_size)
        # the crash flight recorder: bounded rings of the last requests /
        # flushes + the in-flight set, dumped on error bursts, shutdown,
        # the supervisor's pre-kill flare, injected deaths, and the admin
        # endpoint (plus a staleness-bounded background autosave)
        self.flight = FlightRecorder(
            run_dir=run_dir, replica=self.replica_label, events=self.events)
        self.flight.start_autosave()
        faults.add_pre_death_hook(self._fault_last_words)
        self._shutdown_reason = "shutdown"
        self._max_batch = (max(engine.batch_buckets) if max_batch is None
                           else max_batch)
        self._max_queue = max_queue
        self._bulk_threshold = bulk_threshold
        # single-flight request coalescing (async mode): concurrent
        # IDENTICAL queries — same (config hash, params fingerprint,
        # endpoint, month, payload digest) — share ONE in-flight dispatch.
        # Event-loop-local state: no lock needed, and a hot-swap rotates
        # the fingerprint so a post-swap twin can never join a pre-swap
        # flight. Futures hold (ok, value) pairs, never raw exceptions —
        # an owner error with zero waiters must not log an
        # "exception was never retrieved" at GC.
        self.coalesce = bool(coalesce)
        self._inflight: Dict[Any, asyncio.Future] = {}
        self.coalesce_hits = 0
        self.coalesce_dispatches = 0
        # model-health plane (observability/drift.py + engine generation
        # quality → the dlap_model_* gauges on /metrics):
        #   * reference_profile: the training panel's distribution sketch;
        #     every drift_every-th inference request's characteristics
        #     matrix is PSI-scored against it, alerts past
        #     drift_psi_threshold count into dlap_model_drift_alerts_total
        #     and feed the flight recorder's burst trigger;
        #   * canary ring: the last canary_size served request inputs,
        #     replayed across every /v1/reload hot-swap — the divergence
        #     lands in events.jsonl (serve/canary) and a swap whose
        #     replayed outputs are non-finite is REVERTED and 5xx'd.
        self._profile: Optional[Dict[str, Any]] = None
        if reference_profile is not None:
            if isinstance(reference_profile, dict):
                self._profile = reference_profile
            else:
                from ..observability.drift import read_profile

                self._profile = read_profile(reference_profile)
        self.drift_every = max(1, int(drift_every))
        self.drift_psi_threshold = float(drift_psi_threshold)
        self.drift_alerts = 0
        self.drift_scored = 0
        self._drift_psi_last: Optional[float] = None
        self._obs_counter = 0
        self._canary: deque = deque(maxlen=max(0, int(canary_size)))
        # drain support (admin /v1/drain): the front end installs a hook
        # that closes the public listener so the kernel stops routing new
        # connections here while queued work flushes out
        self.draining = False
        self._drain_hook: Optional[Any] = None
        self.cbatcher: Optional[ContinuousBatcher] = None
        self.batcher: Optional[MicroBatcher] = None
        if mode == "threaded":
            self.batcher = MicroBatcher(
                self._handle_batch,
                max_batch=self._max_batch,
                max_delay_s=max_delay_s,
                max_queue=max_queue,
            )
        self.accepting = False  # set by the front end once the socket is up
        self._lock = threading.Lock()
        self._profile_lock = threading.Lock()  # /v1/debug/profile state
        self._profile_dir: Optional[Path] = None
        self._profile_seq = 0
        self._latencies: deque = deque(maxlen=4096)  # seconds
        self._requests: Dict[Tuple[str, str], int] = {}
        self._started = time.monotonic()
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if self.heartbeat is not None:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True, name="serving-heartbeat")
            self._hb_thread.start()

    # -- lifecycle -----------------------------------------------------------

    def _hb_loop(self):
        while not self._hb_stop.wait(HEARTBEAT_INTERVAL_S):
            # the steady section mirrors the lifecycle state: a fleet
            # readiness probe matches on a PERSISTENT "serve/accepting",
            # not a one-shot beat an idle beat could race-overwrite; a
            # draining replica advertises that too (the autoscaler's
            # scale-down watches for it before stopping the process)
            if self.draining:
                section = "serve/draining"
            elif self.accepting:
                section = "serve/accepting"
            else:
                section = "serve/idle"
            self.heartbeat.beat(section)

    def start_async(self) -> None:
        """Create the continuous batcher on the RUNNING event loop (async
        mode only; the aserver front end calls this once at startup)."""
        if self.mode != "async":
            raise RuntimeError("start_async() requires mode='async'")
        if self.cbatcher is None:
            self.cbatcher = ContinuousBatcher(
                self._handle_batch,
                max_batch=self._max_batch,
                max_queue=self._max_queue,
                events=self.events,
                label=self.replica_label,
                flight=self.flight,
                bulk_threshold=self._bulk_threshold,
            )

    def warmup(self) -> int:
        n = self.engine.warmup()
        if self.run_dir is not None:
            # the run dir's manifest carries the roofline story of every
            # AOT bucket program the warmup just compiled
            update_manifest(self.run_dir,
                            xla_programs=self.engine.program_analyses)
        if self.heartbeat is not None:
            self.heartbeat.beat("serve/ready")
        return n

    def close(self):
        self._hb_stop.set()
        faults.remove_pre_death_hook(self._fault_last_words)
        self.flight.stop_autosave()
        # the final flight snapshot: "sigterm" when main() saw the signal,
        # plain "shutdown" otherwise — either way the last requests and
        # anything still in flight are on disk next to metrics.prom
        self.flight.dump(self._shutdown_reason)
        if self.batcher is not None:
            self.batcher.close()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
        # final metrics snapshot: the steady-state recompile gauge (the
        # zero-recompile guarantee, measured on the METRICS plane) plus the
        # full registry state, as scrape-format text the report CLI
        # cross-checks against events
        steady = self.engine.stats().get("steady_state_recompiles")
        if steady is not None:
            self.events.gauge("serve/steady_state_recompiles", steady)
        if self.run_dir is not None:
            try:
                (self.run_dir / "metrics.prom").write_text(
                    self.events.metrics.render_prom())
            except OSError:
                pass  # a snapshot must not turn shutdown into a failure
        if self.heartbeat is not None:
            self.heartbeat.beat("serve/stopped")

    # -- request plumbing ----------------------------------------------------

    def _handle_batch(self, bucket, items: List[InferenceRequest]):
        b = self.cbatcher if self.cbatcher is not None else self.batcher
        # the flush id rides into the engine's serve/dispatch span, so the
        # trace links request rows → flush → device dispatch by one id
        return self.engine.infer(
            items, flush=None if b is None else b.current_flush)

    def _fault_last_words(self, site: str, action: str) -> None:
        """faults.py pre-death hook: an injected kill/hang leaves the same
        flight-recorder evidence a watchdog flare does."""
        self.flight.dump(f"fault:{site}")

    def _record(self, endpoint: str, status: int, seconds: float) -> None:
        with self._lock:
            key = (endpoint, str(status))
            self._requests[key] = self._requests.get(key, 0) + 1
            if status == 200:
                self._latencies.append(seconds)
        self.events.counter("serve/requests", endpoint=endpoint,
                            status=status, replica=self.replica_label)

    def _begin_rec(self, rec: Optional[Dict[str, Any]],
                   trace: Optional[TraceContext], endpoint: str,
                   method: str, t0: float) -> Tuple[Dict[str, Any], bool]:
        """Start one request's trace record; returns (rec, own) where
        ``own`` means THIS call must emit the row (no transport-side
        caller will add serialize/write segments and emit it)."""
        own = rec is None
        if rec is None:
            rec = {}
        if trace is None:
            trace = TraceContext.from_header(None)
        rec.update(trace=trace, endpoint=endpoint, method=method, t0=t0,
                   meta={}, token=self.flight.begin_request(
                       trace.trace_id, endpoint))
        return rec, own

    def emit_request(self, rec: Dict[str, Any],
                     serialize_s: float = 0.0,
                     write_s: Optional[float] = None) -> None:
        """Finish one request's trace record: retire it from the flight
        recorder, emit the compact ``request`` event row (sampled) or the
        aggregate ``span_end`` twin (unsampled — histograms stay complete
        either way), and dump the flight recorder on a 5xx burst.
        ``serialize_s``/``write_s``: transport-side segments the front end
        measured after the handler returned (response encode + socket
        write) — they extend the row's total. Never raises: telemetry
        (disk full, deleted run dir) must not fail a request that was
        already served."""
        rec["_finished"] = True
        try:
            self._emit_request(rec, serialize_s, write_s)
        except Exception:
            pass

    def _emit_request(self, rec: Dict[str, Any], serialize_s: float,
                      write_s: Optional[float]) -> None:
        trace: TraceContext = rec["trace"]
        meta = rec.get("meta") or {}
        status = rec.get("status", 500)
        seconds = rec.get("seconds", 0.0)
        serialize_total = float(meta.get("serialize_s") or 0.0) + serialize_s
        total = seconds + serialize_s + (write_s or 0.0)
        fields: Dict[str, Any] = {
            "endpoint": rec["endpoint"], "method": rec["method"],
            "status": status, "duration_s": round(total, 6),
        }
        if self.replica_label is not None:
            fields["replica"] = self.replica_label
        if rec.get("wire"):
            fields["wire"] = rec["wire"]
        t0 = rec["t0"]
        if "t_parsed" in meta:
            fields["parse_s"] = round(
                meta["t_parsed"] - t0 + rec.get("pre_parse_s", 0.0), 6)
        if meta.get("cached"):
            fields["cached"] = True
        if meta.get("priority"):
            fields["priority"] = meta["priority"]
        if meta.get("coalesced"):
            fields["coalesced"] = True
        if rec.get("shed_reason"):
            fields["shed_reason"] = rec["shed_reason"]
        if "t_enq" in meta and "t_take" in meta:
            fields["queue_s"] = round(meta["t_take"] - meta["t_enq"], 6)
        if "t_take" in meta and "t_dispatch" in meta:
            fields["batch_s"] = round(
                meta["t_dispatch"] - meta["t_take"], 6)
        if "dispatch_s" in meta:
            fields["dispatch_s"] = round(meta["dispatch_s"], 6)
            fields["dispatch_share_s"] = round(
                meta["dispatch_s"] / max(1, meta.get("occupancy", 1)), 6)
        if "flush" in meta:
            fields["flush"] = meta["flush"]
            fields["occupancy"] = meta.get("occupancy")
        if serialize_total:
            fields["serialize_s"] = round(serialize_total, 6)
        if write_s is not None:
            fields["write_s"] = round(write_s, 6)
        self.flight.end_request(rec["token"], dict(
            fields, trace_id=trace.trace_id))
        if trace.sampled:
            self.events.emit("request", "serve/request",
                             trace_id=trace.trace_id,
                             span_id=trace.span_id,
                             parent_id=trace.parent_id, **fields)
        else:
            # the aggregate twin: the SAME label-relevant fields (incl.
            # replica/wire — a partial sampling rate must not split the
            # histogram into different label sets), no per-request identity
            twin = {k: fields[k] for k in
                    ("endpoint", "method", "status", "duration_s",
                     "replica", "wire", "priority") if k in fields}
            self.events.emit("span_end", "serve/request", **twin)
        if isinstance(status, int) and (status >= 500 or status == 429) \
                and self.flight.error_burst():
            self.flight.dump("error_burst")

    def abort_request(self, rec: Dict[str, Any]) -> None:
        """Retire a request whose transport died before emit_request ran
        (client disconnect mid-write): the flight recorder must not carry
        it as in-flight forever."""
        token = rec.get("token")
        if token is None or rec.get("_finished"):
            return
        rec["_finished"] = True
        trace = rec.get("trace")
        self.flight.end_request(token, {
            "trace_id": trace.trace_id if trace is not None else None,
            "endpoint": rec.get("endpoint"), "status": "aborted"})

    def handle(self, method: str, path: str,
               payload: Optional[Dict[str, Any]],
               raw_body: Optional[bytes] = None,
               trace: Optional[TraceContext] = None,
               admin: bool = False) -> Tuple[int, Dict]:
        """One request → (http status, response dict). Never raises.
        `raw_body`: the undecoded request bytes when the caller has them
        (the HTTP shim does) — the cache then fingerprints those instead of
        re-serializing the multi-MB payload on the hot path. ``trace``:
        the request's :class:`TraceContext` when the transport parsed a
        ``traceparent`` header (a fresh edge context otherwise)."""
        t0 = time.monotonic()
        endpoint = path.split("?", 1)[0].rstrip("/") or "/"
        query = path.partition("?")[2]
        rec, _ = self._begin_rec(None, trace, endpoint, method, t0)
        status, body = 500, {"error": "internal"}
        try:
            status, body = self._route(method, endpoint, payload,
                                       raw_body, query=query, admin=admin,
                                       meta=rec["meta"])
        except BadRequest as e:
            status, body = 400, {"error": str(e)}
        except Shed as e:
            status, body = 429, self._shed_body(e, rec)
        except QueueFull as e:
            status, body = 503, {"error": f"overloaded: {e}",
                                 "_retry_after": 1}
        except Exception as e:  # a bad request must not kill the server
            status, body = 500, {"error": f"{type(e).__name__}: {e}"}
        seconds = time.monotonic() - t0
        rec.update(status=status, seconds=seconds)
        self._record(endpoint, status, seconds)
        self.emit_request(rec)
        return status, body

    async def handle_async(self, method: str, path: str,
                           payload: Optional[Dict[str, Any]],
                           raw_body: Optional[bytes] = None,
                           trace: Optional[TraceContext] = None,
                           rec: Optional[Dict[str, Any]] = None,
                           admin: bool = False,
                           priority: Optional[str] = None,
                           deadline_ms: Optional[str] = None
                           ) -> Tuple[int, Dict]:
        """The event-loop twin of :meth:`handle`: inference awaits the
        continuous batcher instead of blocking a handler thread; everything
        else runs inline on the loop. Emits ONE row per request — the
        compact ``request`` trace record (segment timings, trace ids,
        flush id) or its unsampled ``span_end`` twin — at hundreds of rps
        the telemetry write itself is on the hot path. ``rec``: a caller-
        owned record dict; when given, emission is DEFERRED to the
        caller's :meth:`emit_request` so the transport's serialize/write
        segments land on the same row. ``priority``/``deadline_ms``: the
        raw ``x-dlap-priority``/``x-dlap-deadline-ms`` header values the
        transport parsed (admission contract: :func:`priority_for` /
        :func:`deadline_from_header`). No per-request timeout task either:
        queue growth is bounded by the batcher (503), and a truly hung
        dispatch is the heartbeat watchdog's job (the supervisor SIGKILLs
        the replica), not a per-request timer's."""
        t0 = time.monotonic()
        endpoint = path.split("?", 1)[0].rstrip("/") or "/"
        query = path.partition("?")[2]
        rec, own = self._begin_rec(rec, trace, endpoint, method, t0)
        status, body = 500, {"error": "internal"}
        try:
            if endpoint in ("/v1/weights", "/v1/sdf") and method == "POST":
                status, body = 200, await self._infer_endpoint_async(
                    endpoint, payload or {}, raw_body, meta=rec["meta"],
                    priority=priority_for(endpoint, priority),
                    deadline=deadline_from_header(deadline_ms, t0))
            elif ((endpoint in ("/v1/reload", "/v1/macro", "/v1/drain")
                   or endpoint.startswith("/v1/debug/"))
                    and method == "POST"):
                # blocking work (checkpoint re-stack + rescan, LSTM cell
                # step, profiler start/stop + capture-dir walk, flight
                # dump fsync, drain wait): off the loop, or every
                # in-flight connection stalls for its full duration
                status, body = await asyncio.get_running_loop(
                ).run_in_executor(None, functools.partial(
                    self._route, method, endpoint, payload, raw_body,
                    query=query, admin=admin))
            else:
                status, body = self._route(method, endpoint, payload,
                                           raw_body, query=query,
                                           admin=admin)
        except BadRequest as e:
            status, body = 400, {"error": str(e)}
        except Shed as e:
            status, body = 429, self._shed_body(e, rec)
        except QueueFull as e:
            status, body = 503, {"error": f"overloaded: {e}",
                                 "_retry_after": 1}
            rec["retry_after"] = 1
        except Exception as e:  # a bad request must not kill the server
            status, body = 500, {"error": f"{type(e).__name__}: {e}"}
        seconds = time.monotonic() - t0
        rec.update(status=status, seconds=seconds)
        self._record(endpoint, status, seconds)
        if own:
            self.emit_request(rec)
        return status, body

    def _shed_rec(self, e: Shed, rec: Dict[str, Any]) -> int:
        """Fill one shed request's record (Retry-After whole seconds,
        reason) — the ONE place the 429 retry policy lives, shared by the
        JSON and binary wires."""
        retry_after = max(1, int(round(e.retry_after_s))) \
            if e.retry_after_s > 0 else 1
        rec["retry_after"] = retry_after
        rec["shed_reason"] = e.reason
        return retry_after

    def _shed_body(self, e: Shed, rec: Dict[str, Any]) -> Dict[str, Any]:
        """The 429 response for shed work: machine-readable reason +
        Retry-After both in the JSON body and (via ``rec``/``_retry_after``)
        as the HTTP header the transports render."""
        retry_after = self._shed_rec(e, rec)
        return {"error": f"shed: {e}", "reason": e.reason,
                "retry_after_s": retry_after, "_retry_after": retry_after}

    def _route(self, method, endpoint, payload, raw_body,
               query: str = "", admin: bool = False,
               meta: Optional[Dict[str, Any]] = None) -> Tuple[int, Dict]:
        if endpoint == "/healthz":
            return 200, self.healthz()
        if endpoint == "/metrics":
            from urllib.parse import parse_qs

            q = parse_qs(query)
            if q.get("format", [""])[-1] == "prom":
                # Prometheus text exposition from the live registry the
                # EventLog feeds — scrape-ready, same counts as events;
                # exemplars=0 strips the OpenMetrics exemplar suffixes
                # for strictly-classic parsers
                with_ex = q.get("exemplars", ["1"])[-1] not in ("0",
                                                                "false")
                return 200, {"_raw_text": self.metrics_prom(
                                 exemplars=with_ex),
                             "_content_type": PROM_CONTENT_TYPE}
            return 200, self.metrics()
        if endpoint == "/v1/models":
            return 200, self.models_info()
        if endpoint in ("/v1/weights", "/v1/sdf"):
            if method != "POST":
                return 405, {"error": "POST required"}
            return 200, self._infer_endpoint(endpoint, payload or {},
                                             raw_body, meta=meta)
        if endpoint == "/v1/macro":
            if method != "POST":
                return 405, {"error": "POST required"}
            return 200, self._macro_endpoint(payload or {})
        if endpoint == "/v1/reload":
            if method != "POST":
                return 405, {"error": "POST required"}
            return 200, self._reload_endpoint(payload)
        if endpoint == "/v1/drain":
            # graceful scale-down, ADMIN-ONLY like the debug surface: the
            # autoscaler targets one replica's private port; the shared
            # serving socket must never expose a stop-accepting control
            if not admin:
                return 404, {"error": f"unknown endpoint {endpoint}"}
            if method != "POST":
                return 405, {"error": "POST required"}
            return self._drain_endpoint(payload or {})
        if endpoint.startswith("/v1/debug/"):
            # debug surface is ADMIN-ONLY: these endpoints exist solely on
            # the per-replica private 127.0.0.1 port (aserver admin
            # listener) — the shared serving socket answers 404 so the
            # fleet's public surface never grows operational controls
            if not admin:
                return 404, {"error": f"unknown endpoint {endpoint}"}
            if method != "POST":
                return 405, {"error": "POST required"}
            if endpoint == "/v1/debug/flightrecorder":
                path = self.flight.dump("admin")
                if path is None:
                    return 400, {"error": "flight recorder has no run dir "
                                          "to dump into (start the server "
                                          "with --run_dir)"}
                return 200, {"dumped": True, "path": str(path),
                             "in_flight": len(
                                 self.flight.snapshot("")["in_flight"]),
                             "dumps": self.flight.dumps}
            if endpoint == "/v1/debug/profile":
                return self._profile_endpoint(payload or {})
            return 404, {"error": f"unknown endpoint {endpoint}"}
        return 404, {"error": f"unknown endpoint {endpoint}"}

    def _drain_endpoint(self, payload: Dict[str, Any]) -> Tuple[int, Dict]:
        """Graceful drain for autoscaler scale-down: flag the replica
        draining (heartbeat section ``serve/draining``), wait up to
        ``timeout_s`` for the queued lanes to flush, answer, and THEN let
        the front end's drain hook close the public listener — the hook
        fires ~0.5 s after this response so the drain answer reaches the
        caller first; the listener close unwinds the event loop cleanly
        (continuous batcher ``aclose`` drains anything that slipped in,
        the process exits rc 0, the supervisor records success instead of
        restarting). Requests still arriving during the wait keep being
        served — in-flight work is never dropped by the drain itself.
        Runs off the event loop (the run_in_executor branch of
        handle_async), so the wait cannot stall the very flushes it is
        waiting for."""
        try:
            timeout_s = float(payload.get("timeout_s", 10.0))
        except (TypeError, ValueError):
            raise BadRequest("timeout_s must be a number") from None
        self.draining = True
        self.accepting = False
        if self.heartbeat is not None:
            self.heartbeat.beat("serve/draining")
        b = self.cbatcher if self.cbatcher is not None else self.batcher
        deadline = time.monotonic() + max(0.0, timeout_s)
        while b is not None and b.pending() > 0 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        pending = 0 if b is None else b.pending()
        self.events.counter("serve/drain", pending=pending,
                            replica=self.replica_label)
        hook = self._drain_hook
        if hook is not None:
            try:
                hook()
            except Exception:
                pass  # listener already closed / loop shutting down
        return 200, {"draining": True, "pending": pending,
                     "drained": pending == 0}

    def _profile_endpoint(self, payload: Dict[str, Any]) -> Tuple[int, Dict]:
        """Programmatic ``jax.profiler`` capture on a live replica:
        ``{"action": "start"}`` begins a trace into the run dir
        (``profile/<n>``), ``{"action": "stop"}`` ends it and answers with
        the trace dir. Guarded: admin-port only, one capture at a time,
        always writes INSIDE the run dir (no caller-controlled paths), and
        a backend without profiler support answers 501 with the reason
        instead of crashing the replica."""
        action = payload.get("action")
        if action not in ("start", "stop"):
            raise BadRequest("payload requires \"action\": \"start\"|"
                             "\"stop\"")
        if self.run_dir is None:
            return 400, {"error": "profiling requires --run_dir (the "
                                  "capture is written into the run dir)"}
        import jax

        # a DEDICATED lock: the hot-path self._lock (taken by _record on
        # every request) must not be held across profiler start/stop
        with self._profile_lock:
            active = getattr(self, "_profile_dir", None)
            if action == "start":
                if active is not None:
                    return 409, {"error": f"a capture is already running "
                                          f"into {active}"}
                n = getattr(self, "_profile_seq", 0)
                self._profile_seq = n + 1
                trace_dir = self.run_dir / "profile" / f"capture{n}"
                trace_dir.mkdir(parents=True, exist_ok=True)
                try:
                    jax.profiler.start_trace(str(trace_dir))
                except Exception as e:
                    return 501, {"error": "jax.profiler unavailable on "
                                          f"this backend: "
                                          f"{type(e).__name__}: {e}"}
                self._profile_dir = trace_dir
                self.events.counter("serve/profile", action="start",
                                    replica=self.replica_label)
                return 200, {"profiling": True,
                             "trace_dir": str(trace_dir)}
            # stop
            if active is None:
                return 400, {"error": "no capture is running"}
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                self._profile_dir = None
                return 501, {"error": "jax.profiler stop failed: "
                                      f"{type(e).__name__}: {e}"}
            self._profile_dir = None
            self.events.counter("serve/profile", action="stop",
                                replica=self.replica_label)
        # the capture-dir walk happens OUTSIDE any lock: a large capture
        # must not stall concurrent requests
        has_output = any(Path(active).rglob("*"))
        return 200, {"profiling": False, "trace_dir": str(active),
                     "non_empty": bool(has_output)}

    # -- endpoints -----------------------------------------------------------

    def _b64_array(self, payload, key) -> Optional[np.ndarray]:
        """Decode a ``*_b64`` field (base64 of row-major float32 bytes).
        No validate= pass: that is a full-body regex (~0.4 ms on a 500-
        stock payload — half the entire serving path); binascii still
        rejects malformed padding, and a wrong SIZE is caught by the
        shape checks below."""
        blob = payload.get(key)
        if blob is None:
            return None
        try:
            return np.frombuffer(base64.b64decode(blob), np.float32)
        except (binascii.Error, TypeError, ValueError) as e:
            raise BadRequest(f"bad '{key}': {e}") from e

    def _parse_request(self, endpoint, payload) -> InferenceRequest:
        f = self.engine.cfg.individual_feature_dim
        flat = self._b64_array(payload, "individual_b64")
        if flat is not None:
            # compact wire format: float32 bytes, [N, F] row-major
            if flat.size == 0 or flat.size % f:
                raise BadRequest(
                    f"'individual_b64' must decode to N*{f} float32s; got "
                    f"{flat.size}")
            individual = flat.reshape(-1, f)
        elif "individual" in payload:
            try:
                individual = np.asarray(payload["individual"], np.float32)
            except (TypeError, ValueError) as e:
                raise BadRequest(f"bad 'individual': {e}") from e
            if individual.ndim != 2 or individual.shape[1] != f:
                raise BadRequest(
                    f"'individual' must be [N, {f}]; got "
                    f"{list(individual.shape)}")
        else:
            raise BadRequest("payload requires 'individual' ([N, F] floats) "
                             "or 'individual_b64' (base64 float32 bytes)")
        n = individual.shape[0]
        mask = self._b64_array(payload, "mask_b64")
        if mask is None and payload.get("mask") is not None:
            mask = np.asarray(payload["mask"], np.float32)
        if mask is not None and mask.shape != (n,):
            raise BadRequest("'mask' must be [N]")
        returns = self._b64_array(payload, "returns_b64")
        if returns is None and payload.get("returns") is not None:
            returns = np.asarray(payload["returns"], np.float32)
        if endpoint == "/v1/sdf" and returns is None:
            raise BadRequest("/v1/sdf requires 'returns' ([N] floats)")
        if returns is not None and returns.shape != (n,):
            raise BadRequest("'returns' must be [N]")
        month = int(payload.get("month", -1))
        return InferenceRequest(individual=individual, mask=mask,
                                returns=returns, month=month)

    def _observe_request(self, req: InferenceRequest,
                         endpoint: str) -> None:
        """Model-health observation of one parsed inference request: feed
        the canary ring (the inputs every hot-swap is replayed against)
        and, every ``drift_every``-th request when a reference profile is
        configured, PSI-score the characteristics matrix against it.
        Never raises — observation must not fail serving."""
        try:
            if self._canary.maxlen:
                # by REFERENCE, not a copy: the parsed arrays are fresh
                # per request (the b64 route's frombuffer views are even
                # read-only) and the engine copies into its own staging —
                # a per-request O(N·F) copy here would tax the hot path
                # just to maintain a 4-slot ring
                self._canary.append(req)
            if self._profile is None:
                return
            with self._lock:
                self._obs_counter += 1
                due = self._obs_counter % self.drift_every == 1 \
                    or self.drift_every == 1
            if not due:
                return
            from ..observability.drift import score_request

            report = score_request(self._profile, req.individual, req.mask)
            psi = report["max_psi"]
            if psi is None:
                return
            with self._lock:
                self.drift_scored += 1
                self._drift_psi_last = psi
            self.events.gauge("model/drift_psi", round(psi, 6),
                              endpoint=endpoint,
                              replica=self.replica_label)
            if psi > self.drift_psi_threshold:
                with self._lock:
                    self.drift_alerts += 1
                self.events.counter(
                    "model/drift_alert", psi=round(psi, 6),
                    threshold=self.drift_psi_threshold,
                    endpoint=endpoint, replica=self.replica_label)
                # the alert rides the flight recorder's burst trigger: a
                # drift storm dumps the same evidence an error burst does
                self.flight.note_alert()
                if self.flight.error_burst():
                    self.flight.dump("drift_burst")
        except Exception:  # noqa: BLE001 — observation must not fail serving
            pass

    def _infer_prepare(self, endpoint, payload, raw_body):
        """Parse + cache lookup; returns (key, bucket, req, cached_body) —
        ``cached_body`` short-circuits the dispatch when not None."""
        req = self._parse_request(endpoint, payload)
        # resolve a relative month BEFORE building the cache key: a cached
        # month=-1 answer must not outlive a /v1/macro append (the engine's
        # month count is part of the result's identity), and the engine is
        # handed the resolved index so key and computation cannot diverge
        if self.engine.state_dim > 0:
            months = self.engine.months
            resolved = req.month if req.month >= 0 else months + req.month
            if not 0 <= resolved < months:
                raise BadRequest(
                    f"month {req.month} outside the engine's {months} "
                    "macro months")
            req.month = resolved
        try:
            bucket = bucket_for(req.individual.shape[0],
                                self.engine.stock_buckets)
        except ValueError as e:
            raise BadRequest(str(e)) from e
        # only FULLY-validated requests (month resolved, bucket servable)
        # feed the canary ring and drift monitor — a burst of 400s must
        # not stuff the hot-swap safety net with unservable inputs
        self._observe_request(req, endpoint)
        key = None
        if self.cache.capacity > 0 or self.coalesce:
            fp = (hashlib.sha256(raw_body).hexdigest()
                  if raw_body is not None
                  else request_fingerprint(endpoint, payload))
            # params fingerprint in the key: a checkpoint hot-swap (reload)
            # rotates it, so this shard can never serve pre-swap weights —
            # and a post-swap twin query can never join a pre-swap
            # single-flight dispatch (the same key coalesces concurrent
            # identical queries)
            key = (self.engine.config_hash, self.engine.params_fingerprint,
                   endpoint, req.month, fp)
        if self.cache.capacity > 0:
            cached = self.cache.get(key)
            self.events.counter("serve/cache", hit=cached is not None,
                                endpoint=endpoint)
            if cached is not None:
                return key, None, req, dict(cached, cached=True)
        return key, bucket, req, None

    def _infer_finish(self, endpoint, payload, key, res) -> Dict[str, Any]:
        body: Dict[str, Any] = {
            "month": res.month, "n": res.n, "bucket": res.bucket,
            "n_members": self.engine.n_members,
            "config_hash": self.engine.config_hash,
        }
        if self.replica_label is not None:
            body["replica"] = self.replica_label
        b64_out = payload.get("encoding") == "b64"
        if endpoint == "/v1/weights":
            w = np.asarray(res.weights, np.float32)
            if b64_out:
                body["weights_b64"] = base64.b64encode(w.tobytes()).decode()
            else:
                body["weights"] = w.astype(np.float64).tolist()
        else:
            body["sdf"] = res.sdf
            m = np.asarray(res.member_sdf, np.float32)
            if b64_out:
                body["member_sdf_b64"] = base64.b64encode(
                    m.tobytes()).decode()
            else:
                body["member_sdf"] = m.astype(np.float64).tolist()
        if key is not None:
            self.cache.put(key, body)
        return dict(body, cached=False)

    def _infer_endpoint(self, endpoint, payload, raw_body=None,
                        meta: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, Any]:
        meta = {} if meta is None else meta
        key, bucket, req, cached = self._infer_prepare(endpoint, payload,
                                                       raw_body)
        meta["t_parsed"] = time.monotonic()
        if cached is not None:
            meta["cached"] = True
            return cached
        if self.batcher is not None:
            res = self.batcher.submit_wait(bucket, req,
                                           timeout=DISPATCH_TIMEOUT_S,
                                           meta=meta)
        else:
            # no thread batcher (async mode driven synchronously, e.g.
            # tests): one-at-a-time dispatch — the coalescing bit-identity
            # oracle
            res = self.engine.infer([req])[0]
        t_res = time.monotonic()
        out = self._infer_finish(endpoint, payload, key, res)
        meta["serialize_s"] = time.monotonic() - t_res
        return out

    async def _single_flight(self, key, dispatch,
                             meta: Optional[Dict[str, Any]] = None):
        """Single-flight request coalescing: concurrent IDENTICAL queries
        (same ``key`` — config hash, params fingerprint, endpoint, month,
        payload digest, priority class) collapse onto ONE in-flight
        dispatch; every waiter shares the owner's result. O(users)
        identical traffic becomes O(distinct queries) compute. The entry
        is removed the moment the flight completes, so this is NOT a
        cache: only genuinely concurrent twins share, and a post-swap
        identical query (new fingerprint → new key) always misses. Owner
        failures are shared too — the waiters coalesced onto that
        dispatch, its fate is theirs (futures carry (ok, value) pairs so
        an owner error with no waiters never logs an unretrieved-
        exception warning) — EXCEPT admission sheds: an owner 429'd on
        its own deadline/slot does not speak for its waiters, who
        re-dispatch under their own admission identity."""
        if not self.coalesce or key is None:
            return await dispatch()
        entry = self._inflight.get(key)
        if entry is not None:
            fut, owner_meta = entry
            self.coalesce_hits += 1
            if meta is not None:
                meta["coalesced"] = True
            try:
                self.events.counter("serve/coalesce", hit=True,
                                    replica=self.replica_label)
            except Exception:
                pass  # telemetry must never fail the request path
            # shield: one waiter's death must not cancel the shared flight
            ok, value = await asyncio.shield(fut)
            if ok:
                if meta is not None and owner_meta is not None:
                    # the owner's flush DID serve this request: carry its
                    # id so the trace's flow arrows reach the flush slice
                    # for coalesced waiters too
                    for k in ("flush", "occupancy", "dispatch_s"):
                        if k in owner_meta:
                            meta[k] = owner_meta[k]
                return value
            if isinstance(value, Shed):
                # the OWNER was shed on its own admission identity (its
                # deadline expired in the queue, its slot was evicted) —
                # that fate is not this waiter's: dispatch directly under
                # the waiter's own priority/deadline instead of
                # inheriting a 429 it never earned
                return await dispatch()
            raise value
        # fault site: the dispatch-owner path — a plan can raise/kill with
        # waiters coalesced behind this flight
        faults.inject("serve/coalesce", path=self.replica_label or "")
        fut = asyncio.get_running_loop().create_future()
        self._inflight[key] = (fut, meta)
        self.coalesce_dispatches += 1
        try:
            try:
                self.events.counter("serve/coalesce", hit=False,
                                    replica=self.replica_label)
            except Exception:
                # telemetry (disk full, deleted run dir) inside the
                # registration window must not leak the in-flight entry —
                # the finally below owns the cleanup either way
                pass
            res = await dispatch()
        except BaseException as e:
            if not fut.done():
                fut.set_result((False, e))
            raise
        else:
            if not fut.done():
                fut.set_result((True, res))
            return res
        finally:
            entry = self._inflight.get(key)
            if entry is not None and entry[0] is fut:
                del self._inflight[key]

    async def _infer_endpoint_async(self, endpoint, payload, raw_body=None,
                                    meta: Optional[Dict[str, Any]] = None,
                                    priority: str = "interactive",
                                    deadline: Optional[float] = None
                                    ) -> Dict[str, Any]:
        meta = {} if meta is None else meta
        key, bucket, req, cached = self._infer_prepare(endpoint, payload,
                                                       raw_body)
        meta["t_parsed"] = time.monotonic()
        if cached is not None:
            meta["cached"] = True
            return cached
        # priority rides the single-flight key: an interactive query must
        # never coalesce onto a bulk flight (it would wait behind every
        # interactive flush AND share the bulk entry's shed fate)
        res = await self._single_flight(
            key if key is None else key + (priority,),
            lambda: self.cbatcher.submit(
                bucket, req, meta=meta, priority=priority,
                deadline=deadline),
            meta=meta)
        t_res = time.monotonic()
        out = self._infer_finish(endpoint, payload, key, res)
        meta["serialize_s"] = time.monotonic() - t_res
        return out

    async def handle_binary_async(self, body: bytes,
                                  trace: Optional[TraceContext] = None,
                                  rec: Optional[Dict[str, Any]] = None,
                                  priority: Optional[str] = None,
                                  deadline_ms: Optional[str] = None
                                  ) -> Tuple[int, bytes]:
        """``/v1/weights`` over the raw-f32 wire (BINARY_CONTENT_TYPE):
        body = [i32 month][u32 n][n*F f32], response = [n f32 weights].
        Decodes with two ``np.frombuffer`` views — no JSON, no base64 —
        and rides the same continuous batcher, so the returned weights are
        bit-identical to every other route. Uncached by design: this is
        the production hot path, and the fingerprint hash would cost more
        than the lookup saves at these rates — but single-flight
        COALESCING applies (one sha256 of the body buys collapsing
        concurrent identical queries onto one dispatch, the O(users) →
        O(distinct queries) lever; ``coalesce=False`` restores the pure
        hot path). ``trace``/``rec``: same contract as
        :meth:`handle_async`; ``priority``/``deadline_ms``: the raw
        admission header values."""
        t0 = time.monotonic()
        rec, own = self._begin_rec(rec, trace, "/v1/weights", "POST", t0)
        rec["wire"] = "binary"
        meta = rec["meta"]
        status, out = 500, b"internal"
        try:
            f = self.engine.cfg.individual_feature_dim
            if len(body) < 8:
                raise BadRequest("body requires [i32 month][u32 n] header")
            month, n = struct.unpack_from("<iI", body)
            if n == 0 or len(body) != 8 + 4 * n * f:
                raise BadRequest(f"body must be 8 + 4*n*{f} bytes for n={n}")
            individual = np.frombuffer(
                body, np.float32, offset=8).reshape(n, f)
            if self.engine.state_dim > 0:
                months = self.engine.months
                month = month if month >= 0 else months + month
                if not 0 <= month < months:
                    raise BadRequest(
                        f"month outside the engine's {months} macro months")
            req = InferenceRequest(individual=individual, month=month)
            # validate the bucket BEFORE the canary/drift observation —
            # same only-servable-requests rule as _infer_prepare
            bucket = bucket_for(n, self.engine.stock_buckets)
            self._observe_request(req, "/v1/weights")
            pri = priority_for("/v1/weights", priority)
            key = None
            if self.coalesce:
                # month is inside the body bytes, so the body digest alone
                # identifies (month, universe); config + params fingerprint
                # pin the generation like every other key, and priority
                # segregates flights (see _infer_endpoint_async)
                key = (self.engine.config_hash,
                       self.engine.params_fingerprint, "/v1/weights:bin",
                       month, hashlib.sha256(body).hexdigest(), pri)
            meta["t_parsed"] = time.monotonic()
            res = await self._single_flight(
                key, lambda: self.cbatcher.submit(
                    bucket, req,
                    meta=meta, priority=pri,
                    deadline=deadline_from_header(deadline_ms, t0)),
                meta=meta)
            t_res = time.monotonic()
            status = 200
            out = np.ascontiguousarray(res.weights, np.float32).tobytes()
            meta["serialize_s"] = time.monotonic() - t_res
        except Shed as e:
            self._shed_rec(e, rec)
            status, out = 429, f"shed ({e.reason}): {e}".encode()
        except QueueFull as e:
            rec["retry_after"] = 1
            status, out = 503, f"overloaded: {e}".encode()
        except (BadRequest, ValueError) as e:
            status, out = 400, str(e).encode()
        except Exception as e:  # a bad request must not kill the server
            status, out = 500, f"{type(e).__name__}: {e}".encode()
        seconds = time.monotonic() - t0
        rec.update(status=status, seconds=seconds)
        self._record("/v1/weights", status, seconds)
        if own:
            self.emit_request(rec)
        return status, out

    def _macro_endpoint(self, payload) -> Dict[str, Any]:
        if "macro" not in payload:
            raise BadRequest("payload requires 'macro' ([M] floats)")
        try:
            month = self.engine.append_month(
                np.asarray(payload["macro"], np.float32),
                raw=bool(payload.get("raw", False)))
        except ValueError as e:
            raise BadRequest(str(e)) from e
        if self.heartbeat is not None:
            self.heartbeat.beat("serve/macro_append")
        return {"month": month, "months": self.engine.months}

    def _replay_canary(self, canary: List[InferenceRequest]
                       ) -> List[Optional[Any]]:
        """Serve the canary set against the CURRENT generation (direct
        engine dispatch — the compiled bucket programs, no batcher;
        ``observe=False`` so synthetic replays never pollute the
        ``dlap_model_*`` live-traffic gauges). Per-item failures record
        as None instead of failing the reload."""
        results: List[Optional[Any]] = []
        for req in canary:
            try:
                results.append(self.engine.infer_one(req, observe=False))
            except Exception:  # noqa: BLE001 — canary must not 5xx a reload
                results.append(None)
        return results

    def _canary_divergence(self, canary: List[InferenceRequest],
                           baseline: List[Optional[Any]],
                           reload_out: Dict[str, Any]) -> Dict[str, Any]:
        """Replay the canary set against the NEW generation and measure
        the divergence vs the pre-swap baseline. Emits the per-hot-swap
        ``serve/canary`` events row; returns the divergence summary
        (``finite`` False ⇒ the caller reverts the swap). A replay that
        ERRORS counts into ``errors``, not into ``finite``: a transient
        infer failure (fault injection, a month raced out of range) is
        not evidence the new WEIGHTS are degenerate, and must not revert
        a healthy promotion."""
        after = self._replay_canary(canary)
        replayed = errors = 0
        max_w = max_sdf = 0.0
        finite = True
        for pre, post in zip(baseline, after):
            if post is None:
                errors += 1
                continue
            replayed += 1
            w = np.asarray(post.weights, np.float64)
            if not np.isfinite(w).all():
                finite = False
            if post.sdf is not None and not np.isfinite(post.sdf):
                finite = False
            if pre is not None:
                w0 = np.asarray(pre.weights, np.float64)
                if w0.shape == w.shape and w0.size:
                    delta = np.abs(w - w0)
                    max_w = max(max_w, float(
                        delta[np.isfinite(delta)].max(initial=0.0)))
                if pre.sdf is not None and post.sdf is not None \
                        and np.isfinite(pre.sdf) and np.isfinite(post.sdf):
                    max_sdf = max(max_sdf, abs(post.sdf - pre.sdf))
        divergence = {
            "replayed": replayed,
            "errors": errors,
            "max_weight_delta": round(max_w, 8),
            "max_sdf_delta": round(max_sdf, 8),
            "finite": finite,
        }
        self.events.counter(
            "serve/canary", replica=self.replica_label,
            generation=reload_out.get("params_generation"),
            fingerprint=str(reload_out.get("params_fingerprint"))[:16],
            **divergence)
        return divergence

    def _reload_endpoint(self, payload: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
        """Hot-swap params. Source precedence: an explicit
        ``checkpoint_dirs`` payload, else the configured promotion pointer
        (re-read and digest-verified; each member's on-disk bytes must
        match the digests the gate recorded at promotion — a mismatch
        fails the WHOLE reload and the engine keeps serving its current
        generation), else the engine's current dirs. The cache needs no
        flush — its keys carry the params fingerprint, so pre-swap
        entries simply become unreachable (and age out of the LRU)."""
        payload = payload or {}
        from ..reliability.faults import inject

        # fault site: a kill here dies mid-hot-swap; the supervisor
        # restarts the replica and it converges to the pointer on boot
        inject("serve/reload", path=self.replica_label or "")
        dirs = payload.get("checkpoint_dirs")
        pointer = None
        if dirs is None and self.pointer_root is not None:
            from ..reliability.promotion import (
                read_pointer,
                verify_pointer_members,
            )

            pointer = read_pointer(self.pointer_root)
            if pointer is None:
                raise BadRequest(
                    f"no promotion pointer under {self.pointer_root}")
            mismatches = verify_pointer_members(pointer)
            if mismatches:
                # deliberate 5xx, not a swap: the health gate sees the
                # failure and rolls the pointer back
                raise RuntimeError(
                    "promotion pointer member digest mismatch — refusing "
                    "to swap a torn candidate: " + "; ".join(mismatches))
            dirs = pointer["checkpoint_dirs"]
        # post-reload canary: replay the last served request inputs across
        # the swap — the divergence lands in events.jsonl (serve/canary,
        # one row per hot-swap), and a generation whose replayed outputs
        # are non-finite is swapped BACK and the reload 5xx'd (the rolling
        # updater's health gate then rolls the pointer back). The revert
        # restores the held IN-MEMORY snapshot, not a disk re-read: an
        # in-place reload (new bytes under the same dirs) has no old
        # bytes left to re-read. Pointer reloads whose digest-verified
        # members already hash to the serving fingerprint are GUARANTEED
        # no-ops — the common rolling-updater polling path — so they skip
        # the baseline replay instead of serializing up to canary_size
        # inferences against live traffic for nothing.
        noop = (pointer is not None
                and pointer.get("params_fingerprint")
                == self.engine.params_fingerprint)
        snapshot = None if noop else self.engine.snapshot_params()
        canary = [] if noop else list(self._canary)
        baseline = self._replay_canary(canary)
        out = self.engine.reload(checkpoint_dirs=dirs)
        if out.get("swapped"):
            divergence = self._canary_divergence(canary, baseline, out)
            out["canary"] = divergence
            if divergence["finite"] is False and snapshot is not None:
                self.engine.restore_params(snapshot)
                raise RuntimeError(
                    "post-reload canary produced non-finite outputs "
                    f"(replayed {divergence['replayed']} requests); "
                    "reverted to the previous generation")
        if pointer is not None:
            out["pointer_generation"] = pointer["generation"]
            out["converged"] = bool(
                out["params_fingerprint"]
                == pointer.get("params_fingerprint"))
        # the promotion timeline row (distinct from the engine's
        # serve/reload counter): which replica is serving which params
        # generation, as of when
        self.events.counter(
            "serve/generation", replica=self.replica_label,
            fingerprint=out["params_fingerprint"][:16],
            generation=out["params_generation"],
            pointer_generation=(pointer or {}).get("generation"),
            swapped=out.get("swapped"))
        if self.heartbeat is not None:
            self.heartbeat.beat("serve/reload")
        return out

    def models_info(self) -> Dict[str, Any]:
        return {
            "n_members": self.engine.n_members,
            "checkpoint_dirs": self.engine.checkpoint_dirs,
            "config_hash": self.engine.config_hash,
            "config": self.engine.cfg.to_dict(),
            "months": self.engine.months,
            "engine": self.engine.stats(),
        }

    def healthz(self) -> Dict[str, Any]:
        """Liveness + the run dir's on-disk heartbeat (the SAME file a
        bench-format watchdog supervises — the two must agree)."""
        out: Dict[str, Any] = {
            "ok": True,
            "uptime_s": round(time.monotonic() - self._started, 3),
            "run_id": self.events.run_id,
        }
        if self.replica_label is not None:
            out["replica"] = self.replica_label
        if self.heartbeat is not None:
            out["heartbeat"] = (
                read_state(self.heartbeat.path).get("heartbeat"))
        return out

    def metrics_prom(self, exemplars: bool = True) -> str:
        """Prometheus text format from the EventLog's live registry —
        request counts, latency histograms with derived p50/p95/p99 (and
        per-bucket trace-id exemplars unless ``exemplars=False``),
        cache/recompile/flush counters — plus engine steady-state gauges.
        Fed from the SAME emit calls as events.jsonl, so a scrape and the
        post-hoc report CLI agree on every count."""
        extra = []
        stats = self.engine.stats()
        steady = stats.get("steady_state_recompiles")
        if steady is not None:
            extra.append("# TYPE dlap_serve_steady_state_recompiles gauge")
            extra.append(f"dlap_serve_steady_state_recompiles {steady}")
        extra.append("# TYPE dlap_serve_dispatches_total counter")
        extra.append(f"dlap_serve_dispatches_total {stats['dispatches']}")
        # the model-health gauges (dlap_model_*): what the CURRENT params
        # generation is serving — quality of its outputs plus the drift
        # monitor's state. README "Model health" documents the full table.
        quality = self.engine.generation_quality()
        extra.append("# TYPE dlap_model_generation gauge")
        extra.append(f"dlap_model_generation {quality['generation']}")
        extra.append("# TYPE dlap_model_outputs_total counter")
        extra.append(f"dlap_model_outputs_total {quality['outputs']}")
        extra.append("# TYPE dlap_model_finite_fraction gauge")
        extra.append(
            f"dlap_model_finite_fraction {quality['finite_fraction']}")
        for key, name in (("weight_norm_mean", "dlap_model_weight_norm"),
                          ("weight_max_abs", "dlap_model_weight_max_abs"),
                          ("sdf_mean", "dlap_model_sdf_mean"),
                          ("sdf_vol", "dlap_model_sdf_vol")):
            if quality.get(key) is not None:
                extra.append(f"# TYPE {name} gauge")
                extra.append(f"{name} {quality[key]}")
        with self._lock:
            alerts = self.drift_alerts
            scored = self.drift_scored
            psi_last = self._drift_psi_last
        extra.append("# TYPE dlap_model_drift_alerts_total counter")
        extra.append(f"dlap_model_drift_alerts_total {alerts}")
        extra.append("# TYPE dlap_model_drift_scored_total counter")
        extra.append(f"dlap_model_drift_scored_total {scored}")
        if psi_last is not None:
            extra.append("# TYPE dlap_model_drift_psi gauge")
            extra.append(f"dlap_model_drift_psi {round(psi_last, 6)}")
        # host-resource posture (dlap_process_*): both servers share this
        # method, so every scrape — shared or admin port — carries RSS/
        # CPU/fd/thread gauges for resource-exhaustion SLOs
        from ..observability.metrics import render_process_prom

        return (self.events.metrics.render_prom(exemplars=exemplars)
                + "\n".join(extra) + "\n" + render_process_prom())

    def metrics(self) -> Dict[str, Any]:
        from ..observability.report import latency_percentiles_ms

        with self._lock:
            lat = list(self._latencies)
            requests = {f"{ep} {st}": n
                        for (ep, st), n in sorted(self._requests.items())}
        latency = latency_percentiles_ms(lat)
        if latency is not None:
            latency["mean_ms"] = round(sum(lat) / len(lat) * 1e3, 3)
        b = self.cbatcher if self.cbatcher is not None else self.batcher
        batcher: Dict[str, Any] = {"mode": self.mode}
        if b is not None:
            batcher.update(flushes=b.flushes, rejected=b.rejected,
                           pending=b.pending())
        if self.cbatcher is not None:
            mean_depth = self.cbatcher.mean_queue_depth()
            batcher.update(
                occupancy_hist={str(k): v for k, v in sorted(
                    self.cbatcher.occupancy_hist.items())},
                mean_queue_depth=(round(mean_depth, 3)
                                  if mean_depth is not None else None),
                items_flushed=self.cbatcher.items_flushed,
                # admission-control evidence: shed tallies by reason and
                # the per-priority queue split the autoscaler reads
                shed=dict(sorted(self.cbatcher.shed.items())),
                pending_by_priority=self.cbatcher.pending_by_priority(),
                bulk_max=self.cbatcher.bulk_max,
                max_queue=self.cbatcher.max_queue,
            )
        with self._lock:
            model_health = {
                "generation_quality": self.engine.generation_quality(),
                "drift": {
                    "enabled": self._profile is not None,
                    "alerts": self.drift_alerts,
                    "scored": self.drift_scored,
                    "psi_last": self._drift_psi_last,
                    "threshold": self.drift_psi_threshold,
                },
                "canary_size": len(self._canary),
            }
        out = {
            "requests": requests,
            "latency": latency,
            "cache": {"hits": self.cache.hits, "misses": self.cache.misses,
                      "size": len(self.cache)},
            "coalesce": {"enabled": self.coalesce,
                         "hits": self.coalesce_hits,
                         "dispatches": self.coalesce_dispatches},
            "model_health": model_health,
            "batcher": batcher,
            "draining": self.draining,
            "engine": self.engine.stats(),
        }
        if self.replica_label is not None:
            out["replica"] = self.replica_label
        return out


# -- HTTP shim ---------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    # the service is attached to the server object by make_server().
    # HTTP/1.1: keep-alive by default, so the loadgen's persistent raw-
    # socket client talks to the deprecated path too (1.0 closed the
    # connection after every response)
    protocol_version = "HTTP/1.1"

    def _respond(self, status: int, body: Dict) -> None:
        retry_after = None
        if isinstance(body, dict) and "_raw_text" in body:
            # non-JSON response (Prometheus text exposition)
            data = body["_raw_text"].encode()
            ctype = body.get("_content_type", "text/plain")
        else:
            if isinstance(body, dict):
                retry_after = body.pop("_retry_after", None)
            data = json.dumps(body).encode()
            ctype = "application/json"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        if retry_after is not None:
            self.send_header("Retry-After", str(int(retry_after)))
        self.end_headers()
        self.wfile.write(data)

    def _payload(self) -> Tuple[Optional[Dict], Optional[bytes]]:
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return None, None
        raw = self.rfile.read(length)
        try:
            return json.loads(raw), raw
        except json.JSONDecodeError:
            return {"__invalid_json__": True}, raw

    def _dispatch(self, method: str) -> None:
        payload, raw = self._payload() if method == "POST" else (None, None)
        if payload is not None and "__invalid_json__" in payload:
            self._respond(400, {"error": "request body is not valid JSON"})
            return
        status, body = self.server.service.handle(
            method, self.path, payload, raw_body=raw,
            trace=TraceContext.from_header(
                self.headers.get("traceparent")))
        self._respond(status, body)

    def do_GET(self):  # noqa: N802 (stdlib handler API)
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def log_message(self, fmt, *args):  # stdout silence; events.jsonl has it
        pass


def make_server(service: ServingService, host: str = "127.0.0.1",
                port: int = 0) -> ThreadingHTTPServer:
    """Bind a ThreadingHTTPServer for `service`; port 0 picks a free port
    (``server.server_address[1]`` has the real one). Caller runs
    ``serve_forever()`` (typically on a thread) and ``shutdown()``s."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.service = service
    return httpd


# -- CLI ---------------------------------------------------------------------


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Serve an SDF checkpoint ensemble over HTTP")
    p.add_argument("--checkpoint_dirs", type=str, nargs="+", default=None,
                   help="member run dirs (required unless --pointer names "
                        "a promotion pointer to serve from)")
    p.add_argument("--pointer", type=str, default=None,
                   help="promotion control plane root (or the "
                        "serving_current.json file itself): boot from the "
                        "pointer's current generation, and /v1/reload with "
                        "no body re-reads it — so a replica restarted "
                        "mid-promotion converges to the pointer on boot")
    p.add_argument("--admin_port", type=int, default=None, metavar="PORT",
                   help="also serve this replica's API on a PRIVATE "
                        "127.0.0.1 port (not SO_REUSEPORT-shared): the "
                        "rolling-update path targets one replica's "
                        "/v1/reload and /metrics through it (0 picks a "
                        "free port, printed at startup)")
    p.add_argument("--data_dir", type=str, default=None,
                   help="panel dir; the serving macro history comes from "
                        "--macro_split (normalized with train stats)")
    p.add_argument("--macro_split", type=str, default="test",
                   choices=("train", "valid", "test"))
    p.add_argument("--macro_npy", type=str, default=None,
                   help="alternative to --data_dir: a .npy [T, M] macro "
                        "history, ALREADY normalized with train stats "
                        "(bench/test deployments)")
    p.add_argument("--server", type=str, default="async",
                   choices=("async", "threaded"),
                   help="'async' (default): asyncio event loop + "
                        "continuous batcher. 'threaded': DEPRECATED legacy "
                        "thread-per-request ThreadingHTTPServer + deadline "
                        "micro-batcher; kept one release for deliberate "
                        "migration, then removed")
    p.add_argument("--replicas", type=int, default=1,
                   help="serve from R supervisor-managed replica processes "
                        "sharing one SO_REUSEPORT socket (async only); a "
                        "crashed replica is restarted and degrades "
                        "capacity, not availability")
    p.add_argument("--replica_id", type=int, default=None,
                   help="internal: this process's index in a replica fleet")
    p.add_argument("--autoscale", action="store_true",
                   help="load-adaptive fleet (requires --replicas mode): a "
                        "control thread scrapes per-replica metrics and "
                        "grows/shrinks the SO_REUSEPORT replica set "
                        "between --min_replicas and --max_replicas with "
                        "hysteresis + cooldown; every scale event rewrites "
                        "fleet.json atomically")
    p.add_argument("--min_replicas", type=int, default=None,
                   help="autoscale floor (default: 1)")
    p.add_argument("--max_replicas", type=int, default=None,
                   help="autoscale ceiling (default: max(4, --replicas))")
    p.add_argument("--autoscale_up_depth", type=float, default=8.0,
                   help="scale up when mean pending per replica reaches "
                        "this for --autoscale_up_hysteresis ticks")
    p.add_argument("--autoscale_down_depth", type=float, default=1.0,
                   help="scale down when mean pending per replica stays "
                        "at/below this (and nothing is shed) for "
                        "--autoscale_down_hysteresis ticks")
    p.add_argument("--autoscale_up_hysteresis", type=int, default=2)
    p.add_argument("--autoscale_down_hysteresis", type=int, default=8)
    p.add_argument("--autoscale_poll_s", type=float, default=0.5)
    p.add_argument("--autoscale_cooldown_s", type=float, default=5.0,
                   help="minimum seconds between scale events (anti-flap, "
                        "with hysteresis)")
    p.add_argument("--reuse_port", action="store_true",
                   help="bind with SO_REUSEPORT (replica fleets share the "
                        "port)")
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8787)
    p.add_argument("--run_dir", type=str, default=None,
                   help="telemetry dir (manifest/events/heartbeat)")
    p.add_argument("--stock_buckets", type=str, default=None,
                   help="comma-separated stock-bucket ladder override "
                        "(default: powers of two capped at the panel size)")
    p.add_argument("--batch_buckets", type=str, default=None,
                   help="comma-separated batch-bucket ladder override")
    p.add_argument("--mesh", type=str, default=None, metavar="SPEC",
                   help="serve from a multi-device mesh instead of one "
                        "pinned device: a partition.parse_mesh_spec string "
                        "('stocks=4', 'stocks=-1' to fill every device, "
                        "'members=2,stocks=4', or a bare integer for the "
                        "stock axis). Every AOT forward program is lowered "
                        "with NamedSharding structs cutting the stock axis "
                        "(and optionally the ensemble member axis) across "
                        "the mesh; outputs match the single-device engine "
                        "to the stock-GSPMD tolerance contract")
    p.add_argument("--mesh_slices", type=int, default=None, metavar="N",
                   help="fleet mode: partition the visible devices into N "
                        "disjoint contiguous slices "
                        "(partition.slice_devices) and give replica i the "
                        "slice i %% N, so co-hosted replicas never touch "
                        "the same device; requires --mesh whose axes fit "
                        "one slice's width")
    p.add_argument("--mesh_slice", type=str, default=None, metavar="I:N",
                   help="internal: lay this replica's --mesh over device "
                        "slice I of N (written by the fleet parent from "
                        "--mesh_slices)")
    p.add_argument("--max_batch", type=int, default=None,
                   help="max requests per flush (default: largest batch "
                        "bucket)")
    p.add_argument("--max_queue", type=int, default=256,
                   help="bounded backpressure: pending requests beyond "
                        "this are rejected with HTTP 503")
    p.add_argument("--bulk_threshold", type=float, default=0.5,
                   help="DAGOR-style soft admission threshold: bulk-"
                        "priority requests are shed with HTTP 429 + "
                        "Retry-After once pending reaches this fraction "
                        "of --max_queue (interactive keeps the rest of "
                        "the queue)")
    p.add_argument("--no_coalesce", action="store_true",
                   help="disable single-flight request coalescing "
                        "(concurrent identical (month, universe, params "
                        "fingerprint) queries collapsing onto one "
                        "dispatch)")
    p.add_argument("--cache_size", type=int, default=256)
    p.add_argument("--reference_profile", type=str, default=None,
                   help="reference_profile.json (written at train/refit "
                        "time) to drift-score inference requests against; "
                        "default: the first serving member dir carrying "
                        "one. 'off' disables drift scoring entirely")
    p.add_argument("--drift_every", type=int, default=64,
                   help="PSI-score every K-th inference request's "
                        "characteristics against the reference profile")
    p.add_argument("--drift_psi_threshold", type=float, default=0.25,
                   help="PSI above this counts a drift alert "
                        "(dlap_model_drift_alerts_total; a burst of "
                        "alerts dumps the flight recorder)")
    p.add_argument("--max_delay_s", type=float, default=0.002,
                   help="deadline of the DEPRECATED threaded micro-batcher "
                        "(the continuous batcher has no deadline: it "
                        "flushes the moment the device frees up)")
    p.add_argument("--no_warmup", action="store_true",
                   help="skip AOT-compiling every bucket before accepting "
                        "traffic (first requests then pay compiles)")
    return p


def _load_macro(args, events):
    """(macro_history, macro_stats, n_stocks_cap) from --data_dir or
    --macro_npy (already normalized; no stats, no stock cap)."""
    if args.data_dir:
        # chunked panel reader: same bits as load_splits, shard-verified
        from ..data.pipeline import load_splits_chunked

        splits = dict(zip(("train", "valid", "test"),
                          load_splits_chunked(args.data_dir, events=events)))
        ds = splits[args.macro_split]
        train = splits["train"]
        n_max = max(s.N for s in splits.values())
        return ds.macro, (train.mean_macro, train.std_macro), n_max
    if args.macro_npy:
        return np.load(args.macro_npy), None, None
    return None, None, None


def _parse_buckets(spec: Optional[str]) -> Optional[Tuple[int, ...]]:
    if not spec:
        return None
    return tuple(int(x) for x in spec.split(",") if x.strip())


def main(argv=None):
    from ..observability import RunLogger, set_run_logger
    from ..utils.platform import apply_env_platforms

    args = build_arg_parser().parse_args(argv)
    if not args.checkpoint_dirs and not args.pointer:
        print("serving.server: pass --checkpoint_dirs or --pointer",
              file=sys.stderr)
        return 2
    if args.replicas > 1 or args.autoscale:
        # the fleet parent never initializes a backend: it only spawns and
        # supervises replica children (each a fresh `--replica_id i` run of
        # this CLI on a shared SO_REUSEPORT socket). --autoscale implies
        # fleet mode even at --replicas 1: a fleet of one that can grow
        from .fleet import main_from_server_args

        return main_from_server_args(args)

    apply_env_platforms()
    # SIGTERM (fleet stop / plain `kill`) must be a CLEAN shutdown — the
    # close() path writes the final metrics.prom snapshot, the flight-
    # recorder dump, and the terminal heartbeat — so route it through the
    # same KeyboardInterrupt handling as Ctrl-C instead of dying before
    # the finally blocks run
    import signal as _signal

    _svc_holder: Dict[str, Any] = {}

    def _on_sigterm(signum, frame):  # noqa: ARG001 — signal-handler shape
        svc = _svc_holder.get("service")
        if svc is not None:
            svc._shutdown_reason = "sigterm"
        raise KeyboardInterrupt

    def _on_flare(signum, frame):  # noqa: ARG001 — signal-handler shape
        # the supervisor's pre-kill flare (RestartPolicy.prekill_signal):
        # a stale-heartbeat replica gets one grace window to dump its
        # flight recorder before the SIGKILL lands — last words, not a
        # recovery attempt. The dump runs on a FRESH thread: the handler
        # interrupts the main thread mid-bytecode, which may be holding
        # the recorder's (non-reentrant) lock — dumping inline could
        # self-deadlock exactly when the flare matters most
        svc = _svc_holder.get("service")
        if svc is not None:
            threading.Thread(target=svc.flight.dump, args=("watchdog",),
                             daemon=True, name="flare-dump").start()

    _signal.signal(_signal.SIGTERM, _on_sigterm)
    _signal.signal(_signal.SIGUSR1, _on_flare)
    events = EventLog(args.run_dir) if args.run_dir else EventLog()
    set_run_logger(RunLogger(events=events))
    macro_history, macro_stats, n_max = _load_macro(args, events)

    checkpoint_dirs = args.checkpoint_dirs
    boot_pointer = None
    if args.pointer and not checkpoint_dirs:
        # boot from the promotion pointer's current generation. Best
        # effort by design: the verified read falls back a pointer
        # generation past a torn newest write, and the member load path
        # falls back params generations — a replica must come up and
        # serve SOMETHING; strict digest enforcement belongs to the
        # /v1/reload hot-swap path, where an incumbent is still serving
        from ..reliability.promotion import read_pointer

        boot_pointer = read_pointer(args.pointer)
        if boot_pointer is None:
            print(f"serving.server: no promotion pointer under "
                  f"{args.pointer}", file=sys.stderr)
            return 2
        checkpoint_dirs = boot_pointer["checkpoint_dirs"]

    stock_buckets = _parse_buckets(args.stock_buckets)
    if stock_buckets is None:
        # cap the bucket ladder at the loaded panel's stock count: warmup
        # then compiles only programs this deployment can actually hit,
        # instead of the full default ladder up to 16k stocks
        from .engine import DEFAULT_STOCK_BUCKETS

        if n_max is not None:
            top = bucket_for(n_max, DEFAULT_STOCK_BUCKETS)
            stock_buckets = tuple(
                b for b in DEFAULT_STOCK_BUCKETS if b <= top)
    batch_buckets = _parse_buckets(args.batch_buckets)

    engine_kwargs: Dict[str, Any] = dict(
        macro_history=macro_history, macro_stats=macro_stats, events=events)
    if stock_buckets is not None:
        engine_kwargs["stock_buckets"] = stock_buckets
    if batch_buckets is not None:
        engine_kwargs["batch_buckets"] = batch_buckets
    if args.mesh:
        # mesh-native serving: lay the AOT programs over a named device
        # grid. With --mesh_slice I:N (stamped by the fleet parent from
        # --mesh_slices) the grid is restricted to this replica's disjoint
        # contiguous device slice — the same lease contract the sweep
        # scheduler uses — so co-hosted replicas never share a chip
        from ..parallel import partition

        mesh_cfg = partition.parse_mesh_spec(args.mesh)
        if args.mesh_slice:
            import jax

            try:
                idx, n_slices = (int(x)
                                 for x in args.mesh_slice.split(":", 1))
            except ValueError:
                print(f"serving.server: --mesh_slice must be I:N, got "
                      f"{args.mesh_slice!r}", file=sys.stderr)
                return 2
            devs = partition.slice_devices(idx, n_slices,
                                           devices=jax.devices())
            mesh_cfg = partition.MeshConfig(mesh_cfg.axes, devs)
        engine_kwargs["mesh"] = mesh_cfg
    engine = InferenceEngine(checkpoint_dirs, **engine_kwargs)
    # resolve the drift reference profile: explicit path wins; 'off'
    # disables; default = the first serving member dir carrying one (the
    # train/refit CLIs write reference_profile.json next to every
    # checkpoint, so a pointer-booted replica finds its own)
    reference_profile = None
    if args.reference_profile not in (None, "off"):
        from ..observability.drift import read_profile

        reference_profile = read_profile(args.reference_profile)
        if reference_profile is None:
            # an EXPLICITLY configured profile must not silently disable
            # the drift monitor the operator asked for (auto-discovery
            # below stays tolerant by design)
            print(f"serving.server: --reference_profile "
                  f"{args.reference_profile} is missing or unreadable",
                  file=sys.stderr)
            return 2
    elif args.reference_profile is None:
        from ..observability.drift import read_profile

        for d in checkpoint_dirs:
            reference_profile = read_profile(d)
            if reference_profile is not None:
                break
    service = ServingService(
        engine, run_dir=args.run_dir, max_batch=args.max_batch,
        max_delay_s=args.max_delay_s, max_queue=args.max_queue,
        cache_size=args.cache_size, events=events, mode=args.server,
        replica_id=args.replica_id, pointer_root=args.pointer,
        coalesce=not args.no_coalesce, bulk_threshold=args.bulk_threshold,
        reference_profile=reference_profile,
        drift_every=args.drift_every,
        drift_psi_threshold=args.drift_psi_threshold)
    _svc_holder["service"] = service
    if boot_pointer is not None:
        # the boot row of the convergence timeline: this replica came up
        # serving the pointer's generation (a replica that died
        # mid-promotion re-enters here and converges without a reload)
        events.counter(
            "serve/generation", replica=service.replica_label,
            fingerprint=engine.params_fingerprint[:16],
            generation=engine.params_generation,
            pointer_generation=boot_pointer["generation"],
            swapped=None, boot=True)
    if not args.no_warmup:
        n = service.warmup()
        print(f"warmed {n} forward programs "
              f"(buckets {list(engine.stock_buckets)})", flush=True)

    if args.server == "threaded":
        print("WARNING: --server threaded is DEPRECATED (thread-per-request "
              "+ deadline micro-batching); migrate to --server async",
              file=sys.stderr, flush=True)
        httpd = make_server(service, args.host, args.port)
        host, port = httpd.server_address[:2]
        service.accepting = True
        if service.heartbeat is not None:
            service.heartbeat.beat("serve/accepting")
        print(f"serving {engine.n_members} members on http://{host}:{port} "
              f"(config {engine.config_hash[:12]})", flush=True)
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.shutdown()
            service.close()
            events.close()
        return 0

    from .aserver import run_async_server

    try:
        run_async_server(service, args.host, args.port,
                         reuse_port=args.reuse_port,
                         admin_port=args.admin_port)
    except KeyboardInterrupt:
        pass
    finally:
        service.close()
        events.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
