"""Checkpoint promotion control plane: the gated path from "a refit
finished" to "the fleet serves it".

PR 6's ``InferenceEngine.reload()`` will hot-swap *any* checkpoint into a
live engine; this module is the gate in front of it. A **candidate** (K
member run dirs, e.g. one rolling-refit month) is promoted into serving
only after it passes the gate:

  1. **digest verification** — every member's ``config.json`` parses and
     its params artifact's bytes match the ``.sha256`` sidecar
     (:mod:`reliability.verified`). A torn or bit-rotted candidate is
     rejected here, before any deserialization; candidates never fall back
     a generation — the incumbent keeps serving instead.
  2. **architecture compatibility** — the candidate's config hash must
     equal the serving config's (the fleet's AOT programs only serve the
     architecture they were lowered for).
  3. **paper-protocol validation pass** — the stacked ensemble's params
     must be finite; against a validation batch, the served weights and
     SDF must be finite and the validation Sharpe within a configurable
     tolerance of the incumbent's (a regressed refit is rejected, not
     served).

On pass the **promotion pointer** — ``serving_current.json`` under the
control-plane root — atomically advances (``reliability.verified``: tmp +
``os.replace`` + sha256 sidecar + ``.g1`` rotation) to the candidate, with
the previous head retained in an embedded ``history`` list. Promotion is
crash-consistent: a kill at ANY point (the ``promote/validate`` and
``promote/write`` fault sites, or inside the verified write itself) leaves
either the old or the new pointer on disk, never a torn one — asserted by
the tier-1 kill-at-every-site matrix. :func:`rollback` reverts the pointer
to the previous history entry the same atomic way.

The pointer also records each member's exact artifact digest, so a reload
driven from the pointer (``serving/server.py /v1/reload``) can verify it
is swapping in the bytes the gate validated — a member torn AFTER
promotion fails the reload instead of half-swapping a mixed ensemble.

Module level stays stdlib-only (like ``ledger.py``/``verified.py``): the
report CLI and thin fleet parents read pointers without paying the jax
import; the validation pass imports jax lazily.
"""

from __future__ import annotations

import hashlib
import json
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from .faults import inject
from .verified import check_digest, load_verified, verified_exists, write_verified

POINTER_FILENAME = "serving_current.json"
DEFAULT_SHARPE_TOLERANCE = 0.05
DEFAULT_HISTORY_KEEP = 8

# the pointer-head fields a history entry retains (history entries never
# nest their own history)
_HEAD_KEYS = (
    "generation", "checkpoint_dirs", "config_hash", "params_fingerprint",
    "valid_sharpe", "moment_violation_max", "drift_max_psi", "source",
    "promoted_at", "members", "rolled_back_from",
)


class PromotionError(RuntimeError):
    """The control plane itself is unusable (no pointer to roll back to,
    malformed root, ...) — distinct from a candidate failing the gate."""


class GateRejection(PromotionError):
    """The candidate failed the gate; ``reason`` is a stable slug the
    report CLI buckets rejections by."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"candidate rejected ({reason})"
                         + (f": {detail}" if detail else ""))
        self.reason = reason
        self.detail = detail


def pointer_path(root: Union[str, Path]) -> Path:
    """``root`` is the control-plane directory (or the pointer file
    itself, for callers holding a direct path)."""
    root = Path(root)
    return root if root.name.endswith(".json") else root / POINTER_FILENAME


def read_pointer(root: Union[str, Path]) -> Optional[Dict[str, Any]]:
    """The current promotion pointer, digest-verified, falling back a
    generation past a torn newest write (``reliability.verified``); None
    when no pointer exists yet. Raises ``ValueError`` when every
    generation is unusable — serving must not guess."""
    path = pointer_path(root)
    if not verified_exists(path):
        return None

    def parse(data: bytes) -> Dict[str, Any]:
        try:
            obj = json.loads(data.decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(f"corrupt promotion pointer {path}: {e}") from e
        if not isinstance(obj, dict) or "checkpoint_dirs" not in obj:
            raise ValueError(
                f"promotion pointer {path} carries no checkpoint_dirs")
        return obj

    pointer, _ = load_verified(path, parse)
    return pointer


def write_pointer(
    root: Union[str, Path],
    head: Dict[str, Any],
    history_keep: int = DEFAULT_HISTORY_KEEP,
) -> Dict[str, Any]:
    """Advance the pointer to ``head`` atomically, stamping the next
    generation number and folding the previous head into ``history``
    (newest first, bounded). The ``promote/write`` fault site fires with
    the previous pointer still intact; the write itself is a
    ``reliability.verified`` tmp+replace, so a kill anywhere leaves either
    the old or the new pointer — never a torn one."""
    path = pointer_path(root)
    prev = read_pointer(root)
    pointer = dict(head)
    pointer["kind"] = "serving_pointer"
    pointer["generation"] = (int(prev["generation"]) + 1) if prev else 1
    history: List[Dict[str, Any]] = []
    if prev is not None:
        history.append({k: prev[k] for k in _HEAD_KEYS if k in prev})
        history.extend(prev.get("history") or [])
    pointer["history"] = history[:history_keep]
    inject("promote/write", path=str(path), generation=pointer["generation"])
    write_verified(path, json.dumps(pointer, indent=2).encode())
    return pointer


# -- candidate verification ---------------------------------------------------


def member_artifact_path(member_dir: Union[str, Path],
                         which: str = "best_model_sharpe") -> Path:
    return Path(member_dir) / f"{which}.msgpack"


def verify_member_dirs(
    checkpoint_dirs: Sequence[Union[str, Path]],
    which: str = "best_model_sharpe",
) -> Tuple[List[Dict[str, Any]], Optional[Tuple[str, str]]]:
    """Stdlib-only gate stage 1: every member's config parses and its
    params artifact digest-verifies (CURRENT generation only — a torn
    candidate is a rejection, not a fallback). Returns
    ``(members, rejection)`` where members carry each artifact's exact
    sha256 (recorded into the pointer for reload-time verification) and
    rejection is ``(reason, detail)`` or None."""
    members: List[Dict[str, Any]] = []
    for d in checkpoint_dirs:
        d = Path(d)
        cfg_path = d / "config.json"
        try:
            json.loads(cfg_path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            return members, ("config_unreadable", f"{cfg_path}: {e}")
        art = member_artifact_path(d, which)
        if not art.exists():
            return members, ("missing_member", f"{art} does not exist")
        data = art.read_bytes()
        digest = hashlib.sha256(data).hexdigest()
        ok, why = check_digest(art, data, digest=digest)
        if not ok:
            return members, ("digest_mismatch", f"{art}: {why}")
        members.append({
            "dir": str(d),
            "file": art.name,
            "sha256": digest,
            "bytes": len(data),
        })
    return members, None


def verify_pointer_members(pointer: Dict[str, Any]) -> List[str]:
    """Reload-time check: do the on-disk member artifacts still hold the
    exact bytes the gate validated? Returns a list of mismatch
    descriptions (empty = verified). This is what stops a reload from
    half-swapping a mixed ensemble when a member was torn AFTER
    promotion: the reload fails whole, the engine keeps serving the
    incumbent, and the health gate rolls the pointer back."""
    errors: List[str] = []
    for m in pointer.get("members") or []:
        path = Path(m["dir"]) / m["file"]
        try:
            data = path.read_bytes()
        except OSError as e:
            errors.append(f"{path}: unreadable ({e})")
            continue
        got = hashlib.sha256(data).hexdigest()
        if got != m["sha256"]:
            errors.append(
                f"{path}: sha256 {got[:12]}… != promoted {m['sha256'][:12]}…")
    return errors


def evaluate_candidate(
    checkpoint_dirs: Sequence[str],
    valid_batch: Optional[Dict[str, Any]] = None,
    which: str = "best_model_sharpe",
    with_moments: bool = False,
) -> Dict[str, Any]:
    """Gate stage 2 (jax, imported lazily): stack the candidate ensemble,
    check every params leaf is finite, and — when a validation batch is
    given — run the exact paper-protocol ensemble reduction
    (``parallel.ensemble.ensemble_metrics``) to check the served weights
    and SDF are finite and measure the validation Sharpe.

    ``with_moments``: additionally compute the model-health diagnostics
    (``observability.modelhealth.candidate_diagnostics`` — member-vmapped,
    worst case over members): the per-moment conditional violation norms
    the ``moment_violation`` gate thresholds. Computed even for
    non-finite params (the violations are then non-finite, which is
    exactly the evidence the gate needs)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..evaluate_ensemble import stack_checkpoints
    from ..observability.manifest import config_hash
    from ..serving.engine import params_digest

    gan, vparams = stack_checkpoints([str(d) for d in checkpoint_dirs], which)
    finite_params = bool(all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in jax.tree.leaves(vparams)))
    out: Dict[str, Any] = {
        "config_hash": config_hash(gan.cfg),
        "params_fingerprint": params_digest(vparams),
        "finite_params": finite_params,
        "finite_outputs": None,
        "valid_sharpe": None,
        "moment_violation_max": None,
        "moment_violations": None,
        "sdf_finite_frac": None,
    }
    if valid_batch is not None and with_moments:
        from ..observability.modelhealth import candidate_diagnostics

        # n_assets rides along: a stock-padded validation panel must not
        # dilute the violation norms the tolerance gates (the same
        # correction every loss in ops/losses.py takes)
        batch = {k: jnp.asarray(v) for k, v in valid_batch.items()
                 if k in ("macro", "individual", "returns", "mask",
                          "n_assets")}
        diag = candidate_diagnostics(gan, vparams, batch)
        out["moment_violation_max"] = diag["moment_violation_max"]
        out["moment_violations"] = diag["moment_violations"]
        out["sdf_finite_frac"] = diag["sdf_finite_frac"]
    if valid_batch is not None and finite_params:
        from ..parallel.ensemble import ensemble_metrics

        batch = {k: jnp.asarray(v) for k, v in valid_batch.items()}
        metrics = ensemble_metrics(gan, vparams, batch)
        weights = np.asarray(metrics["avg_weights"])
        port = np.asarray(metrics["ensemble_port_returns"])
        sharpe = float(metrics["ensemble_sharpe"])
        out["finite_outputs"] = bool(
            np.isfinite(weights).all() and np.isfinite(port).all()
            and np.isfinite(sharpe))
        out["valid_sharpe"] = sharpe if out["finite_outputs"] else None
    return out


# -- the gate -----------------------------------------------------------------


def _counter(events, name: str, **attrs: Any) -> None:
    if events is not None:
        events.counter(name, **attrs)


def candidate_reference_profile(
    checkpoint_dirs: Sequence[str],
    reference_profile: Optional[Union[str, Path, Dict[str, Any]]] = None,
) -> Optional[Dict[str, Any]]:
    """Resolve the reference profile the drift gate scores against: an
    explicit dict/path wins; otherwise the first member dir carrying a
    ``reference_profile.json`` (written at train/refit time — the
    fingerprint of the data the candidate learned from)."""
    from ..observability.drift import read_profile

    if isinstance(reference_profile, dict):
        return reference_profile
    if reference_profile is not None:
        return read_profile(reference_profile)
    for d in checkpoint_dirs:
        profile = read_profile(d)
        if profile is not None:
            return profile
    return None


def promote(
    root: Union[str, Path],
    checkpoint_dirs: Sequence[str],
    valid_batch: Optional[Dict[str, Any]] = None,
    source: Optional[str] = None,
    expect_config_hash: Optional[str] = None,
    sharpe_tolerance: Optional[float] = DEFAULT_SHARPE_TOLERANCE,
    which: str = "best_model_sharpe",
    history_keep: int = DEFAULT_HISTORY_KEEP,
    events=None,
    moment_tolerance: Optional[float] = None,
    drift_threshold: Optional[float] = None,
    reference_profile: Optional[Union[str, Path, Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Run the candidate through the gate; on pass, atomically advance the
    promotion pointer and return it. Raises :class:`GateRejection` (with a
    stable ``reason``) on any gate failure — the pointer is then untouched
    and the fleet keeps serving the incumbent.

    ``expect_config_hash`` pins the serving architecture explicitly; when
    None, the incumbent pointer's hash is the contract (a first promotion
    with neither accepts any self-consistent architecture).
    ``sharpe_tolerance=None`` disables the regression gate (the Sharpe is
    still measured and recorded when a validation batch is given).

    Model-health gates (both opt-in; require a validation batch):

    * ``moment_tolerance`` — reject with reason ``moment_violation`` when
      the candidate's worst per-moment conditional violation norm
      (``E[h_j · w·R · M]``, member-vmapped worst case) is non-finite or
      exceeds the tolerance. Runs BEFORE the finite-params check, so a
      degenerate candidate is attributed to the moment conditions it
      breaks, not just to its NaN leaves.
    * ``drift_threshold`` — reject with reason ``data_drift`` when the
      validation panel's PSI against the candidate's reference profile
      (``reference_profile.json`` written at train/refit time, or the
      explicit ``reference_profile``) exceeds the threshold: the refit
      learned from data that no longer looks like what it will serve.
      Skipped (recorded as None) when no profile is resolvable."""
    # the ONE finite-float coercion shared with the health plane (lazy:
    # modelhealth's module level is stdlib-only, but importing it still
    # runs the observability package __init__ — not a module-level cost
    # the pointer-reading thin parents should pay)
    from ..observability.modelhealth import _finite_or_none as _finite

    dirs = [str(d) for d in checkpoint_dirs]
    src = source or ";".join(Path(d).name for d in dirs)
    inject("promote/validate", path=src, n_members=len(dirs))

    def reject(reason: str, detail: str = "") -> None:
        _counter(events, "promote/reject", reason=reason, source=src)
        raise GateRejection(reason, detail)

    if not dirs:
        reject("missing_member", "no candidate checkpoint dirs")
    incumbent = read_pointer(root)
    members, rejection = verify_member_dirs(dirs, which)
    if rejection is not None:
        reject(*rejection)
    try:
        evaluation = evaluate_candidate(
            dirs, valid_batch, which,
            with_moments=moment_tolerance is not None)
    except (ValueError, FileNotFoundError) as e:
        # architecture mismatch AMONG members, or an artifact whose every
        # generation is unusable — stack_checkpoints says which
        reject("stack_error", str(e))
    expected = expect_config_hash or (
        incumbent.get("config_hash") if incumbent else None)
    if expected and evaluation["config_hash"] != expected:
        reject("architecture_mismatch",
               f"candidate config {evaluation['config_hash'][:12]}… != "
               f"serving {expected[:12]}…")
    if moment_tolerance is not None and valid_batch is not None:
        # THE threshold decision lives in modelhealth.HealthThresholds
        # (shared with the report tooling); this block only composes the
        # rejection detail
        from ..observability.modelhealth import HealthThresholds

        thresholds = HealthThresholds(
            moment_tolerance=float(moment_tolerance))
        if "moment_violation" in thresholds.classify(evaluation):
            mv = _finite(evaluation.get("moment_violation_max"))
            frac = _finite(evaluation.get("sdf_finite_frac"))
            if mv is None or frac is None or frac < 1.0:
                reject("moment_violation",
                       "candidate per-moment violations / SDF series are "
                       "non-finite on the validation batch")
            reject("moment_violation",
                   f"max per-moment conditional violation {mv:.6f} > "
                   f"tolerance {float(moment_tolerance):.6f}")
    drift_max_psi = None
    if drift_threshold is not None and valid_batch is not None:
        profile = candidate_reference_profile(dirs, reference_profile)
        if profile is not None:
            from ..observability.drift import drift_report

            report = drift_report(profile, valid_batch)
            drift_max_psi = report["max_psi"]
            if drift_max_psi is not None \
                    and drift_max_psi > float(drift_threshold):
                worst = max(
                    (d["psi"], name)
                    for name, d in report["per_series"].items()
                    if d["psi"] is not None)
                reject("data_drift",
                       f"max PSI {drift_max_psi:.4f} > threshold "
                       f"{float(drift_threshold):.4f} (worst series "
                       f"{worst[1]}; panel has drifted from the "
                       "candidate's training data)")
    if not evaluation["finite_params"]:
        reject("nonfinite_params",
               "candidate params contain NaN/Inf leaves")
    if evaluation["finite_outputs"] is False:
        reject("nonfinite_outputs",
               "candidate weights/SDF non-finite on the validation batch")
    if (sharpe_tolerance is not None and incumbent is not None
            and incumbent.get("valid_sharpe") is not None
            and evaluation["valid_sharpe"] is not None
            and evaluation["valid_sharpe"]
            < float(incumbent["valid_sharpe"]) - float(sharpe_tolerance)):
        reject("sharpe_regression",
               f"candidate valid Sharpe {evaluation['valid_sharpe']:.4f} < "
               f"incumbent {float(incumbent['valid_sharpe']):.4f} - "
               f"tolerance {float(sharpe_tolerance):.4f}")

    pointer = write_pointer(root, {
        "checkpoint_dirs": dirs,
        "config_hash": evaluation["config_hash"],
        "params_fingerprint": evaluation["params_fingerprint"],
        "valid_sharpe": evaluation["valid_sharpe"],
        "moment_violation_max": _finite(
            evaluation.get("moment_violation_max")),
        "drift_max_psi": drift_max_psi,
        "source": src,
        "promoted_at": round(time.time(), 3),
        "members": members,
    }, history_keep=history_keep)
    _counter(events, "promote/advance", generation=pointer["generation"],
             source=src, fingerprint=pointer["params_fingerprint"][:16],
             sharpe=pointer["valid_sharpe"])
    return pointer


def rollback(
    root: Union[str, Path],
    reason: str = "",
    history_keep: int = DEFAULT_HISTORY_KEEP,
    events=None,
) -> Dict[str, Any]:
    """Revert the pointer to the previous history entry (same atomic
    write; the bad head joins the history with ``rolled_back_from`` set so
    the audit trail survives). Raises :class:`PromotionError` when there
    is nothing to roll back to."""
    current = read_pointer(root)
    if current is None:
        raise PromotionError(f"no promotion pointer under {root}")
    history = current.get("history") or []
    if not history:
        raise PromotionError(
            f"pointer generation {current.get('generation')} has no "
            "previous generation to roll back to")
    prev = history[0]
    head = {k: prev[k] for k in _HEAD_KEYS
            if k in prev and k not in ("generation", "rolled_back_from")}
    head["rolled_back_from"] = current.get("generation")
    head["rollback_reason"] = reason
    pointer = write_pointer(root, head, history_keep=history_keep)
    _counter(events, "promote/rollback",
             generation=pointer["generation"],
             rolled_back_from=current.get("generation"),
             fingerprint=str(pointer.get("params_fingerprint"))[:16],
             reason=reason)
    return pointer


# -- CLI (used by the refit pipeline and the tier-1 kill matrix) -------------


def _load_valid_npz(path: str) -> Dict[str, Any]:
    import numpy as np

    with np.load(path, allow_pickle=False) as f:
        return {k: np.asarray(f[k]) for k in f.files}


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m deeplearninginassetpricing_paperreplication_tpu"
             ".reliability.promotion",
        description="Gate a candidate checkpoint ensemble into the "
                    "promotion pointer (promote), revert it (rollback), "
                    "or print it (show)")
    sub = p.add_subparsers(dest="cmd", required=True)
    pr = sub.add_parser("promote")
    pr.add_argument("--root", required=True,
                    help="control-plane dir holding serving_current.json")
    pr.add_argument("--candidates", nargs="+", required=True,
                    help="member checkpoint run dirs")
    pr.add_argument("--valid_npz", default=None,
                    help=".npz with individual/returns/mask (+macro) arrays "
                         "— the validation batch for the finite-SDF and "
                         "Sharpe checks")
    pr.add_argument("--source", default=None)
    pr.add_argument("--expect_config_hash", default=None)
    pr.add_argument("--sharpe_tolerance", type=float,
                    default=DEFAULT_SHARPE_TOLERANCE,
                    help="negative disables the regression gate")
    pr.add_argument("--moment_tolerance", type=float, default=None,
                    help="model-health gate: reject (reason "
                         "moment_violation) when the candidate's worst "
                         "per-moment conditional violation norm exceeds "
                         "this, or is non-finite (requires --valid_npz)")
    pr.add_argument("--drift_threshold", type=float, default=None,
                    help="data-drift gate: reject (reason data_drift) "
                         "when the validation panel's max PSI against the "
                         "candidate's reference_profile.json exceeds this "
                         "(0.25 is the standard significant-shift bar; "
                         "requires --valid_npz)")
    pr.add_argument("--reference_profile", type=str, default=None,
                    help="explicit reference_profile.json path for the "
                         "drift gate (default: the first member dir "
                         "carrying one)")
    rb = sub.add_parser("rollback")
    rb.add_argument("--root", required=True)
    rb.add_argument("--reason", default="")
    sh = sub.add_parser("show")
    sh.add_argument("--root", required=True)
    args = p.parse_args(argv)

    if args.cmd == "show":
        pointer = read_pointer(args.root)
        print(json.dumps(pointer, indent=2))
        return 0 if pointer is not None else 1
    if args.cmd == "rollback":
        pointer = rollback(args.root, reason=args.reason)
        print(json.dumps({"generation": pointer["generation"],
                          "rolled_back_from": pointer.get(
                              "rolled_back_from")}))
        return 0
    valid_batch = (_load_valid_npz(args.valid_npz)
                   if args.valid_npz else None)
    tol = (None if args.sharpe_tolerance is not None
           and args.sharpe_tolerance < 0 else args.sharpe_tolerance)
    try:
        pointer = promote(
            args.root, args.candidates, valid_batch=valid_batch,
            source=args.source, expect_config_hash=args.expect_config_hash,
            sharpe_tolerance=tol,
            moment_tolerance=args.moment_tolerance,
            drift_threshold=args.drift_threshold,
            reference_profile=args.reference_profile)
    except GateRejection as e:
        print(json.dumps({"rejected": e.reason, "detail": e.detail}))
        return 1
    print(json.dumps({"generation": pointer["generation"],
                      "params_fingerprint":
                          pointer["params_fingerprint"][:16],
                      "valid_sharpe": pointer["valid_sharpe"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
