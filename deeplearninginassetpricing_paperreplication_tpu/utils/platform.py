"""Make `JAX_PLATFORMS` from the environment actually stick for CLI runs.

Some images (this build environment included) install a sitecustomize that
registers a remote-TPU JAX plugin and pins the platform at interpreter
start, so the documented `JAX_PLATFORMS=cpu python -m ...` override silently
loses — the CLI then hangs or fails on an unreachable tunnel instead of
running on CPU. Every CLI entry point calls `apply_env_platforms()` before
touching a device, re-applying the user's env choice through jax.config
(which wins over the plugin's pin; the same workaround tests/conftest.py
uses for the test lane).
"""

from __future__ import annotations

import os


def apply_env_platforms() -> None:
    val = os.environ.get("JAX_PLATFORMS")
    if val:
        import jax

        jax.config.update("jax_platforms", val)
