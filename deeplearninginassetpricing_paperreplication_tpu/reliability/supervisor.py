"""Supervised execution: spawn an entrypoint, watch its heartbeat, restart it.

``bench.py``'s parent already solved death attribution for remote-attached
TPUs (heartbeat-timed sections, SIGKILL on hang — SIGTERM is ignored inside
tunnel RPCs — and restart with backoff). This module generalizes that loop
to ANY entrypoint that writes the bench-format heartbeat file (the training
CLI, the sweep CLI, the serving server — all of them do, via
``observability.heartbeat``):

  * **hang detection** — the child's heartbeat file goes stale past
    ``heartbeat_timeout_s`` → SIGKILL the child's whole process group, and
    attribute the hang to the section the last beat named;
  * **death attribution** — any death mode (raise, OOM-kill, hang) is
    attributed to the last heartbeat section, logged as a
    ``supervise/restart`` counter in ``events.supervisor.jsonl``;
  * **restart policy** — exponential backoff with jitter; a restart appends
    the resume flag matching the state the run dir holds: ``--resume``
    for a trainer resume state, ``--resume-from-ledger`` for a sweep
    bucket ledger (``sweep_ledger/queue.json``), so the child continues
    from its last verified checkpoint — or last completed bucket — instead
    of from scratch (children that write neither, e.g. the serving server,
    restart with their original argv);
  * **crash-loop detection** — a child that dies within ``min_uptime_s`` of
    spawn counts as a fast death; ``max_restarts`` CONSECUTIVE fast deaths
    end the run with outcome ``crash-loop`` (a child that survives past
    ``min_uptime_s`` resets the counter — it made progress).

CLI: ``python -m deeplearninginassetpricing_paperreplication_tpu.supervise
--run_dir DIR -- python -m ...train --data_dir ... --save_dir DIR``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import signal
import subprocess
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

try:
    from ..observability.events import EventLog
    from ..observability.heartbeat import read_state, staleness_s, write_state
    from ..observability.metrics import MetricsSidecar
    from .faults import ENV_EVENTS, ENV_PLAN, ENV_STATE
except ImportError:
    # Loaded OUTSIDE the package — by path, or executed directly as
    # `python .../reliability/supervisor.py` (the thin, cannot-hang entry
    # for when the jax stack itself is wedged: `python -m ...supervise`
    # pays the package __init__'s jax import, this path does not). The
    # three dependencies are stdlib-only at module level by contract, so
    # they path-load the same way bench.py's parent loads heartbeat.py.
    import importlib.util as _ilu
    from pathlib import Path as _P

    def _load_by_path(name, path):
        spec = _ilu.spec_from_file_location(name, path)
        mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    _here = _P(__file__).resolve().parent
    _hb = _load_by_path("_dlap_heartbeat", _here.parent / "observability" / "heartbeat.py")
    _ev = _load_by_path("_dlap_events", _here.parent / "observability" / "events.py")
    _mx = _load_by_path("_dlap_metrics_sidecar",
                        _here.parent / "observability" / "metrics.py")
    _fa = _load_by_path("_dlap_faults", _here / "faults.py")
    EventLog = _ev.EventLog
    MetricsSidecar = _mx.MetricsSidecar
    read_state, staleness_s, write_state = (
        _hb.read_state, _hb.staleness_s, _hb.write_state)
    ENV_EVENTS, ENV_PLAN, ENV_STATE = _fa.ENV_EVENTS, _fa.ENV_PLAN, _fa.ENV_STATE

SUPERVISOR_EVENTS_FILENAME = "events.supervisor.jsonl"


@dataclasses.dataclass
class RestartPolicy:
    """Everything the supervise loop decides from."""

    heartbeat_timeout_s: float = 300.0
    poll_s: float = 2.0
    max_restarts: int = 5          # consecutive fast deaths → crash-loop
    min_uptime_s: float = 60.0     # uptime that counts as progress
    max_total_restarts: int = 50   # absolute cap (slow-death loops)
    backoff_base_s: float = 5.0
    backoff_max_s: float = 300.0
    jitter_frac: float = 0.2
    auto_resume: bool = True
    resume_flag: str = "--resume"
    # sweep semantics: a restarted sweep child resumes from its bucket
    # LEDGER (reliability/ledger.py), not a trainer checkpoint — detected
    # by the run dir holding sweep_ledger/queue.json
    ledger_resume_flag: str = "--resume-from-ledger"
    # pre-kill flare: send this signal (e.g. SIGUSR1) to the child's
    # process group and wait prekill_grace_s BEFORE the SIGKILL on a stale
    # heartbeat — a serving replica's handler dumps its flight recorder
    # ("last words") in the grace window. None (the default) keeps the
    # immediate-SIGKILL behavior for children that install no handler
    # (SIGUSR1's default disposition would just kill them earlier).
    prekill_signal: Optional[int] = None
    prekill_grace_s: float = 0.75

    def backoff_s(self, consecutive_failures: int, rng=random.random) -> float:
        base = min(
            self.backoff_max_s,
            self.backoff_base_s * (2 ** max(0, consecutive_failures - 1)),
        )
        return base * (1.0 + self.jitter_frac * rng())


def kill_process_group(proc: subprocess.Popen, wait_s: float = 30.0) -> None:
    """SIGKILL the child's whole process group (SIGTERM is ignored by
    processes blocked in tunnel RPCs — the documented outage behavior)."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    try:
        proc.wait(timeout=wait_s)
    except subprocess.TimeoutExpired:
        pass


class Supervisor:
    """One supervised child command + its restart loop."""

    def __init__(
        self,
        cmd: Sequence[str],
        heartbeat_path: Path,
        policy: Optional[RestartPolicy] = None,
        events: Optional[EventLog] = None,
        log_path: Optional[Path] = None,
        env: Optional[Dict[str, str]] = None,
    ):
        self.cmd = list(cmd)
        self.heartbeat_path = Path(heartbeat_path)
        self.policy = policy if policy is not None else RestartPolicy()
        # process_index pinned to 0: the supervisor must never touch a JAX
        # backend (EventLog would otherwise probe jax.process_index())
        self.events = events if events is not None else EventLog(
            process_index=0)
        self.log_path = Path(log_path) if log_path else None
        self.env = env
        self._proc: Optional[subprocess.Popen] = None
        self._stop_requested = False

    # -- public ---------------------------------------------------------------

    def request_stop(self) -> None:
        """Signal-handler hook: kill the child and end the loop."""
        self._stop_requested = True
        if self._proc is not None and self._proc.poll() is None:
            kill_process_group(self._proc)

    @property
    def child_pid(self) -> Optional[int]:
        """The LIVE child's pid (None between incarnations or after
        exit) — detection drills signal the child directly (SIGKILL /
        SIGSTOP) without going through the restart loop."""
        proc = self._proc
        if proc is not None and proc.poll() is None:
            return proc.pid
        return None

    def run(self) -> Dict[str, Any]:
        """Supervise until success, crash-loop, restart exhaustion, or an
        external stop. Returns the summary dict (also logged as the
        ``supervise/outcome`` counter)."""
        pol = self.policy
        summary: Dict[str, Any] = {
            "outcome": None, "returncode": None,
            "restarts": 0, "hang_kills": 0, "deaths": [],
        }
        fast_deaths = 0
        attempt = 0
        log_f = open(self.log_path, "ab") if self.log_path else subprocess.DEVNULL
        try:
            while not self._stop_requested:
                attempt += 1
                child_cmd = list(self.cmd)
                resumed = False
                if attempt > 1 and pol.auto_resume:
                    # continue from the last verified state, not scratch —
                    # ONLY when the run dir actually holds one, and with
                    # the flag that matches its KIND: a trainer resume
                    # state gets --resume, a sweep bucket ledger gets
                    # --resume-from-ledger. Blindly appending a flag would
                    # crash-loop children that don't take it (the serving
                    # server restarts with its original argv).
                    flag = self._detect_resume_flag()
                    if flag and flag not in child_cmd:
                        child_cmd.append(flag)
                        resumed = True
                with self.events.span("supervise/child", attempt=attempt,
                                      resumed=resumed):
                    rc, died_in, hang, uptime = self._run_child(
                        child_cmd, log_f)
                summary["returncode"] = rc
                if self._stop_requested:
                    summary["outcome"] = "stopped"
                    break
                if rc == 0:
                    summary["outcome"] = "success"
                    break
                death = {"section": died_in, "rc": rc, "hang": hang,
                         "uptime_s": round(uptime, 3), "attempt": attempt}
                summary["deaths"].append(death)
                summary["hang_kills"] += int(hang)
                # every death gets a counter (section attribution); the
                # restart counter fires only when a restart actually follows,
                # so the report's restart total matches summary["restarts"]
                self.events.counter("supervise/death", section=died_in,
                                    rc=rc, hang=hang, attempt=attempt,
                                    uptime_s=round(uptime, 3))
                fast_deaths = (fast_deaths + 1
                               if uptime < pol.min_uptime_s else 0)
                if fast_deaths >= pol.max_restarts:
                    summary["outcome"] = "crash-loop"
                    break
                if summary["restarts"] >= pol.max_total_restarts:
                    summary["outcome"] = "restarts-exhausted"
                    break
                summary["restarts"] += 1
                self.events.counter("supervise/restart", section=died_in,
                                    rc=rc, hang=hang, attempt=attempt)
                delay = pol.backoff_s(max(fast_deaths, 1))
                self.events.log(
                    f"child died in {died_in} (rc={rc}, hang={hang}); "
                    f"restart {summary['restarts']} in {delay:.1f}s",
                    level="warning",
                )
                print(f"[supervise] child died in {died_in} (rc={rc}, "
                      f"hang={hang}); restart {summary['restarts']} in "
                      f"{delay:.1f}s", file=sys.stderr, flush=True)
                self._interruptible_sleep(delay)
            if summary["outcome"] is None:
                summary["outcome"] = "stopped"
        finally:
            if log_f is not subprocess.DEVNULL:
                log_f.close()
        self.events.counter(
            "supervise/outcome", outcome=summary["outcome"],
            restarts=summary["restarts"], hang_kills=summary["hang_kills"],
            returncode=summary["returncode"],
        )
        return summary

    def _resumable_state_exists(self) -> bool:
        """Does the run dir hold a trainer resume state (any generation)?
        Checked WITHOUT importing the jax-heavy checkpoint layer — the
        supervisor must stay thin."""
        run_dir = self.heartbeat_path.parent
        for name in ("resume_meta.json", "resume_state.msgpack"):
            base = run_dir / name
            if base.exists() or any(
                    run_dir.glob(name + ".g[0-9]")):
                return True
        return False

    def _sweep_ledger_exists(self) -> bool:
        """Does the run dir hold a sweep bucket ledger (reliability/
        ledger.py)? Its queue manifest is the marker — a restarted sweep
        child can then reconstruct all completed work from records. Name
        literals, not ledger imports: the supervisor stays path-loadable."""
        return (self.heartbeat_path.parent / "sweep_ledger"
                / "queue.json").exists()

    def _detect_resume_flag(self) -> Optional[str]:
        """The resume flag matching the KIND of state the run dir holds
        (trainer checkpoint wins — a sweep run dir never holds one at its
        root), or None when the child must restart from scratch."""
        if self._resumable_state_exists():
            return self.policy.resume_flag
        if self._sweep_ledger_exists():
            return self.policy.ledger_resume_flag
        return None

    def _interruptible_sleep(self, delay: float) -> None:
        """Backoff sleep that a stop request (SIGTERM/SIGINT handler) cuts
        short — a plain time.sleep resumes after the handler returns (PEP
        475) and would stall shutdown for up to backoff_max_s."""
        deadline = time.time() + delay
        while not self._stop_requested:
            remaining = deadline - time.time()
            if remaining <= 0:
                return
            time.sleep(min(0.2, remaining))

    # -- one child lifetime ---------------------------------------------------

    def _run_child(self, child_cmd: List[str], log_f):
        """Spawn, watch the heartbeat, kill on staleness. Returns
        (rc, died_in_section, hang_killed, uptime_s)."""
        pol = self.policy
        self._proc = proc = subprocess.Popen(
            child_cmd,
            stdout=log_f, stderr=subprocess.STDOUT,
            start_new_session=True,  # own pgid → killpg reaches threads
            env=self.env,
        )
        spawn_ts = time.time()
        hang_killed = False
        while proc.poll() is None:
            if self._stop_requested:
                kill_process_group(proc)
                break
            state = read_state(self.heartbeat_path)
            if staleness_s(state, floor_ts=spawn_ts) > pol.heartbeat_timeout_s:
                hang_killed = True
                if pol.prekill_signal is not None:
                    # the flare: one grace window for last words (flight-
                    # recorder dump) before the SIGKILL that cannot be
                    # caught; a child that is too wedged to handle it
                    # just dies prekill_grace_s later than before
                    try:
                        os.killpg(os.getpgid(proc.pid), pol.prekill_signal)
                    except (ProcessLookupError, PermissionError):
                        pass
                    else:
                        time.sleep(pol.prekill_grace_s)
                kill_process_group(proc)
                break
            time.sleep(pol.poll_s)
        uptime = time.time() - spawn_ts
        state = read_state(self.heartbeat_path)
        died_in = (state.get("heartbeat") or {}).get("section", "setup")
        if proc.returncode != 0:
            # drop the dead child's heartbeat: the respawn needs its startup
            # window before it can write one, and a stale section would
            # corrupt both the hang timer and the next death's attribution
            state.pop("heartbeat", None)
            try:
                write_state(self.heartbeat_path, state)
            except OSError:
                pass
        self._proc = None
        return proc.returncode, died_in, hang_killed, uptime


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog=("python -m deeplearninginassetpricing_paperreplication_tpu"
              ".supervise"),
        description="Run any heartbeat-writing entrypoint under supervision: "
                    "hang detection (SIGKILL on stale heartbeat), restart "
                    "with backoff + automatic --resume, crash-loop policy, "
                    "supervise/* telemetry into events.supervisor.jsonl",
    )
    p.add_argument("--run_dir", required=True,
                   help="The child's run directory: heartbeat.json is "
                        "watched here, events.supervisor.jsonl and the child "
                        "log are written here (point the child's --save_dir "
                        "at the same directory)")
    p.add_argument("--timeout", type=float, default=300.0, metavar="S",
                   help="Heartbeat staleness that counts as a hang")
    p.add_argument("--poll", type=float, default=2.0, metavar="S")
    p.add_argument("--max_restarts", type=int, default=5,
                   help="Consecutive fast deaths before declaring a "
                        "crash-loop")
    p.add_argument("--min_uptime", type=float, default=60.0, metavar="S",
                   help="Uptime under which a death counts toward the "
                        "crash-loop counter")
    p.add_argument("--max_total_restarts", type=int, default=50)
    p.add_argument("--backoff", type=float, default=5.0, metavar="S")
    p.add_argument("--backoff_max", type=float, default=300.0, metavar="S")
    p.add_argument("--jitter", type=float, default=0.2)
    p.add_argument("--no_auto_resume", action="store_false",
                   dest="auto_resume",
                   help="Do not append --resume to restarted children")
    p.add_argument("--log", type=str, default=None,
                   help="Child stdout/stderr log (default: "
                        "RUN_DIR/supervised.log)")
    p.add_argument("--metrics_port", type=int, default=None, metavar="PORT",
                   help="Serve the supervisor's live restart/death/hang "
                        "counters as Prometheus text on "
                        "http://127.0.0.1:PORT/metrics (read-only stdlib "
                        "sidecar; port 0 picks a free one)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="The child command, after a literal '--'")
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    cmd = list(args.command)
    if cmd[:1] == ["--"]:
        cmd = cmd[1:]
    if not cmd:
        print("supervise: no child command given (append it after '--')",
              file=sys.stderr)
        return 2
    run_dir = Path(args.run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)

    # fault-plan plumbing: when a plan is set but no state/event files are,
    # default them into the run dir — WITHOUT persistent counter state a
    # `kill` fault would re-fire on every restart and the supervised run
    # could never complete
    env = dict(os.environ)
    if env.get(ENV_PLAN):
        env.setdefault(ENV_STATE, str(run_dir / "fault_state.json"))
        env.setdefault(ENV_EVENTS, str(run_dir / "events.faults.jsonl"))

    events = EventLog(run_dir, process_index=0,
                      filename=SUPERVISOR_EVENTS_FILENAME)
    sidecar = None
    if args.metrics_port is not None:
        sidecar = MetricsSidecar([events.metrics], port=args.metrics_port)
        port = sidecar.start()
        print(f"[supervise] metrics sidecar: "
              f"http://127.0.0.1:{port}/metrics", file=sys.stderr,
              flush=True)
    policy = RestartPolicy(
        heartbeat_timeout_s=args.timeout,
        poll_s=args.poll,
        max_restarts=args.max_restarts,
        min_uptime_s=args.min_uptime,
        max_total_restarts=args.max_total_restarts,
        backoff_base_s=args.backoff,
        backoff_max_s=args.backoff_max,
        jitter_frac=args.jitter,
        auto_resume=args.auto_resume,
    )
    sup = Supervisor(
        cmd,
        heartbeat_path=run_dir / "heartbeat.json",
        policy=policy,
        events=events,
        log_path=Path(args.log) if args.log else run_dir / "supervised.log",
        env=env,
    )

    def _on_signal(signum, frame):  # noqa: ARG001 — signal-handler shape
        sup.request_stop()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    summary = sup.run()
    if sidecar is not None:
        sidecar.stop()
    events.close()
    print(json.dumps(summary))
    if summary["outcome"] == "success":
        return 0
    rc = summary.get("returncode")
    return rc if isinstance(rc, int) and rc > 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
