// Native panel codec: fused mask-build + zero-fill over the raw char array.
//
// The reference builds the validity mask and zero-fills invalid entries in
// several NumPy passes over the [T, N, 1+F] panel
// (/root/reference/src/data_loader.py:45-65): a comparison per channel, an
// all-reduce over features, an isnan pass, then two `np.where` copies. At the
// real workload that is ~6 full sweeps over ~1.2 GB of data on the host.
//
// This codec does the whole thing in ONE multithreaded pass per (t, i) row:
// read the 1+F channel strip once (hot in L1), decide validity, and write the
// zero-filled returns/features + mask. The Python wrapper (native.py) falls
// back to the NumPy path when the shared library cannot be built.
//
// An observation is valid iff: return > MISSING+1, return is not NaN, and
// every feature > MISSING+1 (data_loader.py:50-57).

#include <cmath>
#include <cstdint>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// data:    [T, N, 1+F] float32, returns in channel 0 (read-only)
// returns_out: [T, N] float32 (zero where invalid)
// features_out: [T, N, F] float32 (zero where invalid)
// mask_out: [T, N] uint8 (1 = valid)
// Returns the number of valid observations.
long long panel_decode(const float* data, long long T, long long N,
                       long long F, float missing_threshold,
                       float* returns_out, float* features_out,
                       uint8_t* mask_out) {
  const long long rows = T * N;
  const long long stride = 1 + F;
  long long valid_count = 0;

#if defined(_OPENMP)
#pragma omp parallel for reduction(+ : valid_count) schedule(static)
#endif
  for (long long r = 0; r < rows; ++r) {
    const float* row = data + r * stride;
    const float ret = row[0];
    bool valid = (ret > missing_threshold) && !std::isnan(ret);
    if (valid) {
      for (long long f = 1; f <= F; ++f) {
        if (!(row[f] > missing_threshold)) {  // NaN compares false => invalid
          valid = false;
          break;
        }
      }
    }
    mask_out[r] = valid ? 1 : 0;
    returns_out[r] = valid ? ret : 0.0f;
    float* feat = features_out + r * F;
    if (valid) {
      for (long long f = 0; f < F; ++f) feat[f] = row[1 + f];
    } else {
      for (long long f = 0; f < F; ++f) feat[f] = 0.0f;
    }
    valid_count += valid ? 1 : 0;
  }
  return valid_count;
}

int panel_codec_num_threads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
