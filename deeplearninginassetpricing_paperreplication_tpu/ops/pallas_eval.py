"""Fused EVALUATION kernel: weights + SDF factor + conditional moments in
ONE panel read per period.

Every training epoch runs two eval forwards (valid AND test — reference
``/root/reference/src/train.py:251-259``), and each eval needs the SDF
weights (FFN over the panel) and the conditional-moment means (tanh moment
net over the same panel). As two kernels that is two full panel reads; at
the real shape the evals account for ~43% of the conditional epoch's HBM
traffic. One period's feature-major slice ``x[t] [F, N]`` is only ~0.9 MB
bf16 at N=10k, so the whole per-period pipeline fits VMEM:

    grid (T,):  x_t  →  MLP → raw w → mask → zero-mean → w[t]        (out)
                     └→ F_t = Σ w·R·m · scale_t                      (out)
                     └→ em += tanh(K_mᵀ x + zp_m)·R·m·(1+F_t)·tinv   (acc)

reading the panel ONCE. Eval is never differentiated (dropout off, params
frozen — ``train.py:106-153`` wraps it in no_grad), so this is a plain
pallas_call with no custom_vjp.

The in-kernel math mirrors the two-kernel route exactly: the SDF head's
mask + masked zero-mean (``model.py:271-279``), the weighted-loss period
scale ``N̄/N_t`` (precomputed per period, ``model.py:363-367``), and the
moment contraction of ``ops/pallas_moment.py``. Reductions over the stock
axis run on the MXU (ones-contractions), accumulation f32.

VMEM guard: the per-period working set is ~(F·2 + (3·H + K + 8)·4)·N_pad
bytes doubled for x double-buffering; `fits_vmem` gates the route and the
caller falls back to the two-kernel eval when it doesn't fit (huge N).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_ffn import _dot, _row_to_col

# (interpret, compute_dtype_name)
Static = Tuple[bool, str]

_VMEM_LIMIT_BYTES = 12 * 1024 * 1024
_F_LANES = 128  # the per-period F scalar rides a 128-lane row (legal block)


def fits_vmem(N: int, F: int, hidden: Sequence[int], K: int,
              panel_itemsize: int = 2) -> bool:
    """Whether one period's fused-eval working set fits the VMEM budget.

    `panel_itemsize`: bytes per panel element (2 for the default bf16
    panel, 4 for an f32 panel)."""
    n_pad = -(-N // 128) * 128
    h = max(hidden) if hidden else 8
    # x double-buffered + f32 activations/moments/rows
    per_lane = 2 * F * panel_itemsize + (3 * h + K + 8) * 4
    return per_lane * n_pad <= _VMEM_LIMIT_BYTES


def _rowsum(x):
    """Σ over lanes of [R, N] → [R, 1] via a ones-contraction on the MXU."""
    ones = jnp.ones((1, x.shape[-1]), jnp.float32)
    return _dot(x, ones, 1, 1, jnp.float32)  # [R, 1]


def _eval_kernel(scale_ref, x_ref, zp_ref, zpm_ref, tinv_ref, ret_ref,
                 mask_ref, k1T_ref, *rest, n_mids: int, cdtype=jnp.bfloat16):
    """One period: full SDF MLP + weight normalization + F_t + em update."""
    mid_refs = rest[: 2 * n_mids]
    kout_ref, bout_ref, kmT_ref = rest[2 * n_mids: 2 * n_mids + 3]
    w_ref, f_ref, em_ref = rest[2 * n_mids + 3:]

    t = pl.program_id(0)
    mask = mask_ref[0]  # [1, N] — 0 on padded/invalid lanes by construction
    x = x_ref[0] * mask.astype(x_ref.dtype)  # zero masked lanes
    ret = ret_ref[0] * mask

    # -- SDF MLP (eval: no dropout) ------------------------------------------
    h = jnp.maximum(_dot(k1T_ref[:], x, 1, 0, cdtype)
                    + _row_to_col(zp_ref[0]), 0.0)
    for i in range(n_mids):
        kT, b = mid_refs[2 * i][:], mid_refs[2 * i + 1][:]
        h = jnp.maximum(_dot(kT, h, 1, 0, cdtype) + b, 0.0)
    w_raw = (_dot(kout_ref[:], h, 0, 0, cdtype) + bout_ref[0, 0]) * mask

    # -- masked cross-sectional zero-mean (model.py:273-279) -----------------
    n_t = jnp.maximum(_rowsum(mask)[0, 0], 1.0)
    w = (w_raw - _rowsum(w_raw)[0, 0] / n_t) * mask
    w_ref[0] = w.astype(jnp.float32)

    # -- SDF factor with the weighted-loss period scale ----------------------
    f_t = _rowsum(w * ret)[0, 0] * scale_ref[t]
    f_ref[0] = f_t + jnp.zeros((1, _F_LANES), jnp.float32)  # broadcast row

    # -- conditional-moment accumulation (pallas_moment.py semantics) --------
    hm = jnp.tanh(_dot(kmT_ref[:], x, 1, 0, cdtype) + _row_to_col(zpm_ref[0]))
    contrib = hm * (ret * (1.0 + f_t) * tinv_ref[0])  # [K, N]

    @pl.when(t == 0)
    def _():
        em_ref[:] = contrib

    @pl.when(t != 0)
    def _():
        em_ref[:] = em_ref[:] + contrib


def fused_eval(
    x_t: jnp.ndarray,  # [T, F, N] feature-major panel (f32 or bf16)
    zp: jnp.ndarray,  # [T, H1] per-period SDF first-layer bias
    zp_m: jnp.ndarray,  # [T, K] per-period moment bias
    scale: jnp.ndarray,  # [T] weighted-loss period scale (N̄/N_t, or ones)
    tinv: jnp.ndarray,  # [N] 1/clip(T_i, 1)
    returns: jnp.ndarray,  # [T, N]
    mask: jnp.ndarray,  # [T, N]
    layers,  # [(k1_stock [F, H1], None)] + [(k_i, b_i), ...]
    out_kernel: jnp.ndarray,  # [H_L, 1]
    out_bias: jnp.ndarray,  # [1]
    km_stock: jnp.ndarray,  # [F, K] moment-net stock kernel
    *,
    interpret: bool = False,
    compute_dtype: str = "bfloat16",
):
    """Returns (weights [T, N] — masked, zero-meaned; F [T]; em [K, N]).

    ``conditional_loss == (em²).mean()`` (sum/(K·n_assets) under padding);
    F already carries the weighted-loss scale. One panel read total.
    """
    T, F, N = x_t.shape
    k1T = layers[0][0].T
    mids = [(kT.T, b.reshape(-1, 1)) for kT, b in layers[1:]]
    h1 = k1T.shape[0]
    K = km_stock.shape[1]
    cdtype = jnp.dtype(compute_dtype)

    vmem = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # scale (T,), indexed [t]
        vmem((1, F, N), lambda t: (t, 0, 0)),  # x_t
        vmem((1, 1, h1), lambda t: (t, 0, 0)),  # zp
        vmem((1, 1, K), lambda t: (t, 0, 0)),  # zp_m
        vmem((1, 1, N), lambda t: (0, 0, 0)),  # tinv
        vmem((1, 1, N), lambda t: (t, 0, 0)),  # returns
        vmem((1, 1, N), lambda t: (t, 0, 0)),  # mask
        vmem(),  # k1T
    ]
    for _ in mids:
        in_specs += [vmem(), vmem()]
    in_specs += [
        vmem(),  # kout
        pl.BlockSpec(memory_space=pltpu.SMEM),  # bout (1, 1)
        vmem(),  # kmT
    ]

    out_specs = [
        vmem((1, 1, N), lambda t: (t, 0, 0)),  # w
        vmem((1, 1, _F_LANES), lambda t: (t, 0, 0)),  # F row per period
        vmem((K, N), lambda t: (0, 0)),  # em (resident accumulator)
    ]
    out_shapes = [
        jax.ShapeDtypeStruct((T, 1, N), jnp.float32),
        jax.ShapeDtypeStruct((T, 1, _F_LANES), jnp.float32),
        jax.ShapeDtypeStruct((K, N), jnp.float32),
    ]
    kernel = functools.partial(_eval_kernel, n_mids=len(mids), cdtype=cdtype)
    flat_mids = [a for kb in mids for a in kb]
    w3, f3, em = pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)  # em accumulates across t
        ),
        interpret=interpret,
    )(
        scale.reshape(T), x_t, zp[:, None, :], zp_m[:, None, :],
        jnp.broadcast_to(tinv, (N,)).reshape(1, 1, N),
        returns.reshape(T, 1, N), mask.reshape(T, 1, N),
        k1T, *flat_mids, out_kernel, out_bias.reshape(1, 1), km_stock.T,
    )
    return w3[:, 0, :], f3[:, 0, 0], em
