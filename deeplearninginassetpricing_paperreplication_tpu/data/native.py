"""ctypes loader for the native panel codec (_native/panel_codec.cpp).

The shared library is built with the system C++ toolchain on first use —
but on a BACKGROUND thread: the build can take up to 120 s per compiler
attempt, and paying that synchronously inside the first `load_panel` put the
toolchain on the startup critical path. While the build is in flight (or
when it fails / no toolchain exists), every entry point degrades to the
pure-NumPy decode, so the framework never blocks on, nor hard-depends on, a
compiler at runtime. An already-built, fresh `.so` loads synchronously —
`ctypes.CDLL` of an existing file is milliseconds.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_SRC = Path(__file__).parent / "_native" / "panel_codec.cpp"
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_FAILED = False  # terminal: build/load attempted and lost — stay on NumPy
_BUILD_THREAD: Optional[threading.Thread] = None


def _build(so_path: Path) -> bool:
    cmds = [
        ["g++", "-O3", "-shared", "-fPIC", "-fopenmp", "-o", str(so_path), str(_SRC)],
        ["g++", "-O3", "-shared", "-fPIC", "-o", str(so_path), str(_SRC)],
        ["cc", "-O3", "-shared", "-fPIC", "-lstdc++", "-o", str(so_path), str(_SRC)],
    ]
    for cmd in cmds:
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=120)
            if r.returncode == 0 and so_path.exists():
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def _so_path() -> Path:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return _SRC.with_name("panel_codec" + suffix)


def _finish_load(so_path: Path) -> None:
    """CDLL-load + prototype setup; sets _LIB or marks terminal failure.
    Caller holds _LOCK. The library at `so_path` is always complete (the
    build renames it into place atomically), so a load failure here is a
    real toolchain/ABI problem, not a torn write."""
    global _LIB, _FAILED
    try:
        lib = ctypes.CDLL(str(so_path))
        lib.panel_decode.restype = ctypes.c_longlong
        lib.panel_decode.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_float,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.panel_codec_num_threads.restype = ctypes.c_int
        lib.panel_codec_num_threads.argtypes = []
        _LIB = lib
    except OSError:
        _FAILED = True


def _background_build(so_path: Path) -> None:
    """Build into a tmp path and rename into place: _load's unlocked
    'exists and fresh' fast path must never see (and CDLL, and latch
    _FAILED on) a partially written library — the compiler streams its
    output, so building in place would race every concurrent loader."""
    global _FAILED
    tmp = so_path.with_name(so_path.name + ".build")
    ok = _build(tmp)
    if ok:
        try:
            os.replace(tmp, so_path)  # atomic: readers see old-or-complete
        except OSError:
            ok = False
    tmp.unlink(missing_ok=True)
    with _LOCK:
        if ok:
            _finish_load(so_path)
        else:
            _FAILED = True


def _load(wait: bool = False) -> Optional[ctypes.CDLL]:
    """The library if ready, else None. A missing/stale `.so` kicks off a
    background build; `wait=True` (explicit availability queries, tests)
    joins it, while the hot load path never blocks."""
    global _FAILED, _BUILD_THREAD
    if _LIB is not None:
        return _LIB
    if _FAILED:
        return None
    with _LOCK:
        if _LIB is not None or _FAILED:
            return _LIB
        if os.environ.get("DLAP_NO_NATIVE"):
            _FAILED = True
            return None
        so_path = _so_path()
        if (so_path.exists()
                and so_path.stat().st_mtime >= _SRC.stat().st_mtime):
            _finish_load(so_path)  # built earlier: loading is milliseconds
            return _LIB
        if _BUILD_THREAD is None:
            _BUILD_THREAD = threading.Thread(
                target=_background_build, args=(so_path,),
                daemon=True, name="panel-codec-build",
            )
            _BUILD_THREAD.start()
        thread = _BUILD_THREAD
    if wait:
        thread.join()
    return _LIB


def native_available() -> bool:
    """Is the native codec usable? Joins any in-flight build — this is the
    explicit availability query, not the load hot path."""
    return _load(wait=True) is not None


def decode_panel(
    data: np.ndarray, missing_threshold: float
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Fused mask/zero-fill: data [T, N, 1+F] f32 -> (returns, features, mask).

    Returns None when the native library is unavailable (caller falls back to
    NumPy). Semantics are bit-identical to the NumPy path (panel.py).
    """
    lib = _load()
    if lib is None:
        return None
    data = np.ascontiguousarray(data, dtype=np.float32)
    T, N, C = data.shape
    F = C - 1
    returns = np.empty((T, N), np.float32)
    features = np.empty((T, N, F), np.float32)
    mask = np.empty((T, N), np.uint8)
    fp = ctypes.POINTER(ctypes.c_float)
    lib.panel_decode(
        data.ctypes.data_as(fp), T, N, F, missing_threshold,
        returns.ctypes.data_as(fp), features.ctypes.data_as(fp),
        mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return returns, features, mask.astype(bool)
