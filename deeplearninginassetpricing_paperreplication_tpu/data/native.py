"""ctypes loader for the native panel codec (_native/panel_codec.cpp).

Builds the shared library on first use with the system C++ toolchain and
caches it next to the source; every entry point degrades to the pure-NumPy
path when the toolchain or build is unavailable, so the framework never hard-
depends on a compiler at runtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_SRC = Path(__file__).parent / "_native" / "panel_codec.cpp"
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build(so_path: Path) -> bool:
    cmds = [
        ["g++", "-O3", "-shared", "-fPIC", "-fopenmp", "-o", str(so_path), str(_SRC)],
        ["g++", "-O3", "-shared", "-fPIC", "-o", str(so_path), str(_SRC)],
        ["cc", "-O3", "-shared", "-fPIC", "-lstdc++", "-o", str(so_path), str(_SRC)],
    ]
    for cmd in cmds:
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=120)
            if r.returncode == 0 and so_path.exists():
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("DLAP_NO_NATIVE"):
            return None
        suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
        so_path = _SRC.with_name("panel_codec" + suffix)
        try:
            if (not so_path.exists()
                    or so_path.stat().st_mtime < _SRC.stat().st_mtime):
                if not _build(so_path):
                    return None
            lib = ctypes.CDLL(str(so_path))
            lib.panel_decode.restype = ctypes.c_longlong
            lib.panel_decode.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
                ctypes.c_float,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.panel_codec_num_threads.restype = ctypes.c_int
            lib.panel_codec_num_threads.argtypes = []
            _LIB = lib
        except OSError:
            _LIB = None
        return _LIB


def native_available() -> bool:
    return _load() is not None


def decode_panel(
    data: np.ndarray, missing_threshold: float
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Fused mask/zero-fill: data [T, N, 1+F] f32 -> (returns, features, mask).

    Returns None when the native library is unavailable (caller falls back to
    NumPy). Semantics are bit-identical to the NumPy path (panel.py).
    """
    lib = _load()
    if lib is None:
        return None
    data = np.ascontiguousarray(data, dtype=np.float32)
    T, N, C = data.shape
    F = C - 1
    returns = np.empty((T, N), np.float32)
    features = np.empty((T, N, F), np.float32)
    mask = np.empty((T, N), np.uint8)
    fp = ctypes.POINTER(ctypes.c_float)
    lib.panel_decode(
        data.ctypes.data_as(fp), T, N, F, missing_threshold,
        returns.ctypes.data_as(fp), features.ctypes.data_as(fp),
        mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return returns, features, mask.astype(bool)
