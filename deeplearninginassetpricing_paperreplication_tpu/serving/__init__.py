"""Online SDF inference: run-dir checkpoints → a low-latency service.

The offline pipeline ends at checkpoints (``train``/``evaluate_ensemble``);
this subpackage is the online path from "month of firm characteristics +
macro state" to "portfolio weights / SDF factor":

  * :mod:`.engine`  — ``InferenceEngine``: K stacked checkpoints, AOT-
    compiled per-bucket forward programs with donated inputs + pinned
    staging (zero steady-state recompiles AND allocations), incremental
    O(1) macro LSTM state, ``reload()`` checkpoint hot-swap;
  * :mod:`.batcher` — ``ContinuousBatcher`` (asyncio, flushes fold
    in-flight arrivals) and the deprecated deadline ``MicroBatcher``,
    both with per-bucket lanes and bounded backpressure;
  * :mod:`.server`  — transport-agnostic ``ServingService`` JSON API
    (``/v1/weights``, ``/v1/sdf``, ``/v1/macro``, ``/v1/reload``,
    ``/v1/models``, ``/healthz``, ``/metrics``; JSON / base64 / raw-f32
    wires) with observability events, bench-format heartbeats, and a
    per-process LRU result-cache shard keyed on the params fingerprint;
  * :mod:`.aserver` — the production asyncio HTTP front end
    (keep-alive, ``SO_REUSEPORT``);
  * :mod:`.fleet`   — supervisor-managed replica processes on one
    shared port, as a DYNAMIC set (a dead replica degrades capacity,
    not availability; ``fleet.json`` atomically tracks the live layout);
  * :mod:`.autoscale` — the load-adaptive control loop: per-replica
    metrics → queue-depth/shed-rate/p99 signals → hysteresis+cooldown →
    grow/shrink the replica set live (graceful ``/v1/drain``
    scale-down);
  * :mod:`.loadgen` — open/closed-loop load generator (keep-alive raw
    sockets, retries, rate ladder, error accounting) and the
    ``bench.py`` ``serving`` / ``serving_async`` sections.

Served outputs are bit-identical to the offline ``evaluate_ensemble``
batch path for the same checkpoints and months — under continuous-batch
coalescing, bucket padding, every wire format, and replication (asserted
in tier-1).
"""

from .aserver import AsyncServerThread, pick_free_port, run_async_server
from .autoscale import AutoscalePolicy, Autoscaler, FleetController
from .batcher import ContinuousBatcher, MicroBatcher, QueueFull, Shed
from .engine import (
    InferenceEngine,
    InferenceRequest,
    InferenceResult,
    bucket_for,
    params_digest,
)
from .fleet import (
    REPLICA_POLICY,
    ReplicaFleet,
    read_fleet_json,
    server_child_argv,
    write_fleet_json,
)
from .flight import FlightRecorder, load_flightrecorder
from .loadgen import (
    bench_serving,
    bench_tracing_overhead,
    run_ladder,
    run_loadgen,
)
from .server import LRUCache, ServingService, make_server, priority_for

__all__ = [
    "AsyncServerThread",
    "AutoscalePolicy",
    "Autoscaler",
    "ContinuousBatcher",
    "FleetController",
    "FlightRecorder",
    "InferenceEngine",
    "InferenceRequest",
    "InferenceResult",
    "LRUCache",
    "MicroBatcher",
    "QueueFull",
    "REPLICA_POLICY",
    "ReplicaFleet",
    "ServingService",
    "Shed",
    "bench_serving",
    "bench_tracing_overhead",
    "load_flightrecorder",
    "bucket_for",
    "make_server",
    "params_digest",
    "pick_free_port",
    "priority_for",
    "read_fleet_json",
    "run_async_server",
    "run_ladder",
    "run_loadgen",
    "server_child_argv",
    "write_fleet_json",
]
