"""serving/ tier-1 suite (CPU, loopback only — no external network).

Covers the acceptance criteria:
  * engine outputs BIT-IDENTICAL to the offline `evaluate_ensemble` batch
    path for the same checkpoints and months;
  * bucket-padding invariance (padding the stock axis changes nothing);
  * zero recompiles after warmup (dispatch/compile counters);
  * incremental macro state matches the full re-scan to tolerance;
plus batcher flush/backpressure semantics, LRU cache correctness, the HTTP
surface (/v1/*, /healthz–heartbeat agreement, /metrics), the loadgen, the
report CLI's serving section, checkpoint-stacking validation, and the lint
gate extension to the serving package.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu.evaluate_ensemble import (
    stack_checkpoints,
    validate_stackable_configs,
)
from deeplearninginassetpricing_paperreplication_tpu.models.gan import GAN
from deeplearninginassetpricing_paperreplication_tpu.parallel.ensemble import (
    ensemble_metrics,
)
from deeplearninginassetpricing_paperreplication_tpu.serving import (
    InferenceEngine,
    InferenceRequest,
    LRUCache,
    MicroBatcher,
    QueueFull,
    ServingService,
    bucket_for,
    make_server,
    run_loadgen,
)
from deeplearninginassetpricing_paperreplication_tpu.training.checkpoint import (
    save_params,
)
from deeplearninginassetpricing_paperreplication_tpu.utils.config import GANConfig

REPO = Path(__file__).resolve().parents[1]

T, N, F, M = 12, 64, 10, 6
SEEDS = (1, 2, 3)


def _make_cfg(**overrides):
    base = dict(macro_feature_dim=M, individual_feature_dim=F,
                hidden_dim=(8, 8), num_units_rnn=(4,))
    base.update(overrides)
    return GANConfig(**base)


def _write_member(d: Path, cfg: GANConfig, seed: int):
    d.mkdir(parents=True, exist_ok=True)
    cfg.save(d / "config.json")
    save_params(d / "best_model_sharpe.msgpack",
                GAN(cfg).init(jax.random.key(seed)))
    return str(d)


@pytest.fixture(scope="module")
def serve_cfg():
    return _make_cfg()


@pytest.fixture(scope="module")
def member_dirs(tmp_path_factory, serve_cfg):
    root = tmp_path_factory.mktemp("members")
    return [_write_member(root / f"seed_{s}", serve_cfg, s) for s in SEEDS]


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(7)
    return {
        "macro": rng.standard_normal((T, M)).astype(np.float32),
        "individual": rng.standard_normal((T, N, F)).astype(np.float32),
        "returns": (rng.standard_normal((T, N)) * 0.05).astype(np.float32),
        "mask": (rng.random((T, N)) > 0.15).astype(np.float32),
    }


@pytest.fixture(scope="module")
def offline(member_dirs, panel):
    """The offline evaluate_ensemble batch path — the bit-identity oracle."""
    gan, vparams = stack_checkpoints(member_dirs)
    import jax.numpy as jnp

    return ensemble_metrics(
        gan, vparams, {k: jnp.asarray(v) for k, v in panel.items()})


@pytest.fixture(scope="module")
def engine(member_dirs, panel):
    return InferenceEngine(
        member_dirs, macro_history=panel["macro"],
        stock_buckets=(64, 96), batch_buckets=(1, 2))


# --------------------------------------------------------------------------
# engine: bit-identity, padding invariance, compile discipline, macro state
# --------------------------------------------------------------------------


def test_engine_bit_identical_to_offline_batch_path(engine, panel, offline):
    for t in (0, 3, T - 1):
        res = engine.infer_one(InferenceRequest(
            individual=panel["individual"][t], mask=panel["mask"][t],
            returns=panel["returns"][t], month=t))
        np.testing.assert_array_equal(res.weights,
                                      offline["avg_weights"][t])
        assert res.sdf == float(offline["ensemble_port_returns"][t])


def test_engine_micro_batch_bit_identical(engine, panel, offline):
    """Two months coalesced into one [B=2] program call match the offline
    rows exactly — micro-batching is numerically invisible."""
    res = engine.infer([
        InferenceRequest(individual=panel["individual"][t],
                         mask=panel["mask"][t], month=t)
        for t in (2, 9)
    ])
    for r, t in zip(res, (2, 9)):
        assert r.batch_bucket == 2
        np.testing.assert_array_equal(r.weights, offline["avg_weights"][t])


def test_bucket_padding_invariance(member_dirs, panel, engine, offline):
    """Padding 64 real stocks up to a 96 bucket changes nothing: padded
    entries are masked out and every reduction is mask-aware."""
    eng96 = InferenceEngine(
        member_dirs, macro_history=panel["macro"],
        stock_buckets=(96,), batch_buckets=(1,))
    res = eng96.infer_one(InferenceRequest(
        individual=panel["individual"][4], mask=panel["mask"][4], month=4))
    assert res.bucket == 96 and res.n == N
    assert res.weights.shape == (N,)
    np.testing.assert_array_equal(res.weights, offline["avg_weights"][4])


def test_zero_recompiles_after_warmup(member_dirs, panel):
    eng = InferenceEngine(
        member_dirs, macro_history=panel["macro"],
        stock_buckets=(64, 96), batch_buckets=(1, 2))
    n_programs = eng.warmup()
    assert n_programs == 4  # 2 stock buckets x 2 batch buckets
    compiles_after_warmup = eng.stats()["compiles"]
    dispatches0 = eng.stats()["dispatches"]
    rng = np.random.default_rng(0)
    # traffic across every shape class the buckets admit
    for n_stocks in (10, 40, 64, 70, 96):
        for b in (1, 2):
            reqs = [
                InferenceRequest(
                    individual=rng.standard_normal(
                        (n_stocks, F)).astype(np.float32),
                    month=int(rng.integers(T)))
                for _ in range(b)
            ]
            out = eng.infer(reqs)
            assert len(out) == b
    stats = eng.stats()
    assert stats["compiles"] == compiles_after_warmup, (
        "steady-state serving must not recompile")
    assert stats["dispatches"] == dispatches0 + 10


def test_incremental_macro_state_matches_rescan(member_dirs, panel, engine):
    """Appending months one cell-step at a time matches scanning the full
    history in one pass, to tolerance — and the served weights agree."""
    cut = T - 3
    eng_inc = InferenceEngine(
        member_dirs, macro_history=panel["macro"][:cut],
        stock_buckets=(64,), batch_buckets=(1,))
    for t in range(cut, T):
        assert eng_inc.append_month(panel["macro"][t]) == t
    assert eng_inc.months == T
    for t in (cut, T - 1):
        np.testing.assert_allclose(
            eng_inc.macro_state_for_month(t),
            engine.macro_state_for_month(t), atol=1e-6)
    req = InferenceRequest(individual=panel["individual"][T - 1],
                           mask=panel["mask"][T - 1], month=T - 1)
    np.testing.assert_allclose(
        eng_inc.infer_one(req).weights, engine.infer_one(req).weights,
        atol=1e-6)


def test_macro_append_validation_and_raw_normalization(member_dirs, panel):
    mean = panel["macro"].mean(axis=0, keepdims=True)
    std = panel["macro"].std(axis=0, keepdims=True) + 1e-8
    eng = InferenceEngine(
        member_dirs, macro_history=panel["macro"][:4],
        macro_stats=(mean, std), stock_buckets=(64,), batch_buckets=(1,))
    with pytest.raises(ValueError, match="series"):
        eng.append_month(np.zeros(M + 1, np.float32))
    raw = mean.reshape(-1) + std.reshape(-1) * panel["macro"][4]
    eng.append_month(raw, raw=True)
    eng2 = InferenceEngine(
        member_dirs, macro_history=panel["macro"][:5],
        stock_buckets=(64,), batch_buckets=(1,))
    np.testing.assert_allclose(eng.macro_state_for_month(4),
                               eng2.macro_state_for_month(4), atol=1e-5)
    # no stats at construction -> raw append is a loud error
    eng3 = InferenceEngine(
        member_dirs, macro_history=panel["macro"][:4],
        stock_buckets=(64,), batch_buckets=(1,))
    with pytest.raises(ValueError, match="macro_stats"):
        eng3.append_month(raw, raw=True)


def test_engine_requires_macro_history_when_config_uses_macro(member_dirs):
    with pytest.raises(ValueError, match="macro_history"):
        InferenceEngine(member_dirs)


def test_engine_month_out_of_range(engine, panel):
    with pytest.raises(ValueError, match="month"):
        engine.infer_one(InferenceRequest(
            individual=panel["individual"][0], month=T + 5))


def test_engine_stateless_config(tmp_path):
    """macro_feature_dim == 0: no macro history needed, no state program."""
    cfg = GANConfig(macro_feature_dim=0, individual_feature_dim=F,
                    hidden_dim=(8,), use_rnn=False)
    dirs = [_write_member(tmp_path / f"m{s}", cfg, s) for s in (1, 2)]
    eng = InferenceEngine(dirs, stock_buckets=(64,), batch_buckets=(1,))
    assert eng.months == 0 and eng.state_dim == 0
    rng = np.random.default_rng(3)
    res = eng.infer_one(InferenceRequest(
        individual=rng.standard_normal((N, F)).astype(np.float32)))
    assert res.weights.shape == (N,)
    assert np.isfinite(res.weights).all()


def test_engine_no_rnn_uses_raw_macro_rows(tmp_path, panel):
    """use_rnn=False with macro: the 'state' is the raw macro row, and the
    served weights still match the offline batch path bit-exactly."""
    cfg = _make_cfg(use_rnn=False, num_units_rnn=())
    dirs = [_write_member(tmp_path / f"m{s}", cfg, s) for s in (1, 2)]
    gan, vparams = stack_checkpoints(dirs)
    import jax.numpy as jnp

    off = ensemble_metrics(
        gan, vparams, {k: jnp.asarray(v) for k, v in panel.items()})
    eng = InferenceEngine(dirs, macro_history=panel["macro"],
                          stock_buckets=(64,), batch_buckets=(1,))
    res = eng.infer_one(InferenceRequest(
        individual=panel["individual"][6], mask=panel["mask"][6], month=6))
    np.testing.assert_array_equal(res.weights, off["avg_weights"][6])


def test_bucket_for():
    assert bucket_for(1, (64, 96)) == 64
    assert bucket_for(64, (64, 96)) == 64
    assert bucket_for(65, (96, 64)) == 96
    with pytest.raises(ValueError, match="exceeds"):
        bucket_for(97, (64, 96))


# --------------------------------------------------------------------------
# checkpoint stacking validation (fail fast, legible errors)
# --------------------------------------------------------------------------


def test_stack_checkpoints_architecture_mismatch_fails_fast(tmp_path,
                                                            serve_cfg):
    d1 = _write_member(tmp_path / "a", serve_cfg, 1)
    d2 = _write_member(tmp_path / "b", _make_cfg(hidden_dim=(16, 16)), 2)
    with pytest.raises(ValueError) as ei:
        stack_checkpoints([d1, d2])
    msg = str(ei.value)
    assert "hidden_dim" in msg  # names the differing field
    assert str(tmp_path / "b") in msg  # names the offending directory
    # the same check fires BEFORE any params file is read
    with pytest.raises(ValueError):
        validate_stackable_configs([d1, d2])


def test_stack_checkpoints_nonarchitectural_diff_warns_and_stacks(
        tmp_path, serve_cfg):
    d1 = _write_member(tmp_path / "a", serve_cfg, 1)
    d2 = _write_member(tmp_path / "b", _make_cfg(dropout=0.2), 2)
    with pytest.warns(UserWarning, match="non-architectural"):
        gan, stacked = stack_checkpoints([d1, d2])
    assert jax.tree.leaves(stacked)[0].shape[0] == 2


def test_stack_checkpoints_same_configs_silent(member_dirs):
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        gan, stacked = stack_checkpoints(member_dirs)
    assert jax.tree.leaves(stacked)[0].shape[0] == len(member_dirs)


# --------------------------------------------------------------------------
# micro-batcher: flush and backpressure semantics
# --------------------------------------------------------------------------


class _Recorder:
    def __init__(self, result_fn=lambda b, items: [(b, i) for i in items]):
        self.calls = []
        self.fn = result_fn
        self.lock = threading.Lock()

    def __call__(self, bucket, items):
        with self.lock:
            self.calls.append((bucket, list(items)))
        return self.fn(bucket, items)


def test_batcher_size_trigger_coalesces_one_flush():
    rec = _Recorder()
    mb = MicroBatcher(rec, max_batch=3, max_delay_s=60.0)
    futs = [mb.submit("b64", i) for i in range(3)]
    results = [f.result(timeout=5) for f in futs]
    mb.close()
    assert results == [("b64", 0), ("b64", 1), ("b64", 2)]
    assert len(rec.calls) == 1  # size trigger: ONE flush, not three
    assert rec.calls[0] == ("b64", [0, 1, 2])


def test_batcher_deadline_trigger_flushes_lone_item():
    rec = _Recorder()
    mb = MicroBatcher(rec, max_batch=8, max_delay_s=0.01)
    t0 = time.monotonic()
    fut = mb.submit("b64", "lonely")
    assert fut.result(timeout=5) == ("b64", "lonely")
    assert time.monotonic() - t0 < 2.0  # deadline, not max_batch, released it
    mb.close()


def test_batcher_per_bucket_lanes_do_not_mix():
    rec = _Recorder()
    mb = MicroBatcher(rec, max_batch=2, max_delay_s=0.005)
    futs = [mb.submit(b, i) for i, b in enumerate(("x", "y", "x", "y"))]
    for f in futs:
        f.result(timeout=5)
    mb.close()
    assert sorted(rec.calls) == [("x", [0, 2]), ("y", [1, 3])]


def test_batcher_bounded_backpressure():
    release = threading.Event()

    def blocking(bucket, items):
        release.wait(timeout=10)
        return list(items)

    mb = MicroBatcher(blocking, max_batch=1, max_delay_s=0.0, max_queue=2)
    first = mb.submit("b", 0)  # flushes immediately, blocks the dispatcher
    time.sleep(0.05)
    held = [mb.submit("b", i) for i in (1, 2)]  # fills the queue
    with pytest.raises(QueueFull):
        mb.submit("b", 3)
    assert mb.rejected == 1
    release.set()
    assert first.result(timeout=5) == 0
    for f in held:
        f.result(timeout=5)
    mb.close()


def test_batcher_handler_error_reaches_every_future():
    def boom(bucket, items):
        raise RuntimeError("kaput")

    mb = MicroBatcher(boom, max_batch=2, max_delay_s=60.0)
    futs = [mb.submit("b", i) for i in range(2)]
    for f in futs:
        with pytest.raises(RuntimeError, match="kaput"):
            f.result(timeout=5)
    mb.close()


def test_batcher_rejects_after_close():
    mb = MicroBatcher(_Recorder(), max_batch=1, max_delay_s=0.0)
    mb.close()
    with pytest.raises(RuntimeError, match="closed"):
        mb.submit("b", 1)


# --------------------------------------------------------------------------
# LRU result cache
# --------------------------------------------------------------------------


def test_lru_cache_eviction_order_and_counters():
    c = LRUCache(capacity=2)
    c.put("a", 1)
    c.put("b", 2)
    assert c.get("a") == 1  # refreshes a
    c.put("c", 3)  # evicts b (least recently used)
    assert c.get("b") is None
    assert c.get("a") == 1 and c.get("c") == 3
    assert c.hits == 3 and c.misses == 1
    assert len(c) == 2


def test_cached_latest_month_answer_does_not_outlive_macro_append(
        member_dirs, panel):
    """month=-1 ("latest") responses must drop out of the cache identity
    when /v1/macro advances the state: the month is resolved BEFORE the
    cache key is built."""
    engine = InferenceEngine(
        member_dirs, macro_history=panel["macro"][:6],
        stock_buckets=(64,), batch_buckets=(1,))
    service = ServingService(engine)
    payload = {"individual": panel["individual"][0].tolist()}  # month: -1
    st, b1 = service.handle("POST", "/v1/weights", payload)
    assert st == 200 and b1["month"] == 5 and b1["cached"] is False
    st, b2 = service.handle("POST", "/v1/weights", payload)
    assert b2["cached"] is True and b2["month"] == 5
    st, _ = service.handle(
        "POST", "/v1/macro", {"macro": panel["macro"][6].tolist()})
    assert st == 200
    st, b3 = service.handle("POST", "/v1/weights", payload)
    assert st == 200
    assert b3["cached"] is False and b3["month"] == 6  # not the stale row
    service.close()


def test_lru_cache_zero_capacity_disables():
    c = LRUCache(capacity=0)
    c.put("a", 1)
    assert c.get("a") is None and len(c) == 0


# --------------------------------------------------------------------------
# HTTP service: endpoints, cache, healthz-heartbeat agreement, telemetry
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def http_service(member_dirs, panel, tmp_path_factory):
    run_dir = tmp_path_factory.mktemp("serve_run")
    from deeplearninginassetpricing_paperreplication_tpu.observability import (
        EventLog,
    )

    events = EventLog(run_dir)
    engine = InferenceEngine(
        member_dirs, macro_history=panel["macro"],
        stock_buckets=(64,), batch_buckets=(1, 2), events=events)
    service = ServingService(engine, run_dir=str(run_dir), events=events)
    service.warmup()
    httpd = make_server(service)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    host, port = httpd.server_address[:2]
    yield {"url": f"http://{host}:{port}", "service": service,
           "run_dir": run_dir, "engine": engine}
    httpd.shutdown()
    service.close()
    events.close()


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_weights_bit_identical_and_cached(http_service, panel, offline):
    base = http_service["url"]
    payload = {"individual": panel["individual"][5].tolist(),
               "mask": panel["mask"][5].tolist(), "month": 5}
    st, body = _post(base, "/v1/weights", payload)
    assert st == 200 and body["cached"] is False
    served = np.asarray(body["weights"], np.float64).astype(np.float32)
    np.testing.assert_array_equal(served, offline["avg_weights"][5])
    st2, body2 = _post(base, "/v1/weights", payload)
    assert st2 == 200 and body2["cached"] is True
    assert body2["weights"] == body["weights"]


def test_http_sdf_endpoint(http_service, panel, offline):
    base = http_service["url"]
    st, body = _post(base, "/v1/sdf", {
        "individual": panel["individual"][7].tolist(),
        "mask": panel["mask"][7].tolist(),
        "returns": panel["returns"][7].tolist(), "month": 7})
    assert st == 200
    assert body["sdf"] == pytest.approx(
        float(offline["ensemble_port_returns"][7]), abs=0)
    assert len(body["member_sdf"]) == len(SEEDS)


def test_http_healthz_agrees_with_heartbeat_file(http_service):
    base, run_dir = http_service["url"], http_service["run_dir"]
    from deeplearninginassetpricing_paperreplication_tpu.observability import (
        read_state,
    )

    for _ in range(3):  # the idle beat may land between the two reads
        st, body = _get(base, "/healthz")
        on_disk = read_state(run_dir / "heartbeat.json").get("heartbeat")
        assert st == 200 and body["ok"] is True
        if body["heartbeat"] == on_disk:
            break
    assert body["heartbeat"]["section"] == on_disk["section"]
    assert body["heartbeat"]["ts"] == on_disk["ts"]


def test_http_models_and_metrics(http_service):
    base = http_service["url"]
    st, info = _get(base, "/v1/models")
    assert st == 200
    assert info["n_members"] == len(SEEDS)
    assert info["config_hash"] == http_service["engine"].config_hash
    assert info["engine"]["stock_buckets"] == [64]
    st, m = _get(base, "/metrics")
    assert st == 200
    assert m["engine"]["compiles"] >= 1
    assert "cache" in m and "batcher" in m


def test_http_macro_advance_roundtrip(http_service, panel):
    base = http_service["url"]
    months_before = http_service["engine"].months
    st, body = _post(base, "/v1/macro",
                     {"macro": panel["macro"][3].tolist()})
    assert st == 200 and body["months"] == months_before + 1
    st, w = _post(base, "/v1/weights", {
        "individual": panel["individual"][3].tolist(),
        "month": months_before})
    assert st == 200


def test_http_error_paths(http_service):
    base = http_service["url"]
    st, body = _post(base, "/v1/weights", {"individual": [[1.0, 2.0]]})
    assert st == 400 and "individual" in body["error"]
    st, body = _post(base, "/v1/sdf", {"individual": [[0.0] * F] * 4})
    assert st == 400 and "returns" in body["error"]
    st, body = _get(base, "/v1/nope")
    assert st == 404
    st, body = _get(base, "/v1/weights")  # GET on a POST endpoint
    assert st == 405


def test_loadgen_closed_loop_smoke(http_service, panel):
    out = run_loadgen(
        http_service["url"] + "/v1/weights",
        lambda i: {"individual": panel["individual"][i % T].tolist(),
                   "month": i % T},
        mode="closed", concurrency=2, n_requests=10, warmup_requests=1)
    assert out["n_ok"] == 10 and not out["errors"]
    assert out["latency"]["count"] == 10
    assert out["latency"]["p50_ms"] <= out["latency"]["p99_ms"]
    assert out["throughput_rps"] > 0


def test_loadgen_open_loop_smoke(http_service, panel):
    out = run_loadgen(
        http_service["url"] + "/v1/weights",
        lambda i: {"individual": panel["individual"][i % T].tolist(),
                   "month": i % T},
        mode="open", rate_rps=50.0, n_requests=8, warmup_requests=0)
    assert out["n_ok"] == 8
    assert out["rate_rps"] == 50.0


# --------------------------------------------------------------------------
# report CLI: serving section from a service run dir's events.jsonl
# --------------------------------------------------------------------------


def test_report_prints_serving_section(member_dirs, panel, tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.observability import (
        EventLog,
    )
    from deeplearninginassetpricing_paperreplication_tpu.report import main

    run_dir = tmp_path / "serve_run"
    events = EventLog(run_dir)
    engine = InferenceEngine(
        member_dirs, macro_history=panel["macro"],
        stock_buckets=(64,), batch_buckets=(1,), events=events)
    service = ServingService(engine, run_dir=str(run_dir), events=events)
    service.warmup()
    payload = {"individual": panel["individual"][0].tolist(), "month": 0}
    assert service.handle("POST", "/v1/weights", payload)[0] == 200
    assert service.handle("POST", "/v1/weights", payload)[0] == 200  # hit
    assert service.handle("GET", "/metrics", None)[0] == 200
    service.close()
    events.close()

    rc = main([str(run_dir)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "serving:" in out
    assert "/v1/weights 200: 2" in out
    assert "result cache: 1 hits, 1 misses" in out
    assert "recompiles:" in out

    rc = main([str(run_dir), "--json"])
    summary = json.loads(capsys.readouterr().out)
    assert rc == 0
    sv = summary["serving"]
    assert sv["total_requests"] == 3
    assert sv["cache"] == {"hits": 1, "misses": 1, "hit_rate": 0.5}
    assert sv["latency"]["count"] == 3
    assert sv["recompiles"] >= 1  # warmup compiles are recorded
    assert sv["dispatches"] == 1  # the cache hit never reached the engine


def test_report_nonserving_run_has_no_serving_section(tmp_path, capsys):
    from deeplearninginassetpricing_paperreplication_tpu.report import main

    (tmp_path / "events.jsonl").write_text("")
    rc = main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0 and "serving:" not in out


# --------------------------------------------------------------------------
# bench artifact + lint gate extension to the serving package
# --------------------------------------------------------------------------


def test_bench_serving_artifact_exists_and_is_wellformed():
    data = json.loads((REPO / "BENCH_SERVING.json").read_text())
    for key in ("closed_loop_c1", "closed_loop_c4", "open_loop_0.8cap",
                "compiles", "dispatches"):
        assert key in data, key
    for loop in ("closed_loop_c1", "closed_loop_c4"):
        lat = data[loop]["latency"]
        assert lat["p50_ms"] <= lat["p95_ms"] <= lat["p99_ms"]
    # steady state is recompile-free: every compile is a warmup compile
    # (forward programs + the macro-step program)
    assert data["compiles"] <= data["dispatches"]


SERVING_DIR = (REPO / "deeplearninginassetpricing_paperreplication_tpu"
               / "serving")


def test_serving_package_lints_clean():
    import sys

    from test_observability import _ast_unused_imports

    try:
        import subprocess

        import ruff  # noqa: F401

        proc = subprocess.run(
            [sys.executable, "-m", "ruff", "check", str(SERVING_DIR)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
    except ImportError:
        problems = {}
        for path in sorted(SERVING_DIR.glob("*.py")):
            unused = _ast_unused_imports(path)
            if unused:
                problems[path.name] = unused
        assert not problems, f"unused imports: {problems}"


def test_mesh_pr_touched_modules_lint_clean():
    """PR-17 lint extension: the non-serving modules the mesh-serving PR
    touched (serving/*.py rides the glob above) plus the test files that
    grew the mesh/donation matrices."""
    from test_observability import _ast_unused_imports

    pkg = REPO / "deeplearninginassetpricing_paperreplication_tpu"
    targets = [
        pkg / "training" / "trainer.py",
        pkg / "parallel" / "partition.py",
        REPO / "bench.py",
        REPO / "tests" / "test_serving.py",
        REPO / "tests" / "test_promotion.py",
        REPO / "tests" / "test_training.py",
    ]
    problems = {}
    for path in targets:
        unused = _ast_unused_imports(path)
        if unused:
            problems[path.name] = unused
    assert not problems, f"unused imports: {problems}"


# --------------------------------------------------------------------------
# mesh-native engine: stock-sharded AOT programs on the 8-device test mesh
# --------------------------------------------------------------------------

# the PR-13 identity contract, applied to serving: a DEGENERATE mesh
# (stocks=1, or no mesh) is a placement-only change and must be BITWISE
# identical; a stock-sharded mesh turns the masked cross-sectional sums
# into cross-device psums whose reduction order differs from the serial
# sum — the one surface where bitwise is physically off the table
# (documented at 2e-5 for train steps; serving forwards measure ~1e-8,
# gated here with margin at 1e-6).
SHARDED_ATOL = 1e-6


def _mesh_engine(member_dirs, panel, mesh, **kw):
    kw.setdefault("stock_buckets", (64, 96))
    kw.setdefault("batch_buckets", (1, 2))
    return InferenceEngine(member_dirs, macro_history=panel["macro"],
                           mesh=mesh, **kw)


def test_engine_degenerate_mesh_bitwise_identical(member_dirs, panel,
                                                  engine, offline):
    eng = _mesh_engine(member_dirs, panel, "stocks=1")
    stats = eng.stats()
    assert stats["mesh"] == "stocks=1"
    assert stats["stock_shards"] == 1
    assert stats["sharded_dispatch"] is False
    for t in (0, 5, T - 1):
        res = eng.infer_one(InferenceRequest(
            individual=panel["individual"][t], mask=panel["mask"][t],
            returns=panel["returns"][t], month=t))
        np.testing.assert_array_equal(res.weights,
                                      offline["avg_weights"][t])
        assert res.sdf == float(offline["ensemble_port_returns"][t])


def test_engine_sharded_mesh_matches_single_device(member_dirs, panel,
                                                   offline):
    """stocks=8 over the full test mesh: per-device span staging, sharded
    AOT programs, outputs within the stock-GSPMD tolerance — and ZERO
    steady-state recompiles across every bucket/micro-batch shape."""
    eng = _mesh_engine(member_dirs, panel, "stocks=8")
    stats = eng.stats()
    assert stats["mesh"] == "stocks=8"
    assert stats["stock_shards"] == 8
    assert stats["mesh_devices"] == 8
    assert stats["sharded_dispatch"] is True
    n_programs = eng.warmup()
    assert n_programs == 4
    compiles0 = eng.stats()["compiles"]
    for t in (0, 3, T - 1):
        res = eng.infer_one(InferenceRequest(
            individual=panel["individual"][t], mask=panel["mask"][t],
            returns=panel["returns"][t], month=t))
        np.testing.assert_allclose(res.weights, offline["avg_weights"][t],
                                   atol=SHARDED_ATOL, rtol=0)
        assert abs(res.sdf - float(offline["ensemble_port_returns"][t])) \
            < SHARDED_ATOL
    # micro-batched + padded-bucket traffic through the sharded programs
    res = eng.infer([
        InferenceRequest(individual=panel["individual"][t],
                         mask=panel["mask"][t], month=t)
        for t in (2, 9)
    ])
    for r, t in zip(res, (2, 9)):
        assert r.batch_bucket == 2
        np.testing.assert_allclose(r.weights, offline["avg_weights"][t],
                                   atol=SHARDED_ATOL, rtol=0)
    short = panel["individual"][4][:40]  # pads 40 -> 64 bucket, 8 shards
    r40 = eng.infer_one(InferenceRequest(individual=short, month=4))
    assert r40.bucket == 64 and r40.n == 40
    assert eng.stats()["compiles"] == compiles0, (
        "sharded steady-state serving must not recompile")
    assert eng.stats()["steady_state_recompiles"] == 0


def test_engine_mesh_member_axis(panel, tmp_path_factory, serve_cfg,
                                 offline):
    """members=2,stocks=4: the member axis shards the K-stack, stocks
    shard within each member row — still within tolerance of the
    single-device 2-member engine."""
    root = tmp_path_factory.mktemp("members2")
    dirs2 = [_write_member(root / f"seed_{s}", serve_cfg, s)
             for s in SEEDS[:2]]
    ref = InferenceEngine(dirs2, macro_history=panel["macro"],
                          stock_buckets=(64,), batch_buckets=(1,))
    eng = InferenceEngine(dirs2, macro_history=panel["macro"],
                          stock_buckets=(64,), batch_buckets=(1,),
                          mesh="members=2,stocks=4")
    stats = eng.stats()
    assert stats["member_axis"] == "members"
    assert stats["stock_shards"] == 4
    for t in (1, 7):
        req = InferenceRequest(individual=panel["individual"][t],
                               mask=panel["mask"][t],
                               returns=panel["returns"][t], month=t)
        a, b = ref.infer_one(req), eng.infer_one(req)
        np.testing.assert_allclose(b.weights, a.weights,
                                   atol=SHARDED_ATOL, rtol=0)
        assert abs(a.sdf - b.sdf) < SHARDED_ATOL


def test_engine_mesh_validation(member_dirs, panel):
    # bucket not divisible by the stock-shard count
    with pytest.raises(ValueError, match="divisible"):
        InferenceEngine(member_dirs, macro_history=panel["macro"],
                        stock_buckets=(60,), batch_buckets=(1,),
                        mesh="stocks=8")
    # member axis not dividing the 3-member ensemble
    with pytest.raises(ValueError, match="member"):
        InferenceEngine(member_dirs, macro_history=panel["macro"],
                        stock_buckets=(64,), batch_buckets=(1,),
                        mesh="members=2,stocks=4")


def test_engine_mesh_hot_swap_reload_without_recompile(
        tmp_path_factory, serve_cfg, panel):
    """The PR-9/PR-14 hot-swap discipline holds on sharded programs: a
    reload() re-stacks params and re-derives the macro state with ZERO
    recompiles, and the swapped generation matches a fresh single-device
    engine of the new params within the sharded tolerance."""
    root = tmp_path_factory.mktemp("swap_mesh")
    dirs = [_write_member(root / f"seed_{s}", serve_cfg, s) for s in SEEDS]
    eng = InferenceEngine(dirs, macro_history=panel["macro"],
                          stock_buckets=(64,), batch_buckets=(1,),
                          mesh="stocks=8")
    eng.warmup()
    compiles0 = eng.stats()["compiles"]
    eng.infer_one(InferenceRequest(
        individual=panel["individual"][0], month=0))

    # rewrite member 0 in place (new params, same architecture)
    _write_member(Path(dirs[0]), serve_cfg, 99)
    out = eng.reload()
    assert out["swapped"] is True
    ref = InferenceEngine(dirs, macro_history=panel["macro"],
                          stock_buckets=(64,), batch_buckets=(1,))
    for t in (0, 6):
        req = InferenceRequest(individual=panel["individual"][t],
                               mask=panel["mask"][t], month=t)
        np.testing.assert_allclose(eng.infer_one(req).weights,
                                   ref.infer_one(req).weights,
                                   atol=SHARDED_ATOL, rtol=0)
    assert eng.stats()["compiles"] == compiles0, (
        "hot-swap on a sharded engine must not recompile")
    assert eng.stats()["steady_state_recompiles"] == 0


def test_engine_mesh_macro_append_matches_rescan(member_dirs, panel):
    """Incremental macro appends drive the same sharded programs: the
    appended-state outputs equal a fresh sharded engine scanning the full
    history (same dispatch route, so bitwise)."""
    rng = np.random.default_rng(3)
    new_rows = rng.standard_normal((2, M)).astype(np.float32)
    inc = _mesh_engine(member_dirs, panel, "stocks=8",
                       stock_buckets=(64,), batch_buckets=(1,))
    for row in new_rows:
        inc.append_month(row)
    full = InferenceEngine(
        member_dirs,
        macro_history=np.concatenate([panel["macro"], new_rows]),
        stock_buckets=(64,), batch_buckets=(1,), mesh="stocks=8")
    req = InferenceRequest(individual=panel["individual"][1],
                           mask=panel["mask"][1], month=T + 1)
    np.testing.assert_allclose(inc.infer_one(req).weights,
                               full.infer_one(req).weights,
                               atol=1e-6, rtol=0)


def test_fleet_mesh_slice_argv_and_layout(tmp_path):
    """The fleet parent stamps the replica<->device-slice lease WITHOUT
    importing jax: --mesh_slice i%N:N in each child argv, and fleet.json
    publishes the mapping."""
    from deeplearninginassetpricing_paperreplication_tpu.serving.autoscale import (  # noqa: E501
        FleetController,
    )
    from deeplearninginassetpricing_paperreplication_tpu.serving.fleet import (
        read_fleet_json,
        server_child_argv,
    )
    from deeplearninginassetpricing_paperreplication_tpu.serving.server import (
        build_arg_parser,
    )

    args = build_arg_parser().parse_args([
        "--checkpoint_dirs", "m0", "m1",
        "--mesh", "stocks=-1", "--mesh_slices", "2",
    ])
    for rid, want in ((0, "0:2"), (1, "1:2"), (2, "0:2")):
        argv = server_child_argv(args, rid, tmp_path / f"r{rid}", 8000)
        assert argv[argv.index("--mesh") + 1] == "stocks=-1"
        assert argv[argv.index("--mesh_slice") + 1] == want
    # without --mesh nothing is stamped
    bare = build_arg_parser().parse_args(["--checkpoint_dirs", "m0"])
    argv = server_child_argv(bare, 0, tmp_path / "r", 8000)
    assert "--mesh" not in argv and "--mesh_slice" not in argv

    class _FakeFleet:
        run_dir = tmp_path
        replicas = 2

        @staticmethod
        def live_ids():
            return [0, 1]

    ctl = FleetController(
        _FakeFleet(), make_argv=lambda r, a: [], host="127.0.0.1",
        port=8000, admin_ports={0: 9000, 1: 9001},
        mesh="stocks=-1", mesh_slices=2)
    ctl.publish_layout()
    layout = read_fleet_json(tmp_path)
    assert layout["mesh"] == "stocks=-1"
    assert layout["mesh_slices"] == 2
    assert layout["mesh_slice_by_replica"] == {"0": "0:2", "1": "1:2"}


def test_server_cli_mesh_slice_resolves_disjoint_devices():
    """--mesh stocks=-1 --mesh_slice i:2 resolves to slice i's 4 devices
    (the replica-side half of the lease the fleet parent stamps)."""
    from deeplearninginassetpricing_paperreplication_tpu.parallel import (
        partition,
    )

    devs = jax.devices()
    cfg0 = partition.MeshConfig(
        (("stocks", -1),), partition.slice_devices(0, 2, devices=devs))
    cfg1 = partition.MeshConfig(
        (("stocks", -1),), partition.slice_devices(1, 2, devices=devs))
    m0, m1 = cfg0.build(), cfg1.build()
    assert dict(m0.shape) == {"stocks": 4} == dict(m1.shape)
    assert not (set(m0.devices.flat) & set(m1.devices.flat))


def test_bench_meshserve_artifact_bars():
    """BENCH_MESHSERVE.json holds the bars budgets.json gates, so the
    artifact and the tier-1 budget gate can never disagree."""
    data = json.loads((REPO / "BENCH_MESHSERVE.json").read_text())
    assert data["devices"] == 8
    assert data["bit_identical"] == 1
    assert data["degenerate_bitwise"] == 1
    assert data["sharded_max_abs_diff"] <= data["tolerance"]
    assert data["steady_state_recompiles_max"] == 0
    assert all(v == 0 for v in data["steady_state_recompiles"].values())
    assert data["fault_matrix"]["dropped_requests"] == 0
    assert sum(data["fault_matrix"]["replica_restarts"]) >= 1
    meshes = data["fault_matrix"]["replica_meshes"]
    assert all(m == "stocks=4" for m in meshes.values())
    assert data["hot_swap"]["swapped"] is True
    assert data["hot_swap"]["max_abs_diff"] <= data["tolerance"]
