"""One process of a multi-process distributed run — the executable proof
that `parallel.multihost` coordinates real processes.

Each worker joins the cluster through ``initialize_distributed`` (TCP
coordinator), builds the DCN-outer/ICI-inner hybrid mesh, constructs a
GLOBAL panel batch spanning both processes' devices
(``jax.make_array_from_callback`` — every process materializes only its
addressable shards), runs ONE jitted conditional train step of the GAN with
the member axis on the cross-process 'batch' rows and the stock axis
process-local, and prints a JSON result line. The spawner (the slow-lane
test ``tests/test_parallel.py::test_two_process_distributed_train_step`` and
the ``__graft_entry__`` dryrun) asserts both workers agree on the loss —
which they can only do if the cross-process collectives actually ran.

The reference has no distributed code at all (SURVEY §2b); this is the
TPU-native counterpart of an NCCL/MPI smoke test. Launch (env must be set
BEFORE Python starts — the package import initializes JAX):

    JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=2 \
    python -m deeplearninginassetpricing_paperreplication_tpu.parallel.multihost_worker \
        --coordinator localhost:9876 --num_processes 2 --process_id 0
"""

from __future__ import annotations

import argparse
import json


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--coordinator", required=True,
                   help="host:port of process 0's coordinator service")
    p.add_argument("--num_processes", type=int, required=True)
    p.add_argument("--process_id", type=int, required=True)
    p.add_argument("--n_stocks_per_device", type=int, default=8)
    p.add_argument("--run_dir", type=str, default=None,
                   help="Telemetry dir: every process writes its own "
                        "events file (events.jsonl / events.proc{p}.jsonl) "
                        "and heartbeat.proc{p}.json there; human-readable "
                        "lines come from process 0 only")
    p.add_argument("--run_id", type=str, default=None,
                   help="Shared run id for all processes of one launch "
                        "(the spawner passes one value to every worker so "
                        "their event streams cross-reference); default: "
                        "each process generates its own")
    args = p.parse_args(argv)

    # initialize the distributed runtime BEFORE anything can touch the
    # backend (model-module imports build default ExecutionConfigs etc.)
    import jax

    # this image's sitecustomize re-pins JAX_PLATFORMS=axon at interpreter
    # start, overriding the spawner's env — force the CPU platform via the
    # config, which wins over the env var (same workaround as tests/conftest)
    jax.config.update("jax_platforms", "cpu")

    from .multihost import initialize_distributed, process_local_summary

    ok = initialize_distributed(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert ok, "initialize_distributed returned False with explicit args"

    import numpy as np
    from jax.sharding import PartitionSpec as P

    from ..models.gan import GAN
    from ..observability import (
        EventLog,
        Heartbeat,
        RunLogger,
        set_run_logger,
        write_manifest,
    )
    from ..training.steps import make_optimizer, make_train_step
    from ..utils.config import GANConfig
    from .multihost import create_hybrid_mesh
    from .partition import named_sharding
    assert jax.process_count() == args.num_processes, (
        jax.process_count(), args.num_processes)

    # every process writes its OWN structured stream; only process 0 prints
    # human-readable lines (RunLogger gates on process_index)
    events = (EventLog(args.run_dir, run_id=args.run_id) if args.run_dir
              else EventLog(run_id=args.run_id))
    logger = set_run_logger(RunLogger(events=events))
    hb = None
    if args.run_dir:
        from pathlib import Path

        hb = Heartbeat(
            Path(args.run_dir) / f"heartbeat.proc{args.process_id}.json",
            events=events,
        )
        hb.beat("init")
    logger.info(f"[multihost] {args.num_processes} processes joined; "
                f"{len(jax.devices())} global devices")

    n_dev = len(jax.devices())
    if hb is not None:
        hb.beat("mesh")
    with events.span("multihost/mesh_build"):
        mesh = create_hybrid_mesh(members_per_host_group=args.num_processes)
    if args.run_dir and args.process_id == 0:
        write_manifest(args.run_dir, "multihost_worker", events=events,
                       argv=argv, mesh=mesh)
    # the outer ('batch') axis must cross processes: row p's devices all
    # belong to process-granule p
    for row, devs in enumerate(mesh.devices):
        owners = {d.process_index for d in devs}
        assert owners == {row % args.num_processes}, (
            f"outer mesh row {row} spans processes {owners}")

    T, M, F = 6, 4, 5
    n_batch = mesh.devices.shape[0]
    N = args.n_stocks_per_device * mesh.devices.shape[1]
    rng = np.random.default_rng(0)  # identical panel in every process
    mask = (rng.random((T, N)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0
    host = {
        "macro": rng.standard_normal((T, M)).astype(np.float32),
        "individual": (rng.standard_normal((T, N, F)) * mask[:, :, None]
                       ).astype(np.float32),
        "returns": (rng.standard_normal((T, N)) * 0.05 * mask
                    ).astype(np.float32),
        "mask": mask,
    }

    def put(x, spec):
        sharding = named_sharding(mesh, spec)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx])

    stock_axis = mesh.axis_names[1]
    batch = {
        "macro": put(host["macro"], P()),
        "individual": put(host["individual"], P(None, stock_axis, None)),
        "returns": put(host["returns"], P(None, stock_axis)),
        "mask": put(host["mask"], P(None, stock_axis)),
    }

    cfg = GANConfig(macro_feature_dim=M, individual_feature_dim=F,
                    hidden_dim=(4,), num_units_rnn=(2,), dropout=0.0)
    gan = GAN(cfg)
    tx = make_optimizer(1e-3)
    # members ride the cross-process 'batch' rows: init identically in every
    # process, then lay the member axis over the outer mesh axis
    seeds = jax.random.split(jax.random.key(7), n_batch)
    host_vparams = jax.device_get(
        jax.vmap(lambda k: gan.init(k, T=T, N=N))(seeds))
    vparams = jax.tree.map(
        lambda x: put(np.asarray(x), P(mesh.axis_names[0])), host_vparams)
    step = make_train_step(gan, "conditional", tx)

    def one_member(p, key):
        opt = tx.init(p["sdf_net"])
        _new_p, _opt, m = step(p, opt, batch, key)
        return m["loss"]

    if hb is not None:
        hb.beat("train_step", memory=True)
    with events.span("multihost/train_step", n_members=int(n_batch)):
        losses = jax.jit(jax.vmap(one_member, in_axes=(0, 0)))(
            vparams, jax.random.split(jax.random.key(9), n_batch))
        # fully-addressable replication of the loss vector is itself a
        # cross-process collective; fetching it proves the step really ran
        loss_host = np.asarray(
            jax.device_get(jax.jit(lambda x: x, out_shardings=named_sharding(
                mesh, P()))(losses)))
    assert loss_host.shape == (n_batch,) and np.all(np.isfinite(loss_host))
    if hb is not None:
        hb.beat("done", memory=True)

    # the result line is PROTOCOL output (the spawner parses each worker's
    # stdout for it), not logging — every process prints it, always last
    print(json.dumps({
        "summary": process_local_summary(),
        "mesh_shape": list(mesh.devices.shape),
        "axis_names": list(mesh.axis_names),
        "n_global_devices": n_dev,
        "losses": [round(float(x), 8) for x in loss_host],
    }), flush=True)


if __name__ == "__main__":
    main()
