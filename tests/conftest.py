"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip sharding paths are exercised on CPU via XLA's host-platform device
partitioning — the TPU-native way to test multi-device code without a pod.
Must run before jax is imported anywhere.
"""

import os

# Hard override: this image's sitecustomize pins JAX_PLATFORMS=axon (the TPU
# tunnel) and re-registers the plugin at interpreter start, so setdefault —
# and even an env prefix — is not enough. Set both the env var and the jax
# config before any device is touched.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

import tempfile

# isolate the decoded-panel disk cache (data/diskcache.py) from the user's
# real cache dir: CLI tests exercise the startup pipeline, which would
# otherwise persist tmp fixtures' decodes into ~/.cache. Tests that probe
# cache behavior monkeypatch their own dir over this.
if "DLAP_PANEL_CACHE_DIR" not in os.environ:
    os.environ["DLAP_PANEL_CACHE_DIR"] = tempfile.mkdtemp(
        prefix="dlap_test_panel_cache_"
    )
    import atexit
    import shutil

    atexit.register(
        shutil.rmtree, os.environ["DLAP_PANEL_CACHE_DIR"], ignore_errors=True
    )

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu" and len(jax.devices()) >= 8, (
    "tests require the 8-device virtual CPU mesh; got " + repr(jax.devices())
)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def synthetic_dir(tmp_path_factory):
    """Small seeded synthetic dataset shared by the suite."""
    from deeplearninginassetpricing_paperreplication_tpu.data.synthetic import (
        generate_all_splits,
    )

    out = tmp_path_factory.mktemp("synthetic")
    generate_all_splits(
        out,
        n_periods_train=24,
        n_periods_valid=8,
        n_periods_test=12,
        n_stocks=64,
        n_features=10,
        n_macro=6,
        seed=7,
        verbose=False,
    )
    return out


@pytest.fixture(scope="session")
def splits(synthetic_dir):
    from deeplearninginassetpricing_paperreplication_tpu.data.panel import load_splits

    return load_splits(synthetic_dir)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
