"""Real-data acquisition: the authors' 1.2 GB .npz panel from Google Drive.

Counterpart of the reference's ``src/download_data.py`` (pointers and
expected sizes from ``/root/reference/src/download_data.py:31-45``). The
`gdown` dependency is hard-gated: everything except the actual network pull
(existence checks, size validation, restructuring) works without it, and the
synthetic generator (``data/synthetic.py``) is the offline substitute.

Layout produced:
    data_dir/char/Char_{train,valid,test}.npz
    data_dir/macro/macro_{train,valid,test}.npz
"""

from __future__ import annotations

import argparse
import shutil
import zipfile
from pathlib import Path
from typing import Dict, List, Tuple, Union

# Authors' Google Drive (Chen-Pelger-Zhu replication data)
DATASETS_ZIP_ID = "1h9O7YwPLaRBbghtF50Cr-JmIq0aHHi4Y"
GDRIVE_FOLDER_ID = "1TrYzMUA_xLID5-gXOy_as8sH2ahLwz-l"

EXPECTED_SIZES_BYTES: Dict[str, int] = {
    "Char_train.npz": 317 * 1024 * 1024,
    "Char_valid.npz": 72 * 1024 * 1024,
    "Char_test.npz": 768 * 1024 * 1024,
    "macro_train.npz": 351 * 1024,
    "macro_valid.npz": 96 * 1024,
    "macro_test.npz": 436 * 1024,
}

REQUIRED_FILES: List[Tuple[str, str]] = [
    ("char", "Char_train.npz"),
    ("char", "Char_valid.npz"),
    ("char", "Char_test.npz"),
    ("macro", "macro_train.npz"),
    ("macro", "macro_valid.npz"),
    ("macro", "macro_test.npz"),
]


def check_data_exists(data_dir: Union[str, Path], verbose: bool = True) -> bool:
    """True iff all six .npz files are present (download_data.py:48-76)."""
    data_dir = Path(data_dir)
    missing = [
        sub + "/" + name
        for sub, name in REQUIRED_FILES
        if not (data_dir / sub / name).exists()
    ]
    if verbose:
        if missing:
            print(f"Missing {len(missing)}/6 data files under {data_dir}:")
            for m in missing:
                print(f"  - {m}")
        else:
            print(f"All 6 data files present under {data_dir}")
    return not missing


def validate_sizes(data_dir: Union[str, Path], tolerance: float = 0.5) -> Dict[str, bool]:
    """Compare on-disk sizes against the expected table (±tolerance)."""
    data_dir = Path(data_dir)
    out = {}
    for sub, name in REQUIRED_FILES:
        p = data_dir / sub / name
        if not p.exists():
            out[name] = False
            continue
        expected = EXPECTED_SIZES_BYTES[name]
        out[name] = abs(p.stat().st_size - expected) <= tolerance * expected
    return out


def _require_gdown():
    try:
        import gdown  # noqa

        return gdown
    except ImportError as e:
        raise ImportError(
            "Downloading the real dataset requires `gdown` (not bundled in "
            "this environment). Install it, or use the offline synthetic "
            "generator instead:\n  python -m "
            "deeplearninginassetpricing_paperreplication_tpu.data.synthetic "
            "--output_dir ./data"
        ) from e


def restructure_zip(zip_path: Union[str, Path], data_dir: Union[str, Path]) -> None:
    """Unpack datasets.zip and arrange files into char/ and macro/
    (download_data.py:121-159)."""
    data_dir = Path(data_dir)
    (data_dir / "char").mkdir(parents=True, exist_ok=True)
    (data_dir / "macro").mkdir(parents=True, exist_ok=True)
    extract_dir = data_dir / "_extract"
    with zipfile.ZipFile(zip_path) as zf:
        zf.extractall(extract_dir)
    for npz in extract_dir.rglob("*.npz"):
        sub = "char" if npz.name.startswith("Char") else "macro"
        shutil.move(str(npz), str(data_dir / sub / npz.name))
    shutil.rmtree(extract_dir, ignore_errors=True)


def download_all_data(
    data_dir: Union[str, Path] = "./data",
    force: bool = False,
    quiet: bool = False,
) -> bool:
    """Pull datasets.zip from the authors' Drive and restructure it."""
    data_dir = Path(data_dir)
    if not force and check_data_exists(data_dir, verbose=False):
        if not quiet:
            print("Data already present; use force=True to re-download")
        return True
    gdown = _require_gdown()
    data_dir.mkdir(parents=True, exist_ok=True)
    zip_path = data_dir / "datasets.zip"
    url = f"https://drive.google.com/uc?id={DATASETS_ZIP_ID}"
    if not quiet:
        print(f"Downloading {url} → {zip_path} (~1.2 GB)")
    result = gdown.download(url, str(zip_path), quiet=quiet)
    # gdown returns None (without raising) on failure, e.g. Drive quota
    # exceeded — a common state for this public 1.2 GB file
    if result is None or not zip_path.exists() or not zipfile.is_zipfile(zip_path):
        zip_path.unlink(missing_ok=True)
        raise RuntimeError(
            "Download failed (Google Drive quota exceeded or network error). "
            "Retry later, download manually from "
            f"https://drive.google.com/drive/folders/{GDRIVE_FOLDER_ID}, or "
            "use the offline synthetic generator:\n  python -m "
            "deeplearninginassetpricing_paperreplication_tpu.data.synthetic"
        )
    restructure_zip(zip_path, data_dir)
    zip_path.unlink(missing_ok=True)
    ok = check_data_exists(data_dir, verbose=not quiet)
    if ok:
        bad = [k for k, v in validate_sizes(data_dir).items() if not v]
        if bad and not quiet:
            print(f"WARNING: unexpected file sizes: {bad}")
    return ok


def main(argv=None):
    p = argparse.ArgumentParser(description="Download the real asset-pricing panel")
    p.add_argument("--data_dir", type=str, default="./data")
    p.add_argument("--check", action="store_true", help="Only check existence")
    p.add_argument("--force", action="store_true")
    args = p.parse_args(argv)
    if args.check:
        ok = check_data_exists(args.data_dir)
        raise SystemExit(0 if ok else 1)
    download_all_data(args.data_dir, force=args.force)


if __name__ == "__main__":
    main()
