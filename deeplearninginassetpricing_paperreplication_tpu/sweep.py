"""The paper protocol as ONE command: 384-config search → top-k × 9 seeds →
weight-averaged ensembles → test Sharpe report, checkpointing everything.

The reference has NO sweep code — its README (``/root/reference/README.md:
205-207``) and the paper (§II.E: "384 models … four best … 9 models") describe
the protocol but the repo leaves it to the reader (the ~6 h serial 9-seed loop
in ``demo_full.ipynb`` cell 22 is commented out). Here the whole protocol is
TPU-native: the search trains each architecture bucket's (lr × seed) grid as
one vmapped program, every winner's 9-seed ensemble is one vmapped program,
and evaluation follows ``evaluate_ensemble.py:137-171`` exactly (averaged
normalized weights, re-normalized, negated Sharpe, ddof=0).

    python -m deeplearninginassetpricing_paperreplication_tpu.sweep \
        --data_dir data/synthetic_data --save_dir ./sweep_run --quick

Artifacts in --save_dir:
    sweep_ranking.json                 — every (config, lr, seed) + valid Sharpe
    rank{r}_seed{s}/config.json        — per-member checkpoint dirs in the
    rank{r}_seed{s}/best_model_sharpe.msgpack  reference layout (consumable by
                                         evaluate_ensemble --checkpoint_dirs)
    report.json                        — per-winner + grand ensemble Sharpes
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .models.gan import GAN
from .observability import (
    EventLog,
    Heartbeat,
    RunLogger,
    get_run_logger,
    set_run_logger,
    write_manifest,
)
from .parallel.ensemble import (
    ensemble_metrics,
    ensemble_metrics_from_weights,
    member_weights,
    train_ensemble,
)
from .parallel.sweep import architecture_signature, grid_configs, run_sweep
from .training.checkpoint import save_params
from .utils.config import GANConfig, TrainConfig

PAPER_SEEDS = (42, 123, 456, 789, 1000, 2000, 3000, 4000, 5000)


def _finite(x: float):
    """JSON-safe scalar: -inf (a grid point whose trackers never updated)
    would serialize as the non-standard '-Infinity' and break downstream
    parsers; map non-finite to None."""
    import math

    return x if math.isfinite(x) else None


def load_ranking(path) -> List[Dict]:
    """Parse a written sweep_ranking.json back into run_protocol's ranking
    rows (GANConfig round-trip; JSON null — a never-updated tracker — maps
    back to -inf so it sorts below every real Sharpe)."""
    rows = json.loads(Path(path).read_text())
    return [
        {
            "config": GANConfig.from_dict(r["config"]),
            "lr": r["lr"],
            "seed": r["seed"],
            "valid_sharpe": (
                r["valid_sharpe"] if r["valid_sharpe"] is not None
                else float("-inf")
            ),
        }
        for r in rows
    ]


def select_winners(ranked: List[Dict], top_k: int) -> List[Dict]:
    """Top-k DISTINCT (architecture, lr) combos from a ranked sweep result.

    The search grid ranks (config, lr, seed) points; the protocol's "best 4
    configs" are distinct hyperparameter settings, so multiple seeds of one
    setting collapse to its best-ranked entry."""
    winners, seen = [], set()
    for r in ranked:
        key = (architecture_signature(r["config"]), r["lr"])
        if key not in seen:
            seen.add(key)
            winners.append(r)
        if len(winners) == top_k:
            break
    return winners


def run_protocol(
    configs_and_lrs: Sequence[Tuple[GANConfig, float]],
    train_batch,
    valid_batch,
    test_batch,
    search_tcfg: TrainConfig,
    ensemble_tcfg: TrainConfig,
    search_seeds: Sequence[int] = (42,),
    ensemble_seeds: Sequence[int] = PAPER_SEEDS,
    top_k: int = 4,
    save_dir: Optional[str] = None,
    verbose: bool = True,
    member_chunk: Optional[int] = None,
    exec_cfg=None,
    ranking: Optional[List[Dict]] = None,
    diagnostic_top: int = 8,
    diagnostic_seeds: Sequence[int] = (42, 123, 456),
    heartbeat=None,
) -> Dict:
    """Search → winners → per-winner vmapped 9-seed ensembles → report dict.

    `ranking`: a precomputed stage-1 result (the parsed sweep_ranking.json)
    — skips the search so an interrupted protocol resumes at the ensemble
    stage instead of repaying the full 384-config search.

    `diagnostic_top` / `diagnostic_seeds`: the selection-noise diagnostic
    needs more than top_k pairs to mean anything (VERDICT r4 weak #5: a
    Spearman over n=4 is close to meaningless) — ranks top_k..diagnostic_top
    are ALSO retrained (full schedule, `diagnostic_seeds` members each,
    cheap under the member-fused kernels) purely to widen the
    search-vs-retrain rank comparison to ≥8 pairs. Set diagnostic_top ≤
    top_k to disable the extra retrains.
    """
    t0 = time.time()
    save_dir = Path(save_dir) if save_dir else None
    logger = get_run_logger()

    def log(msg):
        logger.info(msg, verbose=verbose)

    # ---- stage 1: hyperparameter search ----
    search_stats: Dict = {}
    if ranking is not None:
        log(f"[protocol] reusing precomputed search ranking "
            f"({len(ranking)} points)")
        ranked = ranking
    else:
        log(f"[protocol] search: {len(configs_and_lrs)} (config, lr) combos "
            f"× {len(search_seeds)} seeds")
        with logger.events.span("protocol/search",
                                n_combos=len(configs_and_lrs)):
            ranked = run_sweep(
                configs_and_lrs, search_seeds, train_batch, valid_batch,
                tcfg=search_tcfg, top_k=None, keep_params=False,
                verbose=verbose, member_chunk=member_chunk, exec_cfg=exec_cfg,
                stats_out=search_stats, heartbeat=heartbeat,
            )
    search_s = time.time() - t0
    if save_dir:  # also on resume: keep the artifact contract in save_dir
        save_dir.mkdir(parents=True, exist_ok=True)
        (save_dir / "sweep_ranking.json").write_text(json.dumps(
            [
                {
                    "rank": i,
                    "config": r["config"].to_dict(),
                    "lr": r["lr"],
                    "seed": r["seed"],
                    "valid_sharpe": _finite(r["valid_sharpe"]),
                }
                for i, r in enumerate(ranked)
            ],
            indent=2,
        ))
    winners = select_winners(ranked, top_k)
    log(f"[protocol] search done in {search_s:.1f}s; top {len(winners)}:")
    for i, w in enumerate(winners):
        log(f"  #{i}: hidden={w['config'].hidden_dim} "
            f"rnn={w['config'].num_units_rnn} K={w['config'].num_condition_moment} "
            f"drop={w['config'].dropout} lr={w['lr']} "
            f"valid_sharpe={w['valid_sharpe']:.4f}")

    # ---- stage 2: per-winner 9-seed vmapped ensembles ----
    report = {
        "search_seconds": round(search_s, 1),
        "search_resumed_from_ranking": ranking is not None,
        "n_search_points": len(ranked),
        **({"search_stats": search_stats} if search_stats else {}),
        "winners": [],
    }
    all_test_weights = []  # [S, T, N] per winner, for the grand ensemble
    winner_vparams = []  # kept for the same-seed-count diagnostic below
    for rank, w in enumerate(winners):
        tcfg = dataclasses.replace(ensemble_tcfg, lr=w["lr"])
        log(f"[protocol] ensemble #{rank}: {len(ensemble_seeds)} seeds, "
            f"lr={w['lr']}")
        if heartbeat is not None:
            heartbeat.beat("winner_ensemble", rank=rank)
        with logger.events.span("protocol/ensemble", rank=rank,
                                n_seeds=len(ensemble_seeds)):
            gan, vparams, _hist = train_ensemble(
                w["config"], train_batch, valid_batch, test_batch,
                seeds=ensemble_seeds, tcfg=tcfg, verbose=verbose,
                member_chunk=member_chunk, exec_cfg=exec_cfg,
                heartbeat=heartbeat,
            )
        splits = {
            "train": train_batch, "valid": valid_batch, "test": test_batch,
        }
        metrics = {
            name: ensemble_metrics(gan, vparams, b) for name, b in splits.items()
        }
        all_test_weights.append(member_weights(gan, vparams, test_batch))
        winner_vparams.append({"gan": gan, "vparams": vparams})

        if save_dir:
            for si, seed in enumerate(ensemble_seeds):
                mdir = save_dir / f"rank{rank}_seed{seed}"
                mdir.mkdir(parents=True, exist_ok=True)
                w["config"].save(mdir / "config.json")
                save_params(
                    mdir / "best_model_sharpe.msgpack",
                    jax.tree.map(lambda x, i=si: x[i], vparams),
                )
        report["winners"].append({
            "rank": rank,
            "config": w["config"].to_dict(),
            "lr": w["lr"],
            "search_valid_sharpe": _finite(w["valid_sharpe"]),
            "ensemble_sharpe": {
                name: _finite(float(m["ensemble_sharpe"]))
                for name, m in metrics.items()
            },
            "individual_test_sharpes": [
                _finite(s) for s in metrics["test"]["individual_sharpes"].tolist()
            ],
        })
        log(f"  test ensemble sharpe: "
            f"{report['winners'][-1]['ensemble_sharpe']['test']:.4f}")

    # ---- selection-noise diagnostic: search Sharpe vs retrained ensemble --
    # The quick-schedule search Sharpe is a NOISY selector (r3: winners at
    # search valid ≈0.37 retrained to ensemble valid ≈−0.15 on synthetic
    # data). Record the rank agreement so the artifact carries the evidence
    # instead of a prose warning. Ranks beyond top_k are retrained with a
    # smaller seed set purely to make the comparison statistically real
    # (n ≥ 8 pairs instead of the winners' 4).
    # Every diagnostic point must use the SAME member count: a 9-seed
    # ensemble's valid Sharpe carries a level shift from extra averaging
    # that a 3-seed one doesn't, which would fake rank agreement between
    # the top_k and the extra retrains. The winners' points are therefore
    # re-evaluated on the diagnostic_seeds SUBSET of their already-trained
    # members (no extra training); if the subset isn't available, the full
    # ensemble value is used and n_seeds records the mismatch.
    diag_points = []
    subset_idx = ([list(ensemble_seeds).index(s) for s in diagnostic_seeds]
                  if set(diagnostic_seeds) <= set(ensemble_seeds) else None)
    for w, vp in zip(report["winners"], winner_vparams):
        if subset_idx is not None:
            sub = jax.tree.map(
                lambda x: x[jnp.asarray(subset_idx)], vp["vparams"])
            val = _finite(float(ensemble_metrics(
                vp["gan"], sub, valid_batch)["ensemble_sharpe"]))
            n_seeds = len(subset_idx)
        else:
            val = w["ensemble_sharpe"]["valid"]
            n_seeds = len(ensemble_seeds)
        diag_points.append({
            "rank": w["rank"],
            "search_valid_sharpe": w["search_valid_sharpe"],
            "ensemble_valid_sharpe": val,
            "n_seeds": n_seeds,
        })
    extra = (select_winners(ranked, diagnostic_top)[len(winners):]
             if diagnostic_top > len(winners) else [])
    for di, w in enumerate(extra):
        rank = len(winners) + di
        tcfg = dataclasses.replace(ensemble_tcfg, lr=w["lr"])
        log(f"[protocol] diagnostic retrain #{rank}: "
            f"{len(diagnostic_seeds)} seeds, lr={w['lr']}")
        if heartbeat is not None:
            heartbeat.beat("diagnostic_retrain", rank=rank)
        gan, vparams, _hist = train_ensemble(
            w["config"], train_batch, valid_batch, test_batch,
            seeds=diagnostic_seeds, tcfg=tcfg, verbose=False,
            member_chunk=member_chunk, exec_cfg=exec_cfg,
            heartbeat=heartbeat,
        )
        m = ensemble_metrics(gan, vparams, valid_batch)
        diag_points.append({
            "rank": rank,
            "search_valid_sharpe": _finite(w["valid_sharpe"]),
            "ensemble_valid_sharpe": _finite(float(m["ensemble_sharpe"])),
            "n_seeds": len(diagnostic_seeds),
        })
    if len(diag_points) >= 2:
        # None encodes a non-finite tracker (diverged member) — DROP those
        # pairs rather than coercing to 0.0, which would rank a diverged
        # model mid-pack and corrupt the very diagnostic this block records
        pairs = [
            (p["search_valid_sharpe"], p["ensemble_valid_sharpe"])
            for p in diag_points
            if p["search_valid_sharpe"] is not None
            and p["ensemble_valid_sharpe"] is not None
        ]
        spearman = None
        if len(pairs) >= 2:
            sv = np.asarray([p[0] for p in pairs])
            ev = np.asarray([p[1] for p in pairs])

            def _ranks(a):
                r = np.empty(len(a))
                r[np.argsort(a)] = np.arange(len(a))
                return r

            ra, rb = _ranks(sv), _ranks(ev)
            denom = float(np.std(ra) * np.std(rb))
            if denom > 0:
                spearman = float(
                    np.mean((ra - ra.mean()) * (rb - rb.mean())) / denom)
        report["search_vs_retrain"] = {
            "points": diag_points,
            "spearman_rank_correlation": spearman,
            "n_pairs_used": len(pairs),
            "note": "search-rank vs full-schedule-retrain rank agreement "
                    "over the top diagnostic_top distinct settings (the "
                    "winners' full ensembles plus smaller diagnostic "
                    "retrains — n_seeds per point; non-finite entries "
                    "dropped); a low/negative value means the "
                    "quick-schedule search Sharpe would mis-rank candidates "
                    "— on real data, widen the search schedule before "
                    "trusting selection",
        }

    # ---- stage 3: grand ensemble across all winners' members ----
    if heartbeat is not None:
        heartbeat.beat("grand_ensemble")
    grand = ensemble_metrics_from_weights(
        jnp.concatenate(all_test_weights, axis=0), test_batch
    )
    report["grand_ensemble_test_sharpe"] = float(grand["ensemble_sharpe"])
    report["grand_ensemble_test_ev"] = float(grand["explained_variation"])
    report["grand_ensemble_test_xs_r2"] = float(grand["cross_sectional_r2"])
    report["n_grand_members"] = int(len(winners) * len(ensemble_seeds))
    report["total_seconds"] = round(time.time() - t0, 1)
    if save_dir:
        (save_dir / "report.json").write_text(json.dumps(report, indent=2))
    log(f"[protocol] grand ensemble ({report['n_grand_members']} members) "
        f"test sharpe: {report['grand_ensemble_test_sharpe']:.4f}")
    log(f"[protocol] total {report['total_seconds']:.1f}s")
    return report


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Paper protocol: config search → seed ensembles → report"
    )
    p.add_argument("--data_dir", type=str, required=True)
    p.add_argument("--save_dir", type=str, default="./sweep_results")
    p.add_argument("--small_sample", action="store_true")
    p.add_argument("--n_periods", type=int, default=100)
    p.add_argument("--n_stocks", type=int, default=500)

    # search grid (defaults give the paper's 384 combos; --quick shrinks)
    p.add_argument("--quick", action="store_true",
                   help="Tiny grid + short schedules (smoke/demo)")
    p.add_argument("--top_k", type=int, default=4)
    p.add_argument("--search_seeds", type=int, nargs="+", default=[42])
    p.add_argument("--ensemble_seeds", type=int, nargs="+",
                   default=list(PAPER_SEEDS))

    p.add_argument("--resume_ranking", type=str, default=None, metavar="JSON",
                   help="Path to a previously written sweep_ranking.json: "
                        "skip stage 1 (the 384-config search) and go "
                        "straight to the winner ensembles")
    p.add_argument("--diagnostic_top", type=int, default=8,
                   help="Retrain the top-D distinct settings (winners plus "
                        "extra diagnostic retrains) so the search-vs-retrain "
                        "rank correlation has ≥8 pairs; ≤ top_k disables")
    p.add_argument("--diagnostic_seeds", type=int, nargs="+",
                   default=[42, 123, 456])

    # schedules
    p.add_argument("--member_chunk", type=int, default=None,
                   help="Cap members per vmapped program (sequential chunks). "
                        "Rarely needed on TPU — the fused-kernel route costs "
                        "~0.1 GB HBM/member at the real panel shape; the "
                        "plain-XLA route (CPU) needs ~2.1 GB/member")
    p.add_argument("--search_epochs_unc", type=int, default=64)
    p.add_argument("--search_epochs_moment", type=int, default=16)
    p.add_argument("--search_epochs", type=int, default=256)
    p.add_argument("--search_ignore_epoch", type=int, default=16)
    p.add_argument("--epochs_unc", type=int, default=256)
    p.add_argument("--epochs_moment", type=int, default=64)
    p.add_argument("--epochs", type=int, default=1024)
    p.add_argument("--ignore_epoch", type=int, default=64)
    return p


def main(argv=None):
    from .utils.platform import apply_env_platforms

    apply_env_platforms()
    from .utils.cache import enable_compilation_cache

    enable_compilation_cache()
    args = build_arg_parser().parse_args(argv)

    save_dir = Path(args.save_dir)
    save_dir.mkdir(parents=True, exist_ok=True)
    events = EventLog(save_dir)
    hb = Heartbeat(save_dir / "heartbeat.json", events=events)
    logger = set_run_logger(RunLogger(events=events))
    hb.beat("setup")

    logger.info("Paper-protocol sweep (TPU-native)")
    logger.info(f"Devices: {jax.devices()}")
    # cache-aware load: a re-run of the sweep (the common case while
    # iterating on grids) mmaps the decoded panel instead of re-paying the
    # npz decompress + mask build (data/diskcache.py; bit-identical)
    from .data.pipeline import load_splits_cached

    with events.span("data/load"):
        train_ds, valid_ds, test_ds = load_splits_cached(
            args.data_dir, events=events
        )
    if args.small_sample:
        train_ds = train_ds.subsample(args.n_periods, args.n_stocks)
        valid_ds = valid_ds.subsample(min(args.n_periods, valid_ds.T), args.n_stocks)
        test_ds = test_ds.subsample(min(args.n_periods, test_ds.T), args.n_stocks)

    from .data.transfer import device_put_batch
    from .utils.config import ExecutionConfig

    base = GANConfig(
        macro_feature_dim=train_ds.macro_feature_dim,
        individual_feature_dim=train_ds.individual_feature_dim,
    )
    # mask-packed transfer; bf16 wire when every panel consumer reads bf16
    # (ExecutionConfig.bf16_wire_ok). The paper grid varies hidden_dim/lr/
    # dropout/seed only, never hidden_dim_moment, so `base` decides for all
    # swept configs
    _ec = ExecutionConfig()
    bf16_wire = _ec.bf16_wire_ok(base)

    def batch(ds):
        return device_put_batch(ds.full_batch(), bf16_wire=bf16_wire)

    train_b, valid_b, test_b = batch(train_ds), batch(valid_ds), batch(test_ds)

    if args.quick:
        configs = grid_configs(
            base,
            hidden_dims=((64, 64), (32, 32)),
            rnn_units=((4,),),
            num_moments=(8,),
            dropouts=(0.05,),
            lrs=(1e-3, 5e-4),
        )
        search_tcfg = TrainConfig(
            num_epochs_unc=8, num_epochs_moment=4, num_epochs=16,
            ignore_epoch=2, seed=args.search_seeds[0],
        )
        ensemble_tcfg = TrainConfig(
            num_epochs_unc=16, num_epochs_moment=8, num_epochs=32,
            ignore_epoch=4,
        )
        if args.ensemble_seeds == list(PAPER_SEEDS):
            args.ensemble_seeds = [42, 123, 456]
        args.top_k = min(args.top_k, 2)
        args.diagnostic_top = args.top_k  # smoke mode: no extra retrains
    else:
        configs = grid_configs(base)  # the 384-combo paper grid
        search_tcfg = TrainConfig(
            num_epochs_unc=args.search_epochs_unc,
            num_epochs_moment=args.search_epochs_moment,
            num_epochs=args.search_epochs,
            ignore_epoch=args.search_ignore_epoch,
            seed=args.search_seeds[0],
        )
        ensemble_tcfg = TrainConfig(
            num_epochs_unc=args.epochs_unc,
            num_epochs_moment=args.epochs_moment,
            num_epochs=args.epochs,
            ignore_epoch=args.ignore_epoch,
        )

    ranking = load_ranking(args.resume_ranking) if args.resume_ranking else None

    # startup manifest: base config + both schedules + grid size, so the
    # sweep_results dir carries its own provenance
    write_manifest(
        save_dir, "sweep", events=events,
        config=base, tcfg=search_tcfg, seed=args.search_seeds[0],
        data_dir=args.data_dir, argv=argv,
        extra={
            "n_configs": len(configs),
            "quick": bool(args.quick),
            "top_k": args.top_k,
            "ensemble_seeds": list(args.ensemble_seeds),
            "ensemble_train_config": dataclasses.asdict(ensemble_tcfg),
            "resumed_from_ranking": args.resume_ranking,
        },
    )
    hb.beat("protocol")

    report = run_protocol(
        configs, train_b, valid_b, test_b,
        search_tcfg=search_tcfg, ensemble_tcfg=ensemble_tcfg,
        search_seeds=args.search_seeds,
        ensemble_seeds=args.ensemble_seeds,
        top_k=args.top_k, save_dir=args.save_dir,
        member_chunk=args.member_chunk,
        ranking=ranking,
        diagnostic_top=args.diagnostic_top,
        diagnostic_seeds=args.diagnostic_seeds,
        heartbeat=hb,
    )
    hb.beat("done", memory=True)
    logger.info(f"\nReport written to {save_dir / 'report.json'}")
    logger.info("Grand ensemble test Sharpe: "
                f"{report['grand_ensemble_test_sharpe']:.4f}")
    events.close()


if __name__ == "__main__":
    main()
