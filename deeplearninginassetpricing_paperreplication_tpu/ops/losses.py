"""Moment-condition losses as fused masked reductions.

Each loss here compiles to a handful of XLA reductions over the static-shape
[T, N] panel — no Python loops over moments (the reference loops over the 8
moments, ``/root/reference/src/model.py:424-431``) and no loops over periods
(the reference's residual loss loops over T with boolean indexing,
``model.py:454-475``). Semantics are bit-for-bit the reference's, including
the ragged-panel denominators: per-period valid counts N_t (clamped to ≥1)
and per-asset valid lengths T_i (clamped to ≥1).

Notation: weights w [T, N], returns R [T, N], mask m [T, N] (float 0/1),
moments h [K, T, N]. SDF M_t = 1 + F_t with F_t the (optionally N̄/N_t
weighted) aggregate portfolio return (model.py:358-380).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def portfolio_returns(
    weights: jnp.ndarray,
    returns: jnp.ndarray,
    mask: jnp.ndarray,
    weighted: bool = True,
) -> jnp.ndarray:
    """F_t = Σ_i w·R·m, scaled per period by N̄/N_t when `weighted`
    (model.py:358-369)."""
    weighted_returns = weights * returns * mask
    if weighted:
        n_per_period = jnp.clip(mask.sum(axis=1), 1, None)  # [T]
        n_bar = n_per_period.mean()
        return weighted_returns.sum(axis=1) / n_per_period * n_bar
    return weighted_returns.sum(axis=1)


def unconditional_loss(
    weights: jnp.ndarray,
    returns: jnp.ndarray,
    mask: jnp.ndarray,
    weighted: bool = True,
    F: jnp.ndarray = None,
    n_assets: jnp.ndarray = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """E_i[ (Σ_t R·m·M / T_i)² ] with M = 1 + F (model.py:346-387).

    Pass a precomputed `F` to share the portfolio-return reduction with a
    sibling loss. Returns (loss scalar, portfolio_returns [T]).

    `n_assets`: true asset count when the stock axis is padded (sharding /
    kernel tiling). Padded all-masked columns contribute exactly 0 to the
    numerator; dividing by the true count instead of the padded shape keeps
    the loss bit-equal to the unpadded panel's.
    """
    if F is None:
        F = portfolio_returns(weights, returns, mask, weighted)
    sdf = 1.0 + F  # [T]
    t_per_asset = jnp.clip(mask.sum(axis=0), 1, None)  # [N]
    empirical_mean = (returns * mask * sdf[:, None]).sum(axis=0) / t_per_asset
    if n_assets is None:
        return (empirical_mean**2).mean(), F
    return (empirical_mean**2).sum() / n_assets, F


def conditional_loss(
    weights: jnp.ndarray,
    returns: jnp.ndarray,
    mask: jnp.ndarray,
    moments: jnp.ndarray,
    weighted: bool = True,
    F: jnp.ndarray = None,
    n_assets: jnp.ndarray = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """mean_k mean_i (Σ_t h_k·R·m·M / T_i)² — one einsum over the moment axis
    instead of the reference's Python loop (model.py:424-431).

    `n_assets`: see unconditional_loss — true asset count under padding.
    """
    if F is None:
        F = portfolio_returns(weights, returns, mask, weighted)
    sdf = 1.0 + F
    t_per_asset = jnp.clip(mask.sum(axis=0), 1, None)  # [N]
    x = returns * mask * sdf[:, None]  # [T, N]
    empirical_mean = jnp.einsum("ktn,tn->kn", moments, x) / t_per_asset[None, :]
    if n_assets is None:
        return (empirical_mean**2).mean(), F
    return (empirical_mean**2).sum() / (moments.shape[0] * n_assets), F


def residual_loss(
    weights: jnp.ndarray,
    returns: jnp.ndarray,
    mask: jnp.ndarray,
) -> jnp.ndarray:
    """E[‖R − proj_w R‖²] / E[‖R‖²], vectorized over periods.

    Reference semantics (model.py:435-483): a period contributes to the R²
    average iff it has ≥2 valid stocks; it additionally contributes to the
    residual average iff w·w > 1e-8 there. Periods average their own valid
    stocks; the final numbers are plain means over contributing periods.
    Returns 0 when no period contributes a residual.
    """
    count = mask.sum(axis=1)  # [T]
    safe_count = jnp.clip(count, 1, None)
    has_stocks = count >= 2

    ww = (weights * weights * mask).sum(axis=1)  # [T]
    rw = (returns * weights * mask).sum(axis=1)  # [T]
    coef = rw / jnp.where(ww > 1e-8, ww, 1.0)  # [T]
    resid = (returns - coef[:, None] * weights) * mask
    resid_sq = (resid**2).sum(axis=1) / safe_count  # per-period mean
    r_sq = (returns**2 * mask).sum(axis=1) / safe_count

    resid_contrib = has_stocks & (ww > 1e-8)
    n_resid = resid_contrib.sum()
    n_rsq = has_stocks.sum()

    resid_mean = jnp.where(
        n_resid > 0, (resid_sq * resid_contrib).sum() / jnp.clip(n_resid, 1, None), 0.0
    )
    rsq_mean = jnp.where(
        n_rsq > 0, (r_sq * has_stocks).sum() / jnp.clip(n_rsq, 1, None), 0.0
    )
    return jnp.where(
        n_resid > 0, resid_mean / jnp.clip(rsq_mean, 1e-8, None), 0.0
    )
