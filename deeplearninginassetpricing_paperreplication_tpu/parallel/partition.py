"""THE sharding layer: one mesh config + regex partition rules supply every
`NamedSharding` in the codebase.

Before this module, every compute surface hand-rolled device placement —
``parallel/mesh.py`` shipped per-field sharding dicts, ``parallel/ensemble``
threaded an optional ``member_sharding``, the sweep's warm compiler pinned
``SingleDeviceSharding(jax.devices()[0])``, and the serving engine and refit
CLI placed on the default device. Now placement is rule-driven (the
``match_partition_rules`` → ``NamedSharding`` shape of SNIPPETS.md [2]/[3]):

  * a :class:`MeshConfig` names the device grid ONCE — axes ``stocks``
    (panel data parallelism), ``members`` (ensemble seeds), ``grid``
    (the sweep's lr × seed points) — and builds the named mesh, including
    degenerate 1-device meshes (the single-device case is just the
    smallest mesh, not a different code path) and device *slices* (a
    worker fleet packs concurrent buckets onto disjoint sub-meshes);
  * :func:`match_partition_rules` maps ANY pytree — params, optimizer
    state, batch dicts — to `PartitionSpec`s by regex over the leaf's
    ``/``-joined path name: scalars are replicated without consulting the
    rules, the first matching rule wins, and an unmatched leaf raises an
    error NAMING the path (silent default placement is how layouts drift);
  * :func:`tree_shardings` / :func:`shard_tree` turn those specs into
    `NamedSharding`s / committed arrays over a given mesh.

Every other module imports its shardings from here; constructing a
``NamedSharding`` anywhere else is a review error (tier-1 greps for it).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# -- canonical axis names ----------------------------------------------------

STOCK_AXIS = "stocks"    # shards the [T, N, F] panel's stock axis N
MEMBER_AXIS = "members"  # ensemble seed axis (leading axis of stacked params)
GRID_AXIS = "grid"       # sweep (lr × seed) grid axis
# legacy name for the member-ish axis: the PR-1 2-D ensemble mesh called it
# 'batch' and checkpointed run dirs / graft demos still build such meshes
BATCH_AXIS = "batch"

# axes that carry a leading "stacked things" dimension — member_sharding()
# resolves whichever of these the mesh actually has
_STACK_AXES = (MEMBER_AXIS, BATCH_AXIS, GRID_AXIS)


# -- mesh construction -------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """One spec → one named device grid.

    ``axes`` is an ordered ``(name, size)`` tuple; a single size may be -1
    (fill with every remaining device). ``devices`` restricts the grid to an
    explicit slice (the worker device-slice lease contract) — default all
    local devices. ``build()`` returns the ``jax.sharding.Mesh``.
    """

    axes: Tuple[Tuple[str, int], ...]
    devices: Optional[Tuple[Any, ...]] = None

    def build(self) -> Mesh:
        devices = list(self.devices) if self.devices is not None else jax.devices()
        sizes = [int(s) for _, s in self.axes]
        names = [str(n) for n, _ in self.axes]
        fills = [i for i, s in enumerate(sizes) if s == -1]
        if len(fills) > 1:
            raise ValueError(f"MeshConfig: at most one -1 axis: {self.axes}")
        fixed = int(np.prod([s for s in sizes if s != -1], dtype=np.int64))
        if fixed < 1:
            raise ValueError(f"MeshConfig: axis sizes must be >= 1: {self.axes}")
        if fills:
            if len(devices) // fixed < 1:
                raise ValueError(
                    f"MeshConfig {self.axes}: {fixed} fixed-size slots exceed "
                    f"the {len(devices)} available devices")
            sizes[fills[0]] = len(devices) // fixed
        total = int(np.prod(sizes, dtype=np.int64))
        if total > len(devices):
            raise ValueError(
                f"MeshConfig {tuple(zip(names, sizes))} needs {total} "
                f"devices, have {len(devices)}")
        grid = np.array(devices[:total]).reshape(sizes)
        return Mesh(grid, tuple(names))


def parse_mesh_spec(spec: str, devices: Optional[Sequence] = None
                    ) -> MeshConfig:
    """CLI mesh spec → :class:`MeshConfig`.

    Grammar: ``"stocks=4"``, ``"stocks=-1"`` (fill with every remaining
    device), ``"members=2,stocks=4"`` (axis order as written), or a bare
    integer ``"4"`` (shorthand for ``stocks=<n>``). Axis names are free-form
    (the partition layer shards by name), but serving meshes use the
    canonical ``stocks``/``members`` axes. ``devices`` restricts the grid to
    an explicit slice (the replica↔device-slice lease: pass
    :func:`slice_devices`' result)."""
    text = spec.strip()
    if not text:
        raise ValueError("empty mesh spec")
    axes = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, _, size = part.partition("=")
            name, size = name.strip(), size.strip()
        else:
            name, size = STOCK_AXIS, part
        if not name:
            raise ValueError(f"mesh spec axis missing a name: {spec!r}")
        try:
            n = int(size)
        except ValueError:
            raise ValueError(
                f"mesh spec axis {name!r} has non-integer size {size!r} "
                f"in {spec!r}") from None
        if n == 0 or n < -1:
            raise ValueError(
                f"mesh spec axis {name!r} size must be >= 1 or -1 (fill): "
                f"{spec!r}")
        axes.append((name, n))
    if not axes:
        raise ValueError(f"mesh spec names no axes: {spec!r}")
    names = [n for n, _ in axes]
    if len(set(names)) != len(names):
        raise ValueError(f"mesh spec repeats an axis name: {spec!r}")
    return MeshConfig(tuple(axes),
                      tuple(devices) if devices is not None else None)


def mesh_spec_str(mesh: Mesh) -> str:
    """The ``name=size`` spec string for a built mesh (fleet.json's
    human-readable record of what each replica actually laid out)."""
    return ",".join(f"{name}={size}" for name, size in mesh.shape.items())


def create_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = STOCK_AXIS,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D mesh over (up to) all local devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"create_mesh: requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return MeshConfig(((axis_name, len(devices)),), tuple(devices)).build()


def create_2d_mesh(
    n_batch: int,
    n_stocks: Optional[int] = None,
    devices: Optional[Sequence] = None,
    batch_axis: str = BATCH_AXIS,
) -> Mesh:
    """(member-ish, 'stocks') mesh: ensemble/sweep members × panel shards."""
    if devices is None:
        devices = jax.devices()
    total = len(devices)
    if n_stocks is None:
        n_stocks = total // max(n_batch, 1)
    if n_batch < 1 or n_stocks < 1 or n_batch * n_stocks > total:
        raise ValueError(
            f"mesh {n_batch}x{n_stocks} needs {max(n_batch, 1) * max(n_stocks, 1)} "
            f"devices, have {total}"
        )
    return MeshConfig(
        ((batch_axis, n_batch), (STOCK_AXIS, n_stocks)), tuple(devices)
    ).build()


def device_mesh(device=None, axis_name: str = STOCK_AXIS) -> Mesh:
    """The degenerate 1-device mesh: single-device placement expressed in
    the same vocabulary as every other mesh (replaces ad-hoc
    ``SingleDeviceSharding`` construction at the old call sites)."""
    dev = device if device is not None else jax.devices()[0]
    return MeshConfig(((axis_name, 1),), (dev,)).build()


def slice_devices(
    slice_index: int,
    n_slices: int,
    width: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Tuple[Any, ...]:
    """Device slice ``slice_index`` of ``n_slices`` disjoint contiguous
    slices over the local devices — THE contract the scheduler's device-slice
    leases and the worker meshes share, so two workers holding different
    slice leases can never touch the same device."""
    if devices is None:
        devices = jax.devices()
    if n_slices < 1:
        raise ValueError(f"n_slices must be >= 1: {n_slices}")
    if not 0 <= slice_index < n_slices:
        raise ValueError(f"slice_index {slice_index} not in [0, {n_slices})")
    w = width if width is not None else len(devices) // n_slices
    if w < 1 or n_slices * w > len(devices):
        raise ValueError(
            f"{n_slices} slices of width {w} exceed {len(devices)} devices")
    return tuple(devices[slice_index * w:(slice_index + 1) * w])


def grid_slice_mesh(
    slice_index: int = 0,
    n_slices: int = 1,
    width: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D ('grid',) mesh over one device slice: the mesh a leased sweep
    worker lays its (lr × seed) bucket grid over."""
    devs = slice_devices(slice_index, n_slices, width, devices)
    return MeshConfig(((GRID_AXIS, len(devs)),), devs).build()


# -- sharding constructors ---------------------------------------------------


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    """THE NamedSharding constructor. ``spec`` elements are PartitionSpec
    entries (axis name, None, or a tuple of axis names); a single
    PartitionSpec argument passes through unchanged."""
    if len(spec) == 1 and isinstance(spec[0], P):
        return NamedSharding(mesh, spec[0])
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated over the mesh (params, macro series, scalars)."""
    return named_sharding(mesh, P())


def device_sharding(device=None) -> NamedSharding:
    """Single-device placement as the degenerate 1-device mesh (device 0 by
    default) — what the serving engine, the sweep's warm compiler, and the
    sequential pipeline use. Dispatch-equivalent to the
    ``SingleDeviceSharding`` these sites used to hand-roll."""
    return replicated(device_mesh(device))


def member_axis_name(mesh: Mesh) -> str:
    """Which of the stack axes ('members' / legacy 'batch' / 'grid') this
    mesh carries; raises when it has none."""
    for name in _STACK_AXES:
        if name in mesh.shape:
            return name
    raise ValueError(
        f"mesh axes {tuple(mesh.shape)} have no member-ish axis "
        f"(expected one of {_STACK_AXES})")


def member_sharding(mesh: Mesh, axis_name: Optional[str] = None) -> NamedSharding:
    """Leading-axis sharding for member-stacked trees (ensemble seeds /
    grid points) over the mesh's stack axis."""
    return named_sharding(mesh, member_axis_name(mesh) if axis_name is None
                          else axis_name)


# -- regex partition rules ---------------------------------------------------

Rule = Tuple[str, P]


def _path_name(path) -> str:
    """'/'-joined leaf path: dict keys, attr names, sequence indices."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:  # pragma: no cover — future key types
            parts.append(str(k))
    return "/".join(parts)


def _is_scalar(leaf) -> bool:
    shape = getattr(leaf, "shape", ())
    return len(shape) == 0 or int(np.prod(shape)) == 1


def match_partition_rules(rules: Sequence[Rule], tree) -> Any:
    """Pytree of `PartitionSpec` for `tree`, by regex over leaf path names.

    Scalars (0-d or single-element leaves) are replicated without
    consulting the rules; otherwise the FIRST rule whose pattern
    ``re.search``-matches the ``/``-joined path wins (list order is the
    precedence). A leaf no rule matches raises ``ValueError`` naming the
    path — end a rule list with ``(".*", P())`` to opt into replicate-by-
    default explicitly."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(path, leaf):
        if _is_scalar(leaf):
            return P()
        name = _path_name(path)
        for pat, spec in compiled:
            if pat.search(name) is not None:
                return spec
        raise ValueError(
            f"no partition rule matched leaf {name!r} "
            f"(shape {tuple(getattr(leaf, 'shape', ()))}); add a rule or an "
            "explicit ('.*', PartitionSpec()) catch-all")

    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [spec_for(p, leaf) for p, leaf in paths_and_leaves])


def _clip_spec(spec: P, leaf) -> P:
    """Drop trailing spec entries beyond the leaf's rank (a rank-2 rule may
    serve a rank-1 leaf of the same family, e.g. returns vs n_assets)."""
    ndim = len(getattr(leaf, "shape", ()))
    entries = tuple(spec)
    if len(entries) <= ndim:
        return spec
    if any(e is not None for e in entries[ndim:]):
        raise ValueError(
            f"partition spec {entries} names a mesh axis beyond the leaf's "
            f"rank {ndim}")
    return P(*entries[:ndim])


def tree_shardings(mesh: Mesh, tree, rules: Sequence[Rule]) -> Any:
    """Pytree of `NamedSharding` for `tree` under `rules` over `mesh`."""
    specs = match_partition_rules(rules, tree)
    return jax.tree_util.tree_map(
        lambda spec, leaf: named_sharding(mesh, _clip_spec(spec, leaf)),
        specs, tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_tree(tree, mesh: Mesh, rules: Sequence[Rule]):
    """device_put every leaf with its rule-matched sharding."""
    return jax.device_put(tree, tree_shardings(mesh, tree, rules))


# -- canonical rule sets -----------------------------------------------------


def batch_rules(axis_name: str = STOCK_AXIS) -> Tuple[Rule, ...]:
    """The canonical panel-batch layout: stock axis sharded, time/feature
    axes and the macro series replicated. Extra keys (n_assets, dates,
    anything a caller threads through) replicate via the explicit
    catch-all."""
    return (
        (r"(^|/)individual_t$", P(None, None, axis_name)),
        (r"(^|/)individual$", P(None, axis_name, None)),
        (r"(^|/)(returns|mask)$", P(None, axis_name)),
        (r"(^|/)macro$", P()),
        (r".*", P()),
    )


def member_rules(axis_name: str = MEMBER_AXIS) -> Tuple[Rule, ...]:
    """Member/grid-stacked trees: every non-scalar leaf's LEADING axis maps
    onto the mesh's stack dimension (params, optimizer state, best
    trackers, per-member key vectors all share the convention)."""
    return ((r".*", P(axis_name)),)


def grid_rules() -> Tuple[Rule, ...]:
    return member_rules(GRID_AXIS)


# the fixed key set of the canonical batch dict, for shardings-by-key
# consumers (the streamed sharded transfer indexes by key before any array
# exists to match rules against)
BATCH_KEYS = ("returns", "mask", "individual", "individual_t", "macro",
              "n_assets")


def batch_shardings(
    mesh: Mesh, axis_name: str = STOCK_AXIS,
    keys: Sequence[str] = BATCH_KEYS,
) -> Dict[str, NamedSharding]:
    """Per-key `NamedSharding` dict for the canonical batch — the rule set
    of :func:`batch_rules` evaluated against the known key names (shapes are
    not needed: the batch layout is determined by key alone)."""
    rules = batch_rules(axis_name)
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(name: str) -> P:
        # key-only matching: scalar-by-contract keys (n_assets) fall to the
        # rule set's explicit catch-all, same as every other extra key
        for pat, spec in compiled:
            if pat.search(name) is not None:
                return spec
        raise ValueError(f"no batch partition rule matched key {name!r}")

    return {k: named_sharding(mesh, spec_for(k)) for k in keys}


def shard_batch(batch, mesh: Mesh, axis_name: str = STOCK_AXIS):
    """device_put each batch field with its rule-matched stock-axis
    sharding. N must divide the mesh's stock axis — use
    ``PanelDataset.pad_stocks(mesh.shape[axis_name])`` first."""
    sh = batch_shardings(mesh, axis_name)
    out = {}
    for k, v in batch.items():
        sharded_dim = {"returns": 1, "mask": 1, "individual": 1,
                       "individual_t": 2}.get(k)
        n = v.shape[sharded_dim] if sharded_dim is not None else None
        if n is not None and n % mesh.shape[axis_name] != 0:
            raise ValueError(
                f"batch[{k!r}] stock axis {n} not divisible by mesh axis "
                f"{mesh.shape[axis_name]}; pad with PanelDataset.pad_stocks()"
            )
        out[k] = jax.device_put(v, sh.get(k) or replicated(mesh))
    return out


# -- grid/member tree placement ---------------------------------------------


def stack_tree_shardings(mesh: Mesh, tree,
                         axis_name: Optional[str] = None) -> Any:
    """Leading-axis shardings for a member/grid-stacked tree with the naive-
    sharding fallback (SNIPPETS.md [3]): a leaf whose leading dimension the
    mesh's stack axis does not divide is replicated instead — bit-identity
    never depends on divisibility, only the layout does. Scalars replicate."""
    axis = member_axis_name(mesh) if axis_name is None else axis_name
    size = int(mesh.shape[axis])

    def sh(leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0 or shape[0] % size != 0:
            return replicated(mesh)
        return named_sharding(mesh, axis)

    return jax.tree_util.tree_map(sh, tree)


def shard_stack_tree(tree, mesh: Mesh, axis_name: Optional[str] = None):
    """device_put a member/grid-stacked tree under
    :func:`stack_tree_shardings`."""
    return jax.device_put(tree, stack_tree_shardings(mesh, tree, axis_name))
