"""Atomic, digest-verified, generational file IO for checkpoints.

The contract every params/resume-state write in the repo now follows:

  * **atomic** — bytes land in ``<name>.tmp`` and ``os.replace`` onto the
    target, so a kill mid-save leaves the previous file intact, never a
    truncated one;
  * **verified** — a sidecar ``<name>.sha256`` (JSON: ``{"sha256", "bytes"}``)
    is written after the data; loads recompute the digest and reject a file
    whose bytes don't match (bit rot, torn copies, an injected
    ``truncate_file`` fault);
  * **generational** — before each write the previous file rotates to
    ``<name>.g1`` (and ``.g1`` → ``.g2``, …, up to ``generations``); loads
    fall back generation-by-generation to the last good checkpoint, so a
    corrupted newest write can never strand a run.

Files without a sidecar (pre-PR checkpoints) still load: the digest check
is skipped and the caller's parse step is the validator — corruption then
surfaces as a clear ``ValueError`` naming the offending file instead of a
raw flax deserialization traceback.
"""

from __future__ import annotations

import hashlib
import json
import os
import warnings
from pathlib import Path
from typing import Any, Callable, List, Optional, Tuple, Union

from .faults import inject

DIGEST_SUFFIX = ".sha256"
DEFAULT_GENERATIONS = 2  # the current file plus one good predecessor
_MAX_SCAN = 10  # how many generations a load will ever look back through


def digest_path(path: Union[str, Path]) -> Path:
    path = Path(path)
    return path.with_name(path.name + DIGEST_SUFFIX)


def generation_path(path: Union[str, Path], gen: int) -> Path:
    path = Path(path)
    return path if gen == 0 else path.with_name(f"{path.name}.g{gen}")


def generation_candidates(path: Union[str, Path],
                          max_generations: int = _MAX_SCAN) -> List[Path]:
    """Newest-first candidate list: the file itself, then ``.g1``, …"""
    return [generation_path(path, g) for g in range(max_generations)]


def verified_exists(path: Union[str, Path]) -> bool:
    """Does ANY generation of `path` exist on disk?"""
    return any(p.exists() for p in generation_candidates(path))


def compute_digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def check_digest(path: Path, data: bytes,
                 digest: Optional[str] = None) -> Tuple[bool, str]:
    """Verify `data` against `path`'s sidecar. (ok, reason); a missing or
    unreadable sidecar passes — the caller's parse is then the validator.
    ``digest``: `data`'s sha256 when the caller already computed it (skips
    re-hashing the same bytes)."""
    dp = digest_path(path)
    try:
        meta = json.loads(dp.read_text())
    except (OSError, ValueError):
        return True, "no digest sidecar (legacy or torn sidecar)"
    want = meta.get("sha256")
    if want is None:
        return True, "sidecar carries no sha256"
    got = digest or compute_digest(data)
    if got != want:
        return False, (
            f"sha256 mismatch (file {got[:12]}… != recorded {want[:12]}…, "
            f"{len(data)} bytes on disk, {meta.get('bytes')} recorded)"
        )
    return True, "ok"


def rotate_generations(path: Union[str, Path],
                       generations: int = DEFAULT_GENERATIONS) -> None:
    """Shift ``path`` → ``.g1`` → ``.g2`` … keeping at most `generations`
    files total (data and digest sidecars move together)."""
    path = Path(path)
    if generations <= 1 or not path.exists():
        return
    for g in range(generations - 2, -1, -1):
        src, dst = generation_path(path, g), generation_path(path, g + 1)
        if not src.exists():
            continue
        os.replace(src, dst)
        sdig, ddig = digest_path(src), digest_path(dst)
        if sdig.exists():
            os.replace(sdig, ddig)
        else:
            ddig.unlink(missing_ok=True)


def write_verified(path: Union[str, Path], data: bytes,
                   generations: int = DEFAULT_GENERATIONS) -> str:
    """Rotate, atomically write `data`, then its digest sidecar. Returns the
    hex digest (callers embed it to bind paired files together)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    inject("checkpoint/save", path=str(path))
    rotate_generations(path, generations)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, path)
    sha = compute_digest(data)
    _write_sidecar(path, sha, len(data))
    inject("checkpoint/saved", path=str(path))
    return sha


def _write_sidecar(path: Path, sha: str, nbytes: int) -> None:
    dp = digest_path(path)
    tmp = dp.with_name(dp.name + ".tmp")
    tmp.write_text(json.dumps({"sha256": sha, "bytes": nbytes}))
    os.replace(tmp, dp)


def load_verified(
    path: Union[str, Path],
    parse: Callable[[bytes], Any],
    warn: bool = True,
) -> Tuple[Any, Path]:
    """Load the newest generation of `path` that both digest-verifies and
    parses; returns ``(parse(data), actual_path)``.

    Falls back generation-by-generation past corrupt files (warning each
    time); when every existing generation is unusable raises a ``ValueError``
    naming each offending file and why, and when nothing exists at all
    raises ``FileNotFoundError``.
    """
    path = Path(path)
    inject("checkpoint/load", path=str(path))
    errors: List[str] = []
    for p in generation_candidates(path):
        if not p.exists():
            continue
        data = p.read_bytes()
        ok, why = check_digest(p, data)
        if not ok:
            errors.append(f"{p}: {why}")
            continue
        try:
            value = parse(data)
        except Exception as e:  # noqa: BLE001 — every parse failure falls back
            errors.append(f"{p}: {e}")
            continue
        if p != path and warn:
            warnings.warn(
                f"checkpoint {path.name}: newest generation unusable "
                f"({'; '.join(errors)}); fell back to {p.name}",
                stacklevel=2,
            )
        return value, p
    if errors:
        raise ValueError(
            f"no usable generation of checkpoint {path}: " + "; ".join(errors)
        )
    raise FileNotFoundError(f"no generation of {path} exists")


def clear_generations(path: Union[str, Path]) -> None:
    """Remove every generation of `path` plus digest sidecars."""
    for p in generation_candidates(path):
        p.unlink(missing_ok=True)
        digest_path(p).unlink(missing_ok=True)
