"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip sharding paths are exercised on CPU via XLA's host-platform device
partitioning — the TPU-native way to test multi-device code without a pod.
Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture(scope="session")
def synthetic_dir(tmp_path_factory):
    """Small seeded synthetic dataset shared by the suite."""
    from deeplearninginassetpricing_paperreplication_tpu.data.synthetic import (
        generate_all_splits,
    )

    out = tmp_path_factory.mktemp("synthetic")
    generate_all_splits(
        out,
        n_periods_train=24,
        n_periods_valid=8,
        n_periods_test=12,
        n_stocks=64,
        n_features=10,
        n_macro=6,
        seed=7,
        verbose=False,
    )
    return out


@pytest.fixture(scope="session")
def splits(synthetic_dir):
    from deeplearninginassetpricing_paperreplication_tpu.data.panel import load_splits

    return load_splits(synthetic_dir)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
