"""AssetPricingGAN: phase-switched forward pass over the panel.

Pure-functional equivalent of the reference's ``AssetPricingGAN.forward``
(``/root/reference/src/model.py:485-563``): given params and the batch dict,
compute weights, moments, and the phase's loss:

    phase='unconditional' → loss = E[w·R·M]² (generator, h ≡ 1)
    phase='moment'        → loss = −E[h·w·R·M]² (discriminator maximizes)
    phase='conditional'   → loss = E[h·w·R·M]² (+ unconditional for monitor)

plus the optional residual regularizer and the monitoring Sharpe. Everything
returns scalars/arrays inside jit — no host sync.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.losses import conditional_loss, portfolio_returns, residual_loss, unconditional_loss
from ..ops.pallas_moment import fused_conditional_em, fused_conditional_em_sharded
from ..ops.metrics import normalize_weights_abs, sharpe_monitor
from ..utils.config import ExecutionConfig, GANConfig
from .networks import (
    AssetPricingModule,
    moment_output_params,
)

Params = Any
Batch = Dict[str, jnp.ndarray]

PHASES = ("unconditional", "moment", "conditional")


class GAN:
    """Thin stateless wrapper pairing a GANConfig with its Flax module.

    All methods are pure functions of (params, batch) and are safe to close
    over inside jit / scan / vmap.
    """

    def __init__(self, cfg: GANConfig, exec_cfg: Optional[ExecutionConfig] = None):
        self.cfg = cfg
        self.exec_cfg = exec_cfg or ExecutionConfig()
        self.module = AssetPricingModule(cfg, self.exec_cfg)

    # -- init ---------------------------------------------------------------

    def init(self, rng: jax.Array, T: int = 4, N: int = 8) -> Params:
        """Initialize params on dummy shapes (shapes don't affect param dims)."""
        macro = (
            jnp.zeros((T, self.cfg.macro_feature_dim))
            if self.cfg.macro_feature_dim > 0
            else None
        )
        individual = jnp.zeros((T, N, self.cfg.individual_feature_dim))
        mask = jnp.ones((T, N))
        variables = self.module.init(rng, macro, individual, mask, True)
        return variables["params"]

    # -- batch preparation ----------------------------------------------------

    def prepare_batch(self, batch: Batch) -> Batch:
        """Add derived per-batch arrays the active execution route wants.

        For the Pallas route: the feature-major panel `individual_t`
        [T, F, N]. Call OUTSIDE the epoch scan (the trainer does) so the
        transpose runs once per phase program, not once per epoch.
        """
        if (
            self.exec_cfg.use_pallas(self.cfg.hidden_dim)
            and "individual_t" not in batch
        ):
            batch = dict(batch)
            x_t = jnp.transpose(batch["individual"], (0, 2, 1))
            if self.exec_cfg.bf16_panel:
                x_t = x_t.astype(jnp.bfloat16)
            batch["individual_t"] = x_t
        return batch

    # -- forward ------------------------------------------------------------

    def _apply(self, params: Params, method, *args,
               rng: Optional[jax.Array] = None, **method_kwargs):
        deterministic = rng is None
        rngs = None if deterministic else {"dropout": rng}
        return self.module.apply(
            {"params": params}, *args, deterministic, method=method,
            rngs=rngs, **method_kwargs,
        )

    def weights(self, params: Params, batch: Batch, rng=None,
                macro_state=None) -> jnp.ndarray:
        """`macro_state` (optional [T, H]) bypasses the in-module LSTM with a
        caller-carried recurrent state — the serving engine's incremental
        macro path (models/recurrent.py cell/carry split). When given,
        ``batch["macro"]`` is not read."""
        return self._apply(
            params, AssetPricingModule.weights,
            batch.get("macro"), batch["individual"], batch["mask"], rng=rng,
            individual_t=batch.get("individual_t"),
            macro_state=macro_state,
        )

    def moments(self, params: Params, batch: Batch, rng=None) -> jnp.ndarray:
        return self._apply(
            params, AssetPricingModule.moments,
            batch.get("macro"), batch["individual"], rng=rng,
            individual_t=batch.get("individual_t"),
        )

    def normalized_weights(self, params: Params, batch: Batch,
                           macro_state=None) -> jnp.ndarray:
        """Eval-mode weights scaled to Σ|w| = 1 per period (model.py:565-594)."""
        return normalize_weights_abs(
            self.weights(params, batch, macro_state=macro_state),
            batch["mask"])

    def sdf_factor(self, params: Params, batch: Batch, normalized: bool = True) -> jnp.ndarray:
        """Portfolio return series of the SDF portfolio (model.py:596-617)."""
        w = (
            self.normalized_weights(params, batch)
            if normalized
            else self.weights(params, batch)
        )
        return (w * batch["returns"] * batch["mask"]).sum(axis=1)

    def forward(
        self,
        params: Params,
        batch: Batch,
        phase: str = "conditional",
        rng: Optional[jax.Array] = None,
    ) -> Dict[str, jnp.ndarray]:
        """Phase-switched forward. `phase` is a static (trace-time) string.

        Pass `rng` to enable dropout (training); omit for eval.
        """
        if phase not in PHASES:
            raise ValueError(f"phase must be one of {PHASES}, got {phase!r}")
        cfg = self.cfg
        returns, mask = batch["returns"], batch["mask"]
        n_assets = batch.get("n_assets")  # true N when the stock axis is padded

        if rng is None:
            w_rng = m_rng = None
        else:
            w_rng, m_rng = jax.random.split(rng)
        weights = self.weights(params, batch, rng=w_rng)

        # Fused moment+conditional-loss route (ops/pallas_moment.py): the
        # default moment net (no hidden layers, no dropout) contracts
        # directly into the per-(moment, asset) empirical means — h [K,T,N]
        # never materializes, so `moments` is None in the output dict (call
        # `GAN.moments` explicitly if the raw h values are needed).
        use_fused_cond = (
            phase in ("moment", "conditional")
            and self.exec_cfg.pallas_enabled()  # pallas_ffn="off" disables
            and not cfg.hidden_dim_moment
            and batch.get("individual_t") is not None
            and batch.get("macro") is not None
        )
        if phase == "unconditional":
            moments = self.moments(params, batch, rng=m_rng)
            loss_unc, F = unconditional_loss(
                weights, returns, mask, cfg.weighted_loss, n_assets=n_assets)
            loss_cond = jnp.float32(0.0)
        elif use_fused_cond:
            moments = None
            loss_cond, F = self._fused_cond_loss(
                params, batch, weights, n_assets)
        else:
            moments = self.moments(params, batch, rng=m_rng)
            loss_cond, F = conditional_loss(
                weights, returns, mask, moments, cfg.weighted_loss,
                n_assets=n_assets)
        if phase == "moment":
            loss_unc = jnp.float32(0.0)
            total = -loss_cond  # discriminator ascends (model.py:535)
        elif phase == "conditional":
            loss_unc, _ = unconditional_loss(
                weights, returns, mask, cfg.weighted_loss, F=F,
                n_assets=n_assets)
            total = loss_cond
        else:
            total = loss_unc

        total, loss_res = self._residual_term(weights, returns, mask, total)

        return {
            "weights": weights,
            "moments": moments,
            "loss": total,
            "loss_unconditional": loss_unc,
            "loss_conditional": loss_cond,
            "loss_residual": loss_res,
            "sharpe": sharpe_monitor(F),
            "portfolio_returns": F,
        }

    def forward_sdf_switched(
        self,
        params: Params,
        batch: Batch,
        use_cond: jnp.ndarray,
        rng: Optional[jax.Array] = None,
    ) -> Dict[str, jnp.ndarray]:
        """Phases 1 and 3 as ONE program: `use_cond` is a TRACED boolean
        selecting the loss (False → unconditional, True → conditional).

        Exists so the trainer can compile a single shared program for both
        sdf phases instead of two ~6-10 s XLA+Mosaic compiles of
        near-identical scans (the phases differ only in this loss routing).
        Both losses are computed every epoch and a scalar `where` selects —
        deliberately NOT `lax.cond`: a cond region takes its operands by
        tuple, and copying the [T, F, N] panel into the branch cost
        +1.5 ms/epoch at the real shape (measured), far more than the
        ~1.4 ms/epoch of just running the conditional-EM kernel during the
        256 phase-1 epochs. Gradients route through a 0/1 select, so the
        per-phase update math matches :meth:`forward` with the
        corresponding static phase string (to XLA-fusion ulps).
        """
        cfg = self.cfg
        returns, mask = batch["returns"], batch["mask"]
        n_assets = batch.get("n_assets")
        if rng is None:
            w_rng = m_rng = None
        else:
            w_rng, m_rng = jax.random.split(rng)
        weights = self.weights(params, batch, rng=w_rng)
        loss_unc, F = unconditional_loss(
            weights, returns, mask, cfg.weighted_loss, n_assets=n_assets)

        use_fused_cond = (
            self.exec_cfg.pallas_enabled()
            and not cfg.hidden_dim_moment
            and batch.get("individual_t") is not None
            and batch.get("macro") is not None
        )

        if use_fused_cond:
            moments = None  # h never materializes on the fused route
            loss_cond, _ = self._fused_cond_loss(
                params, batch, weights, n_assets, F=F)
        else:
            moments = self.moments(params, batch, rng=m_rng)
            loss_cond, _ = conditional_loss(
                weights, returns, mask, moments, cfg.weighted_loss,
                F=F, n_assets=n_assets)
        total = jnp.where(use_cond, loss_cond, loss_unc)
        total, loss_res = self._residual_term(weights, returns, mask, total)
        return {
            "weights": weights,
            "moments": moments,
            "loss": total,
            "loss_unconditional": loss_unc,
            "loss_conditional": loss_cond,
            "loss_residual": loss_res,
            "sharpe": sharpe_monitor(F),
            "portfolio_returns": F,
        }

    @staticmethod
    def _em_loss(em: jnp.ndarray, n_assets) -> jnp.ndarray:
        """em [K, N] → conditional loss; THE padding-aware normalization
        shared by every fused-em consumer (mean, or sum/(K·true-N))."""
        if n_assets is None:
            return (em**2).mean()
        return (em**2).sum() / (em.shape[0] * n_assets)

    def _residual_term(self, weights, returns, mask, total):
        """(total + λ·residual, residual) — the optional regularizer tail
        shared by the forward variants."""
        if self.cfg.residual_loss_factor > 0:
            loss_res = residual_loss(weights, returns, mask)
            return total + self.cfg.residual_loss_factor * loss_res, loss_res
        return total, jnp.float32(0.0)

    def _fused_cond_loss(self, params, batch, weights, n_assets, F=None):
        """Conditional loss via the fused em kernel; returns (loss, F).

        Under stock sharding the kernel runs per-device via shard_map
        (``fused_conditional_em_sharded``) — em[k, n] is stock-local, so the
        forward needs no communication and only the final (em²) reduction
        below crosses shards.
        """
        cfg = self.cfg
        returns, mask = batch["returns"], batch["mask"]
        k_period, k_stock, bias = moment_output_params(params, cfg)
        zp_m = batch["macro"] @ k_period + bias  # [T, K]
        if F is None:
            F = portfolio_returns(weights, returns, mask, cfg.weighted_loss)
        xr = returns * mask * (1.0 + F)[:, None]
        tinv = 1.0 / jnp.clip(mask.sum(axis=0), 1, None)
        kernel_kw = dict(
            block_stocks=self.exec_cfg.block_stocks,
            interpret=self.exec_cfg.interpret,
            compute_dtype=self.exec_cfg.compute_dtype,
        )
        if self.exec_cfg.shard_mesh is not None:
            em = fused_conditional_em_sharded(
                batch["individual_t"], zp_m, xr, tinv, k_stock,
                self.exec_cfg.shard_mesh, self.exec_cfg.shard_axis,
                **kernel_kw,
            )
        else:
            em = fused_conditional_em(
                batch["individual_t"], zp_m, xr, tinv, k_stock, **kernel_kw,
            )
        return self._em_loss(em, n_assets), F
