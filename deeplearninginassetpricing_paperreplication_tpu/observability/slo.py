"""SLO & alerting plane: error-budget burn-rate engine over the metrics
plane, with a firing/resolved alert state machine and pluggable sinks.

The serving stack is autonomous (refit → promotion gate → rolling hot-swap
→ load-adaptive fleet), which is only safe if the system can tell a human,
fast, when it stops meeting its objectives. This module supplies the
*definition* of "meeting its objectives" (a verified ``slo.json`` spec) and
the *detector* (:class:`SLOEngine`):

  * **Spec** — ``slo.json`` declares objectives over named metric
    *sources*. Two kinds:

      - ``ratio``: an error-budget objective (availability, probe success,
        drift-alert rate). The source yields CUMULATIVE ``(bad, total)``
        counts; the engine differences them over sliding windows and
        evaluates classic multi-window multi-burn-rate alerts — a window
        pair fires when the burn rate (``bad_fraction / (1 - target)``)
        exceeds its threshold over BOTH the long and the short window, so
        a brief blip (short only) or a slow bleed already absorbed
        (long only) does not page.
      - ``value``: a threshold objective (p99 latency, serving freshness =
        months since the last promoted refit). The source yields an
        instantaneous value; the alert fires when every sample inside
        ``sustain_s`` breached ``max`` and the window has real coverage.

    :func:`load_slo` validates the document field by field (unknown kinds,
    non-(0,1) targets, short >= long windows are spec errors, never
    silently ignored) and digest-verifies an adjacent ``.sha256`` sidecar
    when present; :func:`write_slo` writes atomically with the sidecar.

  * **Engine** — :meth:`SLOEngine.tick` samples every source, updates the
    bounded per-objective sample rings, evaluates every window, and drives
    the per-(objective, window) state machine. Transitions emit DURABLE
    ``alert/*`` event rows (kind ``alert`` joins the events fsync set — a
    SIGKILLed process loses at most one flush window of alert evidence),
    land in every configured sink, and ride the
    :class:`~..serving.flight.FlightRecorder` alert ring. Every tick also
    refreshes the ``dlap_alert_*`` gauges (firing / burn rate / budget
    remaining) in the live metrics registry, so every ``/metrics`` scrape
    carries the current alert posture.

  * **Sinks** — :class:`FileAlertSink` (append-only ``alerts.jsonl``) and
    :class:`WebhookAlertSink` (JSON POST; failures are counted, never
    raised — a dead receiver must not take down the detector).

Stdlib-only by contract (like :mod:`.metrics` and
:mod:`..reliability.promotion` at import): the engine runs in thin fleet
parents and ops tooling that never touch jax.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

SCHEMA_VERSION = 1
SLO_FILENAME = "slo.json"

# objective kinds and the alert severities the spec may declare
KINDS = ("ratio", "value")
SEVERITIES = ("page", "ticket", "info")

# sources the standard wiring (serving.probe.build_sources) provides; a
# spec may name others when the caller wires its own callables
KNOWN_SOURCES = (
    "probe", "requests", "drift", "latency_p99_ms", "freshness_months",
)


class SLOSpecError(ValueError):
    """Malformed slo.json — names the offending field."""


# -- the spec ----------------------------------------------------------------


def default_slo() -> Dict[str, Any]:
    """The shipped production spec (repo-root ``slo.json`` mirrors this):
    availability + probe success as multi-window burn rates, p99 latency
    and serving freshness as sustained thresholds, drift-alert rate as a
    slow-burn budget."""
    return {
        "schema": SCHEMA_VERSION,
        "objectives": [
            {
                "name": "availability",
                "kind": "ratio",
                "source": "requests",
                "target": 0.999,
                "windows": [
                    {"long_s": 3600.0, "short_s": 300.0,
                     "burn_rate": 14.4, "severity": "page"},
                    {"long_s": 21600.0, "short_s": 1800.0,
                     "burn_rate": 6.0, "severity": "ticket"},
                ],
            },
            {
                "name": "probe_success",
                "kind": "ratio",
                "source": "probe",
                "target": 0.99,
                "windows": [
                    {"long_s": 600.0, "short_s": 60.0,
                     "burn_rate": 6.0, "severity": "page"},
                ],
            },
            {
                "name": "p99_latency",
                "kind": "value",
                "source": "latency_p99_ms",
                "max": 250.0,
                "sustain_s": 120.0,
                "severity": "ticket",
            },
            {
                "name": "serving_freshness",
                "kind": "value",
                "source": "freshness_months",
                "max": 2.0,
                "sustain_s": 3600.0,
                "severity": "ticket",
            },
            {
                "name": "drift_alert_rate",
                "kind": "ratio",
                "source": "drift",
                "target": 0.95,
                "windows": [
                    {"long_s": 3600.0, "short_s": 600.0,
                     "burn_rate": 4.0, "severity": "ticket"},
                ],
            },
        ],
    }


def drill_spec(long_s: float = 8.0, short_s: float = 2.0,
               burn_rate: float = 6.0) -> Dict[str, Any]:
    """A seconds-scale availability spec for detection drills and benches:
    one probe-success objective whose window pair fires within a few
    seconds of a replica dying under the prober."""
    return {
        "schema": SCHEMA_VERSION,
        "objectives": [
            {
                "name": "availability",
                "kind": "ratio",
                "source": "probe",
                "target": 0.99,
                "windows": [
                    {"long_s": float(long_s), "short_s": float(short_s),
                     "burn_rate": float(burn_rate), "severity": "page"},
                ],
            },
        ],
    }


def validate_slo(doc: Any) -> Dict[str, Any]:
    """Field-by-field spec validation; returns the document. Raises
    :class:`SLOSpecError` naming the offending field — an SLO that cannot
    be evaluated as written must fail loudly, not silently not-alert."""
    if not isinstance(doc, dict):
        raise SLOSpecError("slo spec must be a JSON object")
    if doc.get("schema") != SCHEMA_VERSION:
        raise SLOSpecError(
            f"slo spec schema must be {SCHEMA_VERSION}, "
            f"got {doc.get('schema')!r}")
    objectives = doc.get("objectives")
    if not isinstance(objectives, list) or not objectives:
        raise SLOSpecError("slo spec needs a non-empty 'objectives' list")
    seen: set = set()
    for i, obj in enumerate(objectives):
        where = f"objectives[{i}]"
        if not isinstance(obj, dict):
            raise SLOSpecError(f"{where} must be an object")
        name = obj.get("name")
        if not isinstance(name, str) or not name:
            raise SLOSpecError(f"{where}.name must be a non-empty string")
        if name in seen:
            raise SLOSpecError(f"duplicate objective name {name!r}")
        seen.add(name)
        kind = obj.get("kind")
        if kind not in KINDS:
            raise SLOSpecError(
                f"{where}.kind must be one of {KINDS}, got {kind!r}")
        source = obj.get("source")
        if not isinstance(source, str) or not source:
            raise SLOSpecError(f"{where}.source must be a non-empty string")
        if kind == "ratio":
            target = obj.get("target")
            if not isinstance(target, (int, float)) or not 0 < target < 1:
                raise SLOSpecError(
                    f"{where}.target must be in (0, 1), got {target!r}")
            windows = obj.get("windows")
            if not isinstance(windows, list) or not windows:
                raise SLOSpecError(
                    f"{where}.windows must be a non-empty list")
            for j, w in enumerate(windows):
                ww = f"{where}.windows[{j}]"
                if not isinstance(w, dict):
                    raise SLOSpecError(f"{ww} must be an object")
                for key in ("long_s", "short_s", "burn_rate"):
                    v = w.get(key)
                    if not isinstance(v, (int, float)) or v <= 0:
                        raise SLOSpecError(
                            f"{ww}.{key} must be a positive number, "
                            f"got {v!r}")
                if w["short_s"] >= w["long_s"]:
                    raise SLOSpecError(
                        f"{ww}: short_s ({w['short_s']}) must be < "
                        f"long_s ({w['long_s']})")
                sev = w.get("severity", "page")
                if sev not in SEVERITIES:
                    raise SLOSpecError(
                        f"{ww}.severity must be one of {SEVERITIES}, "
                        f"got {sev!r}")
        else:  # value
            mx = obj.get("max")
            if not isinstance(mx, (int, float)) or mx <= 0:
                raise SLOSpecError(
                    f"{where}.max must be a positive number, got {mx!r}")
            sustain = obj.get("sustain_s")
            if not isinstance(sustain, (int, float)) or sustain <= 0:
                raise SLOSpecError(
                    f"{where}.sustain_s must be a positive number, "
                    f"got {sustain!r}")
            sev = obj.get("severity", "page")
            if sev not in SEVERITIES:
                raise SLOSpecError(
                    f"{where}.severity must be one of {SEVERITIES}, "
                    f"got {sev!r}")
    return doc


def write_slo(path, doc: Dict[str, Any]) -> Path:
    """Validate + atomically write a spec with its ``.sha256`` sidecar
    (the same verified-artifact shape as checkpoints/pointers)."""
    validate_slo(doc)
    path = Path(path)
    data = (json.dumps(doc, indent=2, sort_keys=True) + "\n").encode()
    tmp = path.with_suffix(f".tmp.{os.getpid()}")
    tmp.write_bytes(data)
    os.replace(tmp, path)
    sidecar = path.with_name(path.name + ".sha256")
    tmp = sidecar.with_suffix(f".tmp.{os.getpid()}")
    tmp.write_text(json.dumps(
        {"sha256": hashlib.sha256(data).hexdigest(), "bytes": len(data)}))
    os.replace(tmp, sidecar)
    return path


def load_slo(path) -> Dict[str, Any]:
    """Read + digest-verify (when the sidecar exists) + validate a spec.
    A torn or tampered file raises :class:`SLOSpecError` naming it."""
    path = Path(path)
    try:
        data = path.read_bytes()
    except OSError as e:
        raise SLOSpecError(f"cannot read slo spec {path}: {e}") from e
    sidecar = path.with_name(path.name + ".sha256")
    if sidecar.exists():
        try:
            meta = json.loads(sidecar.read_text())
        except (OSError, json.JSONDecodeError) as e:
            raise SLOSpecError(
                f"unreadable slo sidecar {sidecar}: {e}") from e
        digest = hashlib.sha256(data).hexdigest()
        if meta.get("sha256") != digest:
            raise SLOSpecError(
                f"slo spec {path} does not match its sha256 sidecar "
                f"(file {digest[:12]}…, sidecar "
                f"{str(meta.get('sha256'))[:12]}…)")
    try:
        doc = json.loads(data)
    except json.JSONDecodeError as e:
        raise SLOSpecError(f"slo spec {path} is not valid JSON: {e}") from e
    return validate_slo(doc)


# -- alert sinks -------------------------------------------------------------


class AlertSink:
    """One delivery channel; ``deliver`` must never raise (failures are
    tallied on the sink so the report/console can surface them)."""

    def __init__(self):
        self.delivered = 0
        self.failed = 0

    def deliver(self, alert: Dict[str, Any]) -> None:
        try:
            self._deliver(alert)
        except Exception:
            self.failed += 1
        else:
            self.delivered += 1

    def _deliver(self, alert: Dict[str, Any]) -> None:  # pragma: no cover
        raise NotImplementedError


class FileAlertSink(AlertSink):
    """Append-only JSONL file (one alert transition per line)."""

    def __init__(self, path):
        super().__init__()
        self.path = Path(path)

    def _deliver(self, alert: Dict[str, Any]) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps(alert, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())


class WebhookAlertSink(AlertSink):
    """JSON POST to an HTTP endpoint (PagerDuty/Slack-shaped receivers);
    short timeout so a dead receiver cannot stall the engine thread."""

    def __init__(self, url: str, timeout_s: float = 5.0):
        super().__init__()
        self.url = str(url)
        self.timeout_s = float(timeout_s)

    def _deliver(self, alert: Dict[str, Any]) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.url, data=json.dumps(alert, sort_keys=True).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s):
            pass


# -- sample series -----------------------------------------------------------


class _Series:
    """Bounded ring of (mono_ts, a, b) samples. For ratio objectives the
    payload is CUMULATIVE (bad, total); for value objectives it is
    (value, breached).

    ``maxlen`` must be sized for the window it serves: a ring that holds
    fewer samples than the longest window's span silently shrinks the
    window (the far edge becomes the ring's oldest sample), turning a
    6-hour budget into a minutes-long one. The engine sizes it from the
    objective horizon and its own poll cadence."""

    def __init__(self, max_age_s: float, maxlen: int = 4096):
        self.max_age_s = float(max_age_s)
        self._ring: deque = deque(maxlen=maxlen)

    def append(self, now: float, a: float, b: float) -> None:
        self._ring.append((now, a, b))
        while self._ring and now - self._ring[0][0] > self.max_age_s:
            self._ring.popleft()

    def window_ratio(self, now: float,
                     window_s: float) -> Optional[float]:
        """Bad fraction over the trailing window from cumulative (bad,
        total) samples; None when the window holds no traffic (no new
        totals) or fewer than two samples — no data must mean no alert
        decision, never a spurious 0% or 100%."""
        oldest = None
        newest = None
        for ts, bad, total in self._ring:
            if ts < now - window_s:
                continue
            if oldest is None:
                oldest = (ts, bad, total)
            newest = (ts, bad, total)
        if oldest is None or newest is None or newest is oldest:
            return None
        d_total = newest[2] - oldest[2]
        d_bad = newest[1] - oldest[1]
        if d_total <= 0:
            return None
        return min(1.0, max(0.0, d_bad / d_total))

    def sustained_breach(self, now: float, sustain_s: float
                         ) -> Optional[bool]:
        """True when every sample in the trailing ``sustain_s`` breached
        and the window has coverage from its far edge (>= half the window
        old); None with no samples in the window."""
        samples = [(ts, breached) for ts, _v, breached in self._ring
                   if ts >= now - sustain_s]
        if not samples:
            return None
        if now - samples[0][0] < sustain_s * 0.5:
            return None  # not enough history to call it sustained
        return all(breached for _ts, breached in samples)

    def last_value(self) -> Optional[float]:
        if not self._ring:
            return None
        return self._ring[-1][1]


# -- the engine --------------------------------------------------------------


class SLOEngine:
    """Burn-rate evaluation + alert state machine over pluggable sources.

    ``sources``: ``{source_name: callable}`` where a ratio source returns
    cumulative ``(bad, total)`` (or None while unavailable) and a value
    source returns a float (or None). :meth:`tick` is one full evaluation,
    exposed so tests and the drill drive the engine deterministically;
    :meth:`start` runs it on a supervised daemon thread.
    """

    def __init__(
        self,
        spec: Dict[str, Any],
        sources: Dict[str, Callable[[], Any]],
        events: Any = None,
        flight: Any = None,
        sinks: Tuple[AlertSink, ...] = (),
        poll_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.spec = validate_slo(spec)
        self.sources = dict(sources)
        self.events = events
        self.flight = flight
        self.sinks = list(sinks)
        self.poll_s = float(poll_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._series: Dict[str, _Series] = {}
        # (objective, window_idx) -> {"firing": bool, "since_mono": float,
        #                             "since_ts": float}
        self._states: Dict[Tuple[str, int], Dict[str, Any]] = {}
        # the bounded transition ring the flight recorder dump rides
        self.alerts: deque = deque(maxlen=64)
        self.ticks = 0
        self.source_errors = 0
        # last emitted value per gauge key: rows are written ON CHANGE
        # only, so a quiescent deployment's engine does not grow the
        # event log by ~17 identical rows per tick forever (the metrics
        # registry retains the last value for scrapes, and the console
        # reads "last recorded value" — both unaffected by skipping
        # repeats)
        self._gauge_last: Dict[Tuple[str, Tuple], float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        missing = sorted({obj["source"] for obj in self.spec["objectives"]
                          if obj["source"] not in self.sources})
        if missing:
            # the spec's fail-loud contract extends to the wiring: an
            # objective whose source is not provided would silently
            # never evaluate — no gauge, no alert, ever. Callers that
            # deliberately run a subset must filter the spec first
            # (the probe CLI does, with a printed warning per drop).
            raise SLOSpecError(
                "objectives reference sources with no wired callable: "
                + ", ".join(missing)
                + f" (wired: {sorted(self.sources) or 'none'})")
        for obj in self.spec["objectives"]:
            if obj["kind"] == "ratio":
                horizon = max(w["long_s"] for w in obj["windows"])
            else:
                horizon = obj["sustain_s"]
            # keep one extra window of history so the far edge of the
            # longest window always has a sample to difference against —
            # and size the ring to HOLD that horizon at this poll
            # cadence (a capacity-trimmed ring would silently shrink the
            # window to ring-age), bounded for pathological poll rates
            maxlen = int(horizon * 2.0 / max(self.poll_s, 0.05)) + 16
            self._series[obj["name"]] = _Series(
                max_age_s=horizon * 2.0, maxlen=min(maxlen, 500_000))

    # -- evaluation ----------------------------------------------------------

    def _append(self, obj: Dict[str, Any], sample: Any,
                now: float) -> None:
        if sample is None:
            return
        series = self._series[obj["name"]]
        if obj["kind"] == "ratio":
            bad, total = sample
            series.append(now, float(bad), float(total))
        else:
            value = float(sample)
            series.append(now, value, value > float(obj["max"]))

    def _evaluate_ratio(self, obj: Dict[str, Any], now: float
                        ) -> List[Dict[str, Any]]:
        series = self._series[obj["name"]]
        budget = 1.0 - float(obj["target"])
        out = []
        for idx, w in enumerate(obj["windows"]):
            ratio_long = series.window_ratio(now, w["long_s"])
            ratio_short = series.window_ratio(now, w["short_s"])
            burn_long = (ratio_long / budget
                         if ratio_long is not None else None)
            burn_short = (ratio_short / budget
                          if ratio_short is not None else None)
            should_fire = (burn_long is not None
                           and burn_short is not None
                           and burn_long >= w["burn_rate"]
                           and burn_short >= w["burn_rate"])
            should_resolve = (burn_long is not None
                              and burn_short is not None
                              and burn_long < w["burn_rate"]
                              and burn_short < w["burn_rate"])
            out.append({
                "objective": obj["name"], "window_idx": idx,
                "window": f"{w['long_s']:g}s/{w['short_s']:g}s",
                "severity": w.get("severity", "page"),
                "burn_threshold": w["burn_rate"],
                "burn_long": burn_long, "burn_short": burn_short,
                "ratio_long": ratio_long,
                "budget_remaining": (
                    max(0.0, 1.0 - ratio_long / budget)
                    if ratio_long is not None else None),
                "should_fire": should_fire,
                "should_resolve": should_resolve,
            })
        return out

    def _evaluate_value(self, obj: Dict[str, Any], now: float
                        ) -> List[Dict[str, Any]]:
        series = self._series[obj["name"]]
        breached = series.sustained_breach(now, float(obj["sustain_s"]))
        last = series.last_value()
        return [{
            "objective": obj["name"], "window_idx": 0,
            "window": f"sustain {obj['sustain_s']:g}s",
            "severity": obj.get("severity", "page"),
            "value": last, "max": float(obj["max"]),
            # burn analogue for the gauges: how far past the threshold
            "burn_long": (last / float(obj["max"])
                          if last is not None else None),
            "burn_short": None,
            "budget_remaining": (
                max(0.0, 1.0 - last / float(obj["max"]))
                if last is not None else None),
            "should_fire": breached is True,
            "should_resolve": (breached is False
                               and last is not None
                               and last <= float(obj["max"])),
        }]

    def tick(self) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the alert TRANSITIONS it caused
        (empty on a quiet tick). Gauges refresh every tick regardless."""
        now = self.clock()
        transitions: List[Dict[str, Any]] = []
        # sample every source OUTSIDE the engine lock: a fleet scrape can
        # block for seconds on a wedged replica's timeout, and that must
        # not stall every concurrent firing()/state() reader — exactly
        # the moment those calls matter
        raw: Dict[str, Any] = {}
        errors = 0
        for obj in self.spec["objectives"]:
            try:
                raw[obj["name"]] = self.sources[obj["source"]]()
            except Exception:
                errors += 1
        with self._lock:
            self.ticks += 1
            self.source_errors += errors
            for obj in self.spec["objectives"]:
                self._append(obj, raw.get(obj["name"]), now)
            for obj in self.spec["objectives"]:
                if obj["kind"] == "ratio":
                    verdicts = self._evaluate_ratio(obj, now)
                else:
                    verdicts = self._evaluate_value(obj, now)
                firing_any = False
                for v in verdicts:
                    key = (v["objective"], v["window_idx"])
                    state = self._states.setdefault(
                        key, {"firing": False, "since_mono": None,
                              "since_ts": None})
                    if v["should_fire"] and not state["firing"]:
                        state.update(firing=True, since_mono=now,
                                     since_ts=time.time())
                        transitions.append(self._transition(
                            "firing", v, state))
                    elif v["should_resolve"] and state["firing"]:
                        duration = (now - state["since_mono"]
                                    if state["since_mono"] is not None
                                    else None)
                        state.update(firing=False, since_mono=None,
                                     since_ts=None)
                        t = self._transition("resolved", v, state)
                        if duration is not None:
                            t["firing_duration_s"] = round(duration, 3)
                        transitions.append(t)
                    firing_any = firing_any or state["firing"]
                    self._gauge("alert/burn_rate",
                                v.get("burn_long"),
                                objective=v["objective"],
                                window=v["window"])
                    self._gauge("alert/budget_remaining",
                                v.get("budget_remaining"),
                                objective=v["objective"],
                                window=v["window"])
                self._gauge("alert/firing", float(firing_any),
                            objective=obj["name"])
        for t in transitions:
            self._emit(t)
        return transitions

    def _transition(self, what: str, verdict: Dict[str, Any],
                    state: Dict[str, Any]) -> Dict[str, Any]:
        t = {
            "state": what,
            "objective": verdict["objective"],
            "window": verdict["window"],
            "severity": verdict["severity"],
            "ts": round(time.time(), 6),
        }
        for key in ("burn_long", "burn_short", "burn_threshold",
                    "ratio_long", "value", "max", "budget_remaining"):
            if verdict.get(key) is not None:
                v = verdict[key]
                t[key] = round(v, 6) if isinstance(v, float) else v
        return t

    def _emit(self, transition: Dict[str, Any]) -> None:
        """One state change → the durable event row, every sink, and the
        flight-recorder ring. Never raises: alert delivery failing must
        not stop the detector from detecting."""
        self.alerts.append(transition)
        if self.events is not None:
            try:
                fields = {k: v for k, v in transition.items()
                          if k not in ("state", "ts")}
                # kind "alert" is in events._DURABLE_KINDS: the row
                # fsyncs within one flush window of the transition
                self.events.emit(
                    "alert", f"alert/{transition['state']}", **fields)
            except Exception:
                pass
        if self.flight is not None:
            try:
                self.flight.record_alert(dict(transition))
                if transition["state"] == "firing":
                    # a firing alert is an incident: arm the same burst
                    # trigger 5xx storms use, so the evidence rings dump
                    self.flight.note_alert()
            except Exception:
                pass
        for sink in self.sinks:
            sink.deliver(transition)

    def _gauge(self, name: str, value: Optional[float], **labels) -> None:
        if value is None or self.events is None:
            return
        rounded = round(float(value), 6)
        key = (name, tuple(sorted(labels.items())))
        if self._gauge_last.get(key) == rounded:
            return  # unchanged: no new row (see _gauge_last)
        self._gauge_last[key] = rounded
        try:
            self.events.gauge(name, rounded, **labels)
        except Exception:
            pass

    # -- introspection -------------------------------------------------------

    def firing(self) -> List[Dict[str, Any]]:
        """Currently-firing (objective, window) states, deterministic
        order."""
        with self._lock:
            out = []
            for (objective, idx), state in sorted(self._states.items()):
                if state["firing"]:
                    out.append({"objective": objective, "window_idx": idx,
                                "since_ts": state["since_ts"]})
            return out

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "ticks": self.ticks,
                "source_errors": self.source_errors,
                "firing": [
                    {"objective": obj, "window_idx": idx,
                     "since_ts": st["since_ts"]}
                    for (obj, idx), st in sorted(self._states.items())
                    if st["firing"]],
                "alerts_tail": list(self.alerts)[-8:],
                "sinks": [
                    {"kind": type(s).__name__, "delivered": s.delivered,
                     "failed": s.failed} for s in self.sinks],
            }

    # -- thread lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.poll_s):
                try:
                    self.tick()
                except Exception:
                    pass  # the detector outlives a bad tick

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="slo-engine")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
