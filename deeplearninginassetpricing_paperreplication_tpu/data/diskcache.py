"""Decoded-panel disk cache: skip npz decompress + mask build on re-runs.

The paper workload loads the SAME ~1.2 GB npz panel on every run, then pays
the same decompress, mask build (`panel._build_mask`), zero-fill, and
host-side `flatnonzero`/gather repack (`transfer.pack_rows`) before a single
byte ships to the device. All of that is a pure function of the source file
bytes, so after the first decode this module persists the results as raw
``.npy`` files that later runs ``np.load(mmap_mode="r")`` straight into the
transfer path — no decompress, no mask build, no repack.

Layout: one directory per cache entry under :func:`cache_root`::

    <root>/<key>/meta.json       entry descriptor (version, fingerprints,
                                 shapes, coverage)
    <root>/<key>/returns.npy     [T, N]    float32, zero-filled
    <root>/<key>/individual.npy  [T, N, F] float32, zero-filled
    <root>/<key>/mask.npy        [T, N]    bool
    <root>/<key>/macro.npy       [T, M]    float32 RAW (un-normalized —
                                 normalization depends on the TRAIN split's
                                 stats, so it is applied at load time and the
                                 entry stays keyed by its OWN source files)
    <root>/<key>/dates.npy, variable_names.npy
    <root>/<key>/idx.npy         [V]    int32   ─┐ the packed valid-rows rep
    <root>/<key>/rows.npy        [V, F] float32  ├ transfer.py ships (stored
    <root>/<key>/ret_packed.npy  [V]    float32 ─┘ only when coverage packs)

``<key>`` digests (CACHE_VERSION, char fingerprint, macro fingerprint); a
fingerprint is (resolved path, size, mtime_ns, sha256 of the npz member
directory — names, sizes, CRCs — read from the zip central directory without
touching payload bytes). Any source change (mtime, size, header) therefore
MISSES to a fresh key; :func:`store` evicts superseded entries for the same
source path so the root does not accumulate stale gigabytes.

Stores are atomic (write into a tmp dir, ``os.rename`` into place) and loads
are paranoid: a missing file, a shape mismatch against meta.json, or any
parse error deletes the entry and returns None — the caller falls back to
the npz decode path, never crashes on a corrupt cache.

Location: ``$DLAP_PANEL_CACHE_DIR``, else ``$XDG_CACHE_HOME/dlap/panel_cache``,
else ``~/.cache/dlap/panel_cache``. ``DLAP_PANEL_CACHE=0`` disables entirely.
Clear with ``python -m ...data.diskcache --clear`` (or just delete the dir).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import tempfile
import zipfile
from pathlib import Path
from typing import Any, Dict, Optional, Tuple, Union

import numpy as np

CACHE_VERSION = 1

# entry arrays: filename -> (meta shape key, required). macro/variable_names
# and the packed triple are optional (absent macro / high-coverage panels).
_REQUIRED = ("returns", "individual", "mask", "dates")
_OPTIONAL = ("macro", "variable_names", "idx", "rows", "ret_packed")


def cache_enabled() -> bool:
    return os.environ.get("DLAP_PANEL_CACHE", "1") not in ("0", "false", "off")


def cache_root() -> Path:
    override = os.environ.get("DLAP_PANEL_CACHE_DIR")
    if override:
        return Path(override)
    xdg = os.environ.get("XDG_CACHE_HOME")
    base = Path(xdg) if xdg else Path.home() / ".cache"
    return base / "dlap" / "panel_cache"


def npz_fingerprint(path: Union[str, Path]) -> Dict[str, Any]:
    """Cheap content identity for one .npz: stat fields + a digest of the
    zip central directory (member names, sizes, CRC-32s) — real content
    evidence without reading any payload bytes."""
    path = Path(path)
    st = path.stat()
    h = hashlib.sha256()
    with zipfile.ZipFile(path) as z:
        for info in z.infolist():
            h.update(f"{info.filename}:{info.file_size}:{info.CRC};".encode())
    return {
        "path": str(path.resolve()),
        "size": st.st_size,
        "mtime_ns": st.st_mtime_ns,
        "header_sha": h.hexdigest(),
    }


def entry_key(
    char_path: Union[str, Path],
    macro_path: Optional[Union[str, Path]] = None,
) -> Tuple[str, Dict[str, Any]]:
    """(cache key, the fingerprints that produced it). Any change to either
    source file — or the cache format version — changes the key."""
    fps = {
        "version": CACHE_VERSION,
        "char": npz_fingerprint(char_path),
        "macro": npz_fingerprint(macro_path) if macro_path is not None else None,
    }
    digest = hashlib.sha256(
        json.dumps(fps, sort_keys=True).encode()
    ).hexdigest()[:20]
    return digest, fps


@dataclasses.dataclass
class CacheEntry:
    """One split's decoded arrays, memmapped read-only from the cache.

    ``macro`` is RAW (un-normalized); ``idx``/``rows``/``ret_packed`` are the
    packed valid-rows representation (None when the entry's coverage was
    above the packing threshold at store time)."""

    returns: np.ndarray
    individual: np.ndarray
    mask: np.ndarray
    dates: np.ndarray
    macro: Optional[np.ndarray]
    variable_names: Optional[np.ndarray]
    idx: Optional[np.ndarray]
    rows: Optional[np.ndarray]
    ret_packed: Optional[np.ndarray]
    meta: Dict[str, Any]


def _entry_dir(key: str) -> Path:
    return cache_root() / key


def load(
    char_path: Union[str, Path],
    macro_path: Optional[Union[str, Path]] = None,
) -> Optional[CacheEntry]:
    """Memmap a cache hit for (char_path, macro_path), or None on miss.

    Corruption of any flavor — unreadable meta, missing array file, shape
    drift against meta — deletes the entry and reports a miss so the caller
    re-decodes from the npz."""
    if not cache_enabled():
        return None
    try:
        key, _ = entry_key(char_path, macro_path)
    except (OSError, zipfile.BadZipFile):
        return None  # unreadable SOURCE: let the npz path raise its own error
    d = _entry_dir(key)
    meta_path = d / "meta.json"
    if not meta_path.exists():
        return None
    try:
        meta = json.loads(meta_path.read_text())
        if meta.get("version") != CACHE_VERSION:
            raise ValueError(f"cache version {meta.get('version')}")
        arrays: Dict[str, Optional[np.ndarray]] = {}
        for name in _REQUIRED + _OPTIONAL:
            f = d / f"{name}.npy"
            if not f.exists():
                if name in _REQUIRED or name in meta["shapes"]:
                    raise FileNotFoundError(f.name)
                arrays[name] = None
                continue
            a = np.load(f, mmap_mode="r")
            expect = meta["shapes"].get(name)
            if expect is None or tuple(a.shape) != tuple(expect):
                raise ValueError(
                    f"{name}.npy shape {a.shape} != meta {expect}"
                )
            arrays[name] = a
        return CacheEntry(meta=meta, **arrays)  # type: ignore[arg-type]
    except Exception:
        shutil.rmtree(d, ignore_errors=True)
        return None


def store(
    char_path: Union[str, Path],
    macro_path: Optional[Union[str, Path]],
    arrays: Dict[str, Optional[np.ndarray]],
    extra_meta: Optional[Dict[str, Any]] = None,
) -> Optional[Path]:
    """Persist one split's decoded arrays; returns the entry dir (None when
    caching is disabled or the write fails — a cache must never take down a
    load that already succeeded).

    `arrays` uses the :class:`CacheEntry` field names; missing/None optional
    entries are simply not written. The write is atomic (tmp dir + rename)
    and evicts any older entry recorded for the same source char path."""
    if not cache_enabled():
        return None
    try:
        key, fps = entry_key(char_path, macro_path)
        root = cache_root()
        root.mkdir(parents=True, exist_ok=True)
        final = root / key
        if (final / "meta.json").exists():
            return final  # concurrent writer beat us; entry is complete
        shapes = {}
        tmp = Path(tempfile.mkdtemp(dir=root, prefix=f".{key}."))
        try:
            for name in _REQUIRED + _OPTIONAL:
                a = arrays.get(name)
                if a is None:
                    continue
                a = np.asarray(a)
                np.save(tmp / f"{name}.npy", a, allow_pickle=False)
                shapes[name] = list(a.shape)
            meta = {
                "version": CACHE_VERSION,
                "fingerprints": fps,
                "shapes": shapes,
                **(extra_meta or {}),
            }
            # meta.json is written LAST: its presence marks a complete entry
            (tmp / "meta.json").write_text(json.dumps(meta, indent=1))
            _evict_stale(root, fps["char"]["path"], keep=key)
            os.rename(tmp, final)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return final
    except Exception:
        return None


def _evict_stale(root: Path, source_char_path: str, keep: str) -> None:
    """Remove superseded entries recorded for the same source file (a
    re-generated npz would otherwise leave its old decode behind forever)."""
    for d in root.iterdir():
        if not d.is_dir() or d.name == keep or d.name.startswith("."):
            continue
        try:
            meta = json.loads((d / "meta.json").read_text())
            if meta["fingerprints"]["char"]["path"] == source_char_path:
                shutil.rmtree(d, ignore_errors=True)
        except Exception:
            continue  # unreadable sibling: not ours to judge


def clear() -> int:
    """Delete every cache entry; returns the number removed."""
    root = cache_root()
    if not root.is_dir():
        return 0
    n = 0
    for d in root.iterdir():
        if d.is_dir():
            shutil.rmtree(d, ignore_errors=True)
            n += 1
    return n


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m deeplearninginassetpricing_paperreplication_tpu."
             "data.diskcache",
        description="Inspect or clear the decoded-panel disk cache",
    )
    p.add_argument("--clear", action="store_true", help="delete all entries")
    args = p.parse_args(argv)
    root = cache_root()
    if args.clear:
        print(f"removed {clear()} entries from {root}")
        return 0
    entries = sorted(d for d in root.iterdir() if d.is_dir()) if root.is_dir() else []
    total = 0
    for d in entries:
        size = sum(f.stat().st_size for f in d.iterdir() if f.is_file())
        total += size
        src = "?"
        try:
            meta = json.loads((d / "meta.json").read_text())
            src = meta["fingerprints"]["char"]["path"]
        except Exception:
            pass
        print(f"  {d.name}  {size / (1 << 20):8.1f} MiB  {src}")
    print(f"{len(entries)} entries, {total / (1 << 20):.1f} MiB in {root}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
