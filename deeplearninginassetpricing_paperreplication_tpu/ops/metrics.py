"""Portfolio metrics: Sharpe, max drawdown, weight normalization.

Sharpe-convention trap carried over from the reference, made explicit here:
the reference computes Sharpe with *torch* std (Bessel-corrected, ddof=1) in
training/eval (``/root/reference/src/train.py:29-34``, ``model.py:551``) but
with *numpy* std (ddof=0) in the ensemble evaluator
(``evaluate_ensemble.py:46-50``). Both are monthly (NOT annualized), and the
paper-convention headline number is computed on the NEGATED portfolio return
(``evaluate_ensemble.py:169-171``) while best-model selection during training
uses the un-negated value (``train.py:268, 378``). Use `ddof` to pick.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def sharpe(returns: jnp.ndarray, ddof: int = 1) -> jnp.ndarray:
    """Monthly Sharpe mean/std; 0 when std < 1e-8 (train.py:29-34)."""
    std = returns.std(ddof=ddof)
    return jnp.where(std < 1e-8, 0.0, returns.mean() / std)


def sharpe_monitor(returns: jnp.ndarray) -> jnp.ndarray:
    """The in-forward monitoring Sharpe: mean / (std_ddof1 + 1e-8)
    (model.py:551)."""
    return returns.mean() / (returns.std(ddof=1) + 1e-8)


def max_drawdown(returns: np.ndarray) -> float:
    """Max drawdown of the cumulative-product wealth curve (train.py:37-42)."""
    cumulative = np.cumprod(1.0 + np.asarray(returns))
    running_max = np.maximum.accumulate(cumulative)
    return float(((cumulative - running_max) / running_max).min())


def normalize_weights_abs(weights: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Per-period scaling so Σ_i |w·m| = 1 — vectorized over T (the reference
    loops over periods, model.py:584-592). Weights are assumed already masked;
    the abs-sum is clamped to 1e-8 as in the reference."""
    abs_sum = jnp.clip((jnp.abs(weights) * mask).sum(axis=1, keepdims=True), 1e-8, None)
    return weights / abs_sum


# -- paper Table-1 risk-premium metrics (EV, cross-sectional R²) --------------
#
# The paper (Chen-Pelger-Zhu, Table 1) reports, next to the Sharpe ratio, the
# explained variation EV and the cross-sectional R² of the estimated SDF
# (GAN test row: EV 0.08, XS-R² 0.23 — see BASELINE.md). The reference
# replication implements NEITHER (its evaluate/evaluate_ensemble stop at
# Sharpe/drawdown — /root/reference/src/train.py:106-153,
# evaluate_ensemble.py:159-203), so these are additive capability here.
#
# The paper's conditional loadings β_{t,i} come from a separate conditional
# estimation; the standard replication proxy (used here, and documented as
# such) is the per-stock unconditional OLS beta of R_i on the SDF factor F
# over the stock's valid months. All formulas are masked-panel exact: means
# use each stock's own T_i valid months, and fully-masked entries contribute
# nothing. Both metrics are invariant to the sign of F (β flips with F), so
# the paper's negated-return convention does not affect them.


def factor_betas(
    returns: jnp.ndarray, factor: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Per-stock OLS slope β_i of R_it on F_t over stock i's valid months.

    returns/mask [T, N], factor [T] → β [N]. Stocks with zero valid months or
    (numerically) zero factor variance over their window get β = 0.
    """
    t_i = jnp.clip(mask.sum(axis=0), 1, None)  # [N]
    rbar = (returns * mask).sum(axis=0) / t_i  # [N]
    fbar = (factor[:, None] * mask).sum(axis=0) / t_i  # [N] per-stock F mean
    f_dev = (factor[:, None] - fbar) * mask  # [T, N]
    cov = (f_dev * (returns - rbar)).sum(axis=0) / t_i
    var = (f_dev**2).sum(axis=0) / t_i
    return jnp.where(var > 1e-12, cov / jnp.clip(var, 1e-12, None), 0.0)


def explained_variation(
    returns: jnp.ndarray,
    factor: jnp.ndarray,
    mask: jnp.ndarray,
    betas: jnp.ndarray = None,
) -> jnp.ndarray:
    """EV = 1 − Σ_{t,i} m·ε² / Σ_{t,i} m·R², ε = R − β_i·F_t (paper §II.D).

    The share of total individual-stock return variation explained by the
    single SDF-factor exposure. Pass `betas` to reuse :func:`factor_betas`.
    """
    if betas is None:
        betas = factor_betas(returns, factor, mask)
    eps = (returns - betas[None, :] * factor[:, None]) * mask
    total = jnp.clip((returns**2 * mask).sum(), 1e-12, None)
    return 1.0 - (eps**2).sum() / total


def cross_sectional_r2(
    returns: jnp.ndarray,
    factor: jnp.ndarray,
    mask: jnp.ndarray,
    betas: jnp.ndarray = None,
    min_obs: int = 1,
) -> jnp.ndarray:
    """XS-R² = 1 − Σ_i T_i·ē_i² / Σ_i T_i·R̄_i² over stocks with ≥ min_obs
    valid months — how much of the cross-section of average returns the
    factor's risk premia explain (paper §II.D). ē_i / R̄_i are stock i's
    time-series means of the residual / raw return over its valid months;
    stocks are weighted by observation count T_i so thin histories don't
    dominate.
    """
    if betas is None:
        betas = factor_betas(returns, factor, mask)
    t_i = mask.sum(axis=0)  # [N]
    keep = t_i >= min_obs
    safe_t = jnp.clip(t_i, 1, None)
    eps = (returns - betas[None, :] * factor[:, None]) * mask
    ebar = eps.sum(axis=0) / safe_t
    rbar = (returns * mask).sum(axis=0) / safe_t
    num = (t_i * ebar**2 * keep).sum()
    den = jnp.clip((t_i * rbar**2 * keep).sum(), 1e-12, None)
    return 1.0 - num / den
