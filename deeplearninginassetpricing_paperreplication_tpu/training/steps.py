"""Jitted per-phase train/eval steps with partitioned optimizers.

The reference runs two separate torch Adam optimizers over the sdf/moment
parameter subtrees and freezes the other side per phase
(``/root/reference/src/train.py:210-211, 304-317``). Here each phase's step
differentiates ONLY its trainable subtree (the frozen subtree enters the
forward as a non-differentiated closure argument — exactly equivalent to
``requires_grad=False`` + a scoped optimizer), clips the gradient global norm
at 1.0 (train.py:88-92, scoped to the trainable subtree like torch's scoped
``clip_grad_norm_``), and applies Adam(lr, eps=1e-8) — torch's defaults.

Phase → (loss, trainable subtree):
    unconditional → E[w·R·M]²,    sdf_net
    moment        → −E[h·w·R·M]², moment_net
    conditional   → E[h·w·R·M]²,  sdf_net
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from ..models.gan import GAN
from ..ops.metrics import normalize_weights_abs, sharpe

Params = Any

_TRAINABLE = {
    "unconditional": "sdf_net",
    "moment": "moment_net",
    "conditional": "sdf_net",
}


def make_optimizer(lr: float, grad_clip: float = 1.0) -> optax.GradientTransformation:
    """clip-by-global-norm → Adam, matching torch clip_grad_norm_ + Adam
    (b1=0.9, b2=0.999, eps=1e-8)."""
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adam(lr, b1=0.9, b2=0.999, eps=1e-8),
    )


def trainable_key(phase: str) -> str:
    return _TRAINABLE[phase]


def make_train_step(
    gan: GAN, phase: str, tx: optax.GradientTransformation
) -> Callable:
    """step(params, opt_state, batch, rng) → (params, opt_state, metrics).

    `opt_state` is the Adam state over the phase's trainable subtree only.
    """
    key = trainable_key(phase)
    other = "moment_net" if key == "sdf_net" else "sdf_net"

    def loss_fn(trainable: Params, frozen: Params, batch, rng):
        params = {key: trainable, other: frozen}
        out = gan.forward(params, batch, phase=phase, rng=rng)
        return out["loss"], out

    def step(params: Params, opt_state, batch, rng):
        (loss, out), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params[key], params[other], batch, rng
        )
        updates, opt_state = tx.update(grads, opt_state, params[key])
        new_params = dict(params)
        new_params[key] = optax.apply_updates(params[key], updates)
        metrics = {
            "loss": loss,
            "loss_unc": out["loss_unconditional"],
            "loss_cond": out["loss_conditional"],
            "loss_residual": out["loss_residual"],
            # guarded sharpe (0 when std<1e-8), matching the reference's
            # train_epoch logging (train.py:96-103) rather than the
            # in-forward monitor which would explode on zero variance
            "sharpe": sharpe(out["portfolio_returns"], ddof=1),
            "grad_norm": optax.global_norm(grads),
        }
        return new_params, opt_state, metrics

    return step


def make_sdf_switched_train_step(
    gan: GAN, tx: optax.GradientTransformation
) -> Callable:
    """step(params, opt_state, batch, rng, use_cond) → (params, opt, metrics).

    The sdf-phase step with a TRACED loss switch (False → phase 1's
    unconditional loss, True → phase 3's conditional loss) so both phases
    dispatch one shared compiled program. Math per phase is identical to
    ``make_train_step(gan, phase, tx)``: same trainable subtree (sdf_net),
    same rng splits, same clip→Adam update.
    """
    key, other = "sdf_net", "moment_net"

    def loss_fn(trainable: Params, frozen: Params, batch, rng, use_cond):
        params = {key: trainable, other: frozen}
        out = gan.forward_sdf_switched(params, batch, use_cond, rng=rng)
        return out["loss"], out

    def step(params: Params, opt_state, batch, rng, use_cond):
        (loss, out), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params[key], params[other], batch, rng, use_cond
        )
        updates, opt_state = tx.update(grads, opt_state, params[key])
        new_params = dict(params)
        new_params[key] = optax.apply_updates(params[key], updates)
        metrics = {
            "loss": loss,
            "loss_unc": out["loss_unconditional"],
            "loss_cond": out["loss_conditional"],
            "loss_residual": out["loss_residual"],
            "sharpe": sharpe(out["portfolio_returns"], ddof=1),
            "grad_norm": optax.global_norm(grads),
        }
        return new_params, opt_state, metrics

    return step


def make_eval_step(gan: GAN) -> Callable:
    """eval(params, batch) → scalar metrics dict; dropout off.

    Mirrors the reference's ``evaluate`` (train.py:106-153): Sharpe on the
    abs-sum-normalized weights' portfolio (ddof=1, torch convention), losses
    from a conditional-phase forward.
    """

    def evaluate(params: Params, batch) -> Dict[str, jnp.ndarray]:
        batch = gan.prepare_batch(batch)
        out = gan.forward(params, batch, phase="conditional", rng=None)
        nw = normalize_weights_abs(out["weights"], batch["mask"])
        port = (nw * batch["returns"] * batch["mask"]).sum(axis=1)
        return {
            "loss": out["loss"],
            "loss_unc": out["loss_unconditional"],
            "loss_cond": out["loss_conditional"],
            "sharpe": sharpe(port, ddof=1),
            "mean_return": port.mean(),
            "std_return": port.std(),
        }

    return evaluate
