"""Typed, validated model/training configuration.

The reference reads a plain dict with ``config.get(key, default)`` everywhere
(``/root/reference/src/model.py:298-344``), which silently ignores mistyped
keys — e.g. ``demo_full.ipynb`` passes ``rnn_hidden_dim`` / ``num_moments``
which are never read. This module makes such mistakes loud: unknown keys raise
(or warn, for the documented legacy aliases), and every field is type-checked.

The canonical key names are kept identical to the reference's config.json so
checkpoint directories are interchangeable between the two frameworks.
"""

from __future__ import annotations

import dataclasses
import json
import warnings
from pathlib import Path
from typing import Any, Dict, List, Mapping, Sequence, Tuple, Union


def _as_tuple(x: Union[int, Sequence[int], None]) -> Tuple[int, ...]:
    if x is None:
        return ()
    if isinstance(x, int):
        return (x,)
    return tuple(int(v) for v in x)


# Keys the reference accepts but never reads (documented quirks), and keys it
# derives from others. We accept them for config.json compatibility but they
# carry no information.
_DERIVED_KEYS = {
    "num_layers",
    "num_layers_rnn",
    "num_layers_moment",
    "num_layers_rnn_moment",
    "cell_type_rnn",
    "cell_type_rnn_moment",
}

# Misnamed keys seen in the wild (reference notebooks) → the canonical key.
# The reference silently drops these; we map them and warn.
_LEGACY_ALIASES = {
    "rnn_hidden_dim": "num_units_rnn",
    "rnn_hidden_dim_moment": "num_units_rnn_moment",
    "num_moments": "num_condition_moment",
}


@dataclasses.dataclass(frozen=True)
class GANConfig:
    """Configuration of the SDF-GAN (generator + discriminator).

    Field names and defaults replicate the reference's config dict
    (``/root/reference/src/train.py:530-561``, ``src/model.py:298-344``).
    """

    macro_feature_dim: int
    individual_feature_dim: int

    # SDF network (generator). Paper: [64, 64] hidden, LSTM [4] over macro.
    hidden_dim: Tuple[int, ...] = (64, 64)
    use_rnn: bool = True
    num_units_rnn: Tuple[int, ...] = (4,)

    # Moment network (discriminator). Paper: no hidden layers, 8 moments.
    hidden_dim_moment: Tuple[int, ...] = ()
    num_condition_moment: int = 8
    # Accepted-but-inert in the reference (no RNN is ever built for the moment
    # net — /root/reference/src/model.py:104-116). We keep the fields so
    # reference config.json files round-trip, and warn if they would matter.
    use_rnn_moment: bool = True
    num_units_rnn_moment: Tuple[int, ...] = (32,)

    # Regularization / loss shaping.
    dropout: float = 0.05
    normalize_w: bool = True
    weighted_loss: bool = True
    residual_loss_factor: float = 0.0

    def __post_init__(self):
        if self.macro_feature_dim < 0 or self.individual_feature_dim <= 0:
            raise ValueError(
                f"Invalid feature dims: macro={self.macro_feature_dim}, "
                f"individual={self.individual_feature_dim}"
            )
        object.__setattr__(self, "hidden_dim", _as_tuple(self.hidden_dim))
        object.__setattr__(self, "num_units_rnn", _as_tuple(self.num_units_rnn))
        object.__setattr__(self, "hidden_dim_moment", _as_tuple(self.hidden_dim_moment))
        object.__setattr__(
            self, "num_units_rnn_moment", _as_tuple(self.num_units_rnn_moment)
        )
        if not 0.0 <= self.dropout < 1.0:
            raise ValueError(f"dropout must be in [0, 1): {self.dropout}")
        if self.num_condition_moment <= 0:
            raise ValueError(f"num_condition_moment must be > 0: {self.num_condition_moment}")
        if self.use_rnn and not self.num_units_rnn:
            raise ValueError("use_rnn=True requires non-empty num_units_rnn")

    # -- dict / json round-trip (reference config.json compatible) ----------

    @classmethod
    def from_dict(cls, d: Mapping[str, Any], strict: bool = True) -> "GANConfig":
        """Build from a reference-style config dict.

        Unknown keys raise (strict=True) or warn; documented legacy aliases
        are mapped to their canonical names with a warning.
        """
        known = {f.name for f in dataclasses.fields(cls)}
        clean: Dict[str, Any] = {}
        for k, v in d.items():
            if k in known:
                clean[k] = v
            elif k in _LEGACY_ALIASES:
                canonical = _LEGACY_ALIASES[k]
                warnings.warn(
                    f"Config key {k!r} is a known misnaming of {canonical!r} "
                    f"(the reference silently ignores it); mapping it."
                )
                clean.setdefault(canonical, v)
            elif k in _DERIVED_KEYS:
                continue  # informational only; re-derived on to_dict()
            elif strict:
                raise KeyError(
                    f"Unknown config key {k!r}. Known keys: {sorted(known)}; "
                    f"legacy aliases: {sorted(_LEGACY_ALIASES)}"
                )
            else:
                warnings.warn(f"Ignoring unknown config key {k!r}")
        return cls(**clean)

    def to_dict(self) -> Dict[str, Any]:
        """Dict shaped like the reference's config.json (incl. derived keys)."""
        d = dataclasses.asdict(self)
        d["hidden_dim"] = list(self.hidden_dim)
        d["num_units_rnn"] = list(self.num_units_rnn)
        d["hidden_dim_moment"] = list(self.hidden_dim_moment)
        d["num_units_rnn_moment"] = list(self.num_units_rnn_moment)
        d["num_layers"] = len(self.hidden_dim)
        d["num_layers_rnn"] = len(self.num_units_rnn)
        d["num_layers_moment"] = len(self.hidden_dim_moment)
        d["num_layers_rnn_moment"] = len(self.num_units_rnn_moment)
        d["cell_type_rnn"] = "lstm"
        d["cell_type_rnn_moment"] = "lstm"
        return d

    def save(self, path: Union[str, Path]) -> None:
        Path(path).write_text(json.dumps(self.to_dict(), indent=2))

    @classmethod
    def load(cls, path: Union[str, Path]) -> "GANConfig":
        return cls.from_dict(json.loads(Path(path).read_text()), strict=False)

    # -- derived properties --------------------------------------------------

    @property
    def sdf_input_dim(self) -> int:
        macro = (
            self.num_units_rnn[-1]
            if (self.use_rnn and self.macro_feature_dim > 0)
            else self.macro_feature_dim
        )
        return macro + self.individual_feature_dim

    @property
    def moment_input_dim(self) -> int:
        # Moment net consumes RAW macro (not LSTM state) + individual features
        # (/root/reference/src/model.py:514-518).
        return self.macro_feature_dim + self.individual_feature_dim


@dataclasses.dataclass(frozen=True)
class ExecutionConfig:
    """How to execute the model on this host — NOT a model hyperparameter.

    Kept separate from GANConfig so config.json stays interchangeable with
    the reference checkpoints regardless of execution strategy.

    pallas_ffn: "auto" uses the fused Pallas SDF-FFN kernel
        (ops/pallas_ffn.py) when running on TPU with a non-empty hidden
        stack; "on"/"off" force it. The kernel is bit-identical in output
        ordering but draws dropout masks from the TPU-native PRNG, so
        pallas-on vs pallas-off runs only match exactly with dropout=0.
    block_stocks: stock-tile width for the kernel (0 = auto-size to VMEM).
    compute_dtype: matmul operand dtype inside the kernel; bfloat16 matches
        JAX's default TPU matmul precision class (f32 accumulation always).
    interpret: run the kernel in the Pallas interpreter (CPU testing).
    """

    pallas_ffn: str = "auto"
    block_stocks: int = 0
    compute_dtype: str = "bfloat16"
    interpret: bool = False
    # Store the derived feature-major panel (individual_t) in bfloat16,
    # halving its HBM footprint, and route the moment net through a bf16
    # einsum (f32 accumulation everywhere). Measured at the real shape
    # (T=240, N=10k): 6.9 vs 8.2 ms/epoch for the conditional phase (~15%).
    # End-to-end training parity vs the torch reference is validated on this
    # route — PARITY_BF16.json, |Δ test Sharpe| = 0.0031, identical to the
    # f32-panel route to 4 decimals. Set False for bit-level f32 comparisons.
    bf16_panel: bool = True
    # (A one-panel-read fused EVAL kernel existed through round 3, off by
    # default: it removed ~21% of eval panel bytes but measured net-negative
    # — the epoch was per-cell-overhead-bound, not byte-bound. Multi-period
    # blocking (ops/pallas_ffn.choose_period_block) now attacks that
    # overhead directly, so the eval kernel was removed.)
    # When the panel is GSPMD-sharded along stocks, set these so the kernel
    # runs per-device under shard_map instead of forcing an all-gather.
    # `shard_mesh` is a jax.sharding.Mesh (hashable); None = unsharded.
    shard_mesh: Any = None
    shard_axis: str = "stocks"

    def __post_init__(self):
        if self.pallas_ffn not in ("auto", "on", "off"):
            raise ValueError(
                f"pallas_ffn must be auto|on|off: {self.pallas_ffn!r}"
            )

    def pallas_enabled(self) -> bool:
        """Trace-time master switch for ALL fused kernels (FFN + moment)."""
        if self.pallas_ffn == "off":
            return False
        if self.pallas_ffn == "on":
            return True
        import jax

        return jax.default_backend() == "tpu"

    def use_pallas(self, hidden_dim) -> bool:
        """Routing decision for the fused SDF-FFN kernel specifically."""
        return bool(hidden_dim) and self.pallas_enabled()

    def bf16_wire_ok(self, cfg) -> bool:
        """May the panel ship bfloat16 over the wire for `cfg` (a GANConfig)?

        Only when EVERY panel consumer reads it at bf16 anyway — i.e. the
        fused-kernel route with bf16_panel on, AND the default (empty)
        hidden_dim_moment: a non-empty one sends MomentNet down the
        TorchDenseSplit route, which reads the f32 `individual` panel
        directly, and shipping bf16-rounded f32 there would silently change
        computed values. One predicate for train.py / sweep.py / bench.py so
        the three call sites cannot drift."""
        return (self.bf16_panel and self.use_pallas(cfg.hidden_dim)
                and not cfg.hidden_dim_moment)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """3-phase training schedule (reference CLI defaults, src/train.py:436-464)."""

    num_epochs_unc: int = 256
    num_epochs_moment: int = 64
    num_epochs: int = 1024
    lr: float = 1e-3
    grad_clip: float = 1.0
    ignore_epoch: int = 64
    seed: int = 42
    print_freq: int = 128

    def __post_init__(self):
        for name in ("num_epochs_unc", "num_epochs_moment", "num_epochs"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")
        if self.lr <= 0:
            raise ValueError("lr must be > 0")
