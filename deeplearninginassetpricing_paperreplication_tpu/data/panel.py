"""Panel dataset: the canonical [T, N, F] batch for the SDF-GAN.

Replicates the reference loader's semantics (``/root/reference/src/data_loader.py``)
on top of plain NumPy, producing a static-shape batch dict that is directly
`jax.device_put`-able and shardable along the stock axis:

    {"macro":      float32 [T, M]      (z-scored with TRAIN-set stats),
     "individual": float32 [T, N, F]   (0 where masked),
     "returns":    float32 [T, N]      (0 where masked),
     "mask":       float32 [T, N]      (1 = valid observation)}

Mask semantics (data_loader.py:50-65): an observation is valid iff the return
is > -98.99 (sentinel -99.99 + 1), not NaN, AND every individual feature is
> -98.99. Masked entries are zero-filled so they are inert in the masked
reductions downstream.

The mask is stored as float32 (not bool) because every consumer multiplies by
it; keeping it float avoids T*N bool→float casts inside the jitted step.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, Optional, Sequence, Tuple, Union

import numpy as np

MISSING_VALUE = -99.99
_MISSING_THRESHOLD = MISSING_VALUE + 1  # reference: `> MISSING_VALUE + 1`

Batch = Dict[str, np.ndarray]


@dataclasses.dataclass
class PanelDataset:
    """A (T periods) × (N stocks) panel of returns + characteristics + macro.

    Use :func:`load_panel` / :func:`load_splits` to construct from .npz files.
    """

    returns: np.ndarray  # [T, N] float32, zero-filled where invalid
    individual: np.ndarray  # [T, N, F] float32, zero-filled where invalid
    mask: np.ndarray  # [T, N] bool
    macro: Optional[np.ndarray]  # [T, M] float32 (normalized) or None
    dates: np.ndarray  # [T] int64 YYYYMM
    variable_names: Optional[np.ndarray] = None
    mean_macro: Optional[np.ndarray] = None  # [1, M] stats used to normalize
    std_macro: Optional[np.ndarray] = None
    # true asset count when the stock axis has been padded (pad_stocks);
    # None = no padding. Exported into the batch so the losses divide their
    # asset-mean by the real N, keeping padded runs bit-equal to unpadded.
    n_assets: Optional[int] = None

    @property
    def T(self) -> int:
        return self.returns.shape[0]

    @property
    def N(self) -> int:
        return self.returns.shape[1]

    @property
    def individual_feature_dim(self) -> int:
        return self.individual.shape[2]

    @property
    def macro_feature_dim(self) -> int:
        return 0 if self.macro is None else self.macro.shape[1]

    def full_batch(self) -> Batch:
        """The whole panel as one static-shape batch (training consumes this)."""
        batch = {
            "individual": self.individual,
            "returns": self.returns,
            "mask": self.mask.astype(np.float32),
        }
        if self.macro is not None:
            batch["macro"] = self.macro
        if self.n_assets is not None and self.n_assets != self.N:
            batch["n_assets"] = np.float32(self.n_assets)
        return batch

    def valid_per_period(self) -> np.ndarray:
        """N_t: count of valid stocks per period (data_loader.py:153-155)."""
        return self.mask.sum(axis=1).astype(np.float32)

    def macro_stats(self) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        return self.mean_macro, self.std_macro

    def subsample(self, n_periods: int, n_stocks: int) -> "PanelDataset":
        """First `n_periods` periods × the `n_stocks` stocks with most valid
        observations (reference create_small_sample, data_loader.py:207-237).
        """
        T = min(n_periods, self.T)
        N = min(n_stocks, self.N)
        valid_counts = self.mask.sum(axis=0)
        top = np.argsort(valid_counts)[-N:]
        return PanelDataset(
            returns=self.returns[:T, top],
            individual=self.individual[:T, top, :],
            mask=self.mask[:T, top],
            macro=None if self.macro is None else self.macro[:T],
            dates=self.dates[:T],
            variable_names=self.variable_names,
            mean_macro=self.mean_macro,
            std_macro=self.std_macro,
            # a padded panel keeps its true asset count through subsampling:
            # padded columns have zero valid observations so they sort LAST —
            # they are only retained when N exceeds the real count, in which
            # case the losses must still divide by the real n_assets. When
            # every kept column is real (N <= n_assets) the min() collapses
            # to N and full_batch() omits the key, as for an unpadded panel.
            n_assets=None if self.n_assets is None else min(self.n_assets, N),
        )

    def pad_stocks(self, multiple: int) -> "PanelDataset":
        """Pad the stock axis with masked-out zeros to a multiple of `multiple`.

        Padded entries have mask=0 so every masked reduction is unchanged; this
        lets [T, N, F] shard evenly over a device mesh axis.
        """
        pad = (-self.N) % multiple
        if pad == 0:
            return self
        return PanelDataset(
            returns=np.pad(self.returns, ((0, 0), (0, pad))),
            individual=np.pad(self.individual, ((0, 0), (0, pad), (0, 0))),
            mask=np.pad(self.mask, ((0, 0), (0, pad))),
            macro=self.macro,
            dates=self.dates,
            variable_names=self.variable_names,
            mean_macro=self.mean_macro,
            std_macro=self.std_macro,
            n_assets=self.n_assets if self.n_assets is not None else self.N,
        )


def _build_mask(returns: np.ndarray, individual: np.ndarray) -> np.ndarray:
    mask = (returns > _MISSING_THRESHOLD) & ~np.isnan(returns)
    mask &= np.all(individual > _MISSING_THRESHOLD, axis=2)
    return mask


def macro_train_stats(macro: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """The train split's z-score stats, exactly as `load_panel` computes them
    (single definition so the cache-aware pipeline path is bit-identical)."""
    mean = macro.mean(axis=0, keepdims=True)
    std = macro.std(axis=0, keepdims=True) + 1e-8
    return mean, std


def normalize_macro_with(
    macro: np.ndarray, mean: np.ndarray, std: np.ndarray
) -> np.ndarray:
    """Apply shared z-score stats — the one expression every macro consumer
    (load_panel, load_splits, data.pipeline) must use for bit-identity."""
    return ((macro - mean) / std).astype(np.float32)


def load_panel(
    char_path: Union[str, Path],
    macro_path: Optional[Union[str, Path]] = None,
    macro_idx: Optional[Sequence[int]] = None,
    mean_macro: Optional[np.ndarray] = None,
    std_macro: Optional[np.ndarray] = None,
    normalize_macro: bool = True,
) -> PanelDataset:
    """Load one split from .npz files (schema of data_loader.py:42-94).

    The char .npz holds `data` [T, N, 1+F] with returns in channel 0, plus
    `date` and `variable`. The macro .npz holds `data` [T, M] and `date`.
    Macro series are z-scored; pass `mean_macro`/`std_macro` from the train
    split for valid/test so all splits share the train statistics.
    """
    with np.load(char_path, allow_pickle=True) as f:
        data = f["data"]
        dates = f["date"] if "date" in f.files else np.arange(data.shape[0])
        variables = f["variable"] if "variable" in f.files else None

    decoded = None
    if data.dtype == np.float32:
        # native one-pass codec (data/native.py + _native/panel_codec.cpp);
        # None when no C++ toolchain — then the NumPy path below
        from .native import decode_panel

        decoded = decode_panel(data, _MISSING_THRESHOLD)
    if decoded is not None:
        returns, individual, mask = decoded
    else:
        returns = data[:, :, 0].astype(np.float32)
        individual = data[:, :, 1:].astype(np.float32)
        mask = _build_mask(returns, individual)
        returns = np.where(mask, returns, 0.0).astype(np.float32)
        individual = np.where(mask[:, :, None], individual, 0.0).astype(np.float32)

    macro = None
    out_mean = out_std = None
    if macro_path is not None:
        with np.load(macro_path, allow_pickle=True) as f:
            macro = f["data"].astype(np.float32)
        if macro_idx is not None:
            macro = macro[:, list(macro_idx)]
        if normalize_macro:
            if (mean_macro is None) != (std_macro is None):
                raise ValueError(
                    "mean_macro and std_macro must be provided together "
                    f"(got mean={'set' if mean_macro is not None else 'None'}, "
                    f"std={'set' if std_macro is not None else 'None'})"
                )
            if mean_macro is None:
                out_mean, out_std = macro_train_stats(macro)
            else:
                out_mean, out_std = mean_macro, std_macro
            macro = normalize_macro_with(macro, out_mean, out_std)

    return PanelDataset(
        returns=returns,
        individual=individual,
        mask=mask,
        macro=macro,
        dates=np.asarray(dates),
        variable_names=variables,
        mean_macro=out_mean,
        std_macro=out_std,
    )


def load_splits(
    data_dir: Union[str, Path],
    macro_idx: Optional[Sequence[int]] = None,
) -> Tuple[PanelDataset, PanelDataset, PanelDataset]:
    """Load train/valid/test with train-set macro normalization applied to all
    three (reference create_data_loaders / train.py:485-504).

    Expects the reference directory layout:
        data_dir/char/Char_{train,valid,test}.npz
        data_dir/macro/macro_{train,valid,test}.npz
    """
    import concurrent.futures

    data_dir = Path(data_dir)
    # the three splits are independent I/O+decode jobs (np.load and the
    # native codec both release the GIL for the heavy parts) — load them
    # concurrently, then re-normalize valid/test macro with the train stats
    with concurrent.futures.ThreadPoolExecutor(3) as ex:
        f_train = ex.submit(
            load_panel,
            data_dir / "char" / "Char_train.npz",
            data_dir / "macro" / "macro_train.npz",
            macro_idx=macro_idx,
        )
        futures = {
            name: ex.submit(
                load_panel,
                data_dir / "char" / f"Char_{name}.npz",
                data_dir / "macro" / f"macro_{name}.npz",
                macro_idx=macro_idx,
                normalize_macro=False,
            )
            for name in ("valid", "test")
        }
        train = f_train.result()
        valid, test = futures["valid"].result(), futures["test"].result()
    mean, std = train.macro_stats()
    for ds in (valid, test):
        if ds.macro is not None and mean is not None:
            ds.macro = normalize_macro_with(ds.macro, mean, std)
            ds.mean_macro, ds.std_macro = mean, std
    return train, valid, test
