"""Process-aware structured logging: one human stream, N event streams.

On a multi-process run every worker printing the same progress line turns
stdout into noise; the contract here is that only ``process_index == 0``
emits human-readable lines, while EVERY process records the same message as
a structured ``log`` event in its own ``events.jsonl``. Library code asks
for the active logger (:func:`get_run_logger`) instead of calling
``print`` — the CLI decides once, at startup, where the sink lives
(:func:`set_run_logger`).
"""

from __future__ import annotations

import sys
import threading
from typing import Any, Optional

from .events import EventLog


class RunLogger:
    """info/warning logger gated to process 0, mirrored into an EventLog."""

    def __init__(self, events: Optional[EventLog] = None, verbose: bool = True):
        self.events = events if events is not None else EventLog()
        self.verbose = verbose

    @property
    def is_primary(self) -> bool:
        return self.events.process_index == 0

    def info(self, msg: str, verbose: Optional[bool] = None, **fields: Any):
        self.events.log(msg, level="info", **fields)
        if (self.verbose if verbose is None else verbose) and self.is_primary:
            print(msg, flush=True)

    def warning(self, msg: str, **fields: Any):
        # warnings print regardless of verbosity (still process-0 only);
        # worker processes keep theirs in their own events file
        self.events.log(msg, level="warning", **fields)
        if self.is_primary:
            print(f"WARNING: {msg}", file=sys.stderr, flush=True)


_lock = threading.Lock()
_active: Optional[RunLogger] = None


def get_run_logger() -> RunLogger:
    """The process-wide active logger (a sinkless process-0-gated printer
    until a CLI installs a real one)."""
    global _active
    with _lock:
        if _active is None:
            _active = RunLogger()
        return _active


def set_run_logger(logger: RunLogger) -> RunLogger:
    """Install the active logger (CLI startup); returns it for chaining."""
    global _active
    with _lock:
        _active = logger
    return logger
