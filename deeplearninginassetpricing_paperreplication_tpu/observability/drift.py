"""Data-drift detection: reference profiles + PSI/KS scoring.

A **reference profile** is a compact per-series sketch of the panel a model
was estimated on — for every firm characteristic (mask-weighted over the
[T, N] panel) and every macro series: moments plus a fixed-probability
quantile sketch. It is written into the run dir at train/refit time
(``reference_profile.json``, a :mod:`reliability.verified` artifact,
referenced from ``manifest.json``), so every candidate the promotion gate
sees carries the fingerprint of the data it learned from.

Later panels — a refit month, a validation batch, one serving request's
characteristics matrix — are scored against the profile with the
**population stability index** (PSI, on the profile's own quantile bins,
expected mass uniform by construction) and a quantile-sketch **KS**
statistic. The standard PSI reading applies: < 0.1 stable, 0.1–0.25
moderate shift, > 0.25 drifted — 0.25 is the default alert/rejection
threshold everywhere (promotion gate ``data_drift``, serving
``dlap_model_drift_*``).

numpy-only (no jax, no device): the report CLI, the stdlib-leaning
promotion gate, and the serving hot path all score without touching a
backend.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Dict, Optional, Union

import numpy as np

PROFILE_FILENAME = "reference_profile.json"
N_QUANTILES = 16  # interior quantile edges → N_QUANTILES + 1 PSI bins
DEFAULT_PSI_THRESHOLD = 0.25  # the standard "significant shift" PSI bar
# below this many scored samples PSI/KS are statistically meaningless
# (PSI sampling noise ≈ χ²(bins−1)/n even with zero drift) — the series
# scores as None and drops out of the aggregates instead of alerting on
# noise (e.g. a 3-month refit window's macro series)
MIN_SAMPLES = 32
_EPS = 1e-6


def series_profile(values: np.ndarray) -> Dict[str, Any]:
    """Sketch one series: moments + interior quantile edges. Non-finite
    entries are dropped (and counted via ``finite_fraction``); an empty or
    constant series degrades gracefully (edges collapse; PSI then scores
    any mass off the single point)."""
    v = np.asarray(values, np.float64).ravel()
    finite = v[np.isfinite(v)]
    frac = float(finite.size / v.size) if v.size else 0.0
    if finite.size == 0:
        return {"n": 0, "finite_fraction": frac, "mean": None, "std": None,
                "min": None, "max": None, "quantiles": []}
    probs = np.linspace(0.0, 1.0, N_QUANTILES + 1)[1:-1]
    return {
        "n": int(finite.size),
        "finite_fraction": round(frac, 6),
        "mean": float(finite.mean()),
        "std": float(finite.std()),
        "min": float(finite.min()),
        "max": float(finite.max()),
        "quantiles": [float(q) for q in np.quantile(finite, probs)],
    }


def reference_profile(panel: Dict[str, Any],
                      source: Optional[str] = None) -> Dict[str, Any]:
    """Profile a panel dict (``individual`` [T, N, F] + ``mask`` [T, N],
    optional ``macro`` [T, M]) into the JSON-serializable reference
    document. Characteristic j's sketch covers only mask-valid entries —
    padded stocks must not flatten the distribution."""
    individual = np.asarray(panel["individual"], np.float64)
    mask = np.asarray(panel.get("mask"), np.float64) \
        if panel.get("mask") is not None else np.ones(individual.shape[:2])
    valid = mask > 0
    features = [series_profile(individual[..., j][valid])
                for j in range(individual.shape[-1])]
    macro = []
    if panel.get("macro") is not None:
        m = np.asarray(panel["macro"], np.float64)
        macro = [series_profile(m[:, j]) for j in range(m.shape[1])]
    return {
        "kind": "reference_profile",
        "schema": 1,
        "written_at": round(time.time(), 3),
        "source": source,
        "n_periods": int(individual.shape[0]),
        "n_stocks": int(individual.shape[1]),
        "individual": features,
        "macro": macro,
    }


def _bin_edges(entry: Dict[str, Any]) -> Optional[np.ndarray]:
    q = entry.get("quantiles") or []
    if not q:
        return None
    return np.asarray(q, np.float64)


def psi(entry: Dict[str, Any], values: np.ndarray) -> Optional[float]:
    """Population stability index of ``values`` against one series
    sketch. Bins are the sketch's quantile edges (open-ended outer bins),
    so the expected mass per bin is uniform by construction; duplicate
    edges (near-constant reference series) merge, with their expected
    mass. None when either side has no data."""
    edges = _bin_edges(entry)
    v = np.asarray(values, np.float64).ravel()
    v = v[np.isfinite(v)]
    if edges is None or v.size < MIN_SAMPLES:
        return None
    if entry.get("min") == entry.get("max"):
        # degenerate (constant) reference series: quantile bins cannot
        # discriminate, so score the mass that moved OFF the point
        # through the same eps-clamped PSI formula (0 when the series is
        # still constant at that value, large when it moved)
        ref = float(entry["mean"])
        tol = 1e-9 * max(1.0, abs(ref))
        off = float(np.mean(np.abs(v - ref) > tol))
        a = np.clip(np.asarray([1.0 - off, off]), _EPS, None)
        e = np.clip(np.asarray([1.0, 0.0]), _EPS, None)
        return float(((a - e) * np.log(a / e)).sum())
    # adapt the bin count to the scored sample: PSI over b bins has
    # sampling noise ≈ χ²(b−1)/n even with zero drift, so a single serving
    # request's ~few-hundred-stock cross-section is scored on a coarser
    # subset of the quantile edges (≥ ~32 samples per bin, floor 4 bins) —
    # a full panel still scores at the sketch's full resolution
    n_bins = edges.size + 1
    target = max(4, min(n_bins, v.size // 32))
    if target < n_bins:
        keep = np.round(np.arange(1, target) * n_bins / target).astype(int)
        edges_used = edges[np.clip(keep - 1, 0, edges.size - 1)]
    else:
        edges_used = edges
    # merge duplicate edges (near-constant reference series): the expected
    # CDF at each unique edge pools the uniform mass of every degenerate
    # bin that collapsed onto it
    uniq = np.unique(edges_used)
    cdf = np.searchsorted(edges, uniq, side="right") / n_bins
    expected = np.diff(np.concatenate(([0.0], cdf, [1.0])))
    # actual histogram over (-inf, uniq[0]], (uniq[0], uniq[1]], ..., +inf)
    idx = np.searchsorted(uniq, v, side="right")
    actual = np.bincount(idx, minlength=uniq.size + 1) / v.size
    a = np.clip(actual, _EPS, None)
    e = np.clip(expected, _EPS, None)
    return float(((a - e) * np.log(a / e)).sum())


def ks_stat(entry: Dict[str, Any], values: np.ndarray) -> Optional[float]:
    """Quantile-sketch Kolmogorov–Smirnov statistic: the max gap between
    the values' empirical CDF at the sketch's quantile edges and the
    reference CDF those edges encode (i/(n_bins) by construction)."""
    edges = _bin_edges(entry)
    v = np.asarray(values, np.float64).ravel()
    v = v[np.isfinite(v)]
    if edges is None or v.size == 0:
        return None
    if v.size < MIN_SAMPLES:
        return None
    uniq = np.unique(edges)
    n_bins = edges.size + 1
    ref_cdf = np.searchsorted(edges, uniq, side="right") / n_bins
    emp_cdf = np.searchsorted(np.sort(v), uniq, side="right") / v.size
    return float(np.abs(emp_cdf - ref_cdf).max())


def drift_report(profile: Dict[str, Any],
                 panel: Dict[str, Any]) -> Dict[str, Any]:
    """Score a whole panel against a reference profile: per-feature and
    per-macro-series PSI + KS, with the max/mean aggregates the gate and
    the serving monitors threshold on."""
    individual = np.asarray(panel["individual"], np.float64)
    mask = np.asarray(panel.get("mask"), np.float64) \
        if panel.get("mask") is not None else np.ones(individual.shape[:2])
    valid = mask > 0
    per: Dict[str, Dict[str, Any]] = {}
    for j, entry in enumerate(profile.get("individual") or []):
        if j >= individual.shape[-1]:
            break
        vals = individual[..., j][valid]
        per[f"char{j}"] = {"psi": psi(entry, vals),
                           "ks": ks_stat(entry, vals)}
    if panel.get("macro") is not None:
        m = np.asarray(panel["macro"], np.float64)
        for j, entry in enumerate(profile.get("macro") or []):
            if j >= m.shape[1]:
                break
            per[f"macro{j}"] = {"psi": psi(entry, m[:, j]),
                                "ks": ks_stat(entry, m[:, j])}
    psis = [d["psi"] for d in per.values() if d["psi"] is not None]
    kss = [d["ks"] for d in per.values() if d["ks"] is not None]
    return {
        "per_series": per,
        "n_series": len(per),
        "max_psi": round(max(psis), 6) if psis else None,
        "mean_psi": round(sum(psis) / len(psis), 6) if psis else None,
        "max_ks": round(max(kss), 6) if kss else None,
    }


def score_request(profile: Dict[str, Any], individual: np.ndarray,
                  mask: Optional[np.ndarray] = None) -> Dict[str, Any]:
    """Score ONE serving request's [N, F] characteristics matrix against
    the profile — the serving-time drift monitor's unit of work."""
    ind = np.asarray(individual, np.float64)
    m = (np.ones(ind.shape[0]) if mask is None
         else np.asarray(mask, np.float64))
    return drift_report(profile, {"individual": ind[None],
                                  "mask": m[None]})


# -- artifact IO (reliability.verified; tolerant reads) ----------------------


def write_profile(run_dir: Union[str, Path],
                  profile: Dict[str, Any]) -> Path:
    """Verified write of ``reference_profile.json`` into a run dir."""
    from ..reliability.verified import write_verified

    path = Path(run_dir) / PROFILE_FILENAME
    write_verified(path, json.dumps(profile, indent=1).encode())
    return path


def read_profile(run_dir: Union[str, Path]) -> Optional[Dict[str, Any]]:
    """Digest-verified read (generation fallback included); also accepts a
    direct path to the JSON file. None when absent or unusable — a missing
    profile disables drift scoring, it must never fail a run."""
    from ..reliability.verified import load_verified, verified_exists

    root = Path(run_dir)
    path = root if root.suffix == ".json" else root / PROFILE_FILENAME
    if not verified_exists(path):
        # tolerate a plain (sidecar-less) file: externally produced profiles
        try:
            return json.loads(path.read_text())
        except (OSError, ValueError):
            return None
    try:
        profile, _ = load_verified(path, lambda b: json.loads(b.decode()))
    except (ValueError, OSError):
        return None
    return profile if isinstance(profile, dict) else None
