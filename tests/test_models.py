"""Model parity: torch-LSTM oracle, param counts, weight invariants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearninginassetpricing_paperreplication_tpu import GAN, GANConfig
from deeplearninginassetpricing_paperreplication_tpu.models.networks import SimpleSDF
from deeplearninginassetpricing_paperreplication_tpu.models.recurrent import TorchLSTM

torch = pytest.importorskip("torch")


def _lstm_params_from_torch(lstm):
    sd = lstm.state_dict()
    out = {}
    for li in range(lstm.num_layers):
        out[f"w_ih_l{li}"] = sd[f"weight_ih_l{li}"].numpy()
        out[f"w_hh_l{li}"] = sd[f"weight_hh_l{li}"].numpy()
        out[f"b_ih_l{li}"] = sd[f"bias_ih_l{li}"].numpy()
        out[f"b_hh_l{li}"] = sd[f"bias_hh_l{li}"].numpy()
    return out


@pytest.mark.parametrize("hidden,layers", [(4, 1), (6, 2)])
def test_lstm_matches_torch(rng, hidden, layers):
    """Gate order / parameterization identical to torch.nn.LSTM."""
    T, I = 31, 7
    torch.manual_seed(1234)
    tl = torch.nn.LSTM(input_size=I, hidden_size=hidden, num_layers=layers, batch_first=True)
    x = rng.standard_normal((T, I)).astype(np.float32)
    with torch.no_grad():
        ref, (h_n, c_n) = tl(torch.from_numpy(x).unsqueeze(0))
    ours = TorchLSTM((hidden,) * layers).apply(
        {"params": _lstm_params_from_torch(tl)}, jnp.asarray(x)
    )
    np.testing.assert_allclose(np.asarray(ours), ref.squeeze(0).numpy(), atol=1e-4)


def test_param_count_matches_reference_paper_dims():
    """Reference AssetPricingGAN(macro=178, individual=46) has 12,233 params:
    SDF 10,433 (LSTM 2,944) + moment 1,800 (SURVEY §'What the reference is')."""
    cfg = GANConfig(macro_feature_dim=178, individual_feature_dim=46)
    gan = GAN(cfg)
    params = gan.init(jax.random.key(0))
    total = sum(x.size for x in jax.tree.leaves(params))
    sdf = sum(x.size for x in jax.tree.leaves(params["sdf_net"]))
    moment = sum(x.size for x in jax.tree.leaves(params["moment_net"]))
    lstm = sum(x.size for x in jax.tree.leaves(params["sdf_net"]["macro_lstm"]))
    assert (total, sdf, moment, lstm) == (12233, 10433, 1800, 2944)


def _toy_batch(rng, T=12, N=20, F=5, M=3, mask_frac=0.3):
    mask = (rng.random((T, N)) > mask_frac).astype(np.float32)
    mask[:, 0] = 1.0  # keep at least one valid stock per period
    return {
        "macro": jnp.asarray(rng.standard_normal((T, M)).astype(np.float32)),
        "individual": jnp.asarray(
            rng.standard_normal((T, N, F)).astype(np.float32) * mask[:, :, None]
        ),
        "returns": jnp.asarray(rng.standard_normal((T, N)).astype(np.float32) * mask),
        "mask": jnp.asarray(mask),
    }


def test_weights_zero_mean_and_masked(rng):
    cfg = GANConfig(macro_feature_dim=3, individual_feature_dim=5)
    gan = GAN(cfg)
    params = gan.init(jax.random.key(1))
    batch = _toy_batch(rng)
    w = gan.weights(params, batch)
    m = batch["mask"]
    np.testing.assert_allclose(np.asarray((w * m).sum(axis=1)), 0.0, atol=1e-5)
    assert np.all(np.asarray(w)[np.asarray(m) == 0] == 0.0)


def test_masked_entries_inert(rng):
    """Changing feature/return values at masked entries must not change
    anything (they are zero-filled by the loader; the model must not peek)."""
    cfg = GANConfig(macro_feature_dim=3, individual_feature_dim=5)
    gan = GAN(cfg)
    params = gan.init(jax.random.key(2))
    batch = _toy_batch(rng)
    out1 = gan.forward(params, batch, phase="conditional")

    m = np.asarray(batch["mask"])
    noise = rng.standard_normal(m.shape).astype(np.float32) * (1 - m) * 100
    batch2 = dict(batch)
    batch2["returns"] = batch["returns"] + jnp.asarray(noise)
    batch2["individual"] = batch["individual"] + jnp.asarray(noise[:, :, None] * (1 - m)[:, :, None])
    out2 = gan.forward(params, batch2, phase="conditional")
    np.testing.assert_allclose(float(out1["loss"]), float(out2["loss"]), rtol=1e-5)


def test_normalized_weights_abs_sum_one(rng):
    cfg = GANConfig(macro_feature_dim=3, individual_feature_dim=5)
    gan = GAN(cfg)
    params = gan.init(jax.random.key(3))
    batch = _toy_batch(rng)
    nw = gan.normalized_weights(params, batch)
    abs_sums = np.asarray((jnp.abs(nw) * batch["mask"]).sum(axis=1))
    np.testing.assert_allclose(abs_sums, 1.0, atol=1e-5)


def test_moments_bounded_and_shaped(rng):
    cfg = GANConfig(macro_feature_dim=3, individual_feature_dim=5, num_condition_moment=8)
    gan = GAN(cfg)
    params = gan.init(jax.random.key(4))
    batch = _toy_batch(rng)
    h = np.asarray(gan.moments(params, batch))
    assert h.shape == (8, 12, 20)
    assert np.all(np.abs(h) <= 1.0)


def test_dropout_changes_training_forward_only(rng):
    cfg = GANConfig(macro_feature_dim=3, individual_feature_dim=5, dropout=0.5)
    gan = GAN(cfg)
    params = gan.init(jax.random.key(5))
    batch = _toy_batch(rng)
    eval1 = gan.forward(params, batch, phase="conditional")
    eval2 = gan.forward(params, batch, phase="conditional")
    assert float(eval1["loss"]) == float(eval2["loss"])  # deterministic eval
    tr1 = gan.forward(params, batch, phase="conditional", rng=jax.random.key(10))
    tr2 = gan.forward(params, batch, phase="conditional", rng=jax.random.key(11))
    assert float(tr1["loss"]) != float(tr2["loss"])  # dropout active


def test_no_macro_config(rng):
    cfg = GANConfig(macro_feature_dim=0, individual_feature_dim=5, use_rnn=False)
    gan = GAN(cfg)
    params = gan.init(jax.random.key(6))
    batch = _toy_batch(rng)
    batch = {k: v for k, v in batch.items() if k != "macro"}
    w = gan.weights(params, batch)
    assert w.shape == (12, 20)


def test_simple_sdf(rng):
    batch = _toy_batch(rng)
    model = SimpleSDF(macro_dim=3, individual_dim=5)
    params = model.init(
        jax.random.key(7), batch["macro"], batch["individual"], batch["mask"], True
    )["params"]
    w = model.apply({"params": params}, batch["macro"], batch["individual"], batch["mask"], True)
    np.testing.assert_allclose(
        np.asarray((w * batch["mask"]).sum(axis=1)), 0.0, atol=1e-5
    )
