"""``python -m deeplearninginassetpricing_paperreplication_tpu.supervise`` —
run any heartbeat-writing entrypoint under hang detection, restart with
automatic ``--resume``, and crash-loop policy.

Thin module-runner shim; the implementation lives in
:mod:`.reliability.supervisor`. The supervise loop never touches a JAX
backend, but this ``-m`` entry does pay the package ``__init__``'s jax
import — when the jax stack itself may be wedged, run the implementation
directly instead (it resolves its stdlib-only dependencies by path):

    python deeplearninginassetpricing_paperreplication_tpu/reliability/supervisor.py \\
        --run_dir ckpt -- <child command>
"""

from .reliability.supervisor import build_arg_parser, main  # noqa: F401

if __name__ == "__main__":
    raise SystemExit(main())
