"""Cross-process trace assembly: one run dir → one Chrome trace JSON.

A supervised fleet leaves a FAMILY of event files behind — ``events.jsonl``
(process 0), ``events.proc{p}.jsonl`` (multihost workers),
``events.{wid}.jsonl`` (sweep workers), ``events.supervisor*.jsonl``,
``events.faults.jsonl``, and ``replica{i}/events*.jsonl`` (serving
replicas). :func:`assemble_trace` merges them all into a single Chrome
trace-event JSON openable in Perfetto or ``chrome://tracing``:

  * span begin/end pairs → complete (``"X"``) duration events, laned per
    (file, thread) — the ``tid`` each row carries (0 for pre-telemetry
    rows) keeps a thread pool's concurrent compiles on separate tracks;
  * counters → cumulative counter (``"C"``) tracks; gauges → instantaneous
    counter tracks; device-memory snapshots → a bytes-in-use track;
  * fault/restart/takeover/guard rows → instant (``"i"``) events, so a
    SIGKILL or lease takeover is a visible mark on its process's lane;
  * a ``span_begin`` whose end never made it to disk (the writer was
    SIGKILLed mid-span) is **synthesized**: a duration event from the
    begin to the last timestamp its process logged, tagged
    ``{"synthesized_end": true}`` — a crash leaves a truncated bar, not a
    missing one.

Request-scoped flow: ``request`` rows (the serving plane's per-request
trace records, and the load generator's ``client/request`` rows) become
``"X"`` slices carrying their trace id and segment timings, and every
trace id's slices are chained with Chrome flow events (``"s"``/``"t"``/
``"f"``) — client send → each replica's request lane (retries included:
the client reuses one trace id across retries) → the ``serve/flush_
dispatch`` slice of the flush that served it (linked by flush id within
the serving process). One killed-and-retried request reads as ONE arrowed
trace spanning both replicas.

Clock alignment: ``mono`` timestamps are monotonic but per-process (and
reset across supervised restarts), so rows are grouped by (file, run_id)
and each group's monotonic clock is anchored to wall time via the median
of ``ts - mono`` over the group — cross-process ordering comes from wall
clocks (NTP-grade alignment) while within-process durations keep their
monotonic precision. Rows with no ``mono`` (fault-injector appends) use
``ts`` directly.

Multiple run dirs merge into one trace (``report --trace`` accepts the
client's run dir next to the fleet's): each dir contributes its full
event-file family, process lanes are prefixed with the dir name, and the
same wall-clock alignment orders everything globally.

Determinism: output depends only on file contents — files are walked in
sorted order, events sorted by a total key, and timestamps quantized to
integer microseconds — so two invocations over the same run dir(s) emit
byte-identical JSON (asserted in tier-1).

Pure stdlib file reading: no jax, no device, works on live or crashed
run dirs. Exposed as ``report --trace out.json``.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

# counter rows rendered as instant marks (one visible tick per incident)
# instead of cumulative counter tracks
INSTANT_NAMES = frozenset({
    "fault/injected",
    "supervise/death",
    "supervise/restart",
    "supervise/outcome",
    "sweep/lease_takeover",
    "sweep/quarantine",
    "guard/trip",
    "checkpoint/fallback",
    "checkpoint/unusable",
    # SLO/probe incidents (also emitted as DURABLE kind-"alert"/"probe"
    # rows; either representation renders as one visible mark)
    "alert/firing",
    "alert/resolved",
    "probe/failure",
})

# row attrs copied into instant-event args (bounded; paths/digests stay in
# the event file)
_INSTANT_ARG_KEYS = (
    "site", "action", "section", "rc", "hang", "outcome", "worker",
    "attempt", "phase", "bucket", "seed", "rank",
    "objective", "window", "severity", "target", "error",
    "burn_long", "burn_short", "consecutive",
)

# request-row attrs copied into the X slice's args: the trace identity,
# the segment breakdown, and the flush link
_REQUEST_ARG_KEYS = (
    "trace_id", "span_id", "parent_id", "endpoint", "method", "status",
    "wire", "replica", "cached", "attempts", "retried",
    "parse_s", "queue_s", "batch_s", "dispatch_s", "dispatch_share_s",
    "serialize_s", "write_s", "flush", "occupancy",
)


def trace_file_paths(run_dir) -> List[Path]:
    """The run dir's full event-file family, deterministically ordered
    (the same glob set the report CLI reads, so trace and report can never
    disagree about which processes exist)."""
    run_dir = Path(run_dir)
    return (sorted(run_dir.glob("events*.jsonl"))
            + sorted(run_dir.glob("replica*/events*.jsonl")))


def read_jsonl(path: Path) -> List[Dict[str, Any]]:
    """Tolerant JSONL reader shared with the report CLI: a missing file or
    a torn tail line (crashed writer) yields fewer rows, never an error."""
    rows: List[Dict[str, Any]] = []
    try:
        text = Path(path).read_text()
    except OSError:
        return rows
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail line from a crashed writer
        if isinstance(row, dict):
            rows.append(row)
    return rows


def _median(values: List[float]) -> float:
    s = sorted(values)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _group_offsets(rows: List[Dict[str, Any]]) -> Dict[Any, float]:
    """Per-run_id wall-clock anchor for one file's monotonic clock:
    ``median(ts - mono)`` over the rows that carry both. The median (not
    the first row) rides out scheduler jitter between the two clock reads
    and any mid-run NTP step."""
    samples: Dict[Any, List[float]] = {}
    for r in rows:
        ts, mono = r.get("ts"), r.get("mono")
        if isinstance(ts, (int, float)) and isinstance(mono, (int, float)):
            samples.setdefault(r.get("run_id"), []).append(ts - mono)
    return {rid: _median(v) for rid, v in samples.items()}


def _aligned_ts(row: Dict[str, Any], offsets: Dict[Any, float]
                ) -> Optional[float]:
    """One row's wall-aligned timestamp (seconds), or None when the row
    carries no usable clock at all."""
    mono = row.get("mono")
    if isinstance(mono, (int, float)):
        off = offsets.get(row.get("run_id"))
        if off is not None:
            return mono + off
    ts = row.get("ts")
    if isinstance(ts, (int, float)):
        return ts
    return None


def assemble_trace(run_dirs) -> Dict[str, Any]:
    """Build the Chrome trace dict for one run dir — or a LIST of run
    dirs merged into one timeline (client + fleet: the flow arrows then
    span both sides of every request). Raises FileNotFoundError when any
    directory holds no event files — an empty contribution must not look
    like a successful export."""
    if isinstance(run_dirs, (str, os.PathLike)):
        run_dirs = [run_dirs]
    run_dirs = [Path(d) for d in run_dirs]
    multi = len(run_dirs) > 1
    dir_paths: List[Tuple[Path, Path]] = []  # (run_dir, event file)
    for run_dir in run_dirs:
        paths = trace_file_paths(run_dir)
        if not paths:
            raise FileNotFoundError(
                f"no events*.jsonl files under {run_dir} — nothing to "
                "trace")
        dir_paths.extend((run_dir, p) for p in paths)

    # pass 1: read + align every file, find the global origin
    files: List[Tuple[str, List[Dict], Dict[Any, float]]] = []
    t0: Optional[float] = None
    for run_dir, path in dir_paths:
        rows = read_jsonl(path)
        offsets = _group_offsets(rows)
        rel = str(path.relative_to(run_dir))
        label = f"{run_dir.name}/{rel}" if multi else rel
        files.append((label, rows, offsets))
        for r in rows:
            at = _aligned_ts(r, offsets)
            if at is not None:
                t0 = at if t0 is None else min(t0, at)
    if t0 is None:
        raise FileNotFoundError(
            "event files under "
            + ", ".join(str(d) for d in run_dirs)
            + " contain no timestamped rows")

    def us(aligned: float) -> int:
        return int(round((aligned - t0) * 1e6))

    events: List[Dict[str, Any]] = []
    n_spans = n_synthesized = n_instants = n_requests = 0
    # trace_id -> [(start_us, pid, tid), ...] slice anchors for flow chains
    request_slices: Dict[str, List[Tuple[int, int, int]]] = {}
    # (pid, run_id, flush_id) -> (start_us, pid, tid) flush-dispatch slices
    flush_slices: Dict[Tuple[int, Any, Any], Tuple[int, int, int]] = {}
    # trace_id -> [(pid, run_id, flush_id), ...] flush links seen on rows
    flush_links: Dict[str, List[Tuple[int, Any, Any]]] = {}
    for pid, (label, rows, offsets) in enumerate(files):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        events.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                       "tid": 0, "args": {"sort_index": pid}})
        # per-(run_id, tid) open-span stacks for dangling-begin synthesis;
        # last timestamp per run_id bounds what a dead writer's clock saw
        open_spans: Dict[Tuple[Any, int], List[Tuple[str, int, Dict]]] = {}
        last_ts: Dict[Any, int] = {}
        counters: Dict[str, float] = {}
        for row in rows:
            at = _aligned_ts(row, offsets)
            if at is None:
                continue
            t = us(at)
            rid = row.get("run_id")
            last_ts[rid] = max(last_ts.get(rid, t), t)
            kind = row.get("kind")
            name = str(row.get("name", ""))
            tid = row.get("tid")
            tid = int(tid) if isinstance(tid, (int, float)) else 0
            if kind == "span_begin":
                open_spans.setdefault((rid, tid), []).append((name, t, row))
            elif kind == "request":
                # one per-request trace record → one slice on its lane,
                # anchored for the trace-id flow chain
                dur = row.get("duration_s")
                dur_us = (int(round(float(dur) * 1e6))
                          if isinstance(dur, (int, float)) else 0)
                args = {k: row[k] for k in _REQUEST_ARG_KEYS
                        if row.get(k) is not None}
                start = t - dur_us
                events.append({
                    "ph": "X", "name": name, "cat": "request",
                    "pid": pid, "tid": tid,
                    "ts": start, "dur": dur_us, "args": args,
                })
                n_requests += 1
                trace_id = row.get("trace_id")
                if isinstance(trace_id, str) and trace_id:
                    request_slices.setdefault(trace_id, []).append(
                        (start, pid, tid))
                    if row.get("flush") is not None:
                        flush_links.setdefault(trace_id, []).append(
                            (pid, rid, row["flush"]))
            elif kind == "span_end":
                dur = row.get("duration_s")
                dur_us = (int(round(float(dur) * 1e6))
                          if isinstance(dur, (int, float)) else 0)
                args: Dict[str, Any] = {}
                if row.get("status") and row["status"] != "ok":
                    args["status"] = row["status"]
                    if row.get("error"):
                        args["error"] = row["error"]
                if name == "serve/flush_dispatch":
                    # a flow-arrow target: requests reference this flush
                    # by id within the same process incarnation
                    if row.get("flush") is not None:
                        args["flush"] = row["flush"]
                        flush_slices.setdefault(
                            (pid, rid, row["flush"]),
                            (t - dur_us, pid, tid))
                events.append({
                    "ph": "X", "name": name, "cat": "span",
                    "pid": pid, "tid": tid,
                    "ts": t - dur_us, "dur": dur_us, "args": args,
                })
                n_spans += 1
                # retire the matching begin (topmost with this name) so it
                # is not synthesized at EOF
                stack = open_spans.get((rid, tid))
                if stack:
                    for i in range(len(stack) - 1, -1, -1):
                        if stack[i][0] == name:
                            stack.pop(i)
                            break
            elif (kind in ("alert", "probe")
                  or (kind == "counter" and name in INSTANT_NAMES)):
                # SLO transitions and probe failures are their own durable
                # kinds; they mark the timeline exactly like the counter-
                # shaped incidents
                args = {k: row[k] for k in _INSTANT_ARG_KEYS
                        if row.get(k) is not None}
                events.append({
                    "ph": "i", "name": name, "cat": "incident", "s": "p",
                    "pid": pid, "tid": tid, "ts": t, "args": args,
                })
                n_instants += 1
            elif kind == "counter":
                value = row.get("value")
                inc = float(value) if isinstance(value, (int, float)) else 1.0
                counters[name] = counters.get(name, 0.0) + inc
                events.append({
                    "ph": "C", "name": name, "pid": pid, "tid": 0, "ts": t,
                    "args": {"total": counters[name]},
                })
            elif kind == "gauge":
                value = row.get("value")
                if isinstance(value, (int, float)):
                    events.append({
                        "ph": "C", "name": name, "pid": pid, "tid": 0,
                        "ts": t, "args": {"value": float(value)},
                    })
            elif kind == "memory":
                totals = row.get("totals") or {}
                in_use = totals.get("bytes_in_use")
                if isinstance(in_use, (int, float)):
                    events.append({
                        "ph": "C", "name": "device_memory", "pid": pid,
                        "tid": 0, "ts": t,
                        "args": {"bytes_in_use": float(in_use)},
                    })
        # EOF: every still-open span lost its end row (crash / SIGKILL /
        # torn tail) — synthesize a truncated bar to the last timestamp its
        # run logged so the work is visible, not vanished
        for (rid, tid), stack in sorted(
                open_spans.items(),
                key=lambda kv: (str(kv[0][0]), kv[0][1])):
            for name, t_begin, row in stack:
                t_end = max(last_ts.get(rid, t_begin), t_begin)
                events.append({
                    "ph": "X", "name": name, "cat": "span",
                    "pid": pid, "tid": tid,
                    "ts": t_begin, "dur": t_end - t_begin,
                    "args": {"synthesized_end": True},
                })
                n_synthesized += 1

    # flow chains: every trace id's slices — client send, each server
    # attempt (retries reuse the id), then the flush dispatch(es) that
    # served it — arrowed s → t → … → f in wall-time order. Chains of one
    # slice draw no arrow.
    n_flows = 0
    for trace_id in sorted(request_slices):
        anchors = list(request_slices[trace_id])
        for link in flush_links.get(trace_id, ()):
            slice_ = flush_slices.get(link)
            if slice_ is not None:
                anchors.append(slice_)
        # dedup (a retried request could reference one flush twice), then
        # total order by time/lane
        anchors = sorted(set(anchors))
        if len(anchors) < 2:
            continue
        for i, (ts, pid, tid) in enumerate(anchors):
            ph = "s" if i == 0 else ("f" if i == len(anchors) - 1 else "t")
            ev = {"ph": ph, "id": trace_id, "name": "request_flow",
                  "cat": "flow", "pid": pid, "tid": tid, "ts": ts}
            if ph == "f":
                ev["bp"] = "e"  # bind to the enclosing slice, not the next
            events.append(ev)
            n_flows += 1

    # total deterministic order: metadata first, then by time/lane/name
    def sort_key(e: Dict[str, Any]):
        return (0 if e["ph"] == "M" else 1, e.get("ts", -1), e["pid"],
                e.get("tid", 0), e["ph"], e["name"], str(e.get("id", "")),
                json.dumps(e.get("args", {}), sort_keys=True))

    events.sort(key=sort_key)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_dir": run_dirs[0].name,
            "run_dirs": [d.name for d in run_dirs],
            "n_files": len(files),
            "n_span_events": n_spans,
            "n_synthesized_ends": n_synthesized,
            "n_instant_events": n_instants,
            "n_request_events": n_requests,
            "n_flow_events": n_flows,
            "n_traces": len(request_slices),
        },
    }


def write_trace(run_dirs, out_path) -> Dict[str, Any]:
    """Assemble + write the trace JSON (one run dir or a list — client +
    fleet merge into one timeline); returns the ``otherData`` summary.
    Deterministic serialization (sorted keys, fixed separators) so two
    invocations over the same run dir(s) produce byte-identical files."""
    trace = assemble_trace(run_dirs)
    out_path = Path(out_path)
    out_path.write_text(
        json.dumps(trace, sort_keys=True, separators=(",", ":")))
    return trace["otherData"]
